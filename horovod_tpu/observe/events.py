"""Control-plane flight recorder: correlated cross-subsystem events.

The data plane got first-class tracing in PRs 1–17 (timelines,
anatomies, timeseries, alerts); this module gives the *control* plane
the same treatment.  Every lifecycle actor — the elastic driver,
heartbeat/abort protocol, serving autoscaler, profile-guided tuner,
compression guard, checkpoint writer, watchdog, and the launcher's
restart loop — emits structured events through one API::

    from horovod_tpu.observe import events
    eid = events.record_event("abort.publish", severity="warning",
                              payload={"reason": ...},
                              cause_id=lease_expiry_id)

Each event is ``{id, ts, host, rank, kind, severity, correlation_id,
cause_id, payload}``.  ``cause_id`` links events into causal chains
(lease expiry → abort flag → epoch N+1 → restart → resume-from-step);
``correlation_id`` names the whole incident — it is inherited from the
cause when one is known (even across processes, via ids carried in
abort flags / epoch records) and defaults to the event's own id at a
chain root.

Transport: events append to a bounded per-process ring (overflow drops
the oldest and counts ``hvd_events_dropped_total`` — the recorder must
never block a step).  In the launcher process the recorder is attached
directly to the :class:`~horovod_tpu.run.http_server.RendezvousServer`
(``attach_server``) and each event lands in the journaled ``events``
scope immediately — surviving warm-standby failover like membership
does.  In worker processes a flusher thread (modeled on
metrics/push.py) drains the ring through the relay/batch path
(run/relay.py: ``events`` is a batch scope — every event has a unique
key, so last-writer-wins coalescing can never merge two distinct
events) with permanent fallback to the primary when the relay dies.

Consumers: signed ``GET /events`` with cursor reads
(``scope_since("events", v)``), ``scripts/hvd_events.py`` (text / JSON
/ --follow / --chain), ``scripts/hvd_dash.py`` (unified console +
incident reports), and ``hvd_trace_merge`` (events as an instant-event
row aligned with the per-rank device timeline).  Knobs:
``HVD_EVENTS`` / ``HVD_EVENTS_RING_CAP`` / ``HVD_EVENTS_FLUSH_SECONDS``
/ ``HVD_EVENTS_SERVER_CAP`` (docs/observe.md).
"""

from __future__ import annotations

import atexit
import collections
import itertools
import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)

#: rendezvous KV scope the recorder flushes into (journaled, cursor-read)
EVENTS_SCOPE = "events"

#: kinds are dotted "<subsystem>.<action>" names; the inventory below is
#: documentation, not an enum — emitters may add new kinds freely
KNOWN_KINDS = (
    "epoch.commit", "epoch.remove", "epoch.admit", "epoch.drain",
    "epoch.drain_ack", "epoch.blocklist", "epoch.giveup",
    "lease.expired", "abort.publish", "abort.observe",
    "restart.attempt", "restart.resume",
    "autoscale.grow", "autoscale.shrink",
    "autotune.apply", "autotune.verify", "autotune.rollback",
    "compression.fallback",
    "checkpoint.save", "checkpoint.commit", "checkpoint.restore",
    "snapshot.begin", "snapshot.commit", "snapshot.reprotect",
    "restore.source", "spare.purged",
    "watchdog.alert", "watchdog.arm",
    "preempt.notice", "primary.takeover", "chaos.inject",
)


def _record_metric(name: str, labels=None, n: int = 1) -> None:
    """Count on the metrics plane; never raises (the recorder must not
    take down the caller)."""
    try:
        from .. import metrics

        if metrics.on():
            fam = getattr(metrics, name)
            (fam.labels(*labels) if labels else fam).inc(n)
    except Exception:  # noqa: BLE001
        pass


class Recorder:
    """One process's flight-recorder state: the bounded ring, the
    id → correlation map that threads chains, and whichever sink
    (in-process server or relay-routed flusher) drains it."""

    def __init__(self, cap: Optional[int] = None):
        self.cap = int(cap if cap is not None else env_util.get_int(
            env_util.HVD_EVENTS_RING_CAP,
            env_util.DEFAULT_EVENTS_RING_CAP))
        self._ring: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._host = socket.gethostname() or "localhost"
        self._pid = os.getpid()
        # id → correlation_id for events THIS process recorded, so a
        # same-process cause resolves its chain without a server round
        # trip; bounded like the ring
        self._corr: "collections.OrderedDict[str, str]" = \
            collections.OrderedDict()
        self.dropped = 0
        self.recorded = 0
        self._server = None  # attached RendezvousServer (launcher)
        self._direct_puts = 0
        self._flusher: Optional["EventFlusher"] = None

    # -- the hot path -----------------------------------------------------
    def record(self, kind: str, severity: str = "info",
               payload: Optional[dict] = None,
               correlation_id: Optional[str] = None,
               cause_id: Optional[str] = None,
               rank: Optional[int] = None) -> str:
        """Append one event; returns its id (the handle callers embed in
        flags/records so downstream actors can chain onto it).  A dict
        build + deque append — target <1% of a 1 ms step."""
        eid = f"{self._host}-{self._pid}-{next(self._seq)}"
        if correlation_id is None:
            if cause_id is not None:
                correlation_id = self._corr.get(cause_id, cause_id)
            else:
                correlation_id = eid
        event = {
            "id": eid,
            "ts": time.time(),
            "host": self._host,
            "rank": rank,
            "kind": kind,
            "severity": severity,
            "correlation_id": correlation_id,
            "cause_id": cause_id,
            "payload": payload or {},
        }
        with self._lock:
            self._corr[eid] = correlation_id
            while len(self._corr) > 4 * self.cap:
                self._corr.popitem(last=False)
            self._ring.append(event)
            if len(self._ring) > self.cap:
                self._ring.popleft()
                self.dropped += 1
                dropped = True
            else:
                dropped = False
            self.recorded += 1
            server = self._server
        _record_metric("EVENTS_TOTAL", (kind, severity))
        if dropped:
            _record_metric("EVENTS_DROPPED")
        if server is not None:
            self._drain_to_server(server)
        return eid

    # -- sinks ------------------------------------------------------------
    def attach_server(self, server) -> None:
        """Launcher-side sink: events land in the server's journaled
        ``events`` scope immediately (no flusher thread, no HTTP)."""
        self._server = server
        if server is not None:
            self._drain_to_server(server)

    def _drain_to_server(self, server) -> None:
        for event in self.drain():
            try:
                server.put(EVENTS_SCOPE, event["id"],
                           json.dumps(event).encode())
                self._direct_puts += 1
            except Exception as e:  # noqa: BLE001 — recording is best-effort
                log.debug("event put failed: %s", e)
        # bound the server-side scope so an always-on recorder cannot
        # grow the store (and its journal replay) without limit
        if self._direct_puts and self._direct_puts % 512 == 0:
            try:
                prune_scope(server)
            except Exception:  # noqa: BLE001
                pass

    def drain(self) -> List[dict]:
        """Pop every buffered event (flusher / attached-server sink)."""
        with self._lock:
            out = list(self._ring)
            self._ring.clear()
        return out

    def requeue(self, events: List[dict]) -> None:
        """Put undelivered events back at the front for the next flush
        (newer appends win the ring slots if it overflows)."""
        with self._lock:
            for event in reversed(events):
                self._ring.appendleft(event)
            while len(self._ring) > self.cap:
                self._ring.pop()
                self.dropped += 1
                _record_metric("EVENTS_DROPPED")

    def pending(self) -> int:
        with self._lock:
            return len(self._ring)


def prune_scope(server, cap: Optional[int] = None) -> int:
    """Trim the server's ``events`` scope to the newest ``cap`` events
    (``HVD_EVENTS_SERVER_CAP``); returns how many were dropped."""
    cap = int(cap if cap is not None else env_util.get_int(
        env_util.HVD_EVENTS_SERVER_CAP,
        env_util.DEFAULT_EVENTS_SERVER_CAP))
    items = server.scope_items(EVENTS_SCOPE)
    if len(items) <= cap:
        return 0
    def _ts(kv):
        try:
            return float(json.loads(kv[1]).get("ts") or 0.0)
        except (ValueError, TypeError):
            return 0.0
    excess = sorted(items.items(), key=_ts)[:len(items) - cap]
    for key, _ in excess:
        server.delete(EVENTS_SCOPE, key)
    return len(excess)


class EventFlusher:
    """Worker-side flusher thread (metrics/push.py template): drains the
    ring every ``HVD_EVENTS_FLUSH_SECONDS`` through the relay when one
    is resolved — each event is one loopback PUT the relay coalesces
    into its upstream batch — with permanent fallback to the primary
    (``mark_relay_failed``) when the relay dies; the direct path ships
    the whole drain as one signed ``PUT /batch``.  Never raises."""

    def __init__(self, recorder: Recorder, addr: str, port: int,
                 secret: Optional[bytes] = None,
                 interval: Optional[float] = None):
        self.recorder = recorder
        self.addr = addr
        self.port = int(port)
        self.secret = secret
        self.interval = float(interval if interval is not None
                              else env_util.get_float(
                                  env_util.HVD_EVENTS_FLUSH_SECONDS,
                                  env_util.get_float(
                                      env_util.HVD_METRICS_PUSH_SECONDS,
                                      env_util.DEFAULT_EVENTS_FLUSH_SECONDS)))
        self.flushes = 0
        self.events_flushed = 0
        self.errors = 0
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def flush_now(self) -> bool:
        events = self.recorder.drain()
        if not events:
            return True
        from ..run import relay as relay_mod
        from ..run.http_client import put_batch

        try:
            ep = relay_mod.control_endpoint()
            if ep is not None and ep[2]:
                # relay path: loopback PUTs the relay batches upstream;
                # control_put flips to the direct path permanently on a
                # dead relay, so no event is silently lost behind one
                for event in events:
                    relay_mod.control_put(
                        self.addr, self.port, EVENTS_SCOPE, event["id"],
                        json.dumps(event).encode(), secret=self.secret)
            else:
                put_batch(self.addr, self.port,
                          [(f"/{EVENTS_SCOPE}/{e['id']}",
                            json.dumps(e).encode()) for e in events],
                          secret=self.secret, retry=True)
        except Exception as e:  # noqa: BLE001 — keep them for next flush
            self.errors += 1
            log.debug("event flush failed (%d kept): %s", len(events), e)
            self.recorder.requeue(events)
            return False
        self.flushes += 1
        self.events_flushed += len(events)
        return True

    def _loop(self) -> None:
        while not self._stop_event.wait(self.interval):
            self.flush_now()
        self.flush_now()  # final drain

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hvd-events-flush")
        self._thread.start()
        atexit.register(self.stop)

    def stop(self, final_flush: bool = True) -> None:
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if final_flush:
            self.flush_now()


# ---------------------------------------------------------------------------
# process-wide surface
# ---------------------------------------------------------------------------
_recorder: Optional[Recorder] = None
_recorder_lock = threading.Lock()


def on() -> bool:
    return env_util.get_bool(env_util.HVD_EVENTS, True)


def recorder() -> Recorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = Recorder()
    return _recorder


def record_event(kind: str, severity: str = "info",
                 payload: Optional[dict] = None,
                 correlation_id: Optional[str] = None,
                 cause_id: Optional[str] = None,
                 rank: Optional[int] = None) -> Optional[str]:
    """The one emission API (module docstring).  Returns the event id,
    or None when the recorder is off (callers embed the id in flags /
    epoch records; None serializes harmlessly)."""
    if not on():
        return None
    rec = recorder()
    eid = rec.record(kind, severity=severity, payload=payload,
                     correlation_id=correlation_id, cause_id=cause_id,
                     rank=rank)
    if rec._server is None and rec._flusher is None:
        _maybe_start_flusher(rec)
    return eid


def _maybe_start_flusher(rec: Recorder) -> None:
    """Lazily start the worker-side flusher the first time an event is
    recorded in a process with rendezvous wiring but no attached
    server (workers; the launcher attaches directly)."""
    with _recorder_lock:
        if rec._flusher is not None or rec._server is not None:
            return
        addr = env_util.get_str(env_util.HVD_METRICS_KV_ADDR)
        port = env_util.get_int(env_util.HVD_METRICS_KV_PORT, 0)
        if not addr or not port:
            return
        secret_hex = env_util.get_str(env_util.HVD_METRICS_SECRET)
        secret = bytes.fromhex(secret_hex) if secret_hex else None
        rec._flusher = EventFlusher(rec, addr, port, secret=secret)
        rec._flusher.start()


def attach_server(server) -> None:
    """Wire the launcher's recorder straight into its rendezvous server
    (run/run.py launch_job)."""
    if on():
        recorder().attach_server(server)


def flush() -> None:
    """Force a synchronous drain (tests, shutdown paths)."""
    rec = _recorder
    if rec is None:
        return
    if rec._server is not None:
        rec._drain_to_server(rec._server)
    elif rec._flusher is not None:
        rec._flusher.flush_now()


def correlation_of(event_id: Optional[str]) -> Optional[str]:
    """The correlation id of an event THIS process recorded (None when
    unknown) — emitters embed it next to the event id in flags/records
    so downstream processes join the same chain."""
    if event_id is None or _recorder is None:
        return None
    with _recorder._lock:
        return _recorder._corr.get(event_id)


def _reset_for_tests() -> None:
    global _recorder
    with _recorder_lock:
        if _recorder is not None and _recorder._flusher is not None:
            _recorder._flusher.stop(final_flush=False)
        _recorder = None


# ---------------------------------------------------------------------------
# chain extraction (shared by hvd_events --chain, hvd_dash --incident,
# and the e2e causal-chain test)
# ---------------------------------------------------------------------------
def extract_chain(events: List[dict], event_id: str) -> List[dict]:
    """The causal chain an event belongs to: walk ``cause_id`` links to
    the root, then return every event sharing the root's correlation id
    (plus any linked by cause into the chain), oldest first."""
    by_id = {e.get("id"): e for e in events if isinstance(e, dict)}
    node = by_id.get(event_id)
    if node is None:
        return []
    seen = set()
    while node.get("cause_id") in by_id and node["id"] not in seen:
        seen.add(node["id"])
        node = by_id[node["cause_id"]]
    corr = node.get("correlation_id") or node.get("id")
    chain = [e for e in events if isinstance(e, dict)
             and (e.get("correlation_id") == corr or e.get("id") == corr)]
    chain.sort(key=lambda e: (e.get("ts") or 0.0, str(e.get("id"))))
    return chain


def chain_summary(chain: List[dict]) -> Dict[str, object]:
    """The incident-report digest of a chain: what failed, what the
    control plane did, and what it cost (hvd_dash --incident)."""
    kinds = [e.get("kind") for e in chain]
    failed_rank = None
    steps_lost = None
    for e in chain:
        p = e.get("payload") or {}
        if failed_rank is None:
            failed_rank = p.get("rank") if e.get("kind") in (
                "lease.expired", "epoch.remove") else failed_rank
            if failed_rank is None and e.get("kind") == "lease.expired":
                failed_rank = e.get("rank")
        if e.get("kind") == "restart.resume" and \
                p.get("steps_lost") is not None:
            steps_lost = p.get("steps_lost")
    duration = None
    if len(chain) >= 2:
        ts = [e.get("ts") for e in chain if e.get("ts") is not None]
        if len(ts) >= 2:
            duration = max(ts) - min(ts)
    return {
        "correlation_id": chain[0].get("correlation_id") if chain else None,
        "events": len(chain),
        "kinds": kinds,
        "failed_rank": failed_rank,
        "steps_lost": steps_lost,
        "duration_seconds": duration,
        "severities": sorted({e.get("severity") for e in chain
                              if e.get("severity")}),
    }
