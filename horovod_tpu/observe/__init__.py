"""Online anomaly watchdog (docs/observe.md).

Detectors (detectors.py) run over the always-on telemetry time-series
(metrics/timeseries.py) and emit alert records ``{severity, signal,
evidence, window}``; the watchdog (watchdog.py) runs them next to the
launcher's rendezvous server, publishes alerts to the ``alerts`` KV
scope (``GET /alerts``, ``hvd_alerts_total``), and closes the loop: a
confirmed step-time or straggler alert auto-arms a trace+profile
window — the existing ``HVD_TRACE_*``/``HVD_PROFILE_*`` machinery,
armed rank-consistently via a KV-broadcast start step (autoarm.py) —
so the alert ships with replay/anatomy attribution instead of a bare
number.
"""

from __future__ import annotations

from .detectors import (  # noqa: F401
    comm_beta_drift,
    ewma_mad_regression,
    mfu_drop,
    slo_burn_rate,
    straggler_drift,
    straggler_from_verdicts,
)
from .watchdog import Watchdog  # noqa: F401
