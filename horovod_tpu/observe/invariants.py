"""System invariant monitors: machine-checked recovery promises.

Every fault-tolerance subsystem in this repo makes a promise — epochs
only move forward, aborts reach survivors within a bounded delay, a
lossy recovery costs at most one snapshot interval, ranks restoring
from peers agree on the source generation, a drained serving replica
completes each request exactly once, and nobody is left in the roster
without being live.  Until now those promises were each pinned by one
unit test; nothing checked them *as a system* while composed failures
were in flight.

This module turns each promise into an :class:`Invariant` evaluated
over the flight-recorder event stream (``GET /events``,
observe/events.py) plus optional side evidence (final worker statuses
from the chaos runner, serving completion counts).  A failed check
yields a :class:`Violation` carrying the **causal event chain** as
evidence — the same ``cause_id``/``correlation_id`` walk the incident
console uses (events.extract_chain) — so a red verdict always names
the exact sequence of control-plane actions that broke the promise.

Consumed by the chaos campaign engine (elastic/chaos.py), the
``hvd_chaos --check`` tier-1 fixture, and directly against a live
job's event stream (scripts/hvd_chaos.py ``--events-url`` style use is
left to the consoles; the checkers only need the event dicts).

The catalogue (docs/fault_tolerance.md "Chaos certification"):

===========================  ============================================
invariant                    promise
===========================  ============================================
``epoch-monotonic``          committed epochs strictly increase; no two
                             commits share an epoch number (fencing)
``abort-propagation``        every abort is observed by at least one
                             survivor within 2 x the heartbeat interval
``steps-lost-bound``         a resume loses at most one snapshot
                             interval of steps
``restore-source-agreement`` every rank restoring into the same epoch
                             restores from the same snapshot generation
``serving-exactly-once``     no request id completes twice
``no-hanging-rank``          at quiescence, every roster member is live
                             and every non-member has actually stopped
===========================  ============================================
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..utils.logging import get_logger
from .events import extract_chain

log = get_logger(__name__)


@dataclass
class Violation:
    """One broken promise, with its causal evidence."""

    invariant: str
    message: str
    chain: List[dict] = field(default_factory=list)
    evidence: Dict[str, object] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "invariant": self.invariant,
            "message": self.message,
            "evidence": self.evidence,
            "chain": [{k: e.get(k) for k in
                       ("ts", "kind", "severity", "rank", "id")}
                      for e in self.chain],
        }


@dataclass
class Context:
    """The evidence bundle one check run sees.

    ``events``: flight-recorder events, any order (checks sort).
    ``hb_interval``: the heartbeat interval the run used, seconds.
    ``snapshot_every``: the snapshot commit cadence, steps.
    ``workers``: optional final worker statuses from the chaos runner —
    ``{worker_id: {"status": ..., "step": ...}}``; statuses in
    ``LIVE_END_STATES`` count as a clean end.
    ``final_world``: optional final committed roster.
    ``serving``: optional serving evidence —
    ``{"completed": {request_id: count}}``.
    """

    events: List[dict]
    hb_interval: float = 2.0
    snapshot_every: int = 5
    workers: Optional[Dict[str, dict]] = None
    final_world: Optional[List[str]] = None
    serving: Optional[Dict[str, object]] = None

    def sorted_events(self) -> List[dict]:
        return sorted((e for e in self.events if isinstance(e, dict)),
                      key=lambda e: (e.get("ts") or 0.0,
                                     str(e.get("id"))))

    def of_kind(self, kind: str) -> List[dict]:
        return [e for e in self.sorted_events() if e.get("kind") == kind]

    def chain(self, event: dict) -> List[dict]:
        eid = event.get("id")
        return extract_chain(self.events, eid) if eid else [event]


#: a worker whose scenario ended in one of these states is accounted
#: for; anything else still in the roster is a hanging rank
LIVE_END_STATES = ("running", "finished", "drained", "preempted")


def check_epoch_monotonic(ctx: Context) -> List[Violation]:
    """Commits must strictly increase — a repeated or regressing epoch
    number means the single-writer fence broke (split-brain driver or
    a standby takeover that rolled the world back)."""
    out: List[Violation] = []
    last: Optional[int] = None
    last_event: Optional[dict] = None
    for e in ctx.of_kind("epoch.commit"):
        epoch = (e.get("payload") or {}).get("epoch")
        if epoch is None:
            continue
        if last is not None and epoch <= last:
            out.append(Violation(
                invariant="epoch-monotonic",
                message=(f"epoch.commit regressed or repeated: epoch "
                         f"{epoch} committed after epoch {last}"),
                chain=ctx.chain(e),
                evidence={"epoch": epoch, "previous": last,
                          "previous_event": (last_event or {}).get("id")}))
        last, last_event = epoch, e
    return out


def check_abort_propagation(ctx: Context) -> List[Violation]:
    """Every ``abort.publish`` must gather at least one survivor
    ``abort.observe`` within 2 x the heartbeat interval — the detect →
    propagate promise (docs/fault_tolerance.md).  A publish whose next
    commit left no survivors (give-up, world of one) is exempt."""
    out: List[Violation] = []
    bound = 2.0 * ctx.hb_interval
    observes_by_cause: Dict[str, List[dict]] = {}
    for o in ctx.of_kind("abort.observe"):
        cause = o.get("cause_id")
        if cause:
            observes_by_cause.setdefault(cause, []).append(o)
    commits = ctx.of_kind("epoch.commit")
    for p in ctx.of_kind("abort.publish"):
        observes = observes_by_cause.get(p.get("id"), [])
        late = [o for o in observes
                if (o.get("ts") or 0.0) - (p.get("ts") or 0.0) > bound]
        for o in late:
            out.append(Violation(
                invariant="abort-propagation",
                message=(f"abort observed {((o.get('ts') or 0.0) - (p.get('ts') or 0.0)) * 1000:.0f}ms "
                         f"after publish (bound {bound * 1000:.0f}ms, "
                         f"2 x {ctx.hb_interval * 1000:.0f}ms heartbeat)"),
                chain=ctx.chain(o),
                evidence={"publish": p.get("id"), "observe": o.get("id"),
                          "bound_ms": bound * 1000}))
        if not observes:
            # exempt when no survivor could observe: the commit that
            # followed this publish kept nobody from the old world
            nxt = next((c for c in commits
                        if (c.get("ts") or 0.0) >= (p.get("ts") or 0.0)
                        and (c.get("payload") or {}).get("size")), None)
            if nxt is not None and (nxt.get("payload") or {}).get(
                    "size", 0) > 0:
                out.append(Violation(
                    invariant="abort-propagation",
                    message=("abort.publish was never observed by any "
                             "survivor although the next epoch has "
                             f"{(nxt.get('payload') or {}).get('size')} "
                             "member(s)"),
                    chain=ctx.chain(p),
                    evidence={"publish": p.get("id"),
                              "next_commit": nxt.get("id")}))
    return out


def check_steps_lost_bound(ctx: Context) -> List[Violation]:
    """Every ``restart.resume`` must report ``steps_lost`` of at most
    one snapshot interval — the recovery-cost promise of the peer state
    plane (a lossy removal rolls survivors back to the newest committed
    snapshot, never further)."""
    out: List[Violation] = []
    for e in ctx.of_kind("restart.resume"):
        lost = (e.get("payload") or {}).get("steps_lost")
        if lost is None:
            continue
        if lost > ctx.snapshot_every:
            out.append(Violation(
                invariant="steps-lost-bound",
                message=(f"rank {e.get('rank')} lost {lost} steps on "
                         f"resume — more than one snapshot interval "
                         f"({ctx.snapshot_every})"),
                chain=ctx.chain(e),
                evidence={"steps_lost": lost,
                          "snapshot_every": ctx.snapshot_every,
                          "resume": e.get("id")}))
    return out


def check_restore_source_agreement(ctx: Context) -> List[Violation]:
    """All ``restore.source`` events for the same epoch must name the
    same snapshot generation — ranks restoring from different
    generations silently diverge (the PR 19 collective-agreement
    promise)."""
    out: List[Violation] = []
    by_epoch: Dict[int, List[dict]] = {}
    for e in ctx.of_kind("restore.source"):
        epoch = (e.get("payload") or {}).get("epoch")
        if epoch is not None:
            by_epoch.setdefault(int(epoch), []).append(e)
    for epoch, group in sorted(by_epoch.items()):
        gens = {(e.get("payload") or {}).get("gen") for e in group}
        if len(gens) > 1:
            out.append(Violation(
                invariant="restore-source-agreement",
                message=(f"epoch {epoch}: ranks restored from "
                         f"disagreeing snapshot generations "
                         f"{sorted(gens, key=str)}"),
                chain=ctx.chain(group[0]),
                evidence={"epoch": epoch,
                          "generations": sorted(gens, key=str),
                          "events": [e.get("id") for e in group]}))
    return out


def check_serving_exactly_once(ctx: Context) -> List[Violation]:
    """No request id completes twice — across drains, requeues, and
    replica removals.  Evaluated over ``serve.complete`` events and/or
    the ``ctx.serving`` completion counts; passes vacuously when a run
    produced neither (training-only scenarios)."""
    out: List[Violation] = []
    counts: Dict[str, int] = {}
    first_event: Dict[str, dict] = {}
    for e in ctx.of_kind("serve.complete"):
        rid = (e.get("payload") or {}).get("request_id")
        if rid is None:
            continue
        rid = str(rid)
        counts[rid] = counts.get(rid, 0) + 1
        first_event.setdefault(rid, e)
    for rid, n in ((r, c) for r, c in
                   ((ctx.serving or {}).get("completed") or {}).items()):
        counts[str(rid)] = max(counts.get(str(rid), 0), int(n))
    for rid, n in sorted(counts.items()):
        if n > 1:
            e = first_event.get(rid)
            out.append(Violation(
                invariant="serving-exactly-once",
                message=f"request {rid} completed {n} times",
                chain=ctx.chain(e) if e else [],
                evidence={"request_id": rid, "completions": n}))
    return out


def check_no_hanging_rank(ctx: Context) -> List[Violation]:
    """At quiescence, every member of the final world must be live and
    every worker that is NOT live must be out of the world — a crashed,
    hung, or partitioned rank still in the roster means detection or
    removal never finished.  Needs runner evidence (``ctx.workers`` +
    ``ctx.final_world``); passes vacuously on a pure event stream."""
    if ctx.workers is None or ctx.final_world is None:
        return []
    out: List[Violation] = []
    for wid, info in sorted(ctx.workers.items()):
        status = (info or {}).get("status", "unknown")
        if wid in ctx.final_world and status not in LIVE_END_STATES:
            removes = [e for e in ctx.of_kind("epoch.remove")
                       if (e.get("payload") or {}).get("worker") == wid]
            out.append(Violation(
                invariant="no-hanging-rank",
                message=(f"worker {wid} ended {status!r} but is still "
                         f"in the committed world {ctx.final_world}"),
                chain=ctx.chain(removes[-1]) if removes else [],
                evidence={"worker": wid, "status": status,
                          "final_world": list(ctx.final_world)}))
    return out


#: name → checker; the catalogue the CLI and docs render
INVARIANTS: Dict[str, Callable[[Context], List[Violation]]] = {
    "epoch-monotonic": check_epoch_monotonic,
    "abort-propagation": check_abort_propagation,
    "steps-lost-bound": check_steps_lost_bound,
    "restore-source-agreement": check_restore_source_agreement,
    "serving-exactly-once": check_serving_exactly_once,
    "no-hanging-rank": check_no_hanging_rank,
}


def check_all(events: List[dict], *, hb_interval: float = 2.0,
              snapshot_every: int = 5,
              workers: Optional[Dict[str, dict]] = None,
              final_world: Optional[List[str]] = None,
              serving: Optional[Dict[str, object]] = None,
              only: Optional[List[str]] = None) -> List[Violation]:
    """Run the catalogue (or the ``only`` subset) over one evidence
    bundle; returns every violation, stable-ordered by the catalogue."""
    ctx = Context(events=events, hb_interval=hb_interval,
                  snapshot_every=snapshot_every, workers=workers,
                  final_world=final_world, serving=serving)
    out: List[Violation] = []
    for name, checker in INVARIANTS.items():
        if only is not None and name not in only:
            continue
        try:
            out.extend(checker(ctx))
        except Exception:  # noqa: BLE001 — one broken checker must not
            log.exception("invariant checker %s failed", name)  # mask
            out.append(Violation(                               # others
                invariant=name,
                message=f"checker {name} raised (see launcher log)"))
    return out


def format_violation(v: Violation) -> str:
    """The console rendering: verdict line plus the causal chain,
    oldest first (the hvd_events --chain format)."""
    lines = [f"VIOLATION [{v.invariant}] {v.message}"]
    if v.evidence:
        lines.append("  evidence: " + ", ".join(
            f"{k}={v.evidence[k]}" for k in sorted(v.evidence)))
    if v.chain:
        t0 = v.chain[0].get("ts") or 0.0
        lines.append("  causal chain:")
        for e in v.chain:
            rank = e.get("rank")
            lines.append(
                f"    +{((e.get('ts') or 0.0) - t0) * 1000:7.0f}ms "
                f"{e.get('severity', 'info'):8s} {e.get('kind')}"
                + (f" rank={rank}" if rank is not None else ""))
    return "\n".join(lines)
