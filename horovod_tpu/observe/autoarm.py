"""Rank-consistent auto-arming of trace+profile windows.

A confirmed step-time or straggler alert should ship with attribution,
not a bare number — so the watchdog broadcasts an *arm record* through
the rendezvous KV store and every rank moves its trace+profile window
to the same future training step:

* :func:`broadcast_arm` (watchdog side) writes
  ``{"id", "start_step", "end_step", "signal", "trace_dir", "ts"}``
  to the ``observe/arm`` key — one writer (the watchdog),
  last-writer-wins;
* :func:`poll_and_apply` (worker side) runs on the telemetry flusher
  thread (metrics/timeseries.py), never the step path.  Each arm id is
  applied at most once per process: the rank's current training step
  is read off its cadence series and passed to ``timeline.arm`` /
  ``ComputeProfiler.arm`` as the translation anchor, so the broadcast
  *global* step window lands on the same steps everywhere.

``start_step`` is chosen by the watchdog as ``max(last cadence step
across ranks) + HVD_WATCH_ARM_MARGIN_STEPS`` — far enough ahead that
every rank sees the record before the window opens.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)

#: KV location of the arm record (run/http_server.py declares the scope)
ARM_SCOPE = "observe"
ARM_KEY = "arm"

_lock = threading.Lock()
_profilers: List[Any] = []
_applied_ids: set = set()


def register_profiler(profiler: Any) -> None:
    """Training registers its ComputeProfiler here so an arm record can
    reach it (make_train_step holds it as a closure variable)."""
    with _lock:
        if profiler not in _profilers:
            _profilers.append(profiler)


def unregister_profiler(profiler: Any) -> None:
    with _lock:
        if profiler in _profilers:
            _profilers.remove(profiler)


def reset() -> None:
    """Test seam: forget registered profilers and applied arm ids."""
    with _lock:
        _profilers.clear()
        _applied_ids.clear()


def make_arm_record(arm_id: str, start_step: int, end_step: int,
                    signal: str, trace_dir: Optional[str]) -> Dict[str, Any]:
    return {
        "id": str(arm_id),
        "start_step": int(start_step),
        "end_step": int(end_step),
        "signal": str(signal),
        "trace_dir": trace_dir,
        "ts": time.time(),
    }


def broadcast_arm(server: Any, arm_id: str, start_step: int, end_step: int,
                  signal: str, trace_dir: Optional[str] = None) -> Dict[str, Any]:
    """Watchdog side: publish the arm record through the in-process
    rendezvous server handle (``server.put`` goes through the same
    fence/journal choke point as the HTTP surface)."""
    record = make_arm_record(arm_id, start_step, end_step, signal, trace_dir)
    server.put(ARM_SCOPE, ARM_KEY, json.dumps(record).encode())
    return record


def apply_arm(record: Dict[str, Any]) -> bool:
    """Apply one arm record to this process's timeline + profilers.

    Idempotent per arm id; returns True when this call armed anything.
    """
    arm_id = str(record.get("id", ""))
    if not arm_id:
        return False
    with _lock:
        if arm_id in _applied_ids:
            return False
        _applied_ids.add(arm_id)
        profilers = list(_profilers)
    try:
        start = int(record["start_step"])
        end = int(record["end_step"])
    except (KeyError, TypeError, ValueError):
        log.debug("malformed arm record ignored: %r", record)
        return False
    trace_dir = record.get("trace_dir") or None

    # the rank's current global training step — the translation anchor
    from ..metrics import timeseries

    series = timeseries.store.series(timeseries.STEP_SECONDS)
    current = series.last_step if series is not None else None

    armed = False
    try:
        from ..timeline.timeline import timeline

        armed = timeline.arm(start, end, current_step=current,
                             directory=trace_dir) or armed
    except Exception as e:  # noqa: BLE001 — arming must never kill the flusher
        log.debug("timeline arm failed: %s", e)
    for prof in profilers:
        try:
            prof.arm(start, end, current_step=current, trace_dir=trace_dir)
            armed = True
        except Exception as e:  # noqa: BLE001
            log.debug("profiler arm failed: %s", e)
    if armed:
        log.info("auto-armed trace+profile window [%d, %d] (%s, arm %s)",
                 start, end, record.get("signal"), arm_id)
    return armed


def poll_and_apply(addr: str, port: int,
                   secret: Optional[bytes] = None) -> bool:
    """Worker side: fetch ``observe/arm`` and apply it (once per id).

    Runs on the telemetry flusher thread each flush tick; never raises.
    """
    if not env_util.get_bool(env_util.HVD_WATCH_ARM, True):
        return False
    try:
        from ..run.http_client import get_kv

        raw = get_kv(addr, port, ARM_SCOPE, ARM_KEY, secret=secret,
                     timeout=5.0)
    except Exception as e:  # noqa: BLE001
        log.debug("arm poll failed: %s", e)
        return False
    if not raw:
        return False
    try:
        record = json.loads(raw)
    except (ValueError, TypeError):
        return False
    if not isinstance(record, dict):
        return False
    return apply_arm(record)
