"""Environment-variable knob inventory and parsing.

TPU-native analog of the ``HOROVOD_*`` env system (reference inventory at
horovod/common/common.h:62-87, parsing in horovod/common/operations.cc:392-492
and horovod/common/utils/env_parser.cc:41-106).  Same three-layer contract:
(1) ``HVD_*`` env vars consumed by the runtime, (2) ``tpurun`` CLI flags that
set them for workers (horovod_tpu/run/config_parser.py), (3) optional YAML
config file overriding CLI.
"""

from __future__ import annotations

import os
from typing import Optional

# -- knob names (HOROVOD_* → HVD_*) ------------------------------------------
HVD_FUSION_THRESHOLD = "HVD_FUSION_THRESHOLD"          # bytes; HOROVOD_FUSION_THRESHOLD
HVD_CYCLE_TIME = "HVD_CYCLE_TIME"                      # ms; HOROVOD_CYCLE_TIME
HVD_TIMELINE = "HVD_TIMELINE"                          # trace output dir
HVD_TIMELINE_MARK_CYCLES = "HVD_TIMELINE_MARK_CYCLES"
HVD_TRACE_START_STEP = "HVD_TRACE_START_STEP"          # fork: BYTEPS_TRACE_START_STEP
HVD_TRACE_END_STEP = "HVD_TRACE_END_STEP"              # fork: BYTEPS_TRACE_END_STEP
HVD_TRACE_ON = "HVD_TRACE_ON"                          # fork: BYTEPS_TRACE_ON
HVD_TRACE_DIR = "HVD_TRACE_DIR"                        # fork: BYTEPS_TRACE_DIR
HVD_STALL_CHECK_DISABLE = "HVD_STALL_CHECK_DISABLE"
HVD_STALL_CHECK_TIME_SECONDS = "HVD_STALL_CHECK_TIME_SECONDS"
HVD_STALL_SHUTDOWN_TIME_SECONDS = "HVD_STALL_SHUTDOWN_TIME_SECONDS"
HVD_AUTOTUNE = "HVD_AUTOTUNE"
HVD_AUTOTUNE_LOG = "HVD_AUTOTUNE_LOG"
HVD_AUTOTUNE_WARMUP_SAMPLES = "HVD_AUTOTUNE_WARMUP_SAMPLES"
HVD_AUTOTUNE_STEPS_PER_SAMPLE = "HVD_AUTOTUNE_STEPS_PER_SAMPLE"
HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES = "HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
HVD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE = "HVD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"
# profile-guided tuning loop (optim/profile_guided.py, docs/autotune.md):
# replay what-ifs planned into explicit fusion buckets, applied live and
# verified predicted-vs-realized with automatic rollback
HVD_AUTOTUNE_PROFILE_GUIDED = "HVD_AUTOTUNE_PROFILE_GUIDED"  # 1 enables the loop
HVD_AUTOTUNE_WINDOW_STEPS = "HVD_AUTOTUNE_WINDOW_STEPS"      # steps per measure/verify window (default 20)
HVD_AUTOTUNE_GUARD_BAND_PCT = "HVD_AUTOTUNE_GUARD_BAND_PCT"  # realized-vs-predicted tolerance (default 10)
HVD_AUTOTUNE_ROLLBACK = "HVD_AUTOTUNE_ROLLBACK"              # 0 keeps regressed plans (debug; default 1)
HVD_AUTOTUNE_WARM_START = "HVD_AUTOTUNE_WARM_START"          # 0 skips the α–β GP prior (default 1)
HVD_AUTOTUNE_CYCLE_FLUSH_STEPS = "HVD_AUTOTUNE_CYCLE_FLUSH_STEPS"  # re-plan a verified plan every N steps (0 = pin forever)
HVD_BENCH_AUTOTUNE = "HVD_BENCH_AUTOTUNE"                    # 0 skips bench.py's autotuned second run
HVD_LOG_LEVEL = "HVD_LOG_LEVEL"
HVD_LOG_HIDE_TIME = "HVD_LOG_HIDE_TIME"
HVD_HIERARCHICAL_ALLREDUCE = "HVD_HIERARCHICAL_ALLREDUCE"
HVD_HIERARCHICAL_ALLGATHER = "HVD_HIERARCHICAL_ALLGATHER"
# wire-efficiency tier (ops/compression.py, parallel/hierarchical.py;
# docs/compression.md): gradient compression + two-level reduction
HVD_COMPRESSION = "HVD_COMPRESSION"                    # none|bf16|int8|fp8|fp8_e5m2 wire format
HVD_COMPRESSION_ERROR_FEEDBACK = "HVD_COMPRESSION_ERROR_FEEDBACK"  # 0 drops the residual carry (default 1)
HVD_COMPRESSION_GUARD_STEPS = "HVD_COMPRESSION_GUARD_STEPS"  # residual-norm check cadence (default 25; 0 off)
HVD_COMPRESSION_GUARD_FACTOR = "HVD_COMPRESSION_GUARD_FACTOR"  # divergence = norm > factor x baseline (default 10)
HVD_TWO_LEVEL_ALLREDUCE = "HVD_TWO_LEVEL_ALLREDUCE"    # 1 = compressed two-level (ICI RS + DCN AR) gradient path
HVD_BENCH_COMPRESSION = "HVD_BENCH_COMPRESSION"        # 0 skips bench.py's compressed comparison leg
HVD_CACHE_CAPACITY = "HVD_CACHE_CAPACITY"
# host-plane ring/star crossover: payloads >= this ride the peer ring
# (calibrate per fabric: scripts/host_plane_bench.py --crossover)
HVD_RING_MIN_BYTES = "HVD_RING_MIN_BYTES"
HVD_BATCH_D2D_MEMCOPIES = "HVD_BATCH_D2D_MEMCOPIES"
HVD_NUM_NCCL_STREAMS = "HVD_NUM_NCCL_STREAMS"          # parity stub
# comma list of NIC names the host data plane advertises on (reference
# --network-interface / HOROVOD_GLOO_IFACE + NCCL_SOCKET_IFNAME)
HVD_NETWORK_INTERFACE = "HVD_NETWORK_INTERFACE"
# launcher-set topology vars (analog of HOROVOD_RANK/SIZE/LOCAL_RANK/... set
# by gloo_run, reference run/gloo_run.py:210-216)
HVD_RANK = "HVD_RANK"
HVD_SIZE = "HVD_SIZE"
HVD_LOCAL_RANK = "HVD_LOCAL_RANK"
HVD_LOCAL_SIZE = "HVD_LOCAL_SIZE"
HVD_CROSS_RANK = "HVD_CROSS_RANK"
HVD_CROSS_SIZE = "HVD_CROSS_SIZE"
HVD_COORDINATOR_ADDR = "HVD_COORDINATOR_ADDR"
HVD_NUM_PROCESSES = "HVD_NUM_PROCESSES"
HVD_PROCESS_ID = "HVD_PROCESS_ID"
HVD_CONTROLLER = "HVD_CONTROLLER"
HVD_CPU_OPERATIONS = "HVD_CPU_OPERATIONS"
# native controller wiring (set by the launcher; runtime/eager_controller.py)
HVD_CONTROLLER_ADDR = "HVD_CONTROLLER_ADDR"            # host:port of the coordinator
HVD_CONTROLLER_SERVER = "HVD_CONTROLLER_SERVER"        # "external" = launcher hosts it
HVD_COORD_PORT = "HVD_COORD_PORT"                      # jax.distributed coordinator port
# peer-ring data plane (runtime/ring.py)
HVD_RING = "HVD_RING"                                  # 0 disables the ring (debug aid)
HVD_RING_CHUNK_BYTES = "HVD_RING_CHUNK_BYTES"          # ring pipeline chunk size
HVD_RING_HOST = "HVD_RING_HOST"                        # launcher-known address peers dial
# function-mode plumbing (run/run.py run() ↔ run/task_fn.py)
HVD_RUN_KV_ADDR = "HVD_RUN_KV_ADDR"
HVD_RUN_KV_PORT = "HVD_RUN_KV_PORT"
HVD_RUN_SECRET = "HVD_RUN_SECRET"
HVD_RUN_PID = "HVD_RUN_PID"
HVD_RUN_NP = "HVD_RUN_NP"
# TPU pod host discovery (run/discovery.py)
HVD_TPU_HOSTS = "HVD_TPU_HOSTS"
HVD_TPU_SLOTS = "HVD_TPU_SLOTS"
# force the pure-Python fallbacks over the native csrc paths
HVD_TIMELINE_PYTHON = "HVD_TIMELINE_PYTHON"
HVD_AUTOTUNE_PYTHON = "HVD_AUTOTUNE_PYTHON"
# metrics plane (horovod_tpu/metrics/)
HVD_METRICS = "HVD_METRICS"                            # 0 disables the registry
HVD_METRICS_KV_ADDR = "HVD_METRICS_KV_ADDR"            # launcher rendezvous host
HVD_METRICS_KV_PORT = "HVD_METRICS_KV_PORT"            # launcher rendezvous port
HVD_METRICS_SECRET = "HVD_METRICS_SECRET"              # hex HMAC secret for pushes
HVD_METRICS_PUSH_SECONDS = "HVD_METRICS_PUSH_SECONDS"  # push interval (default 5)
# collective sanitizer + linter (horovod_tpu/analysis/)
HVD_SANITIZER = "HVD_SANITIZER"                        # 1 fingerprints every eager dispatch
HVD_SANITIZER_TIMEOUT_SECONDS = "HVD_SANITIZER_TIMEOUT_SECONDS"  # peer wait (default 60)
HVD_SANITIZER_EPOCH_STRICT = "HVD_SANITIZER_EPOCH_STRICT"  # 0 lets checks span membership epochs (default 1)
HVD_LINT_DISABLE = "HVD_LINT_DISABLE"                  # comma list of rule IDs hvd_lint skips
# schedule model checker (analysis/schedule/, scripts/hvd_verify.py)
HVD_VERIFY_MAX_PATHS = "HVD_VERIFY_MAX_PATHS"          # per-entry path budget (default 64)
HVD_VERIFY_LOOP_BOUND = "HVD_VERIFY_LOOP_BOUND"        # loop unroll bound (default 2)
# compute-anatomy profiler (timeline/profiler.py, docs/profiling.md):
# per-block device-time attribution + roofline/MFU accounting + host-gap
# detection over a BYTEPS_TRACE-style step window
HVD_PROFILE = "HVD_PROFILE"                            # 1 enables the profiled step window
HVD_PROFILE_START_STEP = "HVD_PROFILE_START_STEP"      # window start (default HVD_TRACE_START_STEP or 1)
HVD_PROFILE_END_STEP = "HVD_PROFILE_END_STEP"          # window end (default start + 2: a 3-step window)
HVD_PROFILE_XLA = "HVD_PROFILE_XLA"                    # 1 also runs jax.profiler trace capture into <rank>/xla_trace
HVD_PROFILE_GAP_THRESHOLD_US = "HVD_PROFILE_GAP_THRESHOLD_US"  # inter-dispatch gap flagged as a host-gap span past this (default 25)
HVD_PROFILE_HBM_GBPS = "HVD_PROFILE_HBM_GBPS"          # roofline HBM bandwidth, GB/s (default 819, v5e)
HVD_PEAK_FLOPS = "HVD_PEAK_FLOPS"                      # per-chip peak FLOP/s for every MFU number (default 197e12, v5e bf16)
# dPRO-style replay engine (horovod_tpu/timeline/replay/)
HVD_REPLAY_CLOCK_SYNC = "HVD_REPLAY_CLOCK_SYNC"        # 0 skips the init-time clock handshake
HVD_REPLAY_CLOCK_SAMPLES = "HVD_REPLAY_CLOCK_SAMPLES"  # handshake round trips (default 8)
HVD_REPLAY_ICI_GBPS = "HVD_REPLAY_ICI_GBPS"            # what-if link bandwidth, GB/s (default 186)
HVD_REPLAY_HOP_US = "HVD_REPLAY_HOP_US"                # what-if per-hop latency, µs (default 1)
HVD_REPLAY_DCN_GBPS = "HVD_REPLAY_DCN_GBPS"            # two-level what-if cross bandwidth, GB/s (default 25)
HVD_REPLAY_DCN_HOP_US = "HVD_REPLAY_DCN_HOP_US"        # two-level what-if cross hop latency, µs (default 10)
HVD_REPLAY_LOCAL_SIZE = "HVD_REPLAY_LOCAL_SIZE"        # two-level what-if ICI group size (default HVD_LOCAL_SIZE)
# fleet-scale digital twin (timeline/replay/projection.py,
# docs/projection.md): topology-projected replay + tracked accuracy
HVD_PROJECT_MODE = "HVD_PROJECT_MODE"                  # chain replication: distribution|slowest (default distribution)
HVD_PROJECT_SLO_GUARD = "HVD_PROJECT_SLO_GUARD"        # 0 disables the autoscaler's projected-p99 shrink guard (default 1)
HVD_BENCH_PROJECTION = "HVD_BENCH_PROJECTION"          # 0 skips bench.py's projection-accuracy leg
# failure-domain runtime (horovod_tpu/elastic/, docs/fault_tolerance.md)
HVD_HEARTBEAT_INTERVAL_SECONDS = "HVD_HEARTBEAT_INTERVAL_SECONDS"  # lease renewal (default 2)
HVD_HEARTBEAT_DISABLE = "HVD_HEARTBEAT_DISABLE"        # 1 turns the lease/abort plane off
HVD_TERM_GRACE_SECONDS = "HVD_TERM_GRACE_SECONDS"      # SIGTERM→SIGKILL escalation grace (default 5)
HVD_HTTP_RETRIES = "HVD_HTTP_RETRIES"                  # rendezvous HTTP retry budget (default 2)
HVD_HTTP_BACKOFF_MS = "HVD_HTTP_BACKOFF_MS"            # base retry backoff, ms (default 50)
HVD_FAULT_SPEC = "HVD_FAULT_SPEC"                      # fault-injection spec (elastic/faults.py)
HVD_FAULT_SEED = "HVD_FAULT_SEED"                      # seeds each injector's RNG (mixed with rank + restart) so prob= faults replay deterministically
HVD_RESTART_COUNT = "HVD_RESTART_COUNT"                # incarnation index set by the supervisor
HVD_RESTART_BACKOFF_SECONDS = "HVD_RESTART_BACKOFF_SECONDS"  # restart backoff base (default 1)
# elastic membership (elastic/membership.py + elastic/driver.py;
# docs/fault_tolerance.md): shrink/grow worlds without relaunch
HVD_ELASTIC = "HVD_ELASTIC"                            # 1 = elastic driver supervises the job
HVD_ELASTIC_WORKER_ID = "HVD_ELASTIC_WORKER_ID"        # stable worker identity across epochs
HVD_ELASTIC_MIN_NP = "HVD_ELASTIC_MIN_NP"              # floor world size before giving up (default 1)
HVD_ELASTIC_TIMEOUT_SECONDS = "HVD_ELASTIC_TIMEOUT_SECONDS"  # epoch wait/rebuild budget (default 60)
HVD_ELASTIC_MAX_FLAPS = "HVD_ELASTIC_MAX_FLAPS"        # removals before a worker is blocklisted (default 3)
HVD_ELASTIC_SILENT_GRACE_SECONDS = "HVD_ELASTIC_SILENT_GRACE_SECONDS"  # >0: a stable-epoch member with NO re-established lease this long past stability is removed as dead (default 0 = off)
# metrics-plane histogram shape (metrics/registry.py): the default
# latency bucket scheme is exponential from FLOOR seconds; serving-scale
# request latencies get their own floor below
HVD_METRICS_BUCKET_FLOOR = "HVD_METRICS_BUCKET_FLOOR"  # first latency bucket edge, seconds (default 1e-4)
HVD_METRICS_BUCKET_FACTOR = "HVD_METRICS_BUCKET_FACTOR"  # geometric growth per bucket (default 2)
HVD_METRICS_BUCKET_COUNT = "HVD_METRICS_BUCKET_COUNT"  # finite bucket count (default 18)
# serving plane (horovod_tpu/serving/, docs/inference.md): continuous-
# batching inference replicas + traffic-driven autoscaling on the
# elastic epoch machinery
HVD_SERVE = "HVD_SERVE"                                # 1 = serving plane on (tpurun --serve)
HVD_SERVE_MAX_BATCH = "HVD_SERVE_MAX_BATCH"            # batcher admits up to this many requests (default 8)
HVD_SERVE_MAX_WAIT_MS = "HVD_SERVE_MAX_WAIT_MS"        # flush deadline from first admitted request (default 5)
HVD_SERVE_BUCKET_SIZES = "HVD_SERVE_BUCKET_SIZES"      # comma list of padded batch sizes (default pow2 <= max batch)
HVD_SERVE_SLO_MS = "HVD_SERVE_SLO_MS"                  # p99 latency objective (default 100)
HVD_SERVE_TIMEOUT_SECONDS = "HVD_SERVE_TIMEOUT_SECONDS"  # per-request wait budget (default 30)
HVD_SERVE_QUEUE_LIMIT = "HVD_SERVE_QUEUE_LIMIT"        # admission cap; excess rejected (default 4096)
HVD_SERVE_LATENCY_BUCKET_FLOOR = "HVD_SERVE_LATENCY_BUCKET_FLOOR"  # serving histogram floor, seconds (default 2.5e-4)
HVD_SERVE_AUTOSCALE = "HVD_SERVE_AUTOSCALE"            # 1 = autoscaler drives the elastic driver
HVD_SERVE_QUEUE_HIGH = "HVD_SERVE_QUEUE_HIGH"          # per-replica queue depth read as overload (default 4)
HVD_SERVE_QUEUE_LOW = "HVD_SERVE_QUEUE_LOW"            # per-replica queue depth read as idle (default 0.5)
HVD_SERVE_HYSTERESIS_TICKS = "HVD_SERVE_HYSTERESIS_TICKS"  # sustained ticks before grow/shrink (default 3)
HVD_SERVE_COOLDOWN_SECONDS = "HVD_SERVE_COOLDOWN_SECONDS"  # min spacing between autoscale actions (default 10)
HVD_SERVE_MIN_REPLICAS = "HVD_SERVE_MIN_REPLICAS"      # shrink floor (default 1)
HVD_SERVE_MAX_REPLICAS = "HVD_SERVE_MAX_REPLICAS"      # grow ceiling (default 0 = bounded by spares)
HVD_SERVE_DRAIN_TIMEOUT_SECONDS = "HVD_SERVE_DRAIN_TIMEOUT_SECONDS"  # drain handshake budget (default elastic timeout)
HVD_SERVE_WEIGHT_COMPRESSION = "HVD_SERVE_WEIGHT_COMPRESSION"  # none|bf16|int8|fp8 at-rest weight format
HVD_BENCH_SERVE = "HVD_BENCH_SERVE"                    # 0 skips bench.py's serving leg
# compute-path optimization tier (optim/fused_update.py, training.py,
# data/loader.py, optim/compute_knobs.py; docs/PERF.md "compute tier"):
# fused step kernels + async host pipeline + compute-knob autotuning
HVD_FUSED_OPTIMIZER = "HVD_FUSED_OPTIMIZER"            # 0 forces the per-leaf optax path even for a FusedOptimizer
HVD_FUSED_UPDATE_PALLAS = "HVD_FUSED_UPDATE_PALLAS"    # force the Pallas (1) / jnp (0) fused-update backend; default: Pallas on TPU only
HVD_LOSS_FETCH_STEPS = "HVD_LOSS_FETCH_STEPS"          # trailing async loss fetch cadence (default 16; 0 never fetches)
HVD_PREFETCH_DEPTH = "HVD_PREFETCH_DEPTH"              # device prefetch queue depth in data/loader.py (default 2; 0 disables)
HVD_REMAT_POLICY = "HVD_REMAT_POLICY"                  # none|full|dots rematerialization of the loss closure
HVD_AUTOTUNE_COMPUTE = "HVD_AUTOTUNE_COMPUTE"          # 1 lets the GP autotuner rotate the compute knobs too
HVD_BENCH_COMPUTE_OPT = "HVD_BENCH_COMPUTE_OPT"        # 0 skips bench.py's compute-path A/B leg (host_gap_pct source)
# hierarchical HA control plane (run/store.py, run/journal.py,
# run/relay.py; docs/control_plane.md): sharded KV + per-host relay
# aggregation + warm-standby failover
HVD_CP_SHARDS = "HVD_CP_SHARDS"                        # KV store shard count (default 8)
HVD_RENDEZVOUS_ADDRS = "HVD_RENDEZVOUS_ADDRS"          # ordered host:port,host:port failover list (primary first)
HVD_RENDEZVOUS_JOURNAL = "HVD_RENDEZVOUS_JOURNAL"      # mutation-journal path; enables warm-standby replay
HVD_RELAY = "HVD_RELAY"                                # 1 = local-rank-0 runs the per-host relay daemon
HVD_RELAY_PORT = "HVD_RELAY_PORT"                      # relay listen port (default 0 = ephemeral)
HVD_RELAY_FLUSH_MS = "HVD_RELAY_FLUSH_MS"              # relay upstream batch-flush cadence, ms (default 200)
HVD_HTTP_KEEPALIVE = "HVD_HTTP_KEEPALIVE"              # 0 disables pooled keep-alive connections (debug)
HVD_METRICS_DELTA = "HVD_METRICS_DELTA"                # 0 forces full metric snapshots every push (default delta)
HVD_BENCH_CONTROL = "HVD_BENCH_CONTROL"                # 0 skips bench.py's control-plane churn leg
# always-on telemetry time-series (metrics/timeseries.py, docs/observe.md):
# bounded ring-buffer history of cheap signals, flushed through the relay
# and served on the signed GET /timeseries
HVD_TIMESERIES = "HVD_TIMESERIES"                      # 0 disables the ring-buffer history
HVD_TIMESERIES_CAP = "HVD_TIMESERIES_CAP"              # raw-tier ring capacity, samples (default 512)
HVD_TIMESERIES_TIERS = "HVD_TIMESERIES_TIERS"          # downsampling tiers incl. raw (default 3)
HVD_TIMESERIES_FACTOR = "HVD_TIMESERIES_FACTOR"        # per-tier downsample factor (default 8)
HVD_TIMESERIES_FLUSH_SECONDS = "HVD_TIMESERIES_FLUSH_SECONDS"  # flush interval (default HVD_METRICS_PUSH_SECONDS)
HVD_TIMESERIES_SERVER_CAP = "HVD_TIMESERIES_SERVER_CAP"  # per-series sample cap in the server's per-rank doc (default 2048)
# online anomaly watchdog (horovod_tpu/observe/, docs/observe.md):
# detectors over the time-series history, alerts scope, auto-armed
# trace+profile windows
HVD_WATCH = "HVD_WATCH"                                # 0 disables the launcher-side watchdog
HVD_WATCH_WINDOW = "HVD_WATCH_WINDOW"                  # detector trailing window, samples (default 64)
HVD_WATCH_INTERVAL_SECONDS = "HVD_WATCH_INTERVAL_SECONDS"  # watchdog tick cadence (default 2)
HVD_WATCH_EWMA_ALPHA = "HVD_WATCH_EWMA_ALPHA"          # step-time EWMA smoothing (default 0.5)
HVD_WATCH_MAD_K = "HVD_WATCH_MAD_K"                    # regression threshold, robust sigmas above baseline (default 5)
HVD_WATCH_CONFIRM = "HVD_WATCH_CONFIRM"                # consecutive breaches before an alert (default 3)
HVD_WATCH_STRAGGLER_SKEW = "HVD_WATCH_STRAGGLER_SKEW"  # rank cadence / world median ratio read as straggling (default 1.3)
HVD_WATCH_MFU_DROP_PCT = "HVD_WATCH_MFU_DROP_PCT"      # relative MFU drop vs baseline read as regression (default 20)
HVD_WATCH_BETA_DRIFT = "HVD_WATCH_BETA_DRIFT"          # measured/predicted µs-per-MiB ratio read as comm drift (default 2)
HVD_WATCH_SLO_BUDGET = "HVD_WATCH_SLO_BUDGET"          # tolerated SLO-breach sample fraction (default 0.01)
HVD_WATCH_BURN_RATE = "HVD_WATCH_BURN_RATE"            # breach-fraction / budget ratio that alerts (default 2)
HVD_WATCH_ARM = "HVD_WATCH_ARM"                        # 0 stops alerts from auto-arming trace windows (default 1)
HVD_WATCH_ARM_STEPS = "HVD_WATCH_ARM_STEPS"            # auto-armed trace+profile window length (default 8)
HVD_WATCH_ARM_MARGIN_STEPS = "HVD_WATCH_ARM_MARGIN_STEPS"  # arm start = newest observed step + margin (default 16)
HVD_WATCH_ARM_COOLDOWN_SECONDS = "HVD_WATCH_ARM_COOLDOWN_SECONDS"  # min spacing between auto-arms (default 120)
HVD_WATCH_EVICT = "HVD_WATCH_EVICT"                    # 1 feeds critical straggler alerts to the elastic removal path
HVD_BENCH_WATCH = "HVD_BENCH_WATCH"                    # 0 skips bench.py's watchdog detection leg
# control-plane flight recorder (horovod_tpu/observe/events.py,
# docs/observe.md): append-only correlation-ID-threaded event log of
# every lifecycle action, buffered in a per-process ring, flushed
# through the relay/batch path into the journaled `events` scope, and
# served on the signed GET /events (scripts/hvd_events.py console)
HVD_EVENTS = "HVD_EVENTS"                              # 0 disables the recorder (default on)
HVD_EVENTS_RING_CAP = "HVD_EVENTS_RING_CAP"            # per-process ring capacity, events (default 1024)
HVD_EVENTS_FLUSH_SECONDS = "HVD_EVENTS_FLUSH_SECONDS"  # worker-side flusher cadence (default HVD_METRICS_PUSH_SECONDS)
HVD_EVENTS_SERVER_CAP = "HVD_EVENTS_SERVER_CAP"        # server-side retained event cap per source (default 4096)
# peer-replicated state plane (elastic/peerstate.py,
# docs/fault_tolerance.md#the-peer-state-plane): async snapshots sharded
# to K peer hosts, restore-from-peers with storage-tier fallback
HVD_SNAPSHOT = "HVD_SNAPSHOT"                          # 1 enables the peer checkpoint tier (default off)
HVD_SNAPSHOT_SHARDS = "HVD_SNAPSHOT_SHARDS"            # shards one rank's snapshot splits into (default 4)
HVD_SNAPSHOT_KEEP = "HVD_SNAPSHOT_KEEP"                # own committed generations retained before GC (default 2)
HVD_SNAPSHOT_STORAGE_EVERY = "HVD_SNAPSHOT_STORAGE_EVERY"  # Nth save still hits the orbax storage tier (default 10)
HVD_SNAPSHOT_TIMEOUT_SECONDS = "HVD_SNAPSHOT_TIMEOUT_SECONDS"  # per shard push/pull HTTP budget (default 30)
HVD_SNAPSHOT_COPY = "HVD_SNAPSHOT_COPY"                # 1 also copies numpy leaves at enqueue — for loops that mutate arrays in place (default off)
HVD_PEER_REPLICAS = "HVD_PEER_REPLICAS"                # peer hosts holding each rank's shards, K (default 2)
HVD_BENCH_RESTORE = "HVD_BENCH_RESTORE"                # 0 skips bench.py's peer-restore leg
# chaos campaign engine (elastic/chaos.py, observe/invariants.py,
# scripts/hvd_chaos.py; docs/fault_tolerance.md#chaos-certification):
# scripted multi-fault scenarios run against an in-process elastic
# world and certified by invariant monitors over the flight recorder
HVD_CHAOS_WORLD = "HVD_CHAOS_WORLD"                    # workers per chaos scenario world (default 3)
HVD_CHAOS_STEP_SECONDS = "HVD_CHAOS_STEP_SECONDS"      # simulated train-step duration in the chaos world (default 0.01)
HVD_CHAOS_SNAPSHOT_EVERY = "HVD_CHAOS_SNAPSHOT_EVERY"  # steps between chaos-world snapshot commits (default 5)
HVD_CHAOS_TIMEOUT_SECONDS = "HVD_CHAOS_TIMEOUT_SECONDS"  # per-scenario wall budget before the runner declares a hang (default 30)
HVD_BENCH_CHAOS = "HVD_BENCH_CHAOS"                    # 0 skips bench.py's chaos campaign leg

DEFAULT_FUSION_THRESHOLD_BYTES = 64 * 1024 * 1024  # 64 MB, reference common.h:69
DEFAULT_CYCLE_TIME_MS = 5.0                        # reference common.h:67
FUSION_BUFFER_ATOMIC_UNIT = 64                     # reference common.h:94
DEFAULT_STALL_WARNING_SECONDS = 60.0               # reference stall_inspector.h:72
DEFAULT_HEARTBEAT_INTERVAL_SECONDS = 2.0           # elastic/heartbeat.py lease renewal
DEFAULT_TERM_GRACE_SECONDS = 5.0                   # run/run.py SIGTERM→SIGKILL grace
DEFAULT_HTTP_RETRIES = 2                           # run/http_client.py retry budget
DEFAULT_HTTP_BACKOFF_MS = 50.0                     # run/http_client.py backoff base
DEFAULT_RESTART_BACKOFF_SECONDS = 1.0              # run/run.py restart backoff base
DEFAULT_ELASTIC_TIMEOUT_SECONDS = 60.0             # elastic epoch wait/rebuild budget
DEFAULT_ELASTIC_MAX_FLAPS = 3                      # elastic/driver.py blocklist threshold
DEFAULT_AUTOTUNE_WINDOW_STEPS = 20                 # profile-guided measure/verify window
DEFAULT_AUTOTUNE_GUARD_BAND_PCT = 10.0             # rollback when realized lags predicted by more
DEFAULT_AUTOTUNE_CYCLE_FLUSH_STEPS = 0             # verified plans pinned forever unless set
DEFAULT_COMPRESSION_GUARD_STEPS = 25               # error-feedback residual-norm check cadence
DEFAULT_COMPRESSION_GUARD_FACTOR = 10.0            # residual divergence threshold (x baseline)
DEFAULT_DCN_GBPS = 25.0                            # modeled cross-host (DCN) bandwidth per host
DEFAULT_DCN_HOP_US = 10.0                          # modeled cross-host per-hop latency
DEFAULT_PROFILE_STEPS = 3                          # profiler window length when no end step is configured
DEFAULT_PROFILE_GAP_THRESHOLD_US = 25.0            # host-gap span flagging threshold
DEFAULT_PROFILE_HOST_BOUND_FRACTION = 0.2          # step verdict flips to host-bound past this gap share
DEFAULT_METRICS_BUCKET_FLOOR = 1e-4                # first latency bucket edge, seconds
DEFAULT_METRICS_BUCKET_FACTOR = 2.0                # geometric bucket growth
DEFAULT_METRICS_BUCKET_COUNT = 18                  # finite bucket count
DEFAULT_SERVE_MAX_BATCH = 8                        # serving/batching.py admission cap
DEFAULT_SERVE_MAX_WAIT_MS = 5.0                    # serving flush deadline from first admit
DEFAULT_SERVE_SLO_MS = 100.0                       # serving p99 latency objective
DEFAULT_SERVE_TIMEOUT_SECONDS = 30.0               # per-request wait budget
DEFAULT_SERVE_QUEUE_LIMIT = 4096                   # broker admission cap
DEFAULT_SERVE_LATENCY_BUCKET_FLOOR = 2.5e-4        # serving histogram floor, seconds
DEFAULT_SERVE_QUEUE_HIGH = 4.0                     # overload threshold, per replica
DEFAULT_SERVE_QUEUE_LOW = 0.5                      # idle threshold, per replica
DEFAULT_SERVE_HYSTERESIS_TICKS = 3                 # sustained ticks before an autoscale action
DEFAULT_SERVE_COOLDOWN_SECONDS = 10.0              # spacing between autoscale actions
DEFAULT_SERVE_MIN_REPLICAS = 1                     # autoscaler shrink floor
DEFAULT_LOSS_FETCH_STEPS = 16                      # trailing loss-fetch cadence (training.py)
DEFAULT_PREFETCH_DEPTH = 2                         # device prefetch queue depth (data/loader.py)
DEFAULT_CP_SHARDS = 8                              # run/store.py KV shard count
DEFAULT_RELAY_FLUSH_MS = 500.0                     # run/relay.py upstream batch cadence
DEFAULT_TIMESERIES_CAP = 512                       # metrics/timeseries.py raw-tier ring capacity
DEFAULT_TIMESERIES_TIERS = 3                       # downsampling tiers including the raw tier
DEFAULT_TIMESERIES_FACTOR = 8                      # per-tier downsample factor
DEFAULT_TIMESERIES_SERVER_CAP = 2048               # per-series cap in the server's per-rank doc
DEFAULT_WATCH_WINDOW = 64                          # observe/ detector trailing window, samples
DEFAULT_WATCH_INTERVAL_SECONDS = 2.0               # watchdog tick cadence
DEFAULT_WATCH_EWMA_ALPHA = 0.5                     # step-time regression EWMA smoothing
DEFAULT_WATCH_MAD_K = 5.0                          # regression threshold in robust sigmas
DEFAULT_WATCH_CONFIRM = 3                          # consecutive breaches before an alert
DEFAULT_WATCH_STRAGGLER_SKEW = 1.3                 # cadence / world-median straggler ratio
DEFAULT_WATCH_MFU_DROP_PCT = 20.0                  # relative MFU drop threshold, percent
DEFAULT_WATCH_BETA_DRIFT = 2.0                     # measured/predicted comm-cost drift ratio
DEFAULT_WATCH_SLO_BUDGET = 0.01                    # tolerated SLO-breach sample fraction
DEFAULT_WATCH_BURN_RATE = 2.0                      # breach-fraction / budget alert ratio
DEFAULT_WATCH_ARM_STEPS = 8                        # auto-armed trace+profile window length
DEFAULT_WATCH_ARM_MARGIN_STEPS = 16                # arm start margin past the newest observed step
DEFAULT_WATCH_ARM_COOLDOWN_SECONDS = 120.0         # min spacing between auto-arms
DEFAULT_EVENTS_RING_CAP = 1024                     # observe/events.py per-process ring capacity
DEFAULT_EVENTS_FLUSH_SECONDS = 5.0                 # worker-side event flusher cadence
DEFAULT_EVENTS_SERVER_CAP = 4096                   # server-side retained events per source
DEFAULT_SNAPSHOT_SHARDS = 4                        # elastic/peerstate.py shards per rank snapshot
DEFAULT_SNAPSHOT_KEEP = 2                          # own committed generations kept before GC
DEFAULT_SNAPSHOT_STORAGE_EVERY = 10                # storage-tier save demotion cadence
DEFAULT_SNAPSHOT_TIMEOUT_SECONDS = 30.0            # per shard push/pull HTTP budget
DEFAULT_PEER_REPLICAS = 2                          # peer hosts holding each rank's shards
DEFAULT_ELASTIC_SILENT_GRACE_SECONDS = 0.0         # elastic/driver.py silent-member removal (0 = off)
DEFAULT_CHAOS_WORLD = 3                            # elastic/chaos.py workers per scenario
DEFAULT_CHAOS_STEP_SECONDS = 0.01                  # chaos-world simulated step duration
DEFAULT_CHAOS_SNAPSHOT_EVERY = 5                   # chaos-world snapshot commit cadence, steps
DEFAULT_CHAOS_TIMEOUT_SECONDS = 30.0               # per-scenario wall budget


def get_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return int(v)
    except ValueError:
        return default


def get_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return float(v)
    except ValueError:
        return default


def parse_bool(value: Optional[str], default: bool = False) -> bool:
    """The one truthiness rule for HVD_* flags — shared by the runtime
    (get_bool) and the launcher (which parses worker-bound env dicts),
    so both sides always agree on whether a knob is on."""
    if value is None or value == "":
        return default
    return value.strip().lower() in ("1", "true", "yes", "on")


def get_bool(name: str, default: bool = False) -> bool:
    return parse_bool(os.environ.get(name), default)


def get_str(name: str, default: Optional[str] = None) -> Optional[str]:
    v = os.environ.get(name)
    return v if v not in (None, "") else default


def fusion_threshold_bytes() -> int:
    n = get_int(HVD_FUSION_THRESHOLD, DEFAULT_FUSION_THRESHOLD_BYTES)
    # Round to the atomic unit so fused buffers stay divisible for
    # scatter-style ops (reference controller.cc:357-375).
    if n % FUSION_BUFFER_ATOMIC_UNIT:
        n = (n // FUSION_BUFFER_ATOMIC_UNIT + 1) * FUSION_BUFFER_ATOMIC_UNIT
    return n


def cycle_time_ms() -> float:
    return get_float(HVD_CYCLE_TIME, DEFAULT_CYCLE_TIME_MS)
