"""Checkpoint/resume helpers.

The reference has no checkpointing in its core — the supported pattern
is rank-0-writes + broadcast-on-start (SURVEY §5:
``broadcast_parameters`` / ``broadcast_optimizer_state`` /
BroadcastGlobalVariablesHook; examples gate ModelCheckpoint on rank 0).
This module packages that pattern TPU-natively on orbax (the JAX
checkpoint library: async-capable, works against gs:// paths on pods):

    save_checkpoint(path, state, step=n)          # rank 0 writes
    state = restore_checkpoint(path, state)       # all load + broadcast

``restore_checkpoint`` finishes with ``broadcast_parameters`` so every
controller process holds rank-0's bytes even if their filesystem reads
raced a concurrent save — the reference's broadcast-on-start contract.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from .. import core

#: Commit sentinel written NEXT TO a ``step_N`` dir (``step_N.COMMITTED``)
#: after a successful save — a sibling, not inside the dir, so orbax's
#: own directory layout stays untouched.  ``latest_step`` only considers
#: committed dirs, so a rank-0 crash mid-save can never be resumed from
#: a torn checkpoint: the half-written dir simply does not exist for
#: restore purposes.
COMMIT_MARKER_SUFFIX = ".COMMITTED"


def _flight_event(kind: str, payload: dict,
                  cause_id: Optional[str] = None) -> Optional[str]:
    """Best-effort flight-recorder emit (observe/events.py) — a
    telemetry failure must never take down a save or restore."""
    try:
        from ..observe import events as events_mod

        return events_mod.record_event(kind, severity="info",
                                       payload=payload, cause_id=cause_id)
    except Exception:  # noqa: BLE001
        return None


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def commit_marker_path(path: str, step: int) -> str:
    return os.path.join(path, f"step_{step}{COMMIT_MARKER_SUFFIX}")


def write_commit_marker(path: str, step: int) -> None:
    """Stamp ``step_{step}`` as fully written.  Goes through fsspec so
    remote stores (gs://, memory://) commit the same way local dirs do;
    falls back to plain open() when fsspec is unavailable."""
    marker = commit_marker_path(path, step)
    try:
        import fsspec

        with fsspec.open(marker, "wb") as f:
            f.write(b"1")
    except ImportError:
        with open(marker, "wb") as f:
            f.write(b"1")


def clear_commit_marker(path: str, step: int) -> None:
    """Best-effort removal of the sentinel (the un-commit half of an
    overwrite)."""
    marker = commit_marker_path(path, step)
    try:
        import fsspec

        fs, marker_path = fsspec.core.url_to_fs(marker)
        if fs.exists(marker_path):
            fs.rm(marker_path)
    except ImportError:
        try:
            os.remove(marker)
        except FileNotFoundError:
            pass
    except (FileNotFoundError, OSError):
        pass


def is_committed(path: str, step: int) -> bool:
    """True when ``step_{step}`` under ``path`` carries the commit
    sentinel (a save that ran to completion)."""
    marker = commit_marker_path(path, step)
    try:
        import fsspec

        fs, marker_path = fsspec.core.url_to_fs(marker)
        return bool(fs.exists(marker_path))
    except ImportError:
        return os.path.exists(marker)
    except (FileNotFoundError, OSError):
        return False


def save_checkpoint(path: str, state: Any, *, step: Optional[int] = None,
                    force: bool = True) -> Optional[str]:
    """Write ``state`` (any pytree of arrays) from the root process only
    (reference idiom: rank-0-gated ModelCheckpoint).  Returns the
    written path on the root, None elsewhere.

    Step saves are committed atomically-enough for crash safety: the
    ``COMMITTED`` sentinel is written only after orbax finishes, and
    ``latest_step`` ignores uncommitted dirs."""
    target = os.path.join(path, f"step_{step}") if step is not None else path
    if core.is_initialized() and core.process_rank() != 0:
        return None
    import jax

    state = jax.device_get(state)  # host copy; orbax owns the layout
    if step is not None:
        # proper commit protocol on overwrite: un-commit first, so a
        # crash while orbax rewrites the dir leaves it uncommitted too
        clear_commit_marker(path, step)
    save_eid = _flight_event("checkpoint.save",
                             {"path": target, "step": step})
    _checkpointer().save(target, state, force=force)
    if step is not None:
        write_commit_marker(path, step)
        _flight_event("checkpoint.commit",
                      {"path": target, "step": step}, cause_id=save_eid)
    return target


def latest_step(path: str) -> Optional[int]:
    """Largest *committed* ``step_N`` under ``path`` (None if no step
    dirs).  Dirs without the ``COMMITTED`` sentinel are torn writes (the
    saver died mid-save) and are skipped — resuming from one would load
    a checkpoint that never finished.

    Lists through fsspec so remote stores (gs://, memory://) work the
    same as local directories — ``os.listdir`` would raise on URLs and
    make restore silently target the run root."""
    try:
        import fsspec

        fs, root = fsspec.core.url_to_fs(path)
        # detail=False explicitly: AbstractFileSystem defaults to detail
        # dicts (only LocalFileSystem happens to return plain paths)
        names = [os.path.basename(str(p).rstrip("/"))
                 for p in fs.ls(root, detail=False)]
    except ImportError:
        try:
            names = os.listdir(path)
        except FileNotFoundError:
            return None
    except (FileNotFoundError, OSError):
        return None
    steps = [int(d[len("step_"):]) for d in names
             if d.startswith("step_") and d[len("step_"):].isdigit()]
    # the sentinel names are in the SAME listing — no per-step remote
    # existence probe (a gs:// dir with hundreds of steps would other-
    # wise pay one round trip each on every resume)
    name_set = set(names)
    committed = [s for s in steps
                 if f"step_{s}{COMMIT_MARKER_SUFFIX}" in name_set]
    if steps and not committed:
        from .logging import get_logger

        get_logger(__name__).warning(
            "checkpoint dir %s has step dirs %s but no %s sentinels — "
            "they are either torn writes or pre-commit-marker "
            "checkpoints; refusing to resume from them (touch "
            "step_N%s to bless a checkpoint you trust)",
            path, sorted(steps), COMMIT_MARKER_SUFFIX.lstrip("."),
            COMMIT_MARKER_SUFFIX,
        )
    return max(committed) if committed else None


def restore_checkpoint(path: str, like: Any, *, step: Optional[int] = None,
                       broadcast: bool = True) -> Any:
    """Load the pytree stored at ``path`` (or its ``step_N`` subdir),
    then broadcast root's copy to every controller process (the
    reference's broadcast-on-start resume contract).  ``like`` supplies
    the tree structure/dtypes.

    Multi-host: only rank 0 is required to see ``path`` — when a
    non-root read fails (no shared filesystem), root's restored tree is
    shipped whole via ``broadcast_object``; when every rank can read,
    the broadcast is the cheaper array-plane ``broadcast_parameters``."""
    import jax

    multi = core.is_initialized() and core.process_size() > 1
    if step is None:
        step = latest_step(path)
        if multi:  # rank-consistent choice even if only root sees the dir
            from .. import eager

            step = eager.broadcast_object(step)
    target = os.path.join(path, f"step_{step}") if step is not None else path
    _flight_event("checkpoint.restore", {"path": target, "step": step})

    err: Optional[Exception] = None
    restored = None
    try:
        restored = _checkpointer().restore(target, item=jax.device_get(like))
    except Exception as e:  # noqa: BLE001
        if not (multi and broadcast):
            raise
        err = e  # held until the agreement round, so no rank is stranded

    if broadcast and multi:
        from .. import eager

        # Every rank must pick the SAME collective, and a root failure
        # must surface on every rank (raising before the agreement would
        # leave the others blocked until timeout with no root cause).
        statuses = eager.allgather_object(
            None if restored is not None else repr(err)
        )
        if statuses[0] is not None:
            raise RuntimeError(
                f"rank 0 failed to restore {target!r}: {statuses[0]}"
            )
        if all(s is None for s in statuses):
            from ..optim.distributed import broadcast_parameters

            restored = broadcast_parameters(restored)
        else:
            restored = eager.broadcast_object(restored)
    elif err is not None:
        raise err
    return restored
