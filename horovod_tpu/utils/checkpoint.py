"""Checkpoint/resume helpers.

The reference has no checkpointing in its core — the supported pattern
is rank-0-writes + broadcast-on-start (SURVEY §5:
``broadcast_parameters`` / ``broadcast_optimizer_state`` /
BroadcastGlobalVariablesHook; examples gate ModelCheckpoint on rank 0).
This module packages that pattern TPU-natively on orbax (the JAX
checkpoint library: async-capable, works against gs:// paths on pods):

    save_checkpoint(path, state, step=n)          # rank 0 writes
    state = restore_checkpoint(path, state)       # all load + broadcast

``restore_checkpoint`` finishes with ``broadcast_parameters`` so every
controller process holds rank-0's bytes even if their filesystem reads
raced a concurrent save — the reference's broadcast-on-start contract.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from .. import core


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_checkpoint(path: str, state: Any, *, step: Optional[int] = None,
                    force: bool = True) -> Optional[str]:
    """Write ``state`` (any pytree of arrays) from the root process only
    (reference idiom: rank-0-gated ModelCheckpoint).  Returns the
    written path on the root, None elsewhere."""
    target = os.path.join(path, f"step_{step}") if step is not None else path
    if core.is_initialized() and core.process_rank() != 0:
        return None
    import jax

    state = jax.device_get(state)  # host copy; orbax owns the layout
    _checkpointer().save(target, state, force=force)
    return target


def latest_step(path: str) -> Optional[int]:
    """Largest ``step_N`` under ``path`` (None if no step dirs)."""
    try:
        steps = [int(d[len("step_"):]) for d in os.listdir(path)
                 if d.startswith("step_")]
    except FileNotFoundError:
        return None
    return max(steps) if steps else None


def restore_checkpoint(path: str, like: Any, *, step: Optional[int] = None,
                       broadcast: bool = True) -> Any:
    """Load the pytree stored at ``path`` (or its ``step_N`` subdir),
    then broadcast root's copy to every controller process (the
    reference's broadcast-on-start resume contract).  ``like`` supplies
    the tree structure/dtypes."""
    if step is None:
        step = latest_step(path)
    target = os.path.join(path, f"step_{step}") if step is not None else path
    import jax

    restored = _checkpointer().restore(target, item=jax.device_get(like))
    if broadcast and core.is_initialized() and core.process_size() > 1:
        from ..optim.distributed import broadcast_parameters

        restored = broadcast_parameters(restored)
    return restored
