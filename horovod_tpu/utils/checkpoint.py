"""Checkpoint/resume helpers.

The reference has no checkpointing in its core — the supported pattern
is rank-0-writes + broadcast-on-start (SURVEY §5:
``broadcast_parameters`` / ``broadcast_optimizer_state`` /
BroadcastGlobalVariablesHook; examples gate ModelCheckpoint on rank 0).
This module packages that pattern TPU-natively on orbax (the JAX
checkpoint library: async-capable, works against gs:// paths on pods):

    save_checkpoint(path, state, step=n)          # rank 0 writes
    state = restore_checkpoint(path, state)       # all load + broadcast

``restore_checkpoint`` finishes with ``broadcast_parameters`` so every
controller process holds rank-0's bytes even if their filesystem reads
raced a concurrent save — the reference's broadcast-on-start contract.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from .. import core


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_checkpoint(path: str, state: Any, *, step: Optional[int] = None,
                    force: bool = True) -> Optional[str]:
    """Write ``state`` (any pytree of arrays) from the root process only
    (reference idiom: rank-0-gated ModelCheckpoint).  Returns the
    written path on the root, None elsewhere."""
    target = os.path.join(path, f"step_{step}") if step is not None else path
    if core.is_initialized() and core.process_rank() != 0:
        return None
    import jax

    state = jax.device_get(state)  # host copy; orbax owns the layout
    _checkpointer().save(target, state, force=force)
    return target


def latest_step(path: str) -> Optional[int]:
    """Largest ``step_N`` under ``path`` (None if no step dirs).

    Lists through fsspec so remote stores (gs://, memory://) work the
    same as local directories — ``os.listdir`` would raise on URLs and
    make restore silently target the run root."""
    try:
        import fsspec

        fs, root = fsspec.core.url_to_fs(path)
        # detail=False explicitly: AbstractFileSystem defaults to detail
        # dicts (only LocalFileSystem happens to return plain paths)
        names = [os.path.basename(str(p).rstrip("/"))
                 for p in fs.ls(root, detail=False)]
    except ImportError:
        try:
            names = os.listdir(path)
        except FileNotFoundError:
            return None
    except (FileNotFoundError, OSError):
        return None
    steps = [int(d[len("step_"):]) for d in names
             if d.startswith("step_") and d[len("step_"):].isdigit()]
    return max(steps) if steps else None


def restore_checkpoint(path: str, like: Any, *, step: Optional[int] = None,
                       broadcast: bool = True) -> Any:
    """Load the pytree stored at ``path`` (or its ``step_N`` subdir),
    then broadcast root's copy to every controller process (the
    reference's broadcast-on-start resume contract).  ``like`` supplies
    the tree structure/dtypes.

    Multi-host: only rank 0 is required to see ``path`` — when a
    non-root read fails (no shared filesystem), root's restored tree is
    shipped whole via ``broadcast_object``; when every rank can read,
    the broadcast is the cheaper array-plane ``broadcast_parameters``."""
    import jax

    multi = core.is_initialized() and core.process_size() > 1
    if step is None:
        step = latest_step(path)
        if multi:  # rank-consistent choice even if only root sees the dir
            from .. import eager

            step = eager.broadcast_object(step)
    target = os.path.join(path, f"step_{step}") if step is not None else path

    err: Optional[Exception] = None
    restored = None
    try:
        restored = _checkpointer().restore(target, item=jax.device_get(like))
    except Exception as e:  # noqa: BLE001
        if not (multi and broadcast):
            raise
        err = e  # held until the agreement round, so no rank is stranded

    if broadcast and multi:
        from .. import eager

        # Every rank must pick the SAME collective, and a root failure
        # must surface on every rank (raising before the agreement would
        # leave the others blocked until timeout with no root cause).
        statuses = eager.allgather_object(
            None if restored is not None else repr(err)
        )
        if statuses[0] is not None:
            raise RuntimeError(
                f"rank 0 failed to restore {target!r}: {statuses[0]}"
            )
        if all(s is None for s in statuses):
            from ..optim.distributed import broadcast_parameters

            restored = broadcast_parameters(restored)
        else:
            restored = eager.broadcast_object(restored)
    elif err is not None:
        raise err
    return restored
