"""Compatibility shims for the range of JAX versions the container may
carry.

The codebase (and its tests) target the public ``jax.shard_map`` API
with the ``check_vma`` spelling.  Older releases (<= 0.4.x, the pinned
container toolchain) only ship ``jax.experimental.shard_map.shard_map``
with the ``check_rep`` spelling — same semantics, renamed when the API
was promoted.  :func:`install` publishes a translating wrapper as
``jax.shard_map`` when the public name is absent, so one spelling works
everywhere.  On a JAX that already has the public API this is a no-op.
"""

from __future__ import annotations

import functools

import jax


def _compat_shard_map(f=None, *, mesh, in_specs, out_specs,
                      check_vma: bool = True, **kw):
    """``jax.shard_map`` signature over the experimental implementation:
    usable bare or as a decorator factory, translating ``check_vma`` to
    the pre-promotion ``check_rep`` keyword."""
    if f is None:
        return functools.partial(
            _compat_shard_map, mesh=mesh, in_specs=in_specs,
            out_specs=out_specs, check_vma=check_vma, **kw,
        )
    from jax.experimental.shard_map import shard_map as esm

    try:
        return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_vma, **kw)
    except TypeError:  # a vintage without check_rep either
        return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   **kw)


def _compat_axis_size(axis_name):
    """``lax.axis_size`` for releases that predate it: ``psum(1, axis)``
    is the long-standing idiom and constant-folds to the static size."""
    return jax.lax.psum(1, axis_name)


def _compat_pvary(x, axis_name):
    """``lax.pvary`` predecessor: on pre-VMA releases replication typing
    is tracked by shard_map's check_rep machinery and there is nothing
    to annotate — the data-level meaning of pvary is identity."""
    del axis_name
    return x


def install() -> None:
    """Idempotent: publish missing public-API names onto ``jax``."""
    if getattr(jax, "shard_map", None) is None:
        jax.shard_map = _compat_shard_map
    if getattr(jax.lax, "axis_size", None) is None:
        jax.lax.axis_size = _compat_axis_size
    if getattr(jax.lax, "pvary", None) is None:
        jax.lax.pvary = _compat_pvary
