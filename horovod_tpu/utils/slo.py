"""Projected-SLO arithmetic shared by the digital twin and the serving
autoscaler (docs/projection.md, docs/inference.md).

Deliberately dependency-free: the serving plane consults these before
every autoscale decision, and pulling the whole timeline/replay stack
into that path would couple two planes that only share ten lines of
math.  The functions are re-exported from
``timeline.replay.projection`` as part of the twin's public API.
"""

from __future__ import annotations

from typing import Optional


def project_serving_p99(p50_ms: Optional[float], p99_ms: Optional[float],
                        replicas: int, delta: int = 1) -> Optional[float]:
    """Projected windowed p99 after adding (``delta > 0``) or removing
    (``delta < 0``) replicas: the latency tail above the p50 service
    floor is queueing delay, which scales inversely with the replica
    count at fixed offered load — ``p50 + (p99 − p50) · R / (R+Δ)``.
    Deliberately coarse (an M/M/c tail would need arrival-process
    assumptions the broker can't verify); it is the same lever
    direction the autoscaler acts on, priced before acting."""
    if p99_ms is None or replicas < 1 or replicas + delta < 1:
        return None
    p50 = p50_ms if p50_ms is not None else 0.0
    tail = max(p99_ms - p50, 0.0)
    return round(p50 + tail * replicas / (replicas + delta), 3)


def serving_slo_headroom(stats: dict, replicas: int, slo_ms: float,
                         delta: int = 1) -> Optional[float]:
    """``slo − projected_p99`` after a ``delta`` replica change (None
    when the window has no latency data): positive = the change keeps
    the SLO, negative = it breaches.  The autoscaler consults the
    ``delta=-1`` headroom before a shrink (docs/projection.md)."""
    proj = project_serving_p99(stats.get("p50_ms"), stats.get("p99_ms"),
                               replicas, delta)
    return None if proj is None else round(slo_ms - proj, 3)
