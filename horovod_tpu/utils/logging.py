"""Leveled logging configured from HVD_LOG_LEVEL / HVD_LOG_HIDE_TIME.

Analog of the LOG(level, rank) macro system (reference
horovod/common/logging.cc:39-70: levels trace/debug/info/warning/error/fatal
parsed by ParseLogLevelStr, time prefix suppressed by
HOROVOD_LOG_HIDE_TIME).
"""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "trace": logging.DEBUG - 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
    "off": logging.CRITICAL + 10,
}

logging.addLevelName(_LEVELS["trace"], "TRACE")

_configured = False


def _configure() -> None:
    global _configured
    if _configured:
        return
    level_str = os.environ.get("HVD_LOG_LEVEL", "warning").strip().lower()
    level = _LEVELS.get(level_str, logging.WARNING)
    hide_time = os.environ.get("HVD_LOG_HIDE_TIME", "").strip().lower() in (
        "1", "true", "yes", "on",
    )
    fmt = "[%(levelname)s] hvd: %(message)s" if hide_time else (
        "%(asctime)s [%(levelname)s] hvd: %(message)s"
    )
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(fmt))
    root = logging.getLogger("horovod_tpu")
    root.addHandler(handler)
    root.setLevel(level)
    root.propagate = False
    _configured = True


def get_logger(name: str) -> logging.Logger:
    _configure()
    if not name.startswith("horovod_tpu"):
        name = "horovod_tpu." + name
    return logging.getLogger(name)
