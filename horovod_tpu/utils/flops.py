"""Analytic FLOP accounting for MFU reporting.

The ResNet bench reports MFU from the usual 3×-forward analytic count
(bench.py); this gives the transformer benches the same legibility
(reference docs/benchmarks.rst:66-80 publishes per-model throughput —
MFU is the hardware-normalized form).  Formula is the standard decoder
accounting (PaLM appendix B): 6·N FLOPs per token of parameter math
(fwd + bwd) plus the attention score/value matmuls, 12·L·s·d per token
— halved for causal models whose flash kernels skip fully-future
blocks.

This module is also the single source of the hardware peak numbers
every MFU/roofline consumer divides by: bench.py, the compute-anatomy
profiler (timeline/profiler.py), and the comm report's flops/peak
fallback (timeline/comm_report.py) all route through
:func:`peak_flops` / :func:`hbm_bytes_per_sec`, so a hardware change
(or an ``HVD_PEAK_FLOPS`` override) moves every published MFU number
at once instead of desyncing them.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

V5E_PEAK_FLOPS = 197e12       # bf16 nameplate, per chip
V5E_HBM_BYTES_PER_SEC = 819e9  # HBM bandwidth, per chip

#: ResNet-50 training ≈ 3 × 4.09 GFLOPs forward of model math per image
#: (the usual analytic count bench.py's headline MFU is built on; XLA's
#: own cost_analysis reports ~23.9 GF/img because strided-conv gradients
#: lower to dilated convs that multiply zeros)
RESNET50_TRAIN_FLOPS_PER_IMG = 12.27e9


def peak_flops(default: float = V5E_PEAK_FLOPS) -> float:
    """Per-chip peak FLOP/s for MFU math.  ``HVD_PEAK_FLOPS`` overrides
    (set it when the job runs on different hardware than the v5e
    default) — every consumer reads THIS function, never the raw
    constant, so the override cannot miss one report."""
    from .env import HVD_PEAK_FLOPS, get_float

    return get_float(HVD_PEAK_FLOPS, default)


def hbm_bytes_per_sec(default: float = V5E_HBM_BYTES_PER_SEC) -> float:
    """Per-chip HBM bandwidth for roofline math (the ridge point is
    ``peak_flops / hbm_bytes_per_sec`` flops/byte).
    ``HVD_PROFILE_HBM_GBPS`` overrides, in GB/s."""
    from .env import HVD_PROFILE_HBM_GBPS, get_float

    return get_float(HVD_PROFILE_HBM_GBPS, default / 1e9) * 1e9


def param_count(params) -> int:
    return int(sum(np.prod(x.shape)
                   for x in jax.tree_util.tree_leaves(params)))


def image_model_mfu(img_per_sec_per_chip: float,
                    flops_per_image: float = RESNET50_TRAIN_FLOPS_PER_IMG,
                    *, peak: Optional[float] = None) -> float:
    """MFU of an image model from measured per-chip throughput — the
    bench.py headline math, single-sourced so the bench JSON and the
    ``hvd_mfu`` gauge agree by construction."""
    peak = peak if peak is not None else peak_flops()
    return float(img_per_sec_per_chip) * float(flops_per_image) / peak


def transformer_train_flops_per_seq(n_params: int, num_layers: int,
                                    hidden_dim: int, seq_len: int, *,
                                    causal: bool = False) -> float:
    attn_per_token = 12.0 * num_layers * seq_len * hidden_dim
    if causal:
        attn_per_token /= 2.0
    return seq_len * (6.0 * n_params + attn_per_token)


def transformer_mfu(seq_per_sec_per_chip: float, n_params: int,
                    num_layers: int, hidden_dim: int, seq_len: int, *,
                    causal: bool = False,
                    peak_flops: Optional[float] = None) -> float:
    fps = transformer_train_flops_per_seq(
        n_params, num_layers, hidden_dim, seq_len, causal=causal,
    )
    if peak_flops is None:
        peak_flops = globals()["peak_flops"]()
    return seq_per_sec_per_chip * fps / peak_flops
