"""Analytic FLOP accounting for MFU reporting.

The ResNet bench reports MFU from the usual 3×-forward analytic count
(bench.py); this gives the transformer benches the same legibility
(reference docs/benchmarks.rst:66-80 publishes per-model throughput —
MFU is the hardware-normalized form).  Formula is the standard decoder
accounting (PaLM appendix B): 6·N FLOPs per token of parameter math
(fwd + bwd) plus the attention score/value matmuls, 12·L·s·d per token
— halved for causal models whose flash kernels skip fully-future
blocks.
"""

from __future__ import annotations

import jax
import numpy as np

V5E_PEAK_FLOPS = 197e12  # bf16 nameplate, per chip


def param_count(params) -> int:
    return int(sum(np.prod(x.shape)
                   for x in jax.tree_util.tree_leaves(params)))


def transformer_train_flops_per_seq(n_params: int, num_layers: int,
                                    hidden_dim: int, seq_len: int, *,
                                    causal: bool = False) -> float:
    attn_per_token = 12.0 * num_layers * seq_len * hidden_dim
    if causal:
        attn_per_token /= 2.0
    return seq_len * (6.0 * n_params + attn_per_token)


def transformer_mfu(seq_per_sec_per_chip: float, n_params: int,
                    num_layers: int, hidden_dim: int, seq_len: int, *,
                    causal: bool = False,
                    peak_flops: float = V5E_PEAK_FLOPS) -> float:
    fps = transformer_train_flops_per_seq(
        n_params, num_layers, hidden_dim, seq_len, causal=causal,
    )
    return seq_per_sec_per_chip * fps / peak_flops
