"""horovod_tpu: a TPU-native distributed training framework with the
capabilities of Horovod (the joapolarbear fork of 0.19 with per-rank
auto-profiling).

The data plane is XLA collectives over ICI/DCN (no MPI/NCCL/Gloo); the
rank model is SPMD over a ``jax.sharding.Mesh`` (see core.py); the eager
control path, launcher, timeline, and autotuner mirror the reference's
C++/Python runtime (see SURVEY.md at the repo root for the blueprint).

Typical use::

    import horovod_tpu as hvd

    hvd.init()

    @hvd.spmd
    def train_step(params, batch):
        grads = jax.grad(loss_fn)(params, batch)
        grads = hvd.allreduce_gradients(grads)
        return update(params, grads)
"""

__version__ = "0.1.0"

# Must run before any module touches jax.shard_map (core/spmd/eager do):
# bridges the public-API spelling onto older experimental releases.
from .utils import jax_compat as _jax_compat

_jax_compat.install()

from .core import (  # noqa: F401
    init,
    shutdown,
    is_initialized,
    rank,
    local_rank,
    cross_rank,
    size,
    local_size,
    cross_size,
    process_rank,
    process_size,
    is_homogeneous,
    mesh,
    hierarchical_mesh,
    in_spmd,
    Average,
    Sum,
    Adasum,
    Min,
    Max,
    AXIS,
    CROSS_AXIS,
    LOCAL_AXIS,
    mpi_enabled,
    mpi_built,
    gloo_enabled,
    gloo_built,
    nccl_built,
    ddl_built,
    ccl_built,
    cuda_built,
    rocm_built,
    xla_built,
    mpi_threads_supported,
)
from .spmd import (  # noqa: F401
    spmd,
    rank_context,
    sharded,
    replicated,
    put_per_rank,
    get_per_rank,
)
from .ops import (  # noqa: F401
    allreduce,
    grouped_allreduce,
    allgather,
    allgatherv,
    broadcast,
    alltoall,
    reducescatter,
    allreduce_gradients,
    Compression,
)
from .ops.compression import ErrorFeedback  # noqa: F401
from .parallel.hierarchical import two_level_allreduce  # noqa: F401
from .ops.collectives import ProcessSet  # noqa: F401
from .ops.sparse import (  # noqa: F401
    IndexedSlices,
    allreduce_indexed_slices,
    embedding_grad_as_slices,
)
from .eager import (  # noqa: F401
    allreduce_ as eager_allreduce,
    allgather_ as eager_allgather,
    broadcast_ as eager_broadcast,
    broadcast_object,
    allgather_object,
)
from .optim import (  # noqa: F401
    DistributedOptimizer,
    DistributedGradientTape,
    broadcast_parameters,
    broadcast_optimizer_state,
    broadcast_variables,
)
from .elastic.join import join, join_allreduce  # noqa: F401
from .elastic import (  # noqa: F401
    ElasticState,
    HorovodAbortError,
    abort,
)
