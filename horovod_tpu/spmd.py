"""SPMD execution: the TPU-native replacement for Horovod's per-process model.

In the reference, "every rank runs the training script" and collectives are
enqueued at runtime to a background C++ thread that negotiates a schedule
(reference horovod/common/operations.cc:333 BackgroundThreadLoop,
controller.cc:55 ComputeResponseList).  Under XLA that negotiation is
designed away: the per-rank program is a *function* compiled once over the
whole device mesh (``shard_map`` + ``jit``), and the collective schedule is
static inside the executable — the moral equivalent of Horovod's
response-cache steady state (reference response_cache.h:45-102), where
negotiation cost drops to zero after the first cycle.

Usage::

    hvd.init()

    @hvd.spmd            # per-rank function; inputs sharded on leading axis
    def step(params, batch):
        g = jax.grad(loss)(params, batch)
        g = hvd.allreduce_gradients(g)          # fused psum over the mesh
        return apply(params, g)

``spmd`` wraps the function in ``shard_map`` over the global mesh (axis
"hvd") and ``jit``s it.  Inside, ``hvd.rank()``/``hvd.allreduce()`` resolve
to ``lax.axis_index``/``lax.psum``.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Optional

import jax
from jax.sharding import PartitionSpec as P

from . import core


@contextlib.contextmanager
def rank_context(axes: tuple):
    """Mark (for tracing) that we are inside an SPMD region whose rank axis
    is ``axes``.  Public so custom shard_map users can opt in."""
    prev = core._ctx.axes
    core._ctx.axes = tuple(axes)
    try:
        yield
    finally:
        core._ctx.axes = prev


def _wrap_ctx(fn, axes):
    @functools.wraps(fn)
    def inner(*args, **kwargs):
        with rank_context(axes):
            return fn(*args, **kwargs)

    return inner


def spmd(
    fn=None,
    *,
    in_specs: Any = P(core.AXIS),
    out_specs: Any = P(core.AXIS),
    hierarchical: bool = False,
    jit: bool = True,
    donate_argnums=(),
    static_argnums=(),
):
    """Compile ``fn`` as an SPMD program over the global mesh.

    Args:
      fn: the per-rank function.
      in_specs / out_specs: shard_map specs.  The default shards the leading
        axis of every input/output across ranks — i.e. arguments are the
        stacked per-rank values, matching Horovod's "each rank passes its own
        tensor".  Use ``P()`` (replicated() helper) for weights.
      hierarchical: use the 2-D (cross, local) mesh; ``hvd.rank()`` et al.
        then expose local/cross indices for hierarchical algorithms.
      jit: also wrap in ``jax.jit``.
      donate_argnums/static_argnums: forwarded to ``jax.jit``.
    """

    def deco(f):
        mesh = core.hierarchical_mesh() if hierarchical else core.mesh()
        axes = (
            (core.CROSS_AXIS, core.LOCAL_AXIS)
            if hierarchical
            else (core.AXIS,)
        )
        wrapped = _wrap_ctx(f, axes)
        mapped = jax.shard_map(
            wrapped,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )
        if jit:
            mapped = jax.jit(
                mapped,
                donate_argnums=donate_argnums,
                static_argnums=static_argnums,
            )
        return mapped

    if fn is None:
        return deco
    return deco(fn)


def sharded(*extra) -> P:
    """PartitionSpec sharding the leading dim across ranks (per-rank data)."""
    return P(core.AXIS, *extra)


def replicated() -> P:
    """PartitionSpec for values replicated on every rank (e.g. weights)."""
    return P()


def put_per_rank(xs):
    """Stack a list of per-rank host arrays (len == hvd.size()) into a global
    array sharded across ranks along a new leading axis.

    The eager-API bridge: the analog of each Horovod rank holding its own
    tensor before an allreduce.
    """
    import numpy as np
    from jax.sharding import NamedSharding

    mesh = core.mesh()
    xs = [np.asarray(x) for x in xs]
    if len(xs) != core.size():
        raise ValueError(f"expected {core.size()} per-rank values, got {len(xs)}")
    stacked = np.stack(xs)
    sharding = NamedSharding(mesh, P(core.AXIS))
    return jax.device_put(stacked, sharding)


def get_per_rank(x):
    """Inverse of :func:`put_per_rank`: gather a rank-sharded global array
    back to a list of per-rank host arrays."""
    import numpy as np

    return list(np.asarray(jax.device_get(x)))
