"""horovod_tpu.mxnet: the MXNet-flavored API surface.

Mirror of horovod/mxnet (reference horovod/mxnet/__init__.py +
mpi_ops.py): ``allreduce``/``allreduce_``, ``allgather``, ``broadcast``/
``broadcast_``, ``broadcast_parameters``, and the gluon
``DistributedTrainer``.  The reference pushes ops into the MXNet engine
via MXEnginePushAsync (mxnet/mpi_ops.cc:139-208); here NDArrays bridge to
the framework's eager data plane via numpy interchange — the same
transport as the torch and TF bindings, so all three frameworks share one
wire path.

The fork makes ``DistributedOptimizer`` raise in favor of
``DistributedTrainer`` (reference mxnet/__init__.py:49-50) — mirrored.

Import is gated: ``import horovod_tpu.mxnet`` raises ImportError only if
mxnet itself is unavailable (it is not part of this image; the module is
exercised where mxnet exists, tests skip otherwise).
"""

from __future__ import annotations

import numpy as np

import mxnet as mx  # gate: module import fails cleanly without mxnet

from .. import core, eager
from ..core import Average, Sum, Adasum  # noqa: F401
from ..runtime import eager_controller

init = core.init
shutdown = core.shutdown
rank = core.rank
local_rank = core.local_rank
size = core.size
local_size = core.local_size
is_initialized = core.is_initialized
mpi_enabled = core.mpi_enabled


def _np(tensor) -> np.ndarray:
    return tensor.asnumpy() if hasattr(tensor, "asnumpy") \
        else np.asarray(tensor)


def _like(tensor, arr: np.ndarray):
    nd = mx.nd.array(arr, dtype=arr.dtype)
    ctx = getattr(tensor, "context", None)
    return nd.as_in_context(ctx) if ctx is not None else nd


def allreduce(tensor, average=True, name=None, priority=0):
    """reference mxnet/mpi_ops.py allreduce: Average by default."""
    op = Average if average else Sum
    nm = name or eager_controller.next_name("allreduce.mxnet")
    out = eager.process_allreduce(_np(tensor), op=op, name=nm)
    return _like(tensor, np.ascontiguousarray(np.asarray(out)))


def allreduce_(tensor, average=True, name=None, priority=0):
    """In-place variant (reference allreduce_)."""
    out = allreduce(tensor, average, name, priority)
    tensor[:] = out
    return tensor


def allgather(tensor, name=None, priority=0):
    nm = name or eager_controller.next_name("allgather.mxnet")
    return _like(tensor, eager.process_allgather(_np(tensor), name=nm))


def broadcast(tensor, root_rank: int = 0, name=None, priority=0):
    nm = name or eager_controller.next_name("broadcast.mxnet")
    return _like(
        tensor, eager.process_broadcast(_np(tensor), root_rank, name=nm)
    )


def broadcast_(tensor, root_rank: int = 0, name=None, priority=0):
    out = broadcast(tensor, root_rank, name, priority)
    tensor[:] = out
    return tensor


def _append_broadcast_init(param, root_rank: int, name: str):
    """Wrap ``param._init_impl`` so the data is broadcast from
    ``root_rank`` right after deferred initialization fires (reference
    mxnet/__init__.py:138-145 _append_broadcast_init: same injection,
    minus the explicit wait_to_read — this plane is synchronous)."""
    init_impl = getattr(param, "_init_impl")

    def wrapped_init_impl(self, *args, **kwargs):
        init_impl(*args, **kwargs)
        broadcast_(self.data(), root_rank=root_rank,
                   name=f"parameter.{name}")

    return wrapped_init_impl


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """reference mxnet/__init__.py broadcast_parameters (:148-183):
    accepts a gluon ParameterDict or a dict of NDArrays; in-place.
    Shape-deferred parameters get the reference's post-init broadcast
    hook injected into ``_init_impl`` so every rank converges to root's
    init once the first forward pass materializes them."""
    if hasattr(params, "items"):
        items = sorted(params.items())
    else:
        raise ValueError("invalid params of type: %s" % type(params))
    import types as _types

    from ..utils.logging import get_logger

    log = get_logger(__name__)
    for name, p in items:
        if hasattr(p, "data"):
            try:
                tensor = p.data()
            except mx.gluon.parameter.DeferredInitializationError:
                if hasattr(p, "_init_impl"):
                    # reference behavior: broadcast fires after the
                    # deferred init materializes the data
                    p._init_impl = _types.MethodType(
                        _append_broadcast_init(p, root_rank, name), p
                    )
                else:
                    # no injection point: skipping silently would leave
                    # each rank on its own init — tell the user
                    log.warning(
                        "broadcast_parameters: %s is deferred-initialized "
                        "and was NOT broadcast; run a forward pass first",
                        name,
                    )
                continue
        else:
            tensor = p
        broadcast_(tensor, root_rank, name=f"parameter.{name}")


class DistributedTrainer(mx.gluon.Trainer):
    """gluon Trainer whose gradient aggregation crosses processes
    (reference mxnet/__init__.py:92-134).  The fork wires a Recorder into
    the trainer itself — mandatory, zero user code (reference
    mxnet/__init__.py:92-134 + mxnet/recorder.py:187-302 builds the DAG
    from symbol.debug_str()); here the first ``_allreduce_grads`` dumps
    the gradient manifest, shapes, and the aggregation dataflow DAG to
    ``HVD_TRACE_DIR`` the same way."""

    def __init__(self, params, optimizer, optimizer_params=None, **kwargs):
        # reference scales LR handling by size in the optimizer; keep the
        # reference's rescale_grad convention: divide by local batch only
        super().__init__(params, optimizer, optimizer_params,
                         kvstore=None, **kwargs)
        self._hvd_recorded = False

    def _record_once(self) -> None:
        if self._hvd_recorded:
            return
        self._hvd_recorded = True
        try:
            from ..timeline.recorder import (
                Recorder, structure_dag, write_gml,
                write_gradient_manifest,
            )

            rec = Recorder()
            if not rec.enabled:
                return
            live = [p for p in self._params if p.grad_req != "null"]
            names = [f"gradients/{p.name}" for p in live]
            shapes = {
                f"gradients/{p.name}": list(p.shape)
                for p in live if p.shape is not None
            }
            write_gradient_manifest(rec, names, shapes)
            nodes, edges = structure_dag([p.name for p in live])
            write_gml(nodes, edges, rec._path("dag.gml"))
            rec.dump_metadata(framework="mxnet",
                              num_gradients=len(names))
        except Exception:  # noqa: BLE001 — tracing must never kill a step
            from ..utils.logging import get_logger

            get_logger(__name__).exception("recorder: mxnet dump failed")

    def _allreduce_grads(self):
        self._record_once()
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                for grad in param.list_grad():
                    allreduce_(grad, average=True,
                               name=f"gradient.{i}.{param.name}")


def DistributedOptimizer(*args, **kwargs):
    raise NotImplementedError(
        "use DistributedTrainer instead (the byteprofile fork disables "
        "DistributedOptimizer the same way, reference "
        "mxnet/__init__.py:49-50)"
    )
