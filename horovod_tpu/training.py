"""High-level data-parallel training step builder.

The glue the reference spreads across DistributedOptimizer +
BroadcastGlobalVariablesHook + the example boilerplate (reference
examples/tensorflow2_synthetic_benchmark.py:72-97), packaged as one
TPU-native entry: build a jitted SPMD train step where the global batch is
sharded across ranks, parameters are replicated, and gradients flow through
the fused allreduce.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from . import core
from .core import Average
from .elastic import faults as _faults
from .elastic import heartbeat as _heartbeat
from .ops.compression import Compression, ErrorFeedback
from .ops.fusion import allreduce_pytree
from .spmd import spmd


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    model_state: Any  # mutable collections (e.g. batch_stats); may be {}
    step: jnp.ndarray
    #: error-feedback residual pytree (docs/compression.md) — ``()`` (no
    #: leaves) when compression is stateless.  Living in the state, it
    #: is checkpointed and elastic-rebuilt with params/opt_state.
    residual: Any = ()


class TrailingLossFetcher:
    """The async-host-pipeline loss fetch (docs/PERF.md compute tier).

    ``push(loss)`` is called with every dispatched step's loss handle;
    every ``every`` steps ONE handle is retained, and the retained
    handle from the PREVIOUS cadence — by then ``every`` dispatches
    old, long since complete — is fetched.  The fetch therefore never
    drains the dispatch pipeline the way a per-step ``device_get``
    does (the serialization the compute-anatomy profiler's host-gap
    metric flags); the freshest fetched value is ``.value`` (a float,
    ``every``..2×``every`` steps behind) and is exported as the
    ``hvd_train_loss`` gauge.  ``every <= 0`` disables entirely."""

    def __init__(self, every: int):
        self.every = max(int(every), 0)
        self._pending: list = []
        self._n = 0
        self.value: Optional[float] = None
        self.step: Optional[int] = None

    def push(self, loss) -> None:
        if self.every <= 0:
            return
        self._n += 1
        if self._n % self.every:
            return
        self._pending.append((self._n, loss))
        if len(self._pending) > 1:
            self._fetch(*self._pending.pop(0))

    def _fetch(self, n, loss) -> None:
        import numpy as np

        self.value = float(np.asarray(jax.device_get(loss)))
        self.step = n
        from . import metrics

        if metrics.on():
            metrics.TRAIN_LOSS.set(self.value)

    def flush(self) -> Optional[float]:
        """Drain every retained handle (end-of-training); returns the
        final fetched value."""
        while self._pending:
            self._fetch(*self._pending.pop(0))
        return self.value


def scan_steps(step_fn: Callable, k: int) -> Callable:
    """Compile ``k`` optimizer steps into one program via ``lax.scan``
    (amortizes per-step host dispatch — the round-2 ResNet profiling win,
    docs/PERF.md; the transformer benches reuse it).  ``step_fn(carry,
    *args) -> (carry, loss)``; the returned fn has the same signature and
    yields the LAST step's loss.  ``k <= 1``: identity."""
    if k <= 1:
        return step_fn

    def scanned(carry, *args):
        def body(c, _):
            return step_fn(c, *args)

        carry, losses = jax.lax.scan(body, carry, None, length=k)
        return carry, losses[-1]

    return scanned


def make_train_step(
    *,
    apply_fn: Callable,
    loss_fn: Callable,
    optimizer,
    op: str = Average,
    compression=None,
    has_batch_stats: bool = False,
    threshold_bytes: Optional[int] = None,
    donate: bool = True,
    hierarchical: bool = False,
    two_level: Optional[bool] = None,
    autotune: Optional[bool] = None,
    autotune_log_file: Optional[str] = None,
    profile_guided: Optional[bool] = None,
    profile: Optional[bool] = None,
    in_graph_steps: int = 1,
    fused_optimizer: Optional[bool] = None,
    remat_policy: Optional[str] = None,
    loss_fetch_steps: Optional[int] = None,
):
    """Returns ``step(state, batch, labels) -> (state, loss)`` compiled SPMD
    over the global mesh.

    * ``apply_fn(variables, x, train=True, **mutable_kw)`` — flax-style.
    * ``loss_fn(logits, labels) -> scalar`` (per-rank mean).
    * gradients are bucket-fused and allreduced with ``op``/``compression``;
      the loss is also averaged across ranks for reporting (matching
      MetricAverageCallback semantics, reference _keras/callbacks.py:46-60).
    * ``compression`` (default: the ``HVD_COMPRESSION`` /
      ``HVD_COMPRESSION_ERROR_FEEDBACK`` env knobs, docs/compression.md)
      selects the wire format; an
      :class:`~horovod_tpu.ops.compression.ErrorFeedback` instance
      threads the quantization residual through ``TrainState.residual``
      (initialize it via ``init_train_state(..., compression=...)``;
      with ``in_graph_steps == 1`` an uninitialized residual is created
      lazily at first trace).  A residual-norm convergence guard
      (``HVD_COMPRESSION_GUARD_STEPS``/``_FACTOR``) samples the
      ``hvd_compression_residual_norm`` gauge and, if the residual
      diverges, falls back to uncompressed allreduce
      (``hvd_compression_fallbacks_total``) — training continues.
    * ``two_level`` (default: ``HVD_TWO_LEVEL_ALLREDUCE``) reduces each
      gradient with the compressed two-level path — ICI reduce-scatter,
      ``compression`` on the cross/DCN stage only
      (parallel/hierarchical.py ``two_level_allreduce``).
    * ``autotune`` (default: the HVD_AUTOTUNE env, reference run.py:490-521
      --autotune) drives a live ParameterManager: it scores each step as
      bytes/sec, moves the fusion-threshold / hierarchical knobs, and
      re-jits the step when they change — the compiled-world analog of the
      reference's "new parameters take effect next cycle"
      (parameter_manager.cc Update/Tune).  The returned function exposes
      the manager as ``step.parameter_manager``.
    * ``profile_guided`` (default: the HVD_AUTOTUNE_PROFILE_GUIDED env)
      closes the replay→autotune loop (docs/autotune.md): every
      ``HVD_AUTOTUNE_WINDOW_STEPS`` steps the job's own trace window is
      stitched + replayed, the winning what-if becomes an explicit
      fusion-bucket plan applied through the same re-jit seam, and the
      next window verifies realized against predicted speedup (rollback
      past the guard band).  Exposed as ``step.profile_guided_tuner``.
      The GP prior is warm-started from the α–β cost model
      (HVD_AUTOTUNE_WARM_START=0 disables).
    * ``profile`` (default: the ``HVD_PROFILE`` env, docs/profiling.md)
      arms the compute-anatomy profiler: inside its step window
      (``HVD_PROFILE_START_STEP``/``END_STEP``) the step runs DECOMPOSED
      — forward / backward / grad_allreduce / optimizer_update as
      separately-jitted programs with a device sync at each boundary —
      so each block's device time, ``cost_analysis`` flops/bytes, and
      the inter-dispatch host gaps are measured and reduced into a
      per-rank ``compute.json`` next to ``comm.json``.  Window steps pay
      the decomposition (no cross-block fusion, one sync per block);
      steps outside it run the normal fused program untouched.
    * ``in_graph_steps > 1`` compiles a ``lax.scan`` of that many
      optimizer steps over the SAME batch into one program, so host
      dispatch is amortized away (the synthetic-benchmark mode: the
      reference's timed inner loop also re-feeds one synthetic batch,
      examples/tensorflow2_synthetic_benchmark.py:72-97; measured +6%
      on the v5e, docs/PERF.md).  Real data pipelines keep the default 1.
    * ``fused_optimizer`` (default: ``HVD_FUSED_OPTIMIZER``, on when
      ``optimizer`` is a :class:`~horovod_tpu.optim.fused_update.
      FusedOptimizer`) routes the update through the flat fused
      elementwise kernel instead of the per-leaf optax traversal —
      same flat state either way, so the autotuner can flip the knob
      through the re-jit seam without a state migration.
    * ``remat_policy`` (default ``HVD_REMAT_POLICY``: none|full|dots)
      rematerializes the loss closure under ``jax.checkpoint`` — a
      compute knob the tuner can rotate when activations are the
      HBM bottleneck.
    * ``loss_fetch_steps`` (default ``HVD_LOSS_FETCH_STEPS``, 16)
      fetches loss/metrics through a TRAILING async handle every N
      steps (``step.loss_fetcher.value``) instead of a per-step
      ``device_get`` — the dispatch pipeline stays deep; the forced
      per-step sync survives only inside profiler/tuner measuring
      windows, which need it for honest timing (docs/profiling.md
      host-gap section is the before/after proof).  0 disables.
    """
    from .ops import collectives
    from .parallel.hierarchical import (
        hierarchical_allreduce, two_level_allreduce, use_two_level_default,
    )
    from .utils import env as env_util
    from .utils.logging import get_logger

    log = get_logger(__name__)

    if compression is None:
        from .ops.compression import from_env as _compression_from_env

        compression = _compression_from_env()
    if two_level is None:
        two_level = use_two_level_default()

    # -- compute tier defaults (docs/PERF.md "compute tier") ----------------
    from .optim.fused_update import FusedOptimizer

    fusable = isinstance(optimizer, FusedOptimizer)
    if fused_optimizer is None:
        fused_optimizer = env_util.get_bool(env_util.HVD_FUSED_OPTIMIZER,
                                            fusable)
    if fused_optimizer and not fusable:
        log.info("HVD_FUSED_OPTIMIZER is on but the optimizer is not a "
                 "FusedOptimizer — keeping the per-leaf optax path")
        fused_optimizer = False
    if remat_policy is None:
        remat_policy = env_util.get_str(env_util.HVD_REMAT_POLICY)
    if remat_policy in ("", "none"):
        remat_policy = None
    if loss_fetch_steps is None:
        loss_fetch_steps = env_util.get_int(
            env_util.HVD_LOSS_FETCH_STEPS,
            env_util.DEFAULT_LOSS_FETCH_STEPS)
    fetcher = TrailingLossFetcher(loss_fetch_steps)

    def _remat_wrap(fn, policy):
        """The remat knob: checkpoint the loss closure so the backward
        recomputes activations instead of holding them in HBM."""
        if not policy or policy == "none":
            return fn
        if policy == "dots":
            return jax.checkpoint(
                fn, policy=jax.checkpoint_policies.checkpoint_dots)
        if policy != "full":
            raise ValueError(
                f"unknown remat policy {policy!r} (none|full|dots)")
        return jax.checkpoint(fn)

    def _build(threshold_b, hier, named_buckets=None, comp=None,
               bucket_compression=None, tlvl=None, fused_opt=False,
               remat=None):
        comp = comp if comp is not None else compression
        tlvl = two_level if tlvl is None else tlvl
        # error feedback threads TrainState.residual — only on the fused
        # pytree path (the per-leaf hier/two-level paths carry their own
        # compression semantics; two_level_allreduce documents why EF
        # degrades there)
        plan_comp = bucket_compression is not None \
            and any(bucket_compression) \
            and env_util.get_bool(
                env_util.HVD_COMPRESSION_ERROR_FEEDBACK, True) \
            and in_graph_steps <= 1
        ef = (isinstance(comp, ErrorFeedback) or plan_comp) \
            and not hier and not tlvl

        # The step's four blocks as shared helpers: per_rank_step (the
        # fused program) and the compute-anatomy profiler's decomposed
        # segments (make_profile_fns) both call THESE, so the profiled
        # window runs the same math it attributes.  jax.named_scope
        # threads the block names into HLO op metadata, so a real
        # jax.profiler capture (HVD_PROFILE_XLA=1) carries them too.
        def _compute_loss(params, model_state, x, y):
            with jax.named_scope("hvd_forward"):
                variables = {"params": params, **model_state}
                if has_batch_stats:
                    logits, updates = apply_fn(
                        variables, x, train=True, mutable=["batch_stats"]
                    )
                    return loss_fn(logits, y), updates
                logits = apply_fn(variables, x)
                return loss_fn(logits, y), {}

        def _reduce_grads(grads, residual):
            with jax.named_scope("hvd_grad_allreduce"):
                if tlvl:
                    grads = jax.tree_util.tree_map(
                        lambda g: two_level_allreduce(g, op=op,
                                                      compression=comp),
                        grads,
                    )
                elif hier:
                    grads = jax.tree_util.tree_map(
                        lambda g: hierarchical_allreduce(g, op=op), grads
                    )
                elif ef:
                    if not jax.tree_util.tree_leaves(residual):
                        if in_graph_steps > 1:
                            raise ValueError(
                                "error-feedback compression with "
                                "in_graph_steps > 1 needs an initialized "
                                "residual (lax.scan carries must keep one "
                                "structure) — build the state with "
                                "init_train_state(..., compression=...)")
                        # lazy init at trace time: the first compiled step
                        # returns the full residual structure, later calls
                        # carry it (one extra re-trace, no extra step work)
                        residual = jax.tree_util.tree_map(
                            jnp.zeros_like, grads)
                    grads, residual = allreduce_pytree(
                        grads, op=op, compression=comp,
                        threshold_bytes=threshold_b,
                        named_buckets=named_buckets,
                        bucket_compression=bucket_compression,
                        residual=residual,
                    )
                else:
                    grads = allreduce_pytree(
                        grads, op=op, compression=comp,
                        threshold_bytes=threshold_b,
                        named_buckets=named_buckets,
                        bucket_compression=bucket_compression,
                    )
            return grads, residual

        # fused knob: flat single-kernel update vs per-leaf traversal —
        # both paths of a FusedOptimizer share one flat state layout, so
        # the autotuner can flip this through a re-jit with no state
        # migration (optim/fused_update.py)
        fused_active = bool(fused_opt) and fusable

        def _apply_update(state, grads, new_model_state, residual):
            with jax.named_scope("hvd_optimizer_update"):
                if fused_active:
                    params, opt_state = optimizer.fused_update(
                        grads, state.opt_state, state.params)
                else:
                    updates, opt_state = optimizer.update(
                        grads, state.opt_state, state.params
                    )
                    import optax

                    params = optax.apply_updates(state.params, updates)
            return TrainState(params, opt_state, new_model_state,
                              state.step + 1, residual)

        def per_rank_step(state: TrainState, x, y):
            (loss, new_model_state), grads = jax.value_and_grad(
                _remat_wrap(
                    lambda p: _compute_loss(p, state.model_state, x, y),
                    remat),
                has_aux=True,
            )(state.params)
            grads, residual = _reduce_grads(grads, state.residual)
            loss = collectives.allreduce(loss, op=Average)
            return (
                _apply_update(state, grads, new_model_state, residual),
                loss,
            )

        per_rank_entry = scan_steps(per_rank_step, in_graph_steps)

        # params/opt_state replicated; batch sharded across ranks on dim 0.
        state_spec = TrainState(
            params=P(), opt_state=P(), model_state=P(), step=P(),
            residual=P(),
        )
        fn = spmd(
            per_rank_entry,
            in_specs=(state_spec, P(core.AXIS), P(core.AXIS)),
            out_specs=(state_spec, P()),
            donate_argnums=(0,) if donate else (),
        )

        def make_profile_fns():
            """Separately-jitted step segments for the compute-anatomy
            profiler (timeline/profiler.py): the SAME block helpers as
            per_rank_step, split at block boundaries so each block's
            device time is host-visible.  Per-rank intermediates (loss,
            gradients, batch-stat updates) cross segment boundaries as
            stacked arrays — leading axis = rank, sharded P(AXIS) — so
            every rank round-trips its OWN values and no collective is
            smuggled into the wrong segment."""

            def _stack(t):
                return jax.tree_util.tree_map(lambda l: l[None], t)

            def _unstack(t):
                return jax.tree_util.tree_map(lambda l: l[0], t)

            def forward_seg(state, x, y):
                loss, _ = _compute_loss(state.params, state.model_state,
                                        x, y)
                return loss[None]

            def backward_seg(state, x, y):
                (loss, new_ms), grads = jax.value_and_grad(
                    _remat_wrap(
                        lambda p: _compute_loss(p, state.model_state, x, y),
                        remat),
                    has_aux=True,
                )(state.params)
                return loss[None], _stack(new_ms), _stack(grads)

            def reduce_seg(state, loss_st, grads_st):
                grads, residual = _reduce_grads(_unstack(grads_st),
                                                state.residual)
                loss = collectives.allreduce(loss_st[0], op=Average)
                return grads, residual, loss

            def opt_seg(state, new_ms_st, grads, residual, loss):
                return (_apply_update(state, grads, _unstack(new_ms_st),
                                      residual), loss)

            data = (P(core.AXIS), P(core.AXIS))
            return {
                "forward": spmd(forward_seg,
                                in_specs=(state_spec,) + data,
                                out_specs=P(core.AXIS)),
                "backward": spmd(backward_seg,
                                 in_specs=(state_spec,) + data,
                                 out_specs=(P(core.AXIS), P(core.AXIS),
                                            P(core.AXIS))),
                "grad_allreduce": spmd(
                    reduce_seg,
                    in_specs=(state_spec, P(core.AXIS), P(core.AXIS)),
                    out_specs=(P(), P(), P())),
                # no donation on the decomposed path: the window-entry
                # warm-up executes the chain once with results discarded
                # (so compile time never reads as host gap), which a
                # donated state buffer would not survive.  Cost: one
                # extra live params copy during the profile window only.
                "optimizer_update": spmd(
                    opt_seg,
                    in_specs=(state_spec, P(core.AXIS), P(), P(), P()),
                    out_specs=(state_spec, P())),
            }

        return fn, ef, make_profile_fns

    if autotune is None:
        autotune = env_util.get_bool(env_util.HVD_AUTOTUNE)

    pm = None
    box = {"fused_base": fused_optimizer, "remat_base": remat_policy}
    fetcher_base_every = fetcher.every

    def _rebuild(threshold_b, hier, plan=None, fused=None, remat=None):
        """(Re)compile the SPMD step and remember the knobs + the core
        mesh epoch it was built against, so a later elastic membership
        change (core.reinit bumps the epoch and swaps the mesh) can
        rebuild with the same knobs.  ``plan`` is a profile-guided
        FusionPlanSpec: its explicit bucket vector overrides the scalar
        threshold, its per-bucket ``compression`` names override the
        wire format, and its ``compute`` dict overrides the compute
        knobs (optim/profile_guided.py; a compute-only plan has no
        buckets and leaves threshold bucketing untouched).  ``fused`` /
        ``remat`` move the base compute knobs (the GP tuner's
        categorical dims); None leaves the base unchanged."""
        if fused is not None:
            box["fused_base"] = fused
        if remat is not None:
            box["remat_base"] = None if remat == "none" else remat
        pc = (getattr(plan, "compute", None) or {}) \
            if plan is not None else {}
        fused_eff = pc.get("fused_optimizer", box["fused_base"])
        remat_eff = pc.get("remat_policy", box["remat_base"])
        # the async-pipeline knob is host-side: the plan moves the
        # fetch cadence without a re-jit, rollback restores the base
        fetcher.every = max(int(pc.get("loss_fetch_steps",
                                       fetcher_base_every)), 0)
        named = plan.buckets if plan is not None and plan.buckets \
            else None
        bucket_comp = getattr(plan, "compression", None) \
            if plan is not None else None
        if bucket_comp is not None and box.get("guard_tripped"):
            # the convergence guard already condemned compression in
            # this job; later plans keep their fusion layout but ship
            # uncompressed
            bucket_comp = None
        if bucket_comp is not None and any(bucket_comp) \
                and in_graph_steps > 1:
            # plan compression rides error feedback, and a lax.scan
            # carry can't grow a residual mid-job — keep the fusion
            # layout, ship it uncompressed rather than silently
            # quantizing without the residual carry
            log.info("profile-guided plan carries per-bucket compression "
                     "but in_graph_steps > 1 has no residual carry — "
                     "applying the fusion layout uncompressed")
            bucket_comp = None
        comp = box.get("compression", compression)
        # Everything jit-relevant, hashed: a rebuild whose compiled
        # program would be byte-identical (e.g. a plan moving ONLY the
        # host-side loss-fetch cadence, or its rollback) skips the
        # re-trace/recompile — on a big model that's multi-seconds per
        # knob trial that would otherwise land inside the tuner's
        # verify window.
        sig = (threshold_b, hier and named is None,
               tuple(tuple(b) for b in named) if named else None,
               tuple(bucket_comp) if bucket_comp else None,
               id(comp), two_level and named is None, fused_eff,
               remat_eff, core._require_init().epoch)
        if sig == box.get("build_sig"):
            box["plan"] = plan
            return
        # An explicit bucket plan owns the comm layout: the hierarchical
        # path reduces per leaf and would silently drop named_buckets
        # while the tuner reports the plan applied.  box keeps the
        # original hier so rollback (plan=None) restores it.  A
        # compute-only plan (no buckets) leaves the comm layout alone.
        fn, ef, profile_factory = _build(
            threshold_b, hier and named is None, named,
            comp, bucket_comp, two_level and named is None,
            fused_eff, remat_eff)
        # any rebuild (new plan, elastic epoch, guard trip) invalidates
        # the profiler's cached decomposed segments — they must re-jit
        # against the same knobs as the fused program
        box.pop("profile_fns", None)
        box.update(
            fn=fn, threshold=threshold_b, hier=hier, plan=plan,
            ef_active=ef, compression=comp, fused=fused_eff,
            remat=remat_eff, profile_factory=profile_factory,
            core_epoch=core._require_init().epoch, build_sig=sig,
        )

    if autotune:
        from .optim.autotune import ParameterManager, TunableParams

        initial = TunableParams(
            fusion_threshold_bytes=threshold_bytes
            or env_util.fusion_threshold_bytes(),
            hierarchical_allreduce=hierarchical,
            fused_optimizer=fused_optimizer if fusable else None,
            remat_policy=remat_policy,
        )
        # HVD_AUTOTUNE_COMPUTE widens the GP rotation to the compute
        # knobs — fused_optimizer only where the optimizer can fuse
        tune_compute = env_util.get_bool(env_util.HVD_AUTOTUNE_COMPUTE)
        pm = ParameterManager(
            enabled=True, log_file=autotune_log_file, initial=initial,
            tune_fused_optimizer=tune_compute and fusable,
            tune_remat=tune_compute,
        )
        pm.on_update = lambda p: _rebuild(p.fusion_threshold_bytes,
                                          p.hierarchical_allreduce,
                                          p.fusion_plan,
                                          fused=p.fused_optimizer,
                                          remat=p.remat_policy)
        _rebuild(initial.fusion_threshold_bytes,
                 initial.hierarchical_allreduce)
    else:
        _rebuild(threshold_bytes, hierarchical)

    from . import metrics
    from .metrics import timeseries as _timeseries
    from .timeline.timeline import timeline

    import time as _time

    # Compute-anatomy profiler (timeline/profiler.py, docs/profiling.md):
    # None when off, so steps outside a window pay a single None check.
    if profile is None:
        from .timeline import profiler as _profiler_mod

        profiler = _profiler_mod.from_env()
        if profiler is None and env_util.get_bool(env_util.HVD_WATCH_ARM,
                                                  True):
            # dormant profiler: disabled (on_step = one bool check per
            # step) until the watchdog broadcasts an arm record, which
            # re-enables it with a concrete window (observe/autoarm.py)
            profiler = _profiler_mod.ComputeProfiler(enabled=False)
    elif profile:
        from .timeline.profiler import ComputeProfiler

        profiler = ComputeProfiler(enabled=True)
        profiler = profiler if profiler.enabled else None
    else:
        profiler = None

    if profiler is not None:
        from .observe import autoarm as _autoarm

        _autoarm.register_profiler(profiler)

    def _segment_cost(fn, args):
        """cost_analysis flops/bytes for one decomposed segment, plus
        the AOT-compiled executable (used for the window's calls so the
        lowering isn't compiled twice)."""
        try:
            compiled = fn.lower(*args).compile()
            ca = compiled.cost_analysis()
            if isinstance(ca, list):
                ca = ca[0] if ca else {}
            ca = ca or {}
            return {
                "compiled": compiled,
                "flops": float(ca["flops"]) if "flops" in ca else None,
                "bytes": float(ca["bytes accessed"])
                if "bytes accessed" in ca else None,
            }
        except Exception as e:  # noqa: BLE001 — profiling must not kill the step
            log.debug("segment cost analysis failed: %s", e)
            return {"compiled": None, "flops": None, "bytes": None}

    def _profiled_step(state, x, y):
        """One train step on the decomposed path: each block dispatched
        and synced under a profiler segment span.  Identical math to
        box['fn'] (same block helpers, same knobs); in_graph_steps > 1
        python-loops the chain — lax.scan re-feeds the same batch, so k
        sequential chains are exactly the scanned program's semantics."""
        if box.get("ef_active") and not jax.tree_util.tree_leaves(
                state.residual):
            # materialize the lazy error-feedback residual BEFORE the
            # segments compile: the AOT executables are pinned to the
            # state's pytree structure, and reduce_seg's trace-time
            # lazy init would grow it on the NEXT step's state —
            # crashing the cached call (the fused jit path re-traces,
            # AOT doesn't)
            state = state._replace(residual=jax.tree_util.tree_map(
                jnp.zeros_like, state.params))
        first = "profile_fns" not in box
        if first:
            box["profile_fns"] = {"fns": box["profile_factory"](),
                                  "costs": {}}
        fns, costs = box["profile_fns"]["fns"], box["profile_fns"]["costs"]

        def _prep(name, *args):
            """Compile (AOT, so cost_analysis and the executable come
            from ONE compile) and run a segment once with the result
            discarded — the window-entry warm-up that keeps compile
            time out of the recorded spans (it would otherwise read as
            a giant host gap on step 1)."""
            c = _segment_cost(fns[name], args)
            costs[name] = c
            out = (c["compiled"] or fns[name])(*args)
            jax.block_until_ready(out)
            return out

        if first:
            _prep("forward", state, x, y)
            loss_st, new_ms_st, grads_st = _prep("backward", state, x, y)
            grads, residual, loss = _prep("grad_allreduce",
                                          state, loss_st, grads_st)
            _prep("optimizer_update",
                  state, new_ms_st, grads, residual, loss)

        def run(name, *args):
            c = costs[name]
            return profiler.run_segment(name, c["compiled"] or fns[name],
                                        *args, flops=c["flops"],
                                        nbytes=c["bytes"])

        with profiler.step_span():
            for _ in range(max(in_graph_steps, 1)):
                # timing-only extra pass: XLA fuses fwd+bwd inside
                # value_and_grad, so a standalone forward is the only
                # host-visible way to split them — "backward" below
                # therefore includes a forward recompute (backward-only
                # ≈ backward − forward; docs/profiling.md)
                run("forward", state, x, y)
                loss_st, new_ms_st, grads_st = run("backward", state, x, y)
                grads, residual, loss = run("grad_allreduce",
                                            state, loss_st, grads_st)
                state, loss = run("optimizer_update",
                                  state, new_ms_st, grads, residual, loss)
        return state, loss

    # Step-cadence metrics: blocking on the result every step would
    # serialize the async dispatch pipeline (the very thing the compiled
    # plane buys), so the histogram records the interval between
    # successive dispatches — in steady state the host is throttled by
    # the device queue, making dispatch-to-dispatch time the real step
    # time without a single synchronization.
    last_dispatch = [0.0]
    step_count = [0]

    def _record_step_metrics(x):
        now = _time.perf_counter()
        step_count[0] += 1
        if last_dispatch[0]:
            dt = now - last_dispatch[0]
            metrics.STEP_SECONDS.observe(dt)
            # always-on cadence history (one ring-buffer append): the
            # watchdog's step-time and straggler detectors read this
            if _timeseries.on():
                _timeseries.record(_timeseries.STEP_SECONDS, dt,
                                   step=step_count[0])
        last_dispatch[0] = now
        metrics.STEPS_TOTAL.inc(max(in_graph_steps, 1))
        try:
            metrics.SAMPLES_TOTAL.inc(
                int(x.shape[0]) * max(in_graph_steps, 1)
            )
        except (AttributeError, IndexError, TypeError):
            pass  # batch without a leading dim: samples stay uncounted

    # Error-feedback convergence guard (docs/compression.md): every
    # HVD_COMPRESSION_GUARD_STEPS steps read the residual norm off the
    # returned state (one device sync per guard window — not per step),
    # export the gauge, and fall back to uncompressed allreduce when the
    # norm diverges.  The residual is replicated and the guard logic is
    # deterministic host float math, so every process trips identically.
    guard_steps = env_util.get_int(env_util.HVD_COMPRESSION_GUARD_STEPS,
                                   env_util.DEFAULT_COMPRESSION_GUARD_STEPS)
    guard_box = {"n": 0, "guard": None}

    def _maybe_guard(new_state):
        if not box.get("ef_active") or guard_steps <= 0:
            return
        guard_box["n"] += 1
        if guard_box["n"] % guard_steps:
            return
        from .ops.compression import ErrorFeedbackGuard, residual_norm

        norm = residual_norm(new_state.residual)
        if metrics.on():
            metrics.COMPRESSION_RESIDUAL_NORM.set(norm)
        if _timeseries.on():
            _timeseries.record(_timeseries.RESIDUAL_NORM_SERIES, norm,
                               step=step_count[0])
        if guard_box["guard"] is None:
            guard_box["guard"] = ErrorFeedbackGuard()
        if not guard_box["guard"].observe(norm):
            return
        log.warning(
            "error-feedback residual norm %.3g diverged past %gx its "
            "baseline — falling back to uncompressed allreduce; the "
            "diverged residual is DISCARDED (it is garbage by "
            "construction) and stays frozen in TrainState.residual",
            norm, guard_box["guard"].factor)
        if metrics.on():
            metrics.COMPRESSION_FALLBACKS.inc()
        try:
            from .observe import events as events_mod

            events_mod.record_event(
                "compression.fallback", severity="warning",
                payload={"residual_norm": float(norm),
                         "factor": guard_box["guard"].factor,
                         "step": step_count[0]})
        except Exception:  # noqa: BLE001 — recording is best-effort
            pass
        box["guard_tripped"] = True
        box["compression"] = Compression.none
        plan = box.get("plan")
        if plan is not None and getattr(plan, "compression", None):
            plan = dataclasses_replace_plan(plan)
        _rebuild(box["threshold"], box["hier"], plan)

    def dataclasses_replace_plan(plan):
        """The applied plan minus its compression decision — fusion
        layout survives the fall-back, wire format does not."""
        import dataclasses as _dc

        try:
            return _dc.replace(plan, compression=None)
        except TypeError:
            return plan

    def _invoke(state, x, y, _under_trace=None):
        # Host-side step record: advances the trace window (reference
        # BYTEPS_TRACE_START/END_STEP semantics) and emits a STEP dispatch
        # span.  On the compiled path collective timing lives inside XLA;
        # this records the per-step cadence the tracer windows key on.
        # Skipped while under a jax trace (e.g. Recorder.record_step_function
        # running make_jaxpr) so abstract evaluation doesn't consume window
        # steps or emit phantom spans.  The autotuned wrapper passes its
        # already-computed verdict so big pytrees are scanned once.
        under_trace = _under_trace if _under_trace is not None else any(
            isinstance(leaf, jax.core.Tracer)
            for leaf in jax.tree_util.tree_leaves((state, x, y))
        )
        if not under_trace:
            # Failure-domain seam (docs/fault_tolerance.md): a coordinated
            # abort raises HorovodAbortError here — before this rank
            # dispatches a step its dead peer will never join — and the
            # HVD_FAULT_SPEC harness injects its step-seam faults.
            _heartbeat.maybe_raise_abort()
            _faults.on_step()
            # Elastic rebuild seam: after a membership epoch the mesh is
            # new (core.reinit) and the compiled step — shard_map captured
            # the old mesh at build — must re-trace over it.
            if box["core_epoch"] != core._require_init().epoch:
                _rebuild(box["threshold"], box["hier"], box.get("plan"))
        if not under_trace and metrics.on():
            _record_step_metrics(x)
        if not under_trace:
            box["profiled_last"] = False
        if profiler is not None and not under_trace and profiler.on_step():
            # capture window: the decomposed per-segment path, wrapped
            # in the same timeline STEP span as a normal step so the
            # comm.json window and compute.json envelopes stay aligned
            box["profiled_last"] = True
            if timeline.active:
                timeline.record_step(owner="train_step")
                timeline.mark_cycle_start()
                with timeline.span("train_step", "STEP"):
                    result = _profiled_step(state, x, y)
            else:
                result = _profiled_step(state, x, y)
            _maybe_guard(result[0])
            fetcher.push(result[1])
            return result
        if timeline.active and not under_trace:
            timeline.record_step(owner="train_step")
            timeline.mark_cycle_start()
            with timeline.span("train_step", "STEP"):
                result = box["fn"](state, x, y)
        else:
            result = box["fn"](state, x, y)
        if not under_trace:
            _maybe_guard(result[0])
            fetcher.push(result[1])
        return result

    # Profile-guided loop (optim/profile_guided.py): analyze the job's
    # own trace window, apply the winning bucket plan through the same
    # rebuild seam, verify realized-vs-predicted next window.
    if profile_guided is None:
        profile_guided = env_util.get_bool(
            env_util.HVD_AUTOTUNE_PROFILE_GUIDED)
    tuner = None
    if profile_guided:
        from .optim.profile_guided import tuner_from_env

        trace_dir = env_util.get_str(env_util.HVD_TIMELINE) or \
            env_util.get_str(env_util.HVD_TRACE_DIR)

        def _analyze():
            if not trace_dir:
                return None
            from .timeline.replay import analyze

            # latest step only: SPMD steps share one DAG shape, and a
            # per-window caller must not replay the whole accumulated
            # trace history (it grows with the job)
            return analyze(trace_dir, last_steps=1).summary

        def _apply_plan(plan):
            if pm is not None:
                if plan is not None:
                    pm.apply_plan(plan)
                else:
                    pm.clear_plan()
            else:
                _rebuild(box["threshold"], box["hier"], plan)

        def _anatomy():
            """The compute tier's plan source: the in-job profiler's
            anatomy when a window has finalized, else this rank's
            compute.json from an earlier run of the same trace dir."""
            if profiler is not None and profiler.anatomy is not None:
                return profiler.anatomy
            if trace_dir:
                from .timeline.profiler import own_rank_anatomy

                return own_rank_anatomy(trace_dir)
            return None

        # knobs the base config already has on are not plan candidates:
        # proposing them would be a no-op guaranteed to miss its
        # prediction and get condemned.  loss_fetch_steps is ALWAYS
        # excluded in-job: the tuner's baseline and verify windows both
        # force a per-step result sync for honest timing — exactly the
        # serialization the knob removes — so its realized delta inside
        # a verify window is ~0 by construction and the guard band
        # could only condemn (or falsely verify) it.  The knob stays
        # reachable via HVD_LOSS_FETCH_STEPS, explicit plans, and the
        # offline planner (scripts/compute_path_bench.py).
        active = {"loss_fetch_steps": fetcher.every}
        if fused_optimizer:
            active["fused_optimizer"] = True
        tuner = tuner_from_env(_analyze, _apply_plan, anatomy_fn=_anatomy,
                               fused_available=fusable,
                               active_compute=active)
        if not trace_dir:
            from .utils.logging import get_logger

            get_logger(__name__).warning(
                "profile-guided tuning enabled without HVD_TIMELINE/"
                "HVD_TRACE_DIR: no trace window to analyze, the tuner "
                "will idle in its baseline phase")

    if pm is None and tuner is None:
        _invoke.compute_profiler = profiler
        _invoke.loss_fetcher = fetcher
        return _invoke

    warm_start = env_util.get_bool(env_util.HVD_AUTOTUNE_WARM_START, True)
    pg_last = [0.0]

    def step_autotuned(state, x, y):
        under_trace = any(
            isinstance(leaf, jax.core.Tracer)
            for leaf in jax.tree_util.tree_leaves((state, x, y))
        )
        if tuner is not None and tuner.active and not under_trace:
            # dispatch-to-dispatch interval: real step time in steady
            # state with zero added synchronization (same honesty
            # argument as hvd_step_seconds).  An interval spanning a
            # compute-profiler window step measures the decomposed
            # path (~2x, plus the one-time segment compile) — feeding
            # it to the loop would mis-score knobs or read as a false
            # plan regression, so those steps don't count.
            now = _time.perf_counter()
            if pg_last[0] and not box.get("profiled_last"):
                tuner.on_step(now - pg_last[0])
            pg_last[0] = now
        if pm is None or pm.frozen:
            state, loss = _invoke(state, x, y, _under_trace=under_trace)
            if tuner is not None and tuner.measuring and not under_trace:
                # honest timing while the PG loop measures: the GP path
                # below blocks on the result every step, so without this
                # the baseline window (GP active) would measure serialized
                # step time but the verify window (apply_plan froze the
                # GP) pipelined dispatch time — a "speedup" any plan
                # would pass.  Gated on the MEASURING phases: a steady
                # (plan-pinned) window only counts steps and must keep
                # the async dispatch pipeline the plan bought.
                jax.device_get(loss)
            return state, loss
        if "grad_bytes" not in box:
            import math

            # per-call allreduce volume = the gradient pytree's bytes,
            # once per scanned in-graph step
            box["grad_bytes"] = float(sum(
                math.prod(l.shape) * l.dtype.itemsize
                for l in jax.tree_util.tree_leaves(state.params)
            )) * max(in_graph_steps, 1)
        if warm_start and not under_trace and not box.get("warm_started"):
            # seed the GP with the α–β model's predicted scores so
            # exploration starts near the simulator's optimum.  Gated on
            # its own flag, not the grad_bytes cache: the first call is
            # often a jax trace (Recorder.record_step_function), which
            # fills grad_bytes from tracer leaves but must not burn the
            # only warm-start opportunity.
            box["warm_started"] = True
            from .optim.profile_guided import warm_start_manager

            warm_start_manager(pm, box["grad_bytes"])
        t0 = _time.perf_counter()
        state, loss = _invoke(state, x, y, _under_trace=under_trace)
        # honest timing while tuning: force the step chain to complete
        # (block_until_ready can return early on tunneled platforms)
        jax.device_get(loss)
        dt = _time.perf_counter() - t0
        if box.get("profiled_last"):
            # a profiler-window step ran the decomposed path: its dt is
            # not the knob vector's step time, keep it out of the GP
            # (the window flag is env/step-counter driven, so every
            # process skips — and skips the sync below — in lockstep)
            return state, loss
        if core.process_size() > 1:
            # Synchronize the measurement instead of the decision: every
            # process scores the same averaged step time, and the
            # deterministic tuner (fixed seed) then moves every process's
            # knobs identically — the analog of the reference's
            # SynchronizeParameters broadcast (controller.cc:33-47).
            import numpy as _np

            from . import eager

            dt = float(eager.process_allreduce(
                _np.asarray([dt], _np.float64), op=Average,
                name="autotune.step_time",
            )[0])
        pm.record_step(box["grad_bytes"], dt)
        return state, loss

    step_autotuned.parameter_manager = pm
    step_autotuned.profile_guided_tuner = tuner
    step_autotuned.compute_profiler = profiler
    step_autotuned.loss_fetcher = fetcher
    return step_autotuned


def init_train_state(model, optimizer, sample_input, *, rngs=None,
                    has_batch_stats: bool = False,
                    compression=None) -> TrainState:
    """Initialize replicated TrainState on the mesh (rank-0-initializes +
    broadcast in Horovod terms; under a single controller, replication by
    construction plus hvd.broadcast_parameters for multi-host).

    Pass the same ``compression`` the train step uses: an
    :class:`~horovod_tpu.ops.compression.ErrorFeedback` wrapper gets its
    zero residual pytree here (required for ``in_graph_steps > 1``,
    where ``lax.scan`` needs the carry structure fixed up front)."""
    import numpy as np

    rngs = rngs if rngs is not None else jax.random.PRNGKey(0)
    variables = model.init(rngs, sample_input)
    params = variables["params"]
    model_state = {
        k: v for k, v in variables.items() if k != "params"
    } if has_batch_stats else {}
    opt_state = optimizer.init(params)
    residual = ErrorFeedback.init_state(params) \
        if isinstance(compression, ErrorFeedback) else ()
    state = TrainState(
        params=params, opt_state=opt_state, model_state=model_state,
        step=jnp.zeros((), jnp.int32), residual=residual,
    )
    # Replicate across the mesh explicitly so the donated buffers live on
    # every device before step 1 (no lazy broadcast inside the hot loop).
    mesh = core.mesh()
    repl = NamedSharding(mesh, P())
    state = jax.device_put(state, repl)
    from .optim.distributed import broadcast_parameters

    return broadcast_parameters(state)


def shard_batch(batch):
    """Place a host batch so dim 0 is split across ranks (the per-rank
    shards), without a host-side reshape."""
    mesh = core.mesh()
    return jax.device_put(batch, NamedSharding(mesh, P(core.AXIS)))
