"""Eager (host-level) collectives — the control path.

In the reference, *every* collective goes through the eager path: Python
enqueues a named tensor to the background C++ thread, ranks negotiate, and a
callback fires on completion (reference horovod/common/operations.cc:795
EnqueueTensorAllreduce → tensor_queue → controller.cc ComputeResponseList).
On TPU the hot path is compiled (see spmd.py), so the eager plane only
serves control-flow uses: parameter/optimizer-state broadcast at start-up,
metric averaging, object broadcast, and tests.

Two eager modes:

* **device-plane eager**: input is a list of per-rank values (or a
  rank-sharded global array from :func:`horovod_tpu.put_per_rank`).
  We jit a tiny SPMD program on the fly; the jit cache plays the role of
  the reference's response cache (response_cache.h:45-102) — first call
  negotiates (compiles), repeats are cache hits.
* **process-plane eager**: input is one value per *controller process*
  (multi-host); uses ``jax.experimental.multihost_utils``.  This is the
  analog of Horovod's cross-rank object broadcast
  (reference horovod/torch/__init__.py:446-638 broadcast_object).
"""

from __future__ import annotations

import pickle
from typing import Any, List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from . import core, metrics
from .analysis import sanitizer as _sanitizer
from .elastic import faults as _faults
from .elastic import heartbeat as _heartbeat
from .spmd import put_per_rank, get_per_rank, rank_context
from .core import Average, Sum, Adasum, Min, Max
from .ops import collectives
from .runtime import eager_controller
from .runtime.stall_inspector import inspector
from .timeline.timeline import timeline
from .utils import env as env_util


def _dispatch_guard(name: str, op: str, tensors, stages=None):
    """Shared pre-dispatch path for eager collectives: collective
    sanitizer fingerprint check (HVD_SANITIZER=1; analysis/sanitizer.py) +
    stall watchdog + timeline NEGOTIATE span + metrics (bytes/calls/
    latency per op) + (in multi-controller jobs) the native controller
    handshake that guarantees identical op ordering across processes (see
    runtime/eager_controller.py).

    ``stages`` (a list of parallel/hierarchical.py DispatchStage) is the
    per-group dispatch sequence of a hierarchical collective: the
    sanitizer then fingerprints each stage against its own group's
    members — the two-level intra-host and cross-host stages stop
    cross-matching against the flat world."""
    import contextlib
    import time as _time

    @contextlib.contextmanager
    def ctx():
        sample = tensors[0] if _is_per_rank_list(tensors) else tensors
        shape = np.shape(sample)
        dtype = getattr(sample, "dtype", "float32")
        # First: a coordinated abort must surface HERE, before this rank
        # enters a collective its dead peer will never join (elastic/
        # heartbeat.py polls the flag; docs/fault_tolerance.md).  The
        # fault harness's dispatch-seam faults fire at the same point.
        _heartbeat.maybe_raise_abort()
        _faults.on_dispatch(name)
        # Before the watchdog/negotiation: a divergence must raise the
        # sanitizer's diagnostic, not mature into a stall warning first.
        if stages:
            for st in stages:
                _sanitizer.maybe_check(op=st.op, name=name, shape=shape,
                                       dtype=dtype, group=st.group,
                                       peers=st.peers)
        else:
            _sanitizer.maybe_check(op=op, name=name, shape=shape,
                                   dtype=dtype)
        mon = metrics.on()
        t0 = _time.perf_counter() if mon else 0.0
        t_neg = t0
        with inspector.watch(name):
            timeline.negotiate_start(name, op.upper())
            eager_controller.negotiate(
                name, op=op, shape=shape, dtype=dtype
            )
            timeline.negotiate_end(name, op.upper())
            if mon:
                t_neg = _time.perf_counter()
            try:
                with timeline.span(name, op.upper()):
                    yield
            finally:
                if mon:
                    metrics.record_eager(
                        op, metrics.payload_bytes(shape, dtype),
                        t_neg - t0, _time.perf_counter() - t0,
                    )

    return ctx()


def _is_per_rank_list(x) -> bool:
    return isinstance(x, (list, tuple))


def _host_guard(name: str, activity: str, op: str, transport: str,
                nbytes: int):
    """Watchdog + timeline span + metrics for one host-plane collective
    (the process_* transports: ring / coordinator star / XLA process
    mesh)."""
    import contextlib
    import time as _time

    @contextlib.contextmanager
    def ctx():
        mon = metrics.on()
        t0 = _time.perf_counter() if mon else 0.0
        try:
            with inspector.watch(name), timeline.span(name, activity):
                yield
        finally:
            if mon:
                metrics.record_host(
                    op, transport, nbytes, _time.perf_counter() - t0
                )

    return ctx()


def _spmd_op(fn, *, out_sharded: bool):
    """Build (and jit-cache) a one-collective SPMD program."""
    mesh = core.mesh()
    out_spec = P(core.AXIS) if out_sharded else P()
    return jax.jit(
        jax.shard_map(
            fn, mesh=mesh, in_specs=P(core.AXIS), out_specs=out_spec,
            check_vma=False,
        )
    )


def allreduce_(tensors, *, op: str = Average, name: Optional[str] = None,
               two_level: Optional[bool] = None):
    """Eager allreduce.  ``tensors``: list of per-rank arrays (len == size())
    or a rank-sharded global array.  Returns the same structure, reduced.

    Analog of ``hvd.allreduce_`` / ``allreduce_async_`` + ``synchronize``
    (reference horovod/torch/mpi_ops.py:72-129) — async dispatch is native
    to JAX, so the returned arrays are futures already; materializing them
    is the ``synchronize`` step.

    ``two_level`` selects the hierarchical local/cross decomposition
    (default: the HVD_TWO_LEVEL_ALLREDUCE knob); the dispatch guard then
    fingerprints the per-group stage plan so a sanitized run checks each
    stage against its own group (parallel/hierarchical.py
    process_stage_plan).
    """
    from .parallel import hierarchical as _hier

    name = name or "allreduce.eager"
    if two_level is None:
        two_level = _hier.use_two_level_default()
    # mirror the dispatch exactly: collectives.allreduce only takes the
    # two-level path for the ops the decomposition supports.  The stage
    # plan only feeds sanitizer fingerprints — skip the topology math on
    # the (common) unsanitized path.
    staged = (two_level and op in (Average, Sum, Adasum)
              and _sanitizer.instance() is not None)
    stages = _hier.process_stage_plan("allreduce") if staged else None
    with _dispatch_guard(name, "allreduce", tensors, stages=stages):
        as_list = _is_per_rank_list(tensors)
        x = put_per_rank(list(tensors)) if as_list else tensors

        def body(v):
            with rank_context((core.AXIS,)):
                return collectives.allreduce(
                    v[0], op=op, two_level=two_level)[None]

        out = _spmd_op(body, out_sharded=True)(x)
        return get_per_rank(out) if as_list else out


def allgather_(tensors, *, name: Optional[str] = None):
    """Eager allgather along axis 0 (equal shapes).  List-in/list-out."""
    name = name or "allgather.eager"
    with _dispatch_guard(name, "allgather", tensors):
        as_list = _is_per_rank_list(tensors)
        x = put_per_rank(list(tensors)) if as_list else tensors

        def body(v):
            with rank_context((core.AXIS,)):
                return collectives.allgather(v[0])

        out = _spmd_op(body, out_sharded=False)(x)
        out = np.asarray(jax.device_get(out))
        if as_list:
            # independent per-rank outputs (reference semantics: each rank
            # owns its gathered buffer) — aliasing one ndarray N times would
            # let a caller's mutation of result[0] corrupt every "rank"
            return [out.copy() for _ in range(core.size())]
        return out


def broadcast_(tensors, root_rank: int = 0, *, name: Optional[str] = None):
    """Eager broadcast of per-rank values from ``root_rank``."""
    name = name or "broadcast.eager"
    with _dispatch_guard(name, "broadcast", tensors):
        as_list = _is_per_rank_list(tensors)
        x = put_per_rank(list(tensors)) if as_list else tensors

        def body(v):
            with rank_context((core.AXIS,)):
                return collectives.broadcast(v[0], root_rank=root_rank)[None]

        out = _spmd_op(body, out_sharded=True)(x)
        return get_per_rank(out) if as_list else out


# ---------------------------------------------------------------------------
# process-plane (multi-controller) object collectives
# ---------------------------------------------------------------------------
def _jax_spans_processes() -> bool:
    """True when the XLA plane itself is multi-process (jax.distributed on a
    real pod) — then multihost_utils is the transport.  Otherwise a
    multi-process job must carry host objects over the native controller's
    data plane (csrc/controller.cc HandleData).

    Queried on the MESH devices' backend: the default backend can be a
    single-process accelerator plugin while the CPU mesh backend spans the
    jax.distributed job (or vice versa)."""
    try:
        platform = core.mesh().devices.flat[0].platform
        return jax.process_count(platform) > 1
    except Exception:  # noqa: BLE001 — not initialized / exotic backend
        return jax.process_count() > 1


import functools as _functools


@_functools.lru_cache(maxsize=8)
def _process_mesh_for(job_mesh):
    from jax.sharding import Mesh

    firsts, seen = [], set()
    for d in job_mesh.devices.flat:
        if d.process_index not in seen:
            seen.add(d.process_index)
            firsts.append(d)
    firsts.sort(key=lambda d: d.process_index)
    return Mesh(np.array(firsts, dtype=object), ("proc",))


def _process_mesh():
    """A 1-D mesh with ONE device per controller process, drawn from the
    job mesh — the carrier for host-object collectives on the XLA plane.
    (multihost_utils builds its mesh from ``jax.devices()``, the default
    backend, which on mixed-backend hosts may not be the spanning one;
    the job mesh always is.)"""
    return _process_mesh_for(core.mesh())


@_functools.lru_cache(maxsize=64)
def _replicate_fn(pmesh):
    return jax.jit(lambda x: x, out_shardings=NamedSharding(pmesh, P()))


@_functools.lru_cache(maxsize=64)
def _sum_rows_fn(pmesh):
    # Half-precision rows accumulate in f32 so the mesh transport matches
    # the native host plane's numerics (csrc reduces in double); the call
    # site's astype(arr.dtype) casts back.  Integer/f32+ sums keep their
    # own dtype — widening them would lose int64 exactness.
    def _sum(x):
        acc = jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) \
            else x.dtype
        return jnp.sum(x, axis=0, dtype=acc)

    return jax.jit(_sum, out_shardings=NamedSharding(pmesh, P()))


@_functools.lru_cache(maxsize=64)
def _reduce_rows_fn(pmesh, kind: str):
    red = {"min": jnp.min, "max": jnp.max}[kind]
    return jax.jit(lambda x: red(x, axis=0),
                   out_shardings=NamedSharding(pmesh, P()))


def _mesh_rows_array(row: np.ndarray):
    """The per-process ``row`` assembled as an ``[nproc, ...]`` global
    array sharded one-row-per-process over the job mesh's backend.
    Assembled from single-device shards: the higher-level constructors
    consult the default backend's process count, which may not be the
    mesh's."""
    pmesh = _process_mesh()
    sharding = NamedSharding(pmesh, P("proc"))
    mine = [d for d in pmesh.devices.flat
            if d.process_index == core.process_rank()]
    shards = [jax.device_put(row[None], d) for d in mine]
    return pmesh, jax.make_array_from_single_device_arrays(
        (pmesh.size,) + row.shape, sharding, shards
    )


def _mesh_allgather_rows(row: np.ndarray) -> np.ndarray:
    """Gather one equal-shape numpy row per process into an
    ``[nproc, ...]`` array, replicated to every process."""
    pmesh, garr = _mesh_rows_array(row)
    return np.asarray(_replicate_fn(pmesh)(garr).addressable_data(0))


def _mesh_sum_rows(row: np.ndarray) -> np.ndarray:
    """Elementwise sum of one row per process, replicated — O(payload)
    wire/memory (an allreduce), unlike the O(nproc x payload) gather."""
    pmesh, garr = _mesh_rows_array(row)
    return np.asarray(_sum_rows_fn(pmesh)(garr).addressable_data(0))


def _mesh_minmax_rows(row: np.ndarray, kind: str) -> np.ndarray:
    """Elementwise min/max of one row per process, replicated."""
    pmesh, garr = _mesh_rows_array(row)
    return np.asarray(_reduce_rows_fn(pmesh, kind)(garr).addressable_data(0))


def broadcast_object(obj: Any, root_rank: int = 0, *, name: Optional[str] = None):
    """Serialize ``obj`` on the root process and broadcast it to all
    controller processes (reference horovod/torch/__init__.py:580-638
    ``broadcast_object``: cloudpickle → byte tensor → size bcast → payload
    bcast).  Single-process: identity."""
    if core.process_size() == 1:
        return obj
    if not _jax_spans_processes():
        c = eager_controller.client()
        if c is None:
            raise RuntimeError(
                "multi-process job without a transport: launch with the "
                "native controller (tpurun --controller native) or "
                "jax.distributed"
            )
        nm = name or eager_controller.next_name("broadcast_object")
        payload = pickle.dumps(obj) if core.process_rank() == root_rank else b""
        return pickle.loads(c.broadcast_data(nm, payload, root_rank=root_rank))
    payload = pickle.dumps(obj) if core.process_rank() == root_rank else b""
    # Two-phase: length first, then fixed-size payload — same shape as the
    # reference's sz tensor broadcast followed by the byte tensor.  Both
    # phases are masked psums (non-root contributes zeros): O(payload)
    # wire/memory per process, vs O(nproc x payload) for a gather.
    n = int(_mesh_sum_rows(np.asarray([len(payload)], np.int64))[0])
    buf = np.zeros(n, np.uint8)
    if core.process_rank() == root_rank:
        buf[:] = np.frombuffer(payload, np.uint8)
    out = _mesh_sum_rows(buf)  # single contributor: exact even in uint8
    return pickle.loads(out.tobytes())


def allgather_object(obj: Any, *, name: Optional[str] = None) -> List[Any]:
    """Gather a picklable object from every controller process (reference
    upstream allgather_object pattern).  Single-process: ``[obj]``."""
    if core.process_size() == 1:
        return [obj]
    if not _jax_spans_processes():
        c = eager_controller.client()
        if c is None:
            raise RuntimeError(
                "multi-process job without a transport: launch with the "
                "native controller (tpurun --controller native) or "
                "jax.distributed"
            )
        nm = name or eager_controller.next_name("allgather_object")
        blobs = c.allgather_data(nm, pickle.dumps(obj))
        return [pickle.loads(b) for b in blobs]
    payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    sizes = _mesh_allgather_rows(
        np.asarray([payload.size], np.int64)
    ).reshape(-1)
    maxlen = int(sizes.max())
    padded = np.zeros(maxlen, np.uint8)
    padded[: payload.size] = payload
    gathered = _mesh_allgather_rows(padded)
    return [
        pickle.loads(gathered[i, : int(sizes[i])].tobytes())
        for i in range(core.process_size())
    ]


# payloads at or above this ride the peer ring (flat per-rank wire volume,
# csrc/ring.cc); below it the coordinator star wins on latency (1 RTT vs
# the ring's negotiate + 2(n-1) hops).  The 32 KB default was measured on
# a core-bound CI host — deployments should calibrate on their own fabric
# (scripts/host_plane_bench.py --crossover) and set HVD_RING_MIN_BYTES /
# tpurun --ring-min-bytes / YAML params.ring_min_bytes.
_RING_MIN_BYTES = env_util.get_int(env_util.HVD_RING_MIN_BYTES, 1 << 15)

_WIRE_OPS = {Average: "allreduce", Sum: "allreduce", Min: "min",
             Max: "max", Adasum: "adasum"}

# dtypes the native coordinator and the XLA process mesh can carry as raw
# numeric payloads; anything else is cast (reductions) or pickled (gathers)
_WIRE_DTYPES = ("float32", "float64", "int32", "int64", "bfloat16", "float16")


def _agree_meta(arr: np.ndarray, nm: str, opname: str) -> List[tuple]:
    """The tiny dtype-agnostic (shape, dtype) allgather every rank runs
    BEFORE a transport branch, returning the gathered shapes.  Transport
    must be chosen from these GATHERED facts — a rank-local decision
    (e.g. keyed on the local dtype) would let mismatched inputs send
    ranks down different collectives and hang the job instead of raising
    (reference coordinator validation, controller.cc:377-610).  Raises
    the same ValueError on every rank for a dtype mismatch; shape rules
    differ per op, so callers check the returned shapes themselves."""
    metas = allgather_object((tuple(arr.shape), str(arr.dtype)),
                             name=f"{nm}.meta")
    dtypes = [m[1] for m in metas]
    if len(set(dtypes)) > 1:
        raise ValueError(f"{opname} dtype mismatch across ranks: {dtypes}")
    return [tuple(m[0]) for m in metas]


def process_allreduce(arr, *, op: str = Average,
                      name: Optional[str] = None) -> np.ndarray:
    """Reduce one numpy array per controller process (host plane).

    The torch/TF/MXNet bindings' cross-process reduction.  Transport
    selection (native-controller jobs): large payloads ride the peer
    ring (csrc/ring.cc — the Gloo-ring analog, reference
    gloo_operations.cc:120-158) under coordinator ordering; small ones
    and Adasum (VHDD tree at the coordinator, csrc/controller.cc
    AdasumReduce) use the star.  jax.distributed pods without the native
    plane fall back to the pickle allgather.  All five reference ops
    (Average/Sum/Adasum/Min/Max, reference torch/mpi_ops.py:103-119)
    keep their real semantics on every path.
    """
    arr = np.asarray(arr)
    if op not in _WIRE_OPS:
        raise ValueError(f"unknown reduction op {op!r}")
    if core.process_size() == 1:
        return arr
    c = eager_controller.client()
    if c is not None:
        wire = arr if str(arr.dtype) in _WIRE_DTYPES \
            else arr.astype(np.float32)
        nm = name or eager_controller.next_name("process_allreduce")
        wire_op = _WIRE_OPS[op]
        rx = eager_controller.ring()
        use_ring = (rx is not None
                    and wire_op in ("allreduce", "min", "max")
                    and wire.nbytes >= _RING_MIN_BYTES)
        # host-plane traffic shows up in the per-rank trace with its
        # transport (the reference timelines its CPU-ops path the same
        # way — MPI_ALLREDUCE spans, timeline.cc activity vocabulary)
        activity = "RING_ALLREDUCE" if use_ring else "STAR_ALLREDUCE"
        with _host_guard(nm, activity, "allreduce",
                         "ring" if use_ring else "star", wire.nbytes):
            if use_ring:
                # RingExecutor copies at submit; no defensive copy here
                out = rx.allreduce(nm, wire, op=wire_op)
            else:
                out = c.allreduce_data(nm, wire, op=wire_op)
        if op == Average:
            out = out / core.process_size()
        return out.astype(arr.dtype) if out.dtype != arr.dtype else out
    # No native controller, so the XLA plane spans the job (jax.distributed
    # pod — the only other transport process_size()>1 can stand on).
    # Reductions ride the process mesh as an O(payload) XLA allreduce —
    # never a pickled O(nproc·payload) gather — matching the reference's
    # CPU path, which is always a Gloo ring/halving-doubling (reference
    # horovod/common/ops/gloo_operations.cc:120-158).
    #
    # The transport branch below keys on dtype, so — exactly like
    # process_allgather — every rank first agrees on (shape, dtype) via
    # a tiny metadata allgather and raises on mismatch; a rank-local
    # branch would let mismatched inputs execute different collectives
    # and hang the job (reference coordinator validation,
    # controller.cc:377-610).
    nm = name or eager_controller.next_name("process_allreduce")
    shapes = _agree_meta(arr, nm, "process_allreduce")
    if len(set(shapes)) > 1:
        raise ValueError(
            f"process_allreduce shape mismatch across ranks: {shapes}"
        )
    if str(arr.dtype) not in _WIRE_DTYPES:
        # exotic dtypes (complex, object...) cannot ride the mesh without
        # a lossy cast; reduce the pickled gather exactly, as before
        stacked = np.stack(
            [np.asarray(g) for g in allgather_object(arr, name=nm)]
        )
        if op == Average:
            out = stacked.mean(0)
        elif op == Sum:
            out = stacked.sum(0)
        elif op == Min:
            out = stacked.min(0)
        elif op == Max:
            out = stacked.max(0)
        else:  # Adasum
            from .ops.adasum import numpy_adasum

            out = numpy_adasum(list(stacked))
        return out.astype(arr.dtype)
    wire = arr  # wire dtype guaranteed by the branch above
    with _host_guard(nm, "MESH_ALLREDUCE", "allreduce", "mesh", wire.nbytes):
        if op in (Average, Sum):
            out = _mesh_sum_rows(wire)
            if op == Average:
                out = out / core.process_size()
        elif op in (Min, Max):
            out = _mesh_minmax_rows(wire, "min" if op == Min else "max")
        else:  # Adasum: VHDD needs every row's dot products, so the
            # O(nproc·payload) gather is inherent — but the transport is
            # the XLA-plane gather, not pickle
            from .ops.adasum import numpy_adasum

            out = numpy_adasum(list(_mesh_allgather_rows(wire)))
    out = np.asarray(out)
    return out.astype(arr.dtype) if out.dtype != arr.dtype else out


def process_allgather(arr, *, name: Optional[str] = None) -> np.ndarray:
    """Concatenate one numpy array per controller process along dim 0 —
    the shared transport bridge behind the torch/TF/MXNet bindings'
    allgather (varying first dimensions allowed; single-process:
    identity).

    Large EQUAL-shape gathers ride the ring (csrc/ring.cc Allgather —
    (n−1)/n of the output per link, vs n× the payload through the
    coordinator): a tiny metadata allgather agrees on shapes first, so
    every rank makes the same transport choice; unequal shapes (the
    allgatherv contract) stay on the pickle star."""
    arr = np.asarray(arr)
    if core.process_size() == 1:
        return arr
    rx = eager_controller.ring()
    c = eager_controller.client()
    nm = name or eager_controller.next_name("process_allgather")
    # Every rank ALWAYS runs the tiny dtype-agnostic metadata allgather
    # and derives the transport from the GATHERED facts — a rank-local
    # decision here (e.g. keyed on the local dtype) would let mismatched
    # inputs send ranks down different branches and hang the job instead
    # of raising.
    shapes = _agree_meta(arr, nm, "process_allgather")
    if len({len(s) for s in shapes}) > 1 or \
            any(s[1:] != shapes[0][1:] for s in shapes):
        raise ValueError(
            "process_allgather shape mismatch across ranks (all dims but "
            f"the first must agree): {shapes}"
        )
    wire_ok = str(arr.dtype) in _WIRE_DTYPES
    equal = all(s == shapes[0] for s in shapes)
    if rx is not None and c is not None and wire_ok and equal \
            and arr.nbytes >= _RING_MIN_BYTES:
        with _host_guard(nm, "RING_ALLGATHER", "allgather", "ring",
                         arr.nbytes):
            return rx.allgather(nm, arr)
    if c is None and wire_ok and len(shapes[0]) >= 1:
        # jax.distributed pod without the native plane: rows ride the
        # process mesh (XLA gather), pickle stays for true objects only.
        # Varying first dims pad to the longest row, then slice back —
        # the allgatherv contract.
        with _host_guard(nm, "MESH_ALLGATHER", "allgather", "mesh",
                         arr.nbytes):
            first = [s[0] for s in shapes]
            maxn = max(first)
            padded = np.zeros((maxn,) + shapes[0][1:], arr.dtype)
            padded[: arr.shape[0]] = arr
            rows = _mesh_allgather_rows(padded)
            return np.concatenate(
                [rows[i, : first[i]] for i in range(len(first))], axis=0
            )
    return np.concatenate(
        [np.asarray(g) for g in allgather_object(arr, name=nm)], axis=0
    )


def process_broadcast(arr, root_rank: int = 0, *,
                      name: Optional[str] = None) -> np.ndarray:
    """Root process's numpy array on every process (single-process:
    identity) — the bindings' shared broadcast bridge.  Non-root values
    are ignored, as before.  Large tensors ride the pipelined ring
    broadcast (csrc/ring.cc Broadcast, O(payload) per link): a tiny
    pickled metadata broadcast ships ROOT's (shape, dtype, nbytes) first,
    so every rank makes the same transport choice and lays out its
    receive buffer in root's type — local placeholder values can't
    diverge the ranks.  Small ones pickle through the coordinator."""
    arr = np.asarray(arr)
    if core.process_size() == 1:
        return arr
    rx = eager_controller.ring()
    if rx is None:
        return np.asarray(
            broadcast_object(arr, root_rank=root_rank, name=name)
        )
    nm = name or eager_controller.next_name("process_broadcast")
    shape, dtype_s, nbytes = broadcast_object(
        (arr.shape, str(arr.dtype), arr.nbytes),
        root_rank=root_rank, name=f"{nm}.meta",
    )
    if nbytes < _RING_MIN_BYTES:
        return np.asarray(
            broadcast_object(arr, root_rank=root_rank, name=nm)
        )
    if core.process_rank() == root_rank:
        buf = np.array(arr, copy=True)
    else:
        if dtype_s == "bfloat16":  # not a plain-numpy dtype name
            import ml_dtypes

            dt = np.dtype(ml_dtypes.bfloat16)
        else:
            dt = np.dtype(dtype_s)
        buf = np.zeros(shape, dt)
    with _host_guard(nm, "RING_BROADCAST", "broadcast", "ring", nbytes):
        return rx.broadcast(nm, buf, root_rank)


def normalize_op(average, op):
    """The reference's handle_average_backwards_compatibility
    (torch/mpi_ops.py): exactly one of average/op; default Average."""
    if average is not None and op is not None:
        raise ValueError("cannot specify both average and op")
    if op is not None:
        return op
    if average is False:
        return Sum
    return Average
