"""Training-loop callbacks.

Re-design of the Keras callback set (reference horovod/_keras/callbacks.py:
``BroadcastGlobalVariablesCallbackImpl`` (:21-45), ``MetricAverageCallback``
(:46-60), ``LearningRateWarmupCallback`` / ``LearningRateScheduleCallback``;
exposed via horovod/keras/callbacks.py) for flax/optax training loops.

There's no Keras model object; callbacks hold the same *semantics* against
a (state, metrics) training loop, and the LR policies are also exposed as
optax schedules (the idiomatic carrier).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import numpy as np

from . import core
from . import eager
from .optim.distributed import broadcast_parameters


class Callback:
    """Minimal protocol: wire into your loop where Keras would call these."""

    def on_train_begin(self, state):  # noqa: B027
        return state

    def on_epoch_end(self, epoch: int, state, metrics: Dict[str, float]):
        return metrics

    def on_batch_end(self, step: int, state):  # noqa: B027
        return state


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast initial state from root at train start (reference
    _keras/callbacks.py:21-45; ensures consistent init / checkpoint
    restore across workers)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_train_begin(self, state):
        state = broadcast_parameters(state, self.root_rank)
        self.broadcast_done = True
        return state


class MetricAverageCallback(Callback):
    """Average epoch metrics over all workers before reporting (reference
    _keras/callbacks.py:46-60: allreduce each logged metric at epoch end)."""

    def on_epoch_end(self, epoch, state, metrics):
        if core.process_size() == 1:
            return dict(metrics)
        gathered = eager.allgather_object(metrics)
        out: Dict[str, float] = {}
        for k in metrics:
            out[k] = float(np.mean([m[k] for m in gathered]))
        return out


class LearningRateWarmupCallback(Callback):
    """Gradual LR warmup from lr to lr*multiplier over warmup_epochs
    (reference _keras/callbacks.py LearningRateWarmupCallback, implementing
    the Goyal et al. linear-scaling warmup).  ``lr(step)`` gives the
    current value; ``as_optax_schedule`` returns the equivalent schedule."""

    def __init__(self, initial_lr: float, multiplier: float,
                 warmup_epochs: float = 5, steps_per_epoch: int = 1,
                 verbose: bool = False):
        self.initial_lr = initial_lr
        self.multiplier = multiplier
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose

    def lr(self, step: int) -> float:
        total = self.warmup_epochs * self.steps_per_epoch
        if step >= total:
            return self.initial_lr * self.multiplier
        frac = step / max(total, 1)
        return self.initial_lr * (
            1.0 + frac * (self.multiplier - 1.0)
        )

    def as_optax_schedule(self) -> Callable[[Any], Any]:
        import jax.numpy as jnp

        total = self.warmup_epochs * self.steps_per_epoch

        def schedule(count):
            frac = jnp.minimum(count / max(total, 1), 1.0)
            return self.initial_lr * (1.0 + frac * (self.multiplier - 1.0))

        return schedule


class LearningRateScheduleCallback(Callback):
    """Multiplier schedule over epoch ranges (reference
    _keras/callbacks.py LearningRateScheduleCallback)."""

    def __init__(self, initial_lr: float, multiplier,
                 start_epoch: int = 0, end_epoch: Optional[int] = None,
                 staircase: bool = True, steps_per_epoch: int = 1):
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self.multiplier = (
            multiplier if callable(multiplier) else (lambda epoch: multiplier)
        )

    def lr(self, step: int) -> float:
        epoch = step / max(self.steps_per_epoch, 1)
        if self.staircase:
            epoch = math.floor(epoch)
        if epoch < self.start_epoch:
            return self.initial_lr
        if self.end_epoch is not None and epoch >= self.end_epoch:
            return self.initial_lr
        return self.initial_lr * self.multiplier(epoch)
