"""Synthetic 2-rank fixture trace with a hand-computed critical path.

The replay engine's ground truth: a trace small enough to schedule by
hand, used by ``scripts/hvd_replay.py --check`` (the tier-1 smoke) and
the unit tests.  The step, on the ALIGNED clock (rank 1's raw
timestamps are shifted −25 µs and its ``clock_sync.json`` carries
``offset_us=+25`` — alignment itself is under test):

::

    rank 0:  [compute 100][ wait 200        ][comm 50][compute 100]
    rank 1:  [compute 300 (straggler)       ][comm 50][compute  50]
             0         100                  300      350   400   450

* both ranks negotiate tensor ``g0`` (ALLREDUCE, 4 MiB: f32[1024,1024]
  from tensor_shapes.json); rank 0 arrives at 100, rank 1 at 300 — the
  collective starts at 300, so rank 0 waits 200 µs;
* hand-computed critical path: rank 1's 300 µs compute → the 50 µs
  collective → rank 0's 100 µs tail compute = **450 µs** makespan;
* hand-computed "remove straggler rank 1" what-if: rank 1's leading
  segment clamps to rank 0's 100 µs, the collective starts at 100,
  rank 0's tail ends at 100+50+100 = **250 µs**;
* hand-computed attribution: rank 0 {compute 200, comm 50,
  negotiation 200, idle 0}; rank 1 {compute 350, comm 50,
  negotiation 0, idle 50}.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

from ..recorder import structure_dag, write_gml

TENSOR = "g0"
SHAPE = [1024, 1024]                     # f32 → 4 MiB payload
STEP_NO = 1

#: hand-computed ground truth asserted by --check and the tests
EXPECTED: Dict[str, object] = {
    "makespan_us": 450.0,
    "critical_path": [
        {"kind": "compute", "rank": 1, "dur_us": 300.0},
        {"kind": "comm", "tensor": TENSOR, "dur_us": 50.0},
        {"kind": "compute", "rank": 0, "dur_us": 100.0},
    ],
    "remove_straggler_us": 250.0,
    "straggler_rank": 1,
    "attribution": {
        "0": {"compute_us": 200.0, "comm_us": 50.0,
              "negotiation_us": 200.0, "idle_us": 0.0},
        "1": {"compute_us": 350.0, "comm_us": 50.0,
              "negotiation_us": 0.0, "idle_us": 50.0},
    },
    "tensor_bytes": 1024 * 1024 * 4,
}


def _events_rank0() -> List[dict]:
    t = TENSOR
    return [
        {"name": "NEGOTIATE_ALLREDUCE", "cat": t, "ph": "B", "ts": 100.0,
         "pid": 0, "tid": t},
        {"name": "NEGOTIATE_ALLREDUCE", "cat": t, "ph": "E", "ts": 300.0,
         "pid": 0, "tid": t},
        {"name": "ALLREDUCE", "cat": t, "ph": "X", "ts": 300.0,
         "dur": 50.0, "pid": 0, "tid": t},
        {"name": "STEP", "cat": f"step_{STEP_NO}", "ph": "X", "ts": 0.0,
         "dur": 450.0, "pid": 0, "tid": "step"},
    ]


def _events_rank1() -> List[dict]:
    # raw timestamps 25 µs BEHIND the aligned clock; clock_sync.json says
    # offset_us=+25, so merge/stitch shifts them back onto the shared one
    t = TENSOR
    off = -25.0
    return [
        {"name": "NEGOTIATE_ALLREDUCE", "cat": t, "ph": "B",
         "ts": 300.0 + off, "pid": 1, "tid": t},
        {"name": "NEGOTIATE_ALLREDUCE", "cat": t, "ph": "E",
         "ts": 300.0 + off, "pid": 1, "tid": t},
        {"name": "ALLREDUCE", "cat": t, "ph": "X", "ts": 300.0 + off,
         "dur": 50.0, "pid": 1, "tid": t},
        {"name": "STEP", "cat": f"step_{STEP_NO}", "ph": "X",
         "ts": 0.0 + off, "dur": 400.0, "pid": 1, "tid": "step"},
    ]


#: --- projection ground truth (hvd_replay --project --check) ---------------
#:
#: The digital twin projected from the SAME 2-rank trace, hand-computed
#: (timeline/replay/projection.py, distribution mode, default α–β:
#: hop 1 µs, ICI 186 GB/s, DCN 25 GB/s / 10 µs):
#:
#: * **identity (world 2)**: nothing changes — 450.0 µs, bit-equal to
#:   the replay baseline (the regression anchor);
#: * **2× (world 4)**: ranks 0/2 get rank 0's chain, ranks 1/3 get
#:   rank 1's.  The collective re-prices with the calibrated split:
#:   α₂ = 2·(2−1)·1 = 2 µs, β_cal = 50 − 2 = 48 µs; link volume scales
#:   by [2·3/4] / [2·1/2] = 1.5 → β₄ = 72 µs; α₄ = 2·(4−1)·1 = 6 µs →
#:   comm = **78 µs**.  Readiness still gates at 300 (ranks 1/3), so
#:   the makespan = 300 + 78 + 100 = **478 µs** (efficiency 450/478 =
#:   0.9414);
#: * **world 6, local 2 × cross 3, two_level=on**: the flat measurement
#:   carries no tier split, so the collective is pure model
#:   (predict_collective_us two-level shape): local RS + AG on ICI =
#:   2 × 2 MiB/186 GB/s = 22.550 µs + 2 hops = 2 µs; cross all-reduce
#:   on the 2 MiB shard over DCN = (2·⅔·2 MiB)/25 GB/s = 111.848 µs +
#:   4 hops × 10 µs = 40 µs → comm = **176.398 µs**; makespan =
#:   300 + 176.398 + 100 = **576.398 µs**.
PROJECTION_EXPECTED: Dict[str, object] = {
    "identity_us": 450.0,
    "world4_us": 478.0,
    "world4_comm_us": 78.0,
    "world4_efficiency": 0.9414,
    "world6_local2_us": 576.398,
    "world6_comm_us": 176.398,
    "hop_latency_us": 1.0,
}


#: --- autotune ground truth (scripts/hvd_autotune.py --check) -------------
#:
#: A second hand-computed 2-rank trace, symmetric across ranks (no
#: straggler, no clock skew) so the interesting structure is entirely in
#: the fusion/overlap economics.  Three gradients, hop latency 10 µs
#: (α = 2 hops × 10 = 20 µs per 2-rank ring all-reduce), calibrated
#: β = measured − α:
#:
#: ::
#:
#:     both ranks:  [A 100][g0 120][B 80][g1 50][C 20][g2 50][tail 20]
#:                  0     100     220   300    350   370    420    440
#:
#: Two-thread replay (compute thread ∥ one serialized comm channel):
#: computes run back-to-back (A 0–100, B 100–180, C 180–200, tail
#: 200–220) and each bucket launches at max(its fill time, channel
#: free):
#:
#: * 3 buckets (no fusion):   g0 100→220, g1 220→270, g2 270→320 → 320
#: * 2 buckets {g0},{g1,g2}:  g0 100→220, {g1,g2} = α20+β60 = 80,
#:   220→300 → **300 µs** (the uncompressed optimum)
#: * 1 bucket  {g0,g1,g2}:    fills at 200, α20+β160 = 180 → 380
#: * fuse_all_comm (serial):  200 compute + 180 bucket + 20 tail = 400
#: * overlap_comm (free channels, unimplementable upper bound): 250
#:
#: Wire-efficiency tier (comm_report.COMPRESSION_MODEL constants:
#: int8 ¼β + 1 µs/MiB qd + one scale-exchange α; fp8 ¼β + 1.5 µs/MiB
#: + scale α; bf16 ½β + 0.5 µs/MiB, no scale) on the 2-bucket
#: partition — g0 is 4 MiB f32 (β_cal 100), {g1,g2} 0.5 MiB (β 60):
#:
#: * bucket {g0}:      none 120 | int8 20+25+4+20 = **69** |
#:   fp8 20+25+6+20 = 71 | bf16 20+50+2 = 72
#: * bucket {g1,g2}:   none 80 | int8 20+15+0.5+20 = 55.5 |
#:   fp8 55.75 | bf16 20+30+0.25 = **50.25**
#: * chosen plan [int8, bf16]: g0 100→169, {g1,g2} fills 200,
#:   200→250.25 → **250.25 µs** (the staged optimum — int8 on the
#:   largest gradient, cast-only bf16 on the small bucket where the
#:   scale-exchange α would not pay)
#: * whole-wire compress_int8 (serial replay): 220 compute +
#:   69+47.75+47.75 = **384.5**
AUTOTUNE_TENSORS = ("g0", "g1", "g2")
AUTOTUNE_SHAPES = {"g0": [1024, 1024], "g1": [256, 256], "g2": [256, 256]}
AUTOTUNE_STEP_NO = 1
AUTOTUNE_HOP_US = 10.0

AUTOTUNE_EXPECTED: Dict[str, object] = {
    "baseline_us": 440.0,
    "optimal_num_buckets": 2,
    "optimal_buckets": [["g0"], ["g1", "g2"]],
    # uncompressed bucket economics (the bucket_search table rows)
    "uncompressed_step_us": 300.0,
    "uncompressed_speedup_pct": 31.82,
    "bucket_search_us": {1: 380.0, 2: 300.0, 3: 320.0},
    # the staged wire-format choice on the winning partition — the plan
    # the closed loop must recover END TO END: int8 on the largest
    # gradient, bf16 on the small bucket (hand math in the block above)
    "optimal_compression": ["int8", "bf16"],
    "predicted_step_us": 250.25,
    "predicted_speedup_pct": 43.12,
    "compress_int8_us": 384.5,
    "fuse_all_us": 400.0,
    "overlap_us": 250.0,
    "hop_latency_us": AUTOTUNE_HOP_US,
    "tensor_bytes": {"g0": 1024 * 1024 * 4, "g1": 256 * 256 * 4,
                     "g2": 256 * 256 * 4},
}


def _autotune_events() -> List[dict]:
    """One rank's step (both ranks are identical): serial comm blocks the
    host, negotiation is instantaneous (B == E == span start)."""
    evs: List[dict] = [
        {"name": "STEP", "cat": f"step_{AUTOTUNE_STEP_NO}", "ph": "X",
         "ts": 0.0, "dur": 440.0, "tid": "step"},
    ]
    for tensor, ts, dur in (("g0", 100.0, 120.0), ("g1", 300.0, 50.0),
                            ("g2", 370.0, 50.0)):
        evs += [
            {"name": "NEGOTIATE_ALLREDUCE", "cat": tensor, "ph": "B",
             "ts": ts, "tid": tensor},
            {"name": "NEGOTIATE_ALLREDUCE", "cat": tensor, "ph": "E",
             "ts": ts, "tid": tensor},
            {"name": "ALLREDUCE", "cat": tensor, "ph": "X", "ts": ts,
             "dur": dur, "tid": tensor},
        ]
    return evs


def write_autotune_fixture_trace(trace_dir: str) -> Dict[str, object]:
    """Materialize the autotune ground-truth trace (both ranks identical,
    offsets 0) and return :data:`AUTOTUNE_EXPECTED`."""
    names = list(AUTOTUNE_TENSORS)
    for rank in (0, 1):
        d = os.path.join(trace_dir, str(rank))
        os.makedirs(d, exist_ok=True)
        evs = [dict(ev, pid=rank) for ev in _autotune_events()]
        with open(os.path.join(d, "comm.json"), "w") as f:
            json.dump(evs, f, indent=1)
        with open(os.path.join(d, "clock_sync.json"), "w") as f:
            json.dump({"offset_us": 0.0, "rtt_us": 4.0, "samples": 8,
                       "rank": rank, "method": "fixture"}, f, indent=1)
        with open(os.path.join(d, "tensor_shapes.json"), "w") as f:
            json.dump(AUTOTUNE_SHAPES, f, indent=1)
        with open(os.path.join(d, "tensor_dtypes.json"), "w") as f:
            json.dump({t: "float32" for t in names}, f, indent=1)
        with open(os.path.join(d, "gradient_name_list.json"), "w") as f:
            json.dump(names, f, indent=1)
        with open(os.path.join(d, "metadata.json"), "w") as f:
            json.dump({"rank": rank, "size": 2,
                       "model": "autotune-fixture"}, f, indent=1)
        nodes, edges = structure_dag(names)
        write_gml(nodes, edges, os.path.join(d, "dag.gml"))
    return dict(AUTOTUNE_EXPECTED)


def write_fixture_trace(trace_dir: str) -> Dict[str, object]:
    """Materialize the fixture (comm.json + clock_sync.json +
    tensor_shapes/dtypes + gradient manifest + dag.gml + metadata per
    rank) and return :data:`EXPECTED`."""
    events = {0: _events_rank0(), 1: _events_rank1()}
    offsets = {0: 0.0, 1: 25.0}
    for rank in (0, 1):
        d = os.path.join(trace_dir, str(rank))
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "comm.json"), "w") as f:
            json.dump(events[rank], f, indent=1)
        with open(os.path.join(d, "clock_sync.json"), "w") as f:
            json.dump({"offset_us": offsets[rank], "rtt_us": 8.0,
                       "samples": 8, "rank": rank,
                       "method": "fixture"}, f, indent=1)
        with open(os.path.join(d, "tensor_shapes.json"), "w") as f:
            json.dump({TENSOR: SHAPE}, f, indent=1)
        with open(os.path.join(d, "tensor_dtypes.json"), "w") as f:
            json.dump({TENSOR: "float32"}, f, indent=1)
        with open(os.path.join(d, "gradient_name_list.json"), "w") as f:
            json.dump([TENSOR], f, indent=1)
        with open(os.path.join(d, "metadata.json"), "w") as f:
            json.dump({"rank": rank, "size": 2, "model": "fixture"},
                      f, indent=1)
        nodes, edges = structure_dag([TENSOR])
        write_gml(nodes, edges, os.path.join(d, "dag.gml"))
    return dict(EXPECTED)
