"""dPRO-style replay engine over merged byteprofile traces.

The capture side of this repo (per-rank ``comm.json`` timelines, the
Recorder's DAG/shape/manifest dumps, PR 1's cross-rank merge) exists so
this layer can exist: fuse every rank's artifacts into one clock-aligned
global DAG per step, find the critical path, and answer "what would
fixing X buy me?" by replaying the DAG under modified assumptions
(Hu et al., *dPRO*, MLSys 2022).

Modules:

* :mod:`~horovod_tpu.timeline.replay.clock` — offset-estimation
  handshake against the rendezvous server's ``GET /clock``;
* :mod:`~horovod_tpu.timeline.replay.stitcher` — global step DAG from
  merged comm events joined to ``dag.gml`` / gradient-manifest nodes;
* :mod:`~horovod_tpu.timeline.replay.critical_path` — discrete-event
  schedule, clock-aligned critical path, {compute, negotiation, comm,
  idle} attribution;
* :mod:`~horovod_tpu.timeline.replay.simulator` — what-if scenarios
  (bandwidth, straggler removal, overlap, fusion re-batching) priced
  with the comm_report α–β cost model;
* :mod:`~horovod_tpu.timeline.replay.projection` — the fleet-scale
  digital twin: re-materialize the stitched DAG onto a hypothetical
  topology (``hvd_replay --project``) with tracked
  projected-vs-measured accuracy;
* :mod:`~horovod_tpu.timeline.replay.fixture` — the hand-computed
  2-rank ground-truth trace.

``analyze(trace_dir)`` is the one-call driver behind
``scripts/hvd_replay.py`` and the rendezvous server's ``GET /replay``.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

from ...utils import env as env_util
from ..comm_report import per_tensor_table
from .clock import estimate_offset  # noqa: F401  (public API)
from .critical_path import (  # noqa: F401
    Schedule, attribute, critical_path, describe_path, schedule,
)
from .simulator import CostModel, identify_straggler, what_if
from .stitcher import Artifacts, StepDAG, stitch  # noqa: F401

#: pid used for the synthetic "critical path" track in annotated traces
CRITICAL_PATH_PID = 9999


@dataclasses.dataclass
class ReplayResult:
    """Summary (JSON-ready) plus the internals the CLI's annotated-trace
    writer and the tests reach into."""

    summary: dict
    artifacts: Artifacts
    dags: List[StepDAG]
    schedules: Dict[int, Schedule]


def _cost_model_from_env(world: int) -> CostModel:
    return CostModel(
        world=world,
        ici_bytes_per_sec=env_util.get_float(
            env_util.HVD_REPLAY_ICI_GBPS, 186.0) * 1e9,
        hop_latency_us=env_util.get_float(env_util.HVD_REPLAY_HOP_US, 1.0),
        # two-level what-if shape: the job's real ICI group size unless
        # overridden (HVD_LOCAL_SIZE is launcher-set; 1 = no hierarchy,
        # scenario skipped)
        local_size=env_util.get_int(
            env_util.HVD_REPLAY_LOCAL_SIZE,
            env_util.get_int(env_util.HVD_LOCAL_SIZE, 1)),
        dcn_bytes_per_sec=env_util.get_float(
            env_util.HVD_REPLAY_DCN_GBPS,
            env_util.DEFAULT_DCN_GBPS) * 1e9,
        dcn_hop_latency_us=env_util.get_float(
            env_util.HVD_REPLAY_DCN_HOP_US, env_util.DEFAULT_DCN_HOP_US),
    )


def analyze(trace_dir: str, *, step: Optional[int] = None,
            last_steps: Optional[int] = None,
            cost_model: Optional[CostModel] = None,
            plan_search: bool = True,
            topology=None) -> ReplayResult:
    """Stitch ``trace_dir``, replay every step (or just ``step``), and
    assemble the summary: per-step critical path + attribution +
    ranked what-ifs, a per-tensor cost-model table (predicted vs
    measured, via comm_report.per_tensor_table — the SAME α–β model the
    what-ifs use), and cross-step recommendations.

    ``last_steps`` replays only the N most recent steps — the in-job
    profile-guided tuner passes 1: SPMD steps share one DAG shape, so
    the latest step's plan stands for all, and a window-cadence caller
    must not pay a whole-history replay (incl. the per-step bucket
    search) that grows with the trace."""
    art, dags = stitch(trace_dir,
                       last_steps=last_steps if step is None else None)
    if step is not None:
        dags = [d for d in dags if d.step == step]
        if not dags:
            raise ValueError(f"step {step} not present on every rank "
                             f"under {trace_dir}")
    if not dags:
        raise ValueError(
            f"no replayable step found under {trace_dir} — need matching "
            "STEP windows (or any events) on every rank"
        )
    cm = cost_model or _cost_model_from_env(len(art.ranks))
    steps_out = []
    scheds: Dict[int, Schedule] = {}
    recommendations: List[dict] = []
    for dag in dags:
        sched = schedule(dag)
        scheds[dag.step] = sched
        path = critical_path(dag, sched)
        attr = attribute(dag, sched)
        wi = what_if(dag, cm, plan_search=plan_search, topology=topology)
        measured = dag.measured_step_us
        # aggregate per tensor: a tensor collected k times in the step
        # (microbatch accumulation) contributes k calls and k measured
        # durations — collapsing to the last occurrence would price the
        # what-ifs against a fraction of the real traffic
        tensors: Dict[str, dict] = {}
        measured_comm: Dict[str, float] = {}
        for n in dag.nodes:
            if n.kind != "comm":
                continue
            key = n.tensor or n.label
            t = tensors.setdefault(key, {"op": n.op, "bytes": 0,
                                         "calls": 0})
            t["bytes"] += n.nbytes or 0
            t["calls"] += 1
            measured_comm[key] = measured_comm.get(key, 0.0) + n.dur_us
        cost_table = per_tensor_table(
            tensors, cm.world, measured_us=measured_comm,
            ici_bytes_per_sec=cm.ici_bytes_per_sec,
            ici_hop_latency=cm.hop_latency_us * 1e-6)
        steps_out.append({
            "step": dag.step,
            "ranks": sorted(dag.chains),
            "measured_step_us": round(measured, 3),
            "replay_step_us": round(sched.makespan, 3),
            "replay_error_pct": round(
                (sched.makespan - measured) / measured * 100.0, 2)
            if measured > 0 else None,
            "critical_path": describe_path(dag, sched, path),
            "attribution": attr,
            "cost_model_table": cost_table,
            "what_if": wi,
        })
        for s in wi["scenarios"]:
            recommendations.append(dict(s, step=dag.step))
    recommendations.sort(key=lambda s: -s["speedup_pct"])
    summary = {
        "trace_dir": art.trace_dir,
        "ranks": art.ranks,
        "clock_aligned": art.clock_aligned,
        "clock_offsets_us": {str(r): round(o, 3)
                             for r, o in art.clock_offsets_us.items()},
        "steps": steps_out,
        "recommendations": recommendations,
    }
    return ReplayResult(summary=summary, artifacts=art, dags=dags,
                        schedules=scheds)


def _merged_from_artifacts(art: Artifacts) -> dict:
    """merge_traces-shaped dict from already-loaded (aligned) events —
    the stitcher parsed every comm.json once; re-reading hundreds of MB
    for the annotated trace would double the run's parse cost."""
    events: List[dict] = []
    for rank in art.ranks:
        events.append({"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": f"rank {rank}"}})
        events.append({"name": "process_sort_index", "ph": "M",
                       "pid": rank, "args": {"sort_index": rank}})
        for ev in art.events[rank]:
            ev = dict(ev)
            ev["pid"] = rank
            events.append(ev)
    return {"traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"source": "hvd_replay",
                          "trace_dir": art.trace_dir,
                          "clock_aligned": art.clock_aligned,
                          "clock_offsets_us": {
                              str(r): round(o, 3)
                              for r, o in art.clock_offsets_us.items()}}}


def annotated_trace(trace_dir: str, result: Optional[ReplayResult] = None,
                    out_path: Optional[str] = None) -> dict:
    """The merged Chrome trace plus a synthetic ``critical path`` track:
    one X event per critical-path node (placed at its *scheduled* time on
    the aligned clock) so chrome://tracing shows the determining chain as
    its own row group above the per-rank rows."""
    result = result or analyze(trace_dir)
    merged = _merged_from_artifacts(result.artifacts)
    events = merged["traceEvents"]
    events.append({"name": "process_name", "ph": "M",
                   "pid": CRITICAL_PATH_PID,
                   "args": {"name": "critical path (replay)"}})
    events.append({"name": "process_sort_index", "ph": "M",
                   "pid": CRITICAL_PATH_PID, "args": {"sort_index": -1}})
    for dag in result.dags:
        sched = result.schedules[dag.step]
        for i, row in enumerate(
                describe_path(dag, sched, critical_path(dag, sched))):
            who = f"rank {row['rank']}" if row["rank"] is not None \
                else ",".join(str(r) for r in row["ranks"] or ())
            name = f"CP{i}:{row['kind']}"
            if row["tensor"]:
                name += f":{row['tensor']}"
            events.append({
                "name": name, "ph": "X",
                "ts": dag.t0_us + row["start_us"], "dur": row["dur_us"],
                "pid": CRITICAL_PATH_PID, "tid": f"step {dag.step}",
                "args": {"kind": row["kind"], "who": who,
                         "label": row["label"]},
            })
    merged["otherData"]["critical_path"] = "pid %d" % CRITICAL_PATH_PID
    if out_path:
        d = os.path.dirname(out_path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(merged, f)
    return merged


# the digital-twin projection plane (imported last: projection builds on
# analyze/_cost_model_from_env above)
from .projection import (  # noqa: E402,F401
    base_spec_from_env, live_validation, parse_project_spec,
    project_analysis, project_dag, validate as validate_projection,
)
from ..comm_report import TopologySpec  # noqa: E402,F401  (public API)
