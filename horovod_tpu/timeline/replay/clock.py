"""Clock-offset estimation against the rendezvous server.

The per-rank timelines timestamp events with each process's own
monotonic clock (``time.perf_counter`` relative to the Timeline's
origin) — fine for one rank, useless across ranks: a merged trace built
from raw timestamps can show a collective "ending" on one rank before it
"started" on another, and a cross-rank critical path built on such a
trace is fiction.  dPRO solves this with clock synchronization before
replay (Hu et al., MLSys 2022, §3.1); the classic transport is NTP's
four-timestamp exchange.

Here the job already has one shared, always-up endpoint: the launcher's
rendezvous server.  ``GET /clock`` (run/http_server.py) returns the
server's monotonic clock; each rank samples it a few times and keeps the
minimum-RTT sample — the one whose midpoint approximation is least
polluted by queueing — estimating::

    offset_us = server_us - (t0 + t1) / 2        # local → server clock

``Timeline.initialize`` runs this handshake once per trace and persists
the result as ``<dir>/<rank>/clock_sync.json``; ``merge_traces`` shifts
each rank's events by its offset so the whole job shares the server's
clock.  The error bound is ±rtt/2 — LAN round trips are tens of µs,
far below the negotiation skews (hundreds of µs to ms) the replay
engine attributes.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional


def _default_clock_us() -> float:
    return time.perf_counter() * 1e6


def sample_offset(addr: str, port: int,
                  secret: Optional[bytes] = None,
                  local_clock_us: Optional[Callable[[], float]] = None,
                  timeout: float = 2.0) -> Dict[str, float]:
    """One handshake leg: ``{"offset_us", "rtt_us"}`` for a single
    ``GET /clock`` round trip, midpoint-approximated."""
    from ...run.http_client import get_clock

    clock = local_clock_us or _default_clock_us
    t0 = clock()
    server_us = get_clock(addr, port, secret=secret, timeout=timeout)
    t1 = clock()
    return {
        "offset_us": server_us - (t0 + t1) / 2.0,
        "rtt_us": t1 - t0,
    }


def estimate_offset(addr: str, port: int,
                    secret: Optional[bytes] = None,
                    samples: int = 8,
                    local_clock_us: Optional[Callable[[], float]] = None,
                    timeout: float = 2.0) -> Dict[str, float]:
    """Best-of-N offset estimate: run ``samples`` handshake legs and
    keep the minimum-RTT one (its midpoint assumption has the least
    queueing asymmetry to hide behind).  Raises on total failure —
    callers (Timeline.initialize) treat the handshake as best-effort."""
    samples = max(1, int(samples))
    best: Optional[Dict[str, float]] = None
    failures = 0
    last_err: Optional[Exception] = None
    for _ in range(samples):
        try:
            s = sample_offset(addr, port, secret=secret,
                              local_clock_us=local_clock_us,
                              timeout=timeout)
        except Exception as e:  # noqa: BLE001 — count, keep sampling
            failures += 1
            last_err = e
            if best is None and failures >= 2:
                # server unreachable, not flaky: don't burn the full
                # N×timeout budget inside every rank's initialize
                break
            continue
        if best is None or s["rtt_us"] < best["rtt_us"]:
            best = s
    if best is None:
        raise RuntimeError(
            f"clock handshake failed: {samples} samples, last error: "
            f"{last_err}"
        )
    return {
        "offset_us": best["offset_us"],
        "rtt_us": best["rtt_us"],
        "samples": samples - failures,
        "method": "min-rtt midpoint vs rendezvous GET /clock",
    }
