"""Discrete-event scheduling + critical path over a stitched StepDAG.

The replay core: given the stitcher's global DAG, compute when every
node runs under the chosen assumptions, which chain of nodes actually
determined the step time (the clock-aligned critical path), and where
each rank's share of the step went — ``{compute, comm, negotiation,
idle}``, the dPRO attribution.

Semantics:

* every node's start is the max over its predecessors' ends (plus its
  rank's step-start skew floor); a global comm node therefore starts
  when the LAST participating rank arrives — negotiation waits are an
  *output* of the schedule, not an input;
* by default a rank's chain is fully serial (comm blocks the host, which
  is what the measured trace shows); ``overlap=True`` rebuilds edges so
  comm nodes stop occupying their ranks' serial threads and only gate
  the end of step — the "perfect overlap" what-if;
* ``dur_overrides`` / ``base_overrides`` let scenarios re-cost nodes
  (bandwidth scaling, straggler removal) without mutating the DAG.

The critical path is recovered by walking back from the sink through
each node's *determining* predecessor (the one whose end equals the
node's start).  By construction the path has no internal waiting: every
µs of the step's makespan is attributed to some node on it.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from .stitcher import StepDAG

_EPS = 1e-6


@dataclasses.dataclass
class Schedule:
    start: Dict[int, float]
    end: Dict[int, float]
    dur: Dict[int, float]
    preds: Dict[int, List[int]]
    sink: int
    makespan: float
    rank_end: Dict[int, int]        # rank -> its end-sentinel node id
    overlap: bool


def build_edges(dag: StepDAG, overlap: bool = False,
                extra_preds: Optional[Dict[int, List[int]]] = None,
                ) -> Tuple[Dict[int, List[int]], Dict[int, int], int]:
    """``(preds, rank_end_sentinels, sink)`` — sentinel ids live past
    ``len(dag.nodes)`` and have zero duration.

    ``extra_preds`` merges additional dependency edges into the derived
    set — the simulator's comm-CHANNEL serialization: under
    ``overlap=True`` collectives stop blocking host threads, but a real
    ICI domain still runs one collective at a time, so the bucketed
    what-ifs chain their bucket nodes here (bucket ``i+1`` cannot enter
    the wire before bucket ``i`` leaves it)."""
    preds: Dict[int, List[int]] = {n.nid: [] for n in dag.nodes}
    next_id = len(dag.nodes)
    rank_end: Dict[int, int] = {}

    for rank, chain in dag.chains.items():
        prev: Optional[int] = None      # last node holding the serial thread
        comms: List[int] = []
        for nid in chain:
            node = dag.nodes[nid]
            if node.kind == "comm":
                comms.append(nid)
                # readiness edge from this rank's chain position
                rp = dag.ready_pred.get(nid, {}).get(rank)
                if rp is not None:
                    preds[nid].append(rp)
                if not overlap:
                    prev = nid          # comm blocks the host thread
                # overlap: prev stays the preceding compute — the next
                # compute segment no longer waits for the collective
            else:
                if prev is not None:
                    preds[nid].append(prev)
                elif overlap and comms:
                    pass                # chain starts with comm: floor only
                prev = nid
        end_id = next_id
        next_id += 1
        rank_end[rank] = end_id
        preds[end_id] = []
        if prev is not None:
            preds[end_id].append(prev)
        if overlap:
            # the step still needs every collective result
            preds[end_id].extend(c for c in comms
                                 if c not in preds[end_id])
    sink = next_id
    preds[sink] = list(rank_end.values())
    if extra_preds:
        for nid, ps in extra_preds.items():
            cur = preds.setdefault(nid, [])
            cur.extend(p for p in ps if p not in cur)
    return preds, rank_end, sink


def schedule(dag: StepDAG, *, overlap: bool = False,
             dur_overrides: Optional[Dict[int, float]] = None,
             base_overrides: Optional[Dict[int, float]] = None,
             extra_preds: Optional[Dict[int, List[int]]] = None) -> Schedule:
    """Kahn-order discrete-event pass over the DAG."""
    preds, rank_end, sink = build_edges(dag, overlap=overlap,
                                        extra_preds=extra_preds)
    durs = {n.nid: n.dur_us for n in dag.nodes}
    if dur_overrides:
        durs.update(dur_overrides)
    for sid in list(rank_end.values()) + [sink]:
        durs[sid] = 0.0
    bases = dict(dag.rank_base_us)
    if base_overrides:
        bases.update(base_overrides)

    def floor(nid: int) -> float:
        if nid < len(dag.nodes):
            node = dag.nodes[nid]
            if node.rank is not None:
                return bases.get(node.rank, 0.0)
            if node.kind == "comm" and node.ranks:
                return max(bases.get(r, 0.0) for r in node.ranks)
        return 0.0

    succs: Dict[int, List[int]] = {nid: [] for nid in preds}
    indeg: Dict[int, int] = {nid: len(ps) for nid, ps in preds.items()}
    for nid, ps in preds.items():
        for p in ps:
            succs[p].append(nid)
    ready = [nid for nid, d in indeg.items() if d == 0]
    start: Dict[int, float] = {}
    end: Dict[int, float] = {}
    done = 0
    while ready:
        nid = ready.pop()
        done += 1
        s = max([end[p] for p in preds[nid]] + [floor(nid)], default=0.0)
        start[nid] = s
        end[nid] = s + durs[nid]
        for nxt in succs[nid]:
            indeg[nxt] -= 1
            if indeg[nxt] == 0:
                ready.append(nxt)
    if done != len(preds):
        raise ValueError(
            f"step DAG has a cycle ({len(preds) - done} unscheduled "
            "nodes) — inconsistent collective order across ranks?"
        )
    return Schedule(start=start, end=end, dur=durs, preds=preds,
                    sink=sink, makespan=end[sink], rank_end=rank_end,
                    overlap=overlap)


def critical_path(dag: StepDAG, sched: Schedule) -> List[int]:
    """Real node ids (sentinels dropped) along the determining chain,
    source→sink order.  Ties break toward the lowest node id so the path
    is deterministic across runs."""
    path: List[int] = []
    cur = sched.sink
    while True:
        ps = sched.preds.get(cur, [])
        if not ps:
            break
        det = max(ps, key=lambda p: (sched.end[p], -p))
        if sched.end[det] + _EPS < sched.start[cur]:
            break                       # start was set by the rank floor
        cur = det
        if cur < len(dag.nodes) and sched.dur[cur] > _EPS:
            path.append(cur)
    path.reverse()
    return path


def attribute(dag: StepDAG, sched: Schedule) -> Dict[str, dict]:
    """Where the step time went.

    ``per_rank``: for each rank, ``compute`` (its segments), ``comm``
    (collectives it participates in, when they block its thread),
    ``negotiation`` (Σ comm start − its own arrival: time spent waiting
    for the rest of the job), and ``idle`` (everything else up to the
    step makespan — start skew and post-finish wait for slower ranks).

    ``per_tensor``: per collective, payload/duration plus each rank's
    wait and the max−min ``spread_us`` — the merge-layer straggler
    numbers, now derived from the *scheduled* DAG so every what-if
    reprices them consistently.
    """
    per_rank: Dict[str, dict] = {}
    per_tensor: Dict[str, dict] = {}
    for rank, chain in dag.chains.items():
        compute = comm = nego = 0.0
        for nid in chain:
            node = dag.nodes[nid]
            if node.kind == "compute":
                compute += sched.dur[nid]
            else:
                if not sched.overlap:
                    comm += sched.dur[nid]
                rp = dag.ready_pred.get(nid, {}).get(rank)
                own_ready = sched.end[rp] if rp is not None else \
                    dag.rank_base_us.get(rank, 0.0)
                wait = max(sched.start[nid] - own_ready, 0.0)
                nego += wait
                key = node.label or (node.tensor or str(nid))
                t = per_tensor.setdefault(key, {
                    "tensor": node.tensor,
                    "op": node.op,
                    "bytes": node.nbytes,
                    "comm_us": round(sched.dur[nid], 3),
                    "per_rank_wait_us": {},
                })
                t["per_rank_wait_us"][str(rank)] = round(wait, 3)
        total = sched.makespan - dag.rank_base_us.get(rank, 0.0)
        idle = max(total - compute - comm - nego, 0.0)
        per_rank[str(rank)] = {
            "compute_us": round(compute, 3),
            "comm_us": round(comm, 3),
            "negotiation_us": round(nego, 3),
            "idle_us": round(idle, 3),
        }
    for t in per_tensor.values():
        waits = list(t["per_rank_wait_us"].values())
        t["spread_us"] = round(max(waits) - min(waits), 3) if waits else 0.0
        if len(waits) >= 2:
            # the rank that waited least arrived last — merge.py semantics
            t["straggler_rank"] = int(min(
                t["per_rank_wait_us"], key=t["per_rank_wait_us"].get))
    return {"per_rank": per_rank, "per_tensor": per_tensor}


def describe_path(dag: StepDAG, sched: Schedule,
                  path: List[int]) -> List[dict]:
    """JSON-friendly critical-path rows."""
    rows = []
    for nid in path:
        node = dag.nodes[nid]
        rows.append({
            "kind": node.kind,
            "rank": node.rank if node.kind == "compute" else None,
            "ranks": list(node.ranks) if node.kind == "comm" else None,
            "tensor": node.tensor,
            "op": node.op,
            "label": node.label,
            "start_us": round(sched.start[nid], 3),
            "dur_us": round(sched.dur[nid], 3),
        })
    return rows
