"""Stitcher: per-step GLOBAL DAG from merged traces + Recorder artifacts.

The capture layer leaves three disconnected artifact families per rank
(the byteprofile contract the fork exists for): ``comm.json`` span
streams, the Recorder's ``dag.gml`` / ``tensor_shapes.json`` /
``gradient_name_list.json`` model structure, and (since the clock
handshake) a ``clock_sync.json`` offset sidecar.  This module fuses them
into the object dPRO replays: one directed acyclic graph per training
step spanning every rank, where

* each rank contributes a serial chain of **compute segments** (the gaps
  between its communication spans — host/device work the trace doesn't
  itemize further) in its own timeline order;
* each collective becomes ONE **global comm node** shared by all
  participating ranks, with an incoming readiness edge from every rank's
  chain (the position of its ``NEGOTIATE_<OP>`` "B" — the moment that
  rank arrived).  Negotiation waits are deliberately NOT nodes: a wait
  is a *consequence* of arrival skew, and modeling it as a fixed-length
  task would freeze the very quantity what-if scenarios change.  In
  simulation the comm node starts at ``max`` over its readiness edges
  and the wait re-emerges per rank as ``start - own_ready`` — which is
  exactly what lets "remove the straggler" shrink it;
* tensor names on comm spans are joined against the gradient manifest /
  ``tensor_shapes.json`` / ``dag.gml`` node labels, attaching byte
  counts so the simulator can re-cost collectives with the α–β model.

``stitch(trace_dir)`` is the entry point: artifacts + one
:class:`StepDAG` per step observed on every rank.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from ..merge import clock_shifts, discover_ranks, load_rank_events

#: top-level comm span name (timeline.span activity) -> α–β model op name
COMM_OPS = {
    "ALLREDUCE": "all-reduce",
    "ALLGATHER": "all-gather",
    "REDUCESCATTER": "reduce-scatter",
    "ALLTOALL": "all-to-all",
    "BROADCAST": "broadcast",
    "COLLECTIVE_PERMUTE": "collective-permute",
    "GRAD_ALLREDUCE": "all-reduce",
}

NEGOTIATE_PREFIX = "NEGOTIATE_"

# numpy/jax dtype string -> wire bytes (the jax-side twin of
# comm_report._DTYPE_BYTES, which is keyed by HLO names)
_DTYPE_BYTES = {
    "float32": 4, "float64": 8, "float16": 2, "bfloat16": 2,
    "int8": 1, "uint8": 1, "int16": 2, "uint16": 2, "int32": 4,
    "uint32": 4, "int64": 8, "uint64": 8, "bool": 1,
    "complex64": 8, "complex128": 16,
    "float8_e4m3fn": 1, "float8_e5m2": 1,
}


@dataclasses.dataclass
class Node:
    """One schedulable unit of the global step DAG."""

    nid: int
    kind: str                       # "compute" | "comm"
    dur_us: float
    rank: Optional[int] = None      # owning rank (None for global comm)
    tensor: Optional[str] = None
    op: Optional[str] = None        # α–β op name for comm nodes
    nbytes: Optional[int] = None
    ranks: Tuple[int, ...] = ()     # participants (comm nodes)
    label: str = ""                 # compute-segment identity, cross-rank
    dag_label: Optional[str] = None  # joined dag.gml node label
    dtype: Optional[str] = None     # payload dtype (compression pricing)


@dataclasses.dataclass
class StepDAG:
    """Global DAG for one step: per-rank serial chains threaded through
    shared comm nodes.  Edges are derived (critical_path.build_edges) so
    scenarios can restructure (overlap, fusion) without re-stitching."""

    step: int
    t0_us: float                            # aligned step start (abs µs)
    nodes: List[Node]
    chains: Dict[int, List[int]]            # rank -> ordered node ids
    ready_pred: Dict[int, Dict[int, Optional[int]]]  # comm -> rank -> pred
    rank_base_us: Dict[int, float]          # rank start rel. to t0
    measured_span_us: Dict[int, float]      # rank envelope duration
    world: int

    def node(self, nid: int) -> Node:
        return self.nodes[nid]

    @property
    def measured_step_us(self) -> float:
        """Observed step makespan: latest rank envelope end rel. t0."""
        return max(self.rank_base_us[r] + self.measured_span_us[r]
                   for r in self.rank_base_us)


@dataclasses.dataclass
class Artifacts:
    """Everything the stitcher read out of one trace dir."""

    trace_dir: str
    ranks: List[int]
    events: Dict[int, List[dict]]           # clock-aligned, per rank
    clock_offsets_us: Dict[int, float]
    clock_aligned: bool
    shapes: Dict[str, list]
    dtypes: Dict[str, str]
    gradient_names: List[str]
    dag_nodes: List[dict]                   # parsed dag.gml nodes
    dag_edges: List[Tuple[int, int]]
    metadata: dict
    #: per-rank compute-anatomy profiler events (compute.json,
    #: timeline/profiler.py), clock-aligned like the comm events; empty
    #: for ranks that never profiled
    profile_events: Dict[int, List[dict]] = dataclasses.field(
        default_factory=dict)


# ---------------------------------------------------------------------------
# artifact loading
# ---------------------------------------------------------------------------
_GML_NODE = re.compile(r"node\s*\[(.*?)\]", re.S)
_GML_EDGE = re.compile(
    r"edge\s*\[\s*source\s+(\d+)\s+target\s+(\d+)\s*\]", re.S)
_GML_ATTR = re.compile(r'(\w+)\s+(?:"([^"]*)"|(\S+))')


def read_gml(path: str) -> Tuple[List[dict], List[Tuple[int, int]]]:
    """Minimal reader for the Recorder's dag.gml (inverse of
    recorder.write_gml; tolerant of the nx.read_gml-compatible subset)."""
    with open(path) as f:
        txt = f.read()
    nodes: List[dict] = []
    for m in _GML_NODE.finditer(txt):
        attrs: Dict[str, Any] = {}
        for am in _GML_ATTR.finditer(m.group(1)):
            key = am.group(1)
            val = am.group(2) if am.group(2) is not None else am.group(3)
            attrs[key] = val
        if "id" not in attrs:
            continue
        node = {"id": int(attrs["id"]),
                "label": attrs.get("label", ""),
                "kind": attrs.get("kind", "")}
        if "shape" in attrs:
            node["shape"] = [int(d) for d in
                             re.findall(r"\d+", attrs["shape"])]
        if "dtype" in attrs:
            node["dtype"] = attrs["dtype"]
        nodes.append(node)
    edges = [(int(s), int(t)) for s, t in _GML_EDGE.findall(txt)]
    return nodes, edges


def _load_json(path: str, default):
    if not os.path.isfile(path):
        return default
    try:
        with open(path) as f:
            return json.load(f)
    except (ValueError, OSError):
        return default


def load_artifacts(trace_dir: str) -> Artifacts:
    """Read every rank's events (clock-aligned when all sidecars exist)
    plus the first rank's Recorder artifacts (the model structure is
    SPMD-identical across ranks — per-rank copies are redundancy, not
    information)."""
    ranks = discover_ranks(trace_dir)
    # same all-or-nothing policy as merge_traces (one shared helper, so
    # the Chrome trace and the replay DAG can never disagree)
    aligned, shift, offsets = clock_shifts(trace_dir, ranks)
    events: Dict[int, List[dict]] = {}
    for rank, path in ranks.items():
        evs = []
        for ev in load_rank_events(path):
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = float(ev["ts"]) + shift[rank]
            evs.append(ev)
        events[rank] = evs

    # compute-anatomy segments (compute.json, timeline/profiler.py):
    # per rank, shifted onto the same clock as its comm events so the
    # stitcher can split compute chains at segment boundaries.  An
    # artifact recorded on the profiler's own 'local' clock shares no
    # origin with comm.json — splitting at its (meaningless here)
    # boundaries would misattribute blocks, so the chain stays opaque.
    profile_events: Dict[int, List[dict]] = {}
    for rank in ranks:
        cj = _load_json(os.path.join(trace_dir, str(rank),
                                     "compute.json"), {})
        if not isinstance(cj, dict) or cj.get("clock") == "local":
            cj = {}
        evs = []
        for ev in cj.get("events", []):
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = float(ev["ts"]) + shift[rank]
            evs.append(ev)
        if evs:
            profile_events[rank] = evs

    shapes: Dict[str, list] = {}
    dtypes: Dict[str, str] = {}
    grad_names: List[str] = []
    dag_nodes: List[dict] = []
    dag_edges: List[Tuple[int, int]] = []
    metadata: dict = {}
    for rank in ranks:
        d = os.path.join(trace_dir, str(rank))
        if not shapes:
            shapes = _load_json(os.path.join(d, "tensor_shapes.json"), {})
        if not dtypes:
            dtypes = _load_json(os.path.join(d, "tensor_dtypes.json"), {})
        if not grad_names:
            grad_names = _load_json(
                os.path.join(d, "gradient_name_list.json"), [])
        if not metadata:
            metadata = _load_json(os.path.join(d, "metadata.json"), {})
        gml = os.path.join(d, "dag.gml")
        if not dag_nodes and os.path.isfile(gml):
            dag_nodes, dag_edges = read_gml(gml)
    return Artifacts(
        trace_dir=os.path.abspath(trace_dir),
        ranks=sorted(ranks),
        events=events,
        clock_offsets_us=offsets,
        clock_aligned=aligned,
        shapes=shapes,
        dtypes=dtypes,
        gradient_names=grad_names,
        dag_nodes=dag_nodes,
        dag_edges=dag_edges,
        metadata=metadata,
        profile_events=profile_events,
    )


# ---------------------------------------------------------------------------
# tensor-name joins
# ---------------------------------------------------------------------------
def _dtype_bytes(dtype: Optional[str]) -> int:
    return _DTYPE_BYTES.get(str(dtype), 4)  # unknown → f32 assumption


def join_tensor(tensor: str, art: Artifacts) -> Tuple[Optional[int],
                                                      Optional[str],
                                                      Optional[str]]:
    """``(nbytes, dag_label, dtype)`` for a comm span's tensor name, joined
    against the Recorder artifacts: exact ``tensor_shapes.json`` key
    first, then a manifest suffix match (eager dispatch names are often
    the trailing path component of ``gradients/...`` manifest names),
    then ``dag.gml`` node labels (``allreduce/<t>`` / ``grad/<t>`` from
    the structure DAG, or any shaped node whose label matches)."""
    shape = art.shapes.get(tensor)
    dtype = art.dtypes.get(tensor)
    label: Optional[str] = None
    if shape is None:
        for name, s in art.shapes.items():
            if name.endswith("/" + tensor) or name.split(".")[0] == tensor:
                shape, dtype = s, art.dtypes.get(name)
                break
    if shape is None:
        for node in art.dag_nodes:
            nl = str(node.get("label", ""))
            if nl == tensor or nl in (f"allreduce/{tensor}",
                                      f"grad/{tensor}") \
                    or nl.endswith("/" + tensor):
                label = nl
                if "shape" in node:
                    shape = node["shape"]
                    dtype = node.get("dtype", dtype)
                    break
    else:
        # comm spans join the collective op node first, then the bare
        # tensor, then the gradient input (structure_dag vocabulary)
        labels = {str(n.get("label", "")) for n in art.dag_nodes}
        for cand in (f"allreduce/{tensor}", tensor, f"grad/{tensor}"):
            if cand in labels:
                label = cand
                break
    if shape is None:
        return None, label, dtype
    n = 1
    for d in shape:
        n *= int(d)
    return n * _dtype_bytes(dtype), label, dtype


# ---------------------------------------------------------------------------
# per-rank span extraction
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class _CommSpan:
    tensor: str
    op: str                   # α–β name
    start_us: float
    dur_us: float
    ready_us: float           # this rank's NEGOTIATE "B" (arrival)


def _rank_step_windows(events: List[dict]) -> List[Tuple[int, float, float]]:
    """(step_no, t0, t1) windows from STEP spans; a trace without STEP
    spans is treated as one step 0 covering everything."""
    wins = []
    lo, hi = None, None
    for ev in events:
        if ev.get("ph") != "X":
            continue
        ts = float(ev.get("ts", 0.0))
        end = ts + float(ev.get("dur", 0.0))
        lo = ts if lo is None else min(lo, ts)
        hi = end if hi is None else max(hi, end)
        if ev.get("name") == "STEP":
            m = re.search(r"(\d+)$", str(ev.get("cat", "")))
            step_no = int(m.group(1)) if m else len(wins)
            wins.append((step_no, ts, end))
    if wins:
        return sorted(wins)
    if lo is None:
        return []
    return [(0, lo, hi)]


def _extract_comm_spans(events: List[dict], t0: float,
                        t1: float) -> List[_CommSpan]:
    """Ordered comm spans inside one step window, each paired with the
    latest same-tensor NEGOTIATE arrival at or before its start (no
    negotiation recorded → ready at span start)."""
    readies: Dict[str, List[float]] = {}
    spans: List[_CommSpan] = []
    for ev in events:
        name = str(ev.get("name", ""))
        ts = float(ev.get("ts", 0.0))
        if not (t0 - 1e-6 <= ts <= t1 + 1e-6):
            continue
        tensor = str(ev.get("cat") or ev.get("tid") or "")
        if name.startswith(NEGOTIATE_PREFIX):
            ph = ev.get("ph")
            if ph in ("B", "X"):     # X: complete-span negotiation form
                readies.setdefault(tensor, []).append(ts)
            continue
        if ev.get("ph") == "X" and name in COMM_OPS:
            spans.append(_CommSpan(
                tensor=tensor, op=COMM_OPS[name], start_us=ts,
                dur_us=float(ev.get("dur", 0.0)), ready_us=ts))
    spans.sort(key=lambda s: s.start_us)
    for s in spans:
        cands = [r for r in readies.get(s.tensor, ())
                 if r <= s.start_us + 1e-6]
        if cands:
            r = max(cands)
            readies[s.tensor].remove(r)
            s.ready_us = r
    return spans


# ---------------------------------------------------------------------------
# DAG construction
# ---------------------------------------------------------------------------
def _profile_segments(events: List[dict]) -> List[Tuple[str, float, float]]:
    """``(name, start, end)`` of one rank's profiler segment spans
    (compute.json events minus the STEP envelopes), start-ordered."""
    segs = []
    for ev in events:
        name = str(ev.get("name", ""))
        if ev.get("ph") != "X" or not name or name == "STEP":
            continue
        ts = float(ev.get("ts", 0.0))
        segs.append((name, ts, ts + float(ev.get("dur", 0.0))))
    segs.sort(key=lambda s: s[1])
    return segs


def _split_compute(segs: List[Tuple[str, float, float]], lo: float,
                   hi: float, base_label: str) -> List[Tuple[str, float]]:
    """Split one compute range ``[lo, hi)`` at the profiler-segment
    boundaries inside it: each overlapping segment becomes its own
    ``<base>|<name>`` piece (clipped to the range) and uncovered time
    becomes ``<base>|host<j>`` — so the replay DAG, the critical path,
    and what-ifs like remove_straggler attribute to *blocks*, not
    opaque per-rank chains.  Piece durations always sum to ``hi − lo``
    (the measured totals the calibrated replay depends on); with no
    overlapping segments the range stays ONE node under its original
    label, so unprofiled traces stitch exactly as before."""
    pieces: List[Tuple[str, float]] = []
    cursor, host_i = lo, 0
    for name, s, e in segs:
        if e <= lo + 1e-9 or s >= hi - 1e-9:
            continue
        s2, e2 = max(s, cursor), min(e, hi)
        if e2 <= s2 + 1e-9:
            continue
        if s2 > cursor + 1e-9:
            pieces.append((f"{base_label}|host{host_i}", s2 - cursor))
            host_i += 1
        pieces.append((f"{base_label}|{name}", e2 - s2))
        cursor = e2
    if not pieces:
        return [(base_label, hi - lo)]
    if hi > cursor + 1e-9:
        pieces.append((f"{base_label}|host{host_i}", hi - cursor))
    return pieces


def build_step_dag(art: Artifacts, step_no: int,
                   windows: Dict[int, Tuple[float, float]]) -> StepDAG:
    """One global DAG for ``step_no`` given each rank's step window."""
    t0 = min(w[0] for w in windows.values())
    nodes: List[Node] = []
    chains: Dict[int, List[int]] = {}
    ready_pred: Dict[int, Dict[int, Optional[int]]] = {}
    rank_base: Dict[int, float] = {}
    span_us: Dict[int, float] = {}
    # comm key (tensor, occurrence) -> comm node id
    comm_ids: Dict[Tuple[str, int], int] = {}

    def add(node: Node) -> int:
        node.nid = len(nodes)
        nodes.append(node)
        return node.nid

    for rank in art.ranks:
        r_t0, r_t1 = windows[rank]
        rank_base[rank] = r_t0 - t0
        span_us[rank] = r_t1 - r_t0
        spans = _extract_comm_spans(art.events[rank], r_t0, r_t1)
        prof_segs = _profile_segments(art.profile_events.get(rank, []))
        chain: List[int] = []
        occ: Dict[str, int] = {}
        cursor = r_t0
        for s in spans:
            k = occ.get(s.tensor, 0)
            occ[s.tensor] = k + 1
            seg = s.ready_us - cursor
            if seg > 1e-9:
                for lbl, dur in _split_compute(prof_segs, cursor,
                                               s.ready_us,
                                               f"pre:{s.tensor}:{k}"):
                    chain.append(add(Node(0, "compute", dur, rank=rank,
                                          label=lbl)))
            key = (s.tensor, k)
            if key not in comm_ids:
                nbytes, dag_label, dtype = join_tensor(s.tensor, art)
                comm_ids[key] = add(Node(
                    0, "comm", s.dur_us, tensor=s.tensor, op=s.op,
                    nbytes=nbytes, dag_label=dag_label, dtype=dtype,
                    label=f"comm:{s.tensor}:{k}"))
                ready_pred[comm_ids[key]] = {}
            cid = comm_ids[key]
            cnode = nodes[cid]
            cnode.dur_us = max(cnode.dur_us, s.dur_us)  # sync collective
            cnode.ranks = tuple(sorted(set(cnode.ranks) | {rank}))
            ready_pred[cid][rank] = chain[-1] if chain else None
            chain.append(cid)
            cursor = s.start_us + s.dur_us
        tail = r_t1 - cursor
        if tail > 1e-9:
            for lbl, dur in _split_compute(prof_segs, cursor, r_t1,
                                           "tail"):
                chain.append(add(Node(0, "compute", dur, rank=rank,
                                      label=lbl)))
        chains[rank] = chain

    return StepDAG(
        step=step_no, t0_us=t0, nodes=nodes, chains=chains,
        ready_pred=ready_pred, rank_base_us=rank_base,
        measured_span_us=span_us, world=len(art.ranks),
    )


def stitch(trace_dir: str,
           last_steps: Optional[int] = None
           ) -> Tuple[Artifacts, List[StepDAG]]:
    """Artifacts + one StepDAG per step observed on EVERY rank (a step
    captured on a subset of ranks — a truncated trace — can't be
    globally replayed and is dropped).

    ``last_steps`` builds DAGs for only the N newest common steps — the
    in-job tuner's window-cadence path, where constructing the whole
    accumulated history each window would grow with the job.  (The
    per-rank event files are still parsed in full; the DAG builds are
    the dominant cost.)"""
    art = load_artifacts(trace_dir)
    per_rank_windows: Dict[int, Dict[int, Tuple[float, float]]] = {}
    for rank in art.ranks:
        per_rank_windows[rank] = {
            step: (lo, hi)
            for step, lo, hi in _rank_step_windows(art.events[rank])
        }
    common = None
    for rank, wins in per_rank_windows.items():
        common = set(wins) if common is None else common & set(wins)
    wanted = sorted(common or ())
    if last_steps is not None and last_steps > 0:
        wanted = wanted[-last_steps:]
    dags = []
    for step_no in wanted:
        windows = {r: per_rank_windows[r][step_no] for r in art.ranks}
        dags.append(build_step_dag(art, step_no, windows))
    return art, dags
