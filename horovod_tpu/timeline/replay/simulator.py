"""What-if simulation: re-run a stitched step DAG under modified
assumptions and rank the scenarios by predicted speedup.

This is the payoff of the whole byteprofile→stitch→replay chain: the
merge can say "rank 3 is late", but only replay can say what fixing it
is *worth*.  Each scenario rewrites one assumption and re-schedules the
same DAG (critical_path.schedule):

* ``remove_straggler_rank_<r>`` — the blamed rank's compute segments are
  clamped to the fastest rank's matching segments (matched by segment
  label, i.e. which tensor the segment feeds), as if its slowdown —
  thermal throttling, a noisy neighbor, input skew — were gone;
* ``ici_bandwidth_x<F>`` — every collective is re-costed with the α–β
  model *calibrated per node*: the measured duration is split into an α
  share (hop latency, from the ring-hop count) and a β share (bytes on
  the wire), and only β shrinks with bandwidth — exactly how the comm
  report models scaling (comm_report.predict_collective_us is the shared
  cost model);
* ``overlap_comm`` — collectives stop blocking their ranks' host
  threads and only gate the end of step (perfect compute/comm overlap,
  the upper bound fusion+async dispatch chase);
* ``fuse_all_comm`` — all collectives in the step re-batched into one
  bucket: one α, summed β, readiness gated by the LAST gradient — the
  fusion-buffer ceiling (bucket re-batching is the reference's whole
  fusion rationale);
* ``fuse_buckets_<k>`` — the *implementable* middle ground the
  profile-guided planner (optim/profile_guided.py) consumes: the step's
  collectives re-batched into ``k`` explicit buckets that dispatch on a
  serialized comm channel while compute proceeds (two-thread model: one
  host/compute thread per rank, ONE wire).  The bucket search is
  agglomerative — start from singletons in gradient-ready order, merge
  the adjacent pair that most improves the replayed makespan — and every
  ``fuse_buckets_*`` scenario carries a machine-readable ``plan``
  payload (bucket membership by tensor name, dispatch order, predicted
  step µs) so the planner can turn the ranking into live knob settings.

Predictions are *calibrated replays*: the baseline is the DAG replayed
with measured durations, so a scenario's delta isolates exactly the
assumption it changes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from ..comm_report import (
    DEFAULT_DCN_BYTES_PER_SEC, DEFAULT_DCN_HOP_LATENCY,
    DEFAULT_ICI_BYTES_PER_SEC, DEFAULT_ICI_HOP_LATENCY, TopologySpec,
    _link_volume, _ring_hops, compression_overhead_us,
    compression_scale_exchange, compression_terms_us,
    compression_wire_ratio, predict_collective_us,
)
from .critical_path import Schedule, attribute, schedule
from .stitcher import Node, StepDAG, _dtype_bytes

#: single-sourced with comm_report's TopologySpec defaults (v5e ICI)
DEFAULT_HOP_LATENCY_US = DEFAULT_ICI_HOP_LATENCY * 1e6

#: wire formats the compression what-ifs and the per-bucket choice
#: search rank (ops/compression.py registry names priced by
#: comm_report.COMPRESSION_MODEL)
COMPRESSION_CANDIDATES = ("int8", "fp8", "bf16")


@dataclasses.dataclass
class CostModel:
    """α–β parameters every scenario prices collectives with."""

    world: int
    ici_bytes_per_sec: float = DEFAULT_ICI_BYTES_PER_SEC
    hop_latency_us: float = DEFAULT_HOP_LATENCY_US
    #: two-level (ICI/DCN) shape parameters — local_size <= 1 disables
    #: the two_level_comm what-if (no hierarchy to exploit)
    local_size: int = 1
    dcn_bytes_per_sec: float = DEFAULT_DCN_BYTES_PER_SEC
    dcn_hop_latency_us: float = DEFAULT_DCN_HOP_LATENCY * 1e6

    @classmethod
    def from_topology(cls, spec: TopologySpec) -> "CostModel":
        """The calibrated-replay cost model for one topology spec —
        the projection engine's constructor (every α–β/tier number
        comes from the shared ``TopologySpec``, never re-declared)."""
        return cls(world=spec.world,
                   ici_bytes_per_sec=spec.ici_bytes_per_sec,
                   hop_latency_us=spec.ici_hop_latency_us,
                   local_size=spec.local_size,
                   dcn_bytes_per_sec=spec.dcn_bytes_per_sec,
                   dcn_hop_latency_us=spec.dcn_hop_latency_us)

    @property
    def topology(self) -> TopologySpec:
        """This model's parameters as the shared spec object."""
        return TopologySpec(world=self.world, local_size=self.local_size,
                            ici_bytes_per_sec=self.ici_bytes_per_sec,
                            ici_hop_latency_us=self.hop_latency_us,
                            dcn_bytes_per_sec=self.dcn_bytes_per_sec,
                            dcn_hop_latency_us=self.dcn_hop_latency_us)

    def alpha_us(self, node: Node) -> float:
        return _ring_hops(node.op or "all-reduce",
                          self.world) * self.hop_latency_us

    def beta_us(self, node: Node) -> Optional[float]:
        if not node.nbytes:
            return None
        return _link_volume(node.op or "all-reduce", node.nbytes,
                            self.world) / self.ici_bytes_per_sec * 1e6

    def predict_us(self, node: Node) -> Optional[float]:
        if not node.nbytes:
            return None
        return predict_collective_us(
            node.op or "all-reduce", node.nbytes, self.world,
            ici_bytes_per_sec=self.ici_bytes_per_sec,
            ici_hop_latency=self.hop_latency_us * 1e-6)

    def calibrated_beta_us(self, node: Node) -> float:
        """The measured duration's bandwidth-dependent share: measured
        minus the α floor (never negative).  Calibration keeps what-ifs
        honest on hardware whose effective bandwidth differs from the
        datasheet — the model shape is analytic, the level is measured."""
        return max(node.dur_us - self.alpha_us(node), 0.0)

    # -- wire-efficiency tier ------------------------------------------------
    def compressible(self, node: Node) -> bool:
        """Float payloads compress; integer/bool payloads ride as-is
        (the compressors gate the same way, ops/compression.py)."""
        if node.kind != "comm" or not node.nbytes:
            return False
        d = str(node.dtype) if node.dtype else "float32"
        return d.startswith(("float", "bfloat"))

    def compression_ratio(self, node: Node, compression: str) -> float:
        orig = _dtype_bytes(node.dtype)
        return compression_wire_ratio(compression, orig)

    def compressed_dur_us(self, node: Node, compression: str) -> float:
        """Calibrated compressed cost: the measured β share shrinks by
        the wire ratio; quantize/dequantize and the quantizers' scalar
        scale exchange (one all-reduce α) are added — the same curve
        predict_collective_us prices, anchored on the measured level
        (terms from the shared comm_report.compression_terms_us)."""
        if not self.compressible(node):
            return node.dur_us
        ratio, qd, scale = compression_terms_us(
            compression, node.nbytes or 0, self.world,
            self.hop_latency_us, _dtype_bytes(node.dtype))
        return self.alpha_us(node) + self.calibrated_beta_us(node) * ratio \
            + qd + scale

    def two_level_dur_us(self, node: Node,
                         compression: Optional[str] = None,
                         spec: Optional[TopologySpec] = None) -> float:
        """Model-priced two-level cost (parallel/hierarchical.py shape):
        the measured flat duration carries no information about the
        ICI/DCN split, so this scenario is pure predict_collective_us —
        the fixture-checkable arithmetic, not a calibrated replay.
        ``spec`` supplies the hierarchy to price against (default: this
        model's own) — the what-if can evaluate two-level for a target
        topology the trace never ran on."""
        if node.kind != "comm" or not node.nbytes \
                or (node.op or "all-reduce") != "all-reduce":
            return node.dur_us
        spec = spec if spec is not None else self.topology
        return predict_collective_us(
            "all-reduce", node.nbytes, self.world,
            ici_bytes_per_sec=spec.ici_bytes_per_sec,
            ici_hop_latency=spec.ici_hop_latency_us * 1e-6,
            compression=compression if self.compressible(node) else None,
            orig_itemsize=_dtype_bytes(node.dtype),
            two_level=True, local_size=spec.local_size,
            dcn_bytes_per_sec=spec.dcn_bytes_per_sec,
            dcn_hop_latency=spec.dcn_hop_latency_us * 1e-6)

    def two_level_possible(self) -> bool:
        return self.topology.two_level_possible()


def identify_straggler(dag: StepDAG, sched: Schedule) -> Optional[int]:
    """The rank that cost the others the most negotiation wait: per
    collective, the last-arriving rank is blamed for that tensor's
    max−min wait spread; highest total blame wins."""
    blame: Dict[int, float] = {r: 0.0 for r in dag.chains}
    for cid, rp in dag.ready_pred.items():
        if len(rp) < 2:
            continue
        arrivals = {}
        for rank, pred in rp.items():
            arrivals[rank] = sched.end[pred] if pred is not None else \
                dag.rank_base_us.get(rank, 0.0)
        last = max(arrivals, key=arrivals.get)
        blame[last] += max(arrivals.values()) - min(arrivals.values())
    if not blame or max(blame.values()) <= 0.0:
        return None
    return max(blame, key=blame.get)


# ---------------------------------------------------------------------------
# scenario builders
# ---------------------------------------------------------------------------
def bandwidth_overrides(dag: StepDAG, cm: CostModel,
                        factor: float) -> Dict[int, float]:
    return {
        n.nid: cm.alpha_us(n) + cm.calibrated_beta_us(n) / factor
        for n in dag.nodes if n.kind == "comm"
    }


def remove_rank_overrides(dag: StepDAG, rank: int
                          ) -> Dict[str, Dict[int, float]]:
    """Clamp ``rank``'s compute segments to the fastest rank's matching
    segment (by label); its step-start skew is clamped to the earliest
    rank's."""
    best_by_label: Dict[str, float] = {}
    for r, chain in dag.chains.items():
        if r == rank:
            continue
        for nid in chain:
            node = dag.nodes[nid]
            if node.kind == "compute":
                cur = best_by_label.get(node.label)
                best_by_label[node.label] = node.dur_us if cur is None \
                    else min(cur, node.dur_us)
    durs: Dict[int, float] = {}
    for nid in dag.chains.get(rank, ()):
        node = dag.nodes[nid]
        if node.kind == "compute" and node.label in best_by_label:
            durs[nid] = min(node.dur_us, best_by_label[node.label])
    bases = {rank: min(dag.rank_base_us.values())}
    return {"dur_overrides": durs, "base_overrides": bases}


def fused_dag(dag: StepDAG, cm: CostModel) -> Optional[StepDAG]:
    """The step DAG with every collective re-batched into ONE bucket:
    per rank the bucket sits where its last collective sat (readiness =
    the last gradient's arrival — fusion can't launch before the bucket
    fills), computes keep their relative order, and the bucket costs one
    α plus the summed calibrated β of its members.  None when there are
    fewer than two collectives (nothing to fuse)."""
    comm_nodes = [n for n in dag.nodes if n.kind == "comm"]
    if len(comm_nodes) < 2:
        return None
    alpha = max(cm.alpha_us(n) for n in comm_nodes)
    beta = sum(cm.calibrated_beta_us(n) for n in comm_nodes)
    total_bytes = sum(n.nbytes or 0 for n in comm_nodes) or None

    nodes: List[Node] = []
    chains: Dict[int, List[int]] = {}
    ready_pred: Dict[int, Dict[int, Optional[int]]] = {}
    id_map: Dict[int, int] = {}

    def clone(node: Node) -> int:
        new = dataclasses.replace(node, nid=len(nodes))
        nodes.append(new)
        id_map[node.nid] = new.nid
        return new.nid

    fused = Node(0, "comm", alpha + beta, tensor="<fused>",
                 op="all-reduce", nbytes=total_bytes, label="comm:<fused>",
                 ranks=tuple(sorted({r for n in comm_nodes
                                     for r in n.ranks})))
    fused_id: Optional[int] = None
    for rank, chain in dag.chains.items():
        old_comms = [nid for nid in chain
                     if dag.nodes[nid].kind == "comm"]
        last_comm = old_comms[-1] if old_comms else None
        new_chain: List[int] = []
        for nid in chain:
            node = dag.nodes[nid]
            if node.kind == "compute":
                new_chain.append(clone(node))
            elif nid == last_comm:
                if fused_id is None:
                    fused.nid = len(nodes)
                    nodes.append(fused)
                    fused_id = fused.nid
                    ready_pred[fused_id] = {}
                # the bucket fills when this rank's LAST gradient is
                # ready: its readiness pred is whatever precedes it in
                # the rebuilt (compute-only-so-far) chain
                ready_pred[fused_id][rank] = new_chain[-1] if new_chain \
                    else None
                new_chain.append(fused_id)
        chains[rank] = new_chain
    return StepDAG(
        step=dag.step, t0_us=dag.t0_us, nodes=nodes, chains=chains,
        ready_pred=ready_pred, rank_base_us=dict(dag.rank_base_us),
        measured_span_us=dict(dag.measured_span_us), world=dag.world,
    )


def comm_channel_order(dag: StepDAG) -> List[int]:
    """Comm node ids in collective dispatch order.  Ranks dispatch
    collectives in one consistent order (anything else deadlocks the real
    job and the linter/sanitizer reject it), so the lowest rank's chain
    order IS the wire order; comm nodes a subset rank never joined are
    appended in nid order."""
    first = min(dag.chains) if dag.chains else None
    order = [nid for nid in dag.chains.get(first, ())
             if dag.nodes[nid].kind == "comm"]
    seen = set(order)
    order.extend(n.nid for n in dag.nodes
                 if n.kind == "comm" and n.nid not in seen)
    return order


def _bucket_dur_us(cm: CostModel, members: List[Node],
                   compression: Optional[str]) -> float:
    """One bucket's cost: max member α + summed calibrated β (scaled by
    the wire ratio when compressed) + the members' quantize/dequantize
    overhead + ONE scale-exchange α for the whole bucket (the per-tensor
    scale scalars ride one fused collective)."""
    alpha = max(cm.alpha_us(m) for m in members)
    if not compression:
        return alpha + sum(cm.calibrated_beta_us(m) for m in members)
    beta = qd = 0.0
    any_scale = False
    for m in members:
        if cm.compressible(m):
            beta += cm.calibrated_beta_us(m) * \
                cm.compression_ratio(m, compression)
            qd += compression_overhead_us(m.nbytes or 0, compression)
            any_scale = any_scale or compression_scale_exchange(compression)
        else:
            beta += cm.calibrated_beta_us(m)
    scale = (_ring_hops("all-reduce", cm.world) * cm.hop_latency_us
             if any_scale else 0.0)
    return alpha + beta + qd + scale


def bucketed_dag(dag: StepDAG, cm: CostModel,
                 buckets: List[List[int]],
                 bucket_compression: Optional[List[Optional[str]]] = None):
    """The step DAG with the given comm nodes re-batched into explicit
    buckets (each a list of original comm node ids): per rank a bucket
    node sits where its LAST member sat, earlier members vanish, and the
    bucket costs one α (the members' max) plus the summed calibrated β.
    Readiness per rank is the last compute segment preceding the bucket's
    last member — a bucket can't launch before it fills.
    ``bucket_compression`` (registry names aligned with ``buckets``)
    prices a per-bucket wire format via :func:`_bucket_dur_us` — the
    planner's compression choice replayed on the same DAG.

    Returns ``(new_dag, bucket_ids, chain_edges)`` where ``chain_edges``
    serializes the bucket nodes on one comm channel in dispatch order —
    pass it as ``schedule(..., overlap=True, extra_preds=chain_edges)``
    for the two-thread (compute ∥ wire) replay the profile-guided plans
    are priced with."""
    order = comm_channel_order(dag)
    pos = {nid: i for i, nid in enumerate(order)}
    bucket_of: Dict[int, int] = {}
    for bi, members in enumerate(buckets):
        for nid in members:
            bucket_of[nid] = bi
    # comm nodes not covered by any bucket ride as singletons
    for nid in order:
        if nid not in bucket_of:
            buckets = buckets + [[nid]]
            bucket_of[nid] = len(buckets) - 1

    nodes: List[Node] = []
    chains: Dict[int, List[int]] = {}
    ready_pred: Dict[int, Dict[int, Optional[int]]] = {}
    bucket_ids: Dict[int, int] = {}         # bucket index -> new node id

    def bucket_node(bi: int) -> Node:
        members = [dag.nodes[nid] for nid in buckets[bi]]
        comp = bucket_compression[bi] if bucket_compression is not None \
            and bi < len(bucket_compression) else None
        nbytes = sum(m.nbytes or 0 for m in members) or None
        names = ",".join(m.tensor or m.label for m in members)
        tag = f"|{comp}" if comp else ""
        return Node(0, "comm", _bucket_dur_us(cm, members, comp),
                    tensor=f"<bucket{bi}>",
                    op=members[0].op or "all-reduce", nbytes=nbytes,
                    label=f"comm:<bucket{bi}:{names}{tag}>",
                    ranks=tuple(sorted({r for m in members
                                        for r in m.ranks})))

    for rank, chain in dag.chains.items():
        # the member that appears LAST in this rank's chain, per bucket
        last_member: Dict[int, int] = {}
        for nid in chain:
            if nid in bucket_of:
                last_member[bucket_of[nid]] = nid
        new_chain: List[int] = []
        last_compute: Optional[int] = None
        for nid in chain:
            node = dag.nodes[nid]
            if node.kind == "compute":
                new = dataclasses.replace(node, nid=len(nodes))
                nodes.append(new)
                new_chain.append(new.nid)
                last_compute = new.nid
                continue
            bi = bucket_of[nid]
            if last_member.get(bi) != nid:
                continue                    # folded into a later position
            if bi not in bucket_ids:
                bn = bucket_node(bi)
                bn.nid = len(nodes)
                nodes.append(bn)
                bucket_ids[bi] = bn.nid
                ready_pred[bn.nid] = {}
            bid = bucket_ids[bi]
            ready_pred[bid][rank] = last_compute
            new_chain.append(bid)
        chains[rank] = new_chain

    # wire order: buckets sorted by their last member's dispatch position
    wire = sorted(bucket_ids,
                  key=lambda bi: max(pos[nid] for nid in buckets[bi]))
    chain_edges: Dict[int, List[int]] = {}
    for prev_bi, next_bi in zip(wire, wire[1:]):
        chain_edges[bucket_ids[next_bi]] = [bucket_ids[prev_bi]]
    new_dag = StepDAG(
        step=dag.step, t0_us=dag.t0_us, nodes=nodes, chains=chains,
        ready_pred=ready_pred, rank_base_us=dict(dag.rank_base_us),
        measured_span_us=dict(dag.measured_span_us), world=dag.world,
    )
    ordered_ids = [bucket_ids[bi] for bi in wire]
    return new_dag, ordered_ids, chain_edges


def _bucket_plan(dag: StepDAG, partition: List[List[int]],
                 predicted_us: float,
                 compression: Optional[List[Optional[str]]] = None) -> dict:
    """Machine-readable plan payload for one bucketing — the contract
    optim/profile_guided.py consumes (docs/autotune.md).  ``compression``
    (aligned with ``partition``) records the per-bucket wire-format
    decision; it is re-ordered with the buckets into wire order."""
    order = comm_channel_order(dag)
    pos = {nid: i for i, nid in enumerate(order)}
    idx = sorted(range(len(partition)),
                 key=lambda i: max(pos[n] for n in partition[i]))
    wire = [partition[i] for i in idx]
    plan = {
        "num_buckets": len(wire),
        "buckets": [[dag.nodes[n].tensor or dag.nodes[n].label
                     for n in sorted(b, key=pos.get)] for b in wire],
        "bucket_bytes": [sum(dag.nodes[n].nbytes or 0 for n in b) or None
                         for b in wire],
        "overlap": True,
        "predicted_step_us": round(predicted_us, 3),
    }
    if compression is not None:
        plan["compression"] = [compression[i] for i in idx]
    return plan


def compression_choice_search(dag: StepDAG, cm: CostModel,
                              partition: List[List[int]],
                              candidates=COMPRESSION_CANDIDATES):
    """Per-bucket wire-format choice for a FIXED bucket partition:
    greedy over buckets in descending payload order, picking per bucket
    the candidate that most improves the two-thread replayed makespan
    (ties broken toward the cheaper bucket duration, so a bucket hidden
    behind the critical path still takes the best format).  Staged
    after the partition search (docs/autotune.md): the joint
    partition × format space is exponential, and the partition choice
    is driven by α amortization while the format choice is driven by β
    — factoring them keeps both searches hand-checkable.

    Returns ``(compression, makespan_us)`` with ``compression`` aligned
    to ``partition`` (None = uncompressed)."""
    comp: List[Optional[str]] = [None] * len(partition)

    def evaluate(c):
        bdag, _ids, chain = bucketed_dag(dag, cm, partition,
                                         bucket_compression=c)
        return schedule(bdag, overlap=True, extra_preds=chain).makespan

    def bucket_dur(bi, name):
        return _bucket_dur_us(cm, [dag.nodes[n] for n in partition[bi]],
                              name)

    best_m = evaluate(comp)
    order = sorted(range(len(partition)), key=lambda bi: -sum(
        dag.nodes[n].nbytes or 0 for n in partition[bi]))
    for bi in order:
        if not any(cm.compressible(dag.nodes[n]) for n in partition[bi]):
            continue
        best = (best_m, bucket_dur(bi, comp[bi]), comp[bi])
        for cand in candidates:
            trial = list(comp)
            trial[bi] = cand
            key = (evaluate(trial), bucket_dur(bi, cand), cand)
            if key[:2] < best[:2]:
                best = key
        if best[2] != comp[bi]:
            comp[bi] = best[2]
            best_m = best[0]
    return comp, best_m


def bucket_plan_search(dag: StepDAG, cm: CostModel,
                       max_initial: int = 64,
                       patience: int = 8) -> List[dict]:
    """Agglomerative search over contiguous bucketings of the comm
    sequence: start from singletons in dispatch order, repeatedly merge
    the adjacent pair whose fusion most improves the two-thread replayed
    makespan, and record the best partition seen at every bucket count.
    Returns one row per bucket count (``num_buckets``,
    ``predicted_step_us``, ``plan``), best-first.

    The descent stops early once ``patience`` consecutive merge levels
    fail to improve on the best makespan seen — past the optimum, every
    further merge only serializes more payload behind one α, so the
    abandoned tail of the table is diagnostics we already know lose
    (bounds the O(n²) full-DAG replays on big traces; the fixture's
    3-level table is far under the patience and stays complete)."""
    order = comm_channel_order(dag)
    if len(order) < 2:
        return []
    parts: List[List[int]] = [[nid] for nid in order]
    # very long steps: pre-merge the cheapest adjacent pairs so the
    # O(n^2) greedy stays bounded (the dropped granularity is logged in
    # the plan's num_buckets, not silently hidden)
    while len(parts) > max_initial:
        betas = [sum(cm.calibrated_beta_us(dag.nodes[n]) for n in b)
                 for b in parts]
        i = min(range(len(parts) - 1),
                key=lambda j: betas[j] + betas[j + 1])
        parts[i:i + 2] = [parts[i] + parts[i + 1]]

    def evaluate(partition: List[List[int]]) -> float:
        bdag, _ids, chain = bucketed_dag(dag, cm, partition)
        return schedule(bdag, overlap=True, extra_preds=chain).makespan

    results: List[dict] = []

    def record(partition: List[List[int]], makespan: float) -> None:
        row = _bucket_plan(dag, partition, makespan)
        # node-id partition, for the staged compression_choice_search
        # (tensor names in `buckets` are the plan contract; node ids are
        # this DAG's internals)
        row["node_partition"] = [list(b) for b in partition]
        results.append(row)

    best_seen = evaluate(parts)
    record(parts, best_seen)
    cur, stale = parts, 0
    while len(cur) > 1 and stale < patience:
        best: Optional[tuple] = None
        for i in range(len(cur) - 1):
            cand = cur[:i] + [cur[i] + cur[i + 1]] + cur[i + 2:]
            m = evaluate(cand)
            if best is None or m < best[0]:
                best = (m, cand)
        cur = best[1]
        record(cur, best[0])
        if best[0] < best_seen:
            best_seen, stale = best[0], 0
        else:
            stale += 1
    results.sort(key=lambda r: (r["predicted_step_us"], r["num_buckets"]))
    return results


# ---------------------------------------------------------------------------
# the what-if driver
# ---------------------------------------------------------------------------
def what_if(dag: StepDAG, cm: Optional[CostModel] = None,
            bandwidth_factors: tuple = (2.0, 4.0),
            plan_search: bool = True,
            topology: Optional[TopologySpec] = None) -> dict:
    """Baseline replay + every scenario, ranked by predicted speedup.

    ``plan_search=False`` skips the agglomerative bucket search (the
    `fuse_buckets_<k>` scenario + `bucket_search` table) — it is the
    expensive part on big traces (O(n²) full-DAG replays, patience-
    bounded), and a consumer after a straggler report doesn't need a
    fusion plan (`hvd_replay.py --no-plan-search`).

    ``topology`` supplies the hierarchy/tier assumptions the
    ``two_level_comm`` scenario is gated and priced on (default: the
    cost model's own) — so a trace captured on a FLAT world can still
    evaluate two-level reduction against a projected multi-host target
    (``hvd_replay --project``) instead of silently omitting it."""
    cm = cm or CostModel(world=dag.world)
    tl_spec = (topology if topology is not None
               else cm.topology).with_world(cm.world)
    base = schedule(dag)
    baseline_us = base.makespan
    scenarios: List[dict] = []

    def add(name: str, sched_, detail: str, plan: Optional[dict] = None
            ) -> None:
        predicted = sched_.makespan if isinstance(sched_, Schedule) \
            else float(sched_)
        row = {
            "scenario": name,
            "predicted_step_us": round(predicted, 3),
            "speedup_pct": round(
                (baseline_us - predicted) / baseline_us * 100.0, 2)
            if baseline_us > 0 else 0.0,
            "detail": detail,
        }
        if plan is not None:
            row["plan"] = plan
        scenarios.append(row)

    straggler = identify_straggler(dag, base)
    if straggler is not None:
        ov = remove_rank_overrides(dag, straggler)
        add(f"remove_straggler_rank_{straggler}",
            schedule(dag, dur_overrides=ov["dur_overrides"],
                     base_overrides=ov["base_overrides"]),
            f"rank {straggler}'s compute clamped to the fastest rank's "
            "matching segments")
    for f in bandwidth_factors:
        add(f"ici_bandwidth_x{f:g}",
            schedule(dag, dur_overrides=bandwidth_overrides(dag, cm, f)),
            f"β share of every collective divided by {f:g} "
            "(α latency floor kept)")
    add("overlap_comm", schedule(dag, overlap=True),
        "collectives no longer block host threads; they only gate "
        "step end")
    fdag = fused_dag(dag, cm)
    if fdag is not None:
        add("fuse_all_comm", schedule(fdag),
            "all collectives re-batched into one bucket: one α, "
            "summed β, launch gated by the last gradient")
    # wire-efficiency tier (docs/compression.md): every float payload
    # re-costed in one wire format — β scaled by the compression ratio,
    # quantize/dequantize and scale-exchange overheads added, all from
    # comm_report's COMPRESSION_MODEL (the same curve
    # predict_collective_us prices)
    for comp in COMPRESSION_CANDIDATES:
        overrides = {n.nid: cm.compressed_dur_us(n, comp)
                     for n in dag.nodes if cm.compressible(n)}
        if overrides:
            add(f"compress_{comp}", schedule(dag, dur_overrides=overrides),
                f"every float gradient quantized to {comp} on the wire "
                "(error-feedback residual carried, "
                "HVD_COMPRESSION=" + comp + ")")
    if tl_spec.two_level_possible():
        overrides = {
            n.nid: cm.two_level_dur_us(n, spec=tl_spec) for n in dag.nodes
            if n.kind == "comm" and n.nbytes
            and (n.op or "all-reduce") == "all-reduce"
        }
        if overrides:
            add("two_level_comm", schedule(dag, dur_overrides=overrides),
                f"two-level allreduce: ICI reduce-scatter over "
                f"{tl_spec.local_size} local ranks + DCN all-reduce on "
                "the shard + ICI all-gather (model-priced, "
                "HVD_TWO_LEVEL_ALLREDUCE=1)")
    search = bucket_plan_search(dag, cm) if plan_search else []
    if search:
        best = search[0]
        add(f"fuse_buckets_{best['num_buckets']}",
            best["predicted_step_us"],
            f"{best['num_buckets']} explicit fusion buckets dispatched "
            "on a serialized comm channel overlapping compute — the "
            "implementable plan the profile-guided tuner applies",
            plan=best)
        # staged wire-format choice on the winning partition: the
        # per-bucket compression decision the planner applies/verifies/
        # rolls back exactly like the fusion decision
        comp, m = compression_choice_search(dag, cm,
                                            best["node_partition"])
        if any(comp) and m < best["predicted_step_us"]:
            plan = _bucket_plan(dag, best["node_partition"], m,
                                compression=comp)
            chosen = ",".join(f"{c or 'none'}" for c in plan["compression"])
            add(f"fuse_buckets_{plan['num_buckets']}_compressed", m,
                f"the {plan['num_buckets']}-bucket plan with per-bucket "
                f"wire formats [{chosen}] — compression ranked against "
                "fusion on one scale",
                plan=plan)
    scenarios.sort(key=lambda s: s["predicted_step_us"])
    return {
        "baseline_replay_us": round(baseline_us, 3),
        "straggler_rank": straggler,
        "cost_model": {
            "world": cm.world,
            "ici_bytes_per_sec": cm.ici_bytes_per_sec,
            "hop_latency_us": cm.hop_latency_us,
            "local_size": tl_spec.local_size,
        },
        "scenarios": scenarios,
        "bucket_search": search,
    }


def attribution_with_baseline(dag: StepDAG) -> dict:
    """Convenience: baseline schedule's attribution (CLI/server path)."""
    return attribute(dag, schedule(dag))
