"""Fleet-scale digital twin: re-materialize a stitched step DAG onto a
hypothetical topology and predict how it would run (docs/projection.md).

The endgame of the dPRO chain (profile → global DAG → simulate →
optimize): every what-if so far re-schedules *the world the trace ran
in*.  This module rewrites the trace onto a world we have NOT run —
more ranks, a different ``local_size``, ICI vs DCN tiers, a different
wire format — and replays it through the SAME discrete-event scheduler
(critical_path.schedule), so a capacity question ("what does 64× buy
me?", ``hvd_replay --project 64x``) is answered with the calibrated
machinery instead of a spreadsheet:

* **compute chains replicate** per target rank — ``distribution`` mode
  hands target rank *t* source rank ``t mod N``'s chain (the per-rank
  duration distribution, straggler structure included, survives the
  projection; with an unchanged world this is the identity, so an
  identity projection bit-matches the replay baseline), ``slowest``
  mode hands every target rank the slowest source chain (the
  conservative bound when source heterogeneity is noise);
* **collectives re-price** for the target world with the calibrated
  α–β split the bandwidth what-if uses: the measured duration's β share
  scales by the target/source link-volume-over-bandwidth ratio and the
  target α floor is rebuilt from its hop count — hardware whose
  effective bandwidth differs from the datasheet keeps its measured
  level.  The wire format is chosen the way the runtime/planner would
  (``TopologySpec.two_level`` policy: flat, two-level, compressed —
  two-level is model-priced, the flat trace carries no tier split);
* **traces without comm spans** (SPMD jobs keep collectives inside the
  compiled program; a 1-rank world has none at all) get ONE synthesized
  fused all-reduce per step carrying the gradient manifest's total
  bytes, gated by each rank's last compute — the fused-bucket shape the
  runtime actually dispatches — whenever the target world differs from
  the source's.

Accuracy is a first-class observable (the PR 6 predicted-vs-realized
discipline): :func:`validate` pins projected-vs-measured step-time
error between two trace dirs, :func:`live_validation` drives the
1-rank → 8-device CPU-mesh comparison end to end (tier-1 +
``bench.py``'s ``projection_err_pct``), and the error is exported as
``hvd_projection_err_pct`` next to the per-world
``hvd_projection_step_us`` / ``hvd_projection_efficiency`` gauges and
served on the signed ``GET /projection``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import statistics
from typing import Dict, List, Optional, Tuple

from ...utils import env as env_util
from ...utils.slo import (  # noqa: F401  (public API lives here too)
    project_serving_p99, serving_slo_headroom,
)
from ..comm_report import (
    TopologySpec, _link_volume, _ring_hops, compression_terms_us,
)
from .critical_path import attribute, schedule
from .simulator import CostModel
from .stitcher import Artifacts, Node, StepDAG, _dtype_bytes

#: chain-replication modes (HVD_PROJECT_MODE picks the CLI default)
PROJECT_MODES = ("distribution", "slowest")

#: tensor name of the synthesized fused gradient all-reduce
SYNTH_TENSOR = "<grads>"


def project_mode_from_env() -> str:
    mode = (env_util.get_str(env_util.HVD_PROJECT_MODE) or
            PROJECT_MODES[0]).strip().lower()
    return mode if mode in PROJECT_MODES else PROJECT_MODES[0]


# ---------------------------------------------------------------------------
# spec parsing (the --project grammar)
# ---------------------------------------------------------------------------
_RANGE_RE = re.compile(r"^(\d+)x\.\.(\d+)x$")
_FACTOR_RE = re.compile(r"^(\d+)x$")

_SPEC_KEYS = {
    "local": "local_size", "local_size": "local_size",
    "ici_gbps": "ici_bytes_per_sec", "hop_us": "ici_hop_latency_us",
    "ici_hop_us": "ici_hop_latency_us",
    "dcn_gbps": "dcn_bytes_per_sec", "dcn_hop_us": "dcn_hop_latency_us",
    "compression": "compression", "two_level": "two_level",
}


def base_spec_from_env(world: int) -> TopologySpec:
    """The projection base spec: the replay cost model's env-driven
    α–β/tier numbers (HVD_REPLAY_ICI_GBPS & friends — ONE source), with
    ``two_level="auto"`` — a projection chooses the cheaper wire shape
    per collective the way the planner would, instead of assuming the
    knob setting of the job that happened to record the trace."""
    from . import _cost_model_from_env

    return dataclasses.replace(
        _cost_model_from_env(world).topology, two_level="auto")


def parse_project_spec(text: str, source_world: int,
                       base: Optional[TopologySpec] = None
                       ) -> List[Tuple[str, TopologySpec]]:
    """``(name, TopologySpec)`` rows for one ``--project`` argument.

    Grammar (comma-separated tokens, order-free)::

        4x                  target world = 4 x source world
        2x..64x             doubling sweep: 2x, 4x, ..., 64x
        16  |  world=16     absolute target world
        local=8             ranks per ICI domain (cross = world/local)
        ici_gbps= hop_us= dcn_gbps= dcn_hop_us=   α–β overrides
        compression=int8    wire format (none clears)
        two_level=auto|on|off   tier policy (default auto)

    With no world token the overrides apply to the source world itself
    (the ``identity`` row — the bit-match regression anchor)."""
    base = base or base_spec_from_env(source_world)
    worlds: List[int] = []
    kw: Dict[str, object] = {}
    for tok in str(text).split(","):
        tok = tok.strip().lower()
        if not tok:
            continue
        m = _RANGE_RE.match(tok)
        if m:
            lo, hi = int(m.group(1)), int(m.group(2))
            if lo < 1 or hi < lo:
                raise ValueError(f"bad projection range {tok!r}")
            f = lo
            while f <= hi:
                worlds.append(source_world * f)
                f *= 2
            continue
        m = _FACTOR_RE.match(tok)
        if m:
            worlds.append(source_world * int(m.group(1)))
            continue
        if tok.isdigit():
            worlds.append(int(tok))
            continue
        key, sep, val = tok.partition("=")
        if not sep:
            raise ValueError(
                f"unrecognized projection token {tok!r} (want Nx, "
                f"N..Mx, world=N, or one of {sorted(_SPEC_KEYS)})")
        if key == "world":
            worlds.append(int(val))
            continue
        field = _SPEC_KEYS.get(key)
        if field is None:
            raise ValueError(
                f"unknown projection key {key!r} (known: world, "
                f"{', '.join(sorted(_SPEC_KEYS))})")
        if field == "local_size":
            kw[field] = int(val)
        elif field == "compression":
            kw[field] = None if val in ("none", "") else val
        elif field == "two_level":
            if val in ("1", "true", "yes"):
                val = "on"
            elif val in ("0", "false", "no"):
                val = "off"
            if val not in ("auto", "on", "off"):
                raise ValueError(f"two_level wants auto|on|off, got {val!r}")
            kw[field] = val
        elif field.endswith("bytes_per_sec"):
            kw[field] = float(val) * 1e9
        else:
            kw[field] = float(val)
    if not worlds:
        worlds = [source_world]
    out: List[Tuple[str, TopologySpec]] = []
    for w in worlds:
        if w < 1:
            raise ValueError(f"projection world must be >= 1, got {w}")
        spec = dataclasses.replace(base, world=w, **kw)
        if w == source_world and not kw:
            name = "identity"
        elif w % source_world == 0 and w > source_world:
            name = f"{w // source_world}x"
        else:
            name = f"world={w}"
        out.append((name, spec))
    return out


# ---------------------------------------------------------------------------
# comm re-pricing
# ---------------------------------------------------------------------------
def slowest_source_rank(dag: StepDAG) -> int:
    """The source rank with the largest total compute time (ties break
    toward the lowest rank so projections are deterministic)."""
    totals = {
        r: sum(dag.nodes[nid].dur_us for nid in chain
               if dag.nodes[nid].kind == "compute")
        for r, chain in dag.chains.items()
    }
    return max(sorted(totals), key=lambda r: totals[r])


def project_comm_dur(node: Node, src_cm: CostModel,
                     spec: TopologySpec) -> Tuple[str, float]:
    """``(wire_format, projected_dur_us)`` of one measured collective on
    the target topology.

    Flat pricing is *calibrated*: measured duration = α + β; the target
    β is the measured β scaled by (target link-volume / target
    bandwidth) over (source link-volume / source bandwidth), the target
    α is rebuilt from the target hop count.  A source world of 1 has
    zero link volume (nothing was measured on any wire), so the target
    β is pure model.  Two-level is always pure model
    (``CostModel.two_level_dur_us`` semantics: the flat measurement
    carries no ICI/DCN split).  The format choice follows the spec's
    policy via the same comparison ``TopologySpec.wire_choice`` makes.

    Identity anchor: at an UNCHANGED world with unchanged link
    parameters, no compression, and no explicit ``two_level="on"``
    request, the measurement itself is returned bit for bit — the
    trace already ran on that world, tiers and all, so any
    re-derivation (α/β round trips, fabric guesses from an
    env-declared ``local_size``) could only drift away from ground
    truth.  Explicit α–β overrides (``ici_gbps=`` etc. at the same
    world — "my world on slower links") and ``two_level="on"`` opt
    back into re-pricing."""
    op = node.op or "all-reduce"
    if node.kind != "comm" or not node.nbytes:
        return "measured", node.dur_us
    comp = spec.compression if (spec.compression
                                and src_cm.compressible(node)) else None
    unchanged = (spec.world == src_cm.world
                 and spec.ici_bytes_per_sec == src_cm.ici_bytes_per_sec
                 and spec.ici_hop_latency_us == src_cm.hop_latency_us
                 and spec.dcn_bytes_per_sec == src_cm.dcn_bytes_per_sec
                 and spec.dcn_hop_latency_us == src_cm.dcn_hop_latency_us)
    if unchanged and not comp and spec.two_level != "on":
        return "measured", node.dur_us
    flat_bw, flat_hop_s = spec._flat_params()
    flat_hop_us = flat_hop_s * 1e6
    lv_s = _link_volume(op, node.nbytes, src_cm.world)
    lv_t = _link_volume(op, node.nbytes, spec.world)
    if lv_s > 0:
        beta = src_cm.calibrated_beta_us(node) * (lv_t / lv_s) \
            * (src_cm.ici_bytes_per_sec / flat_bw)
    else:
        beta = lv_t / flat_bw * 1e6
    ratio, qd, scale = compression_terms_us(
        comp, node.nbytes, spec.world, flat_hop_us,
        _dtype_bytes(node.dtype))
    flat_us = _ring_hops(op, spec.world) * flat_hop_us \
        + beta * ratio + qd + scale
    wire, dur = TopologySpec._tag("flat", comp), flat_us
    if op == "all-reduce" and spec.two_level != "off" \
            and spec.two_level_possible():
        target_cm = CostModel.from_topology(spec)
        two = target_cm.two_level_dur_us(
            dataclasses.replace(node, ranks=()), compression=comp)
        if spec.two_level == "on" or two < flat_us:
            wire, dur = TopologySpec._tag("two_level", comp), two
    return wire, dur


def synthesized_comm_bytes(art: Optional[Artifacts]) -> Optional[int]:
    """Total gradient payload bytes from the Recorder manifest (the
    fused bucket a comm-less trace's collectives would carry), or None
    when no manifest is available."""
    if art is None:
        return None
    names = list(art.gradient_names) or sorted(art.shapes)
    total = 0
    for name in names:
        shape = art.shapes.get(name)
        if shape is None:
            continue
        n = 1
        for d in shape:
            n *= int(d)
        total += n * _dtype_bytes(art.dtypes.get(name))
    return total or None


# ---------------------------------------------------------------------------
# DAG re-materialization
# ---------------------------------------------------------------------------
def project_dag(dag: StepDAG, src_cm: CostModel, spec: TopologySpec,
                mode: Optional[str] = None,
                synth_bytes: Optional[int] = None,
                source_world: Optional[int] = None
                ) -> Tuple[StepDAG, dict]:
    """The source step DAG re-materialized onto ``spec``'s topology:
    ``(projected_dag, info)`` where ``info`` records the per-collective
    wire formats and whether a gradient all-reduce was synthesized.
    Schedule the result with the ordinary discrete-event scheduler —
    projection changes the DAG, never the replay semantics.

    ``source_world`` is the job size the trace STANDS FOR (a
    single-process SPMD trace is one rank dir standing for a whole
    mesh — :func:`source_world_of`); it gates comm synthesis so the
    identity projection of such a trace stays the replay baseline."""
    mode = mode or project_mode_from_env()
    if mode not in PROJECT_MODES:
        raise ValueError(f"unknown projection mode {mode!r} "
                         f"(want one of {PROJECT_MODES})")
    src_ranks = sorted(dag.chains)
    if not src_ranks:
        raise ValueError("cannot project an empty step DAG")
    if mode == "slowest":
        slow = slowest_source_rank(dag)
        src_of = {t: slow for t in range(spec.world)}
    else:
        src_of = {t: src_ranks[t % len(src_ranks)]
                  for t in range(spec.world)}

    nodes: List[Node] = []
    chains: Dict[int, List[int]] = {}
    ready_pred: Dict[int, Dict[int, Optional[int]]] = {}
    comm_clone: Dict[int, int] = {}         # source comm nid -> new nid
    wire_formats: Dict[str, str] = {}
    has_comm = any(n.kind == "comm" for n in dag.nodes)
    # synthesize the fused gradient all-reduce only when the target
    # world actually differs from the job size the trace stands for:
    # an identity projection must stay the replay baseline bit for bit,
    # whatever the trace looks like (an SPMD trace's in-graph
    # collectives already live inside its measured compute spans)
    sw = source_world if source_world else dag.world
    synth = (not has_comm and spec.world > 1 and spec.world != sw
             and synth_bytes)
    synth_id: Optional[int] = None

    for t in range(spec.world):
        src = src_of[t]
        clone_of: Dict[int, int] = {}
        chain: List[int] = []
        for nid in dag.chains[src]:
            node = dag.nodes[nid]
            if node.kind == "compute":
                new = dataclasses.replace(node, nid=len(nodes), rank=t)
                nodes.append(new)
                clone_of[nid] = new.nid
                chain.append(new.nid)
                continue
            if nid not in comm_clone:
                wire, dur = project_comm_dur(node, src_cm, spec)
                new = dataclasses.replace(node, nid=len(nodes),
                                          dur_us=dur, ranks=())
                nodes.append(new)
                comm_clone[nid] = new.nid
                ready_pred[new.nid] = {}
                wire_formats[node.label or node.tensor or str(nid)] = wire
            cid = comm_clone[nid]
            cnode = nodes[cid]
            cnode.ranks = tuple(sorted(set(cnode.ranks) | {t}))
            rp = dag.ready_pred.get(nid, {}).get(src)
            if rp is None:
                pred = None
            else:
                pred = clone_of.get(rp, comm_clone.get(rp))
            ready_pred[cid][t] = pred
            chain.append(cid)
        if synth:
            if synth_id is None:
                wire, dur = spec.wire_choice("all-reduce", int(synth_bytes),
                                             compression=spec.compression)
                if sw > 1:
                    # marginal pricing: a multi-rank SPMD trace keeps its
                    # own world's collective time INSIDE the measured
                    # compute spans (in-graph dispatch), so the
                    # synthesized node bills only the increment over the
                    # source world's flat cost — not a second full
                    # collective on top of the embedded one
                    embedded = src_cm.topology.with_world(sw)._flat_us(
                        "all-reduce", int(synth_bytes))
                    dur = max(dur - embedded, 0.0)
                syn = Node(len(nodes), "comm", dur, tensor=SYNTH_TENSOR,
                           op="all-reduce", nbytes=int(synth_bytes),
                           label=f"comm:{SYNTH_TENSOR}", dtype="float32")
                nodes.append(syn)
                synth_id = syn.nid
                ready_pred[synth_id] = {}
                wire_formats[syn.label] = wire
            snode = nodes[synth_id]
            snode.ranks = tuple(sorted(set(snode.ranks) | {t}))
            ready_pred[synth_id][t] = chain[-1] if chain else None
            chain.append(synth_id)
        chains[t] = chain

    pdag = StepDAG(
        step=dag.step, t0_us=dag.t0_us, nodes=nodes, chains=chains,
        ready_pred=ready_pred,
        rank_base_us={t: dag.rank_base_us.get(src_of[t], 0.0)
                      for t in range(spec.world)},
        measured_span_us={t: dag.measured_span_us.get(src_of[t], 0.0)
                          for t in range(spec.world)},
        world=spec.world,
    )
    info = {
        "mode": mode,
        "wire_formats": wire_formats,
        "synthesized_comm": bool(synth),
        "synth_bytes": int(synth_bytes) if synth else None,
    }
    return pdag, info


def project_step(dag: StepDAG, src_cm: CostModel, spec: TopologySpec,
                 mode: Optional[str] = None,
                 synth_bytes: Optional[int] = None,
                 source_world: Optional[int] = None,
                 baseline_us: Optional[float] = None) -> dict:
    """One projection row: re-materialize, schedule, attribute.
    ``baseline_us`` reuses a caller-computed source-DAG makespan so a
    multi-row sweep doesn't re-replay the unchanged source per row."""
    pdag, info = project_dag(dag, src_cm, spec, mode=mode,
                             synth_bytes=synth_bytes,
                             source_world=source_world)
    sched = schedule(pdag)
    attr = attribute(pdag, sched)
    baseline = baseline_us if baseline_us is not None \
        else schedule(dag).makespan
    ranks = attr["per_rank"].values()

    def mean(key: str) -> float:
        return round(sum(a[key] for a in ranks) / max(len(ranks), 1), 3)

    row = {
        "world": spec.world,
        "local_size": spec.local_size,
        "spec": spec.to_dict(),
        "projected_step_us": round(sched.makespan, 3),
        "baseline_replay_us": round(baseline, 3),
        "scaling_efficiency": round(baseline / sched.makespan, 4)
        if sched.makespan > 0 else None,
        "phases": {k: mean(f"{k}_us") for k in
                   ("compute", "comm", "negotiation", "idle")},
    }
    row.update(info)
    return row


# ---------------------------------------------------------------------------
# the --project driver
# ---------------------------------------------------------------------------
def source_world_of(result) -> int:
    """The job size the trace stands for — the base of ``Nx`` factors.
    A single-process SPMD trace is one rank dir standing for a whole
    mesh, so the Recorder's ``metadata.json`` size wins when larger."""
    world = result.dags[-1].world
    meta = result.artifacts.metadata.get("size")
    if isinstance(meta, int) and meta > world:
        return meta
    return world


def _source_mfu(trace_dir: str) -> Optional[float]:
    """Mean profiled MFU across ranks (compute.json anatomies), or None
    when the trace was captured without the compute-anatomy profiler."""
    try:
        from ..profiler import load_compute_json

        mfus = [a["mfu"] for a in load_compute_json(trace_dir).values()
                if isinstance(a, dict) and a.get("mfu") is not None]
    except Exception:  # noqa: BLE001 — anatomy is optional garnish
        return None
    return round(sum(mfus) / len(mfus), 4) if mfus else None


def project_analysis(result, specs: List[Tuple[str, TopologySpec]],
                     mode: Optional[str] = None,
                     cost_model: Optional[CostModel] = None) -> dict:
    """The projection summary for a ``ReplayResult``: the newest stitched
    step projected onto every spec, plus the source anchor (baseline
    replay, measured step, profiled MFU).  ``projected_mfu`` scales the
    source MFU by the step-time ratio — per-rank work is held fixed, so
    utilization moves inversely with the projected step."""
    mode = mode or project_mode_from_env()
    art = result.artifacts
    dag = result.dags[-1]
    sw = source_world_of(result)
    cm = cost_model or CostModel.from_topology(
        base_spec_from_env(dag.world).with_world(dag.world))
    synth = synthesized_comm_bytes(art)
    baseline = schedule(dag).makespan
    mfu = _source_mfu(art.trace_dir)
    rows = []
    for name, spec in specs:
        row = project_step(dag, cm, spec, mode=mode, synth_bytes=synth,
                           source_world=sw, baseline_us=baseline)
        row["name"] = name
        if mfu is not None and row["projected_step_us"] > 0:
            row["projected_mfu"] = round(
                mfu * baseline / row["projected_step_us"], 4)
        else:
            row["projected_mfu"] = None
        rows.append(row)
    return {
        "trace_dir": art.trace_dir,
        "mode": mode,
        "source": {
            "world": dag.world,
            "size": sw,
            "ranks": sorted(dag.chains),
            "step": dag.step,
            "baseline_replay_us": round(baseline, 3),
            "measured_step_us": round(dag.measured_step_us, 3),
            "mfu": mfu,
        },
        "projections": rows,
    }


# ---------------------------------------------------------------------------
# projected-vs-measured accuracy (the tracked observable)
# ---------------------------------------------------------------------------
def projection_error_pct(projected_us: float, measured_us: float) -> float:
    return round((projected_us - measured_us) / measured_us * 100.0, 2)


def validate(source_dir: str, measured_dir: str,
             spec: Optional[TopologySpec] = None,
             mode: Optional[str] = None,
             source_result=None) -> dict:
    """Pin the twin's accuracy on a world we CAN run: project
    ``source_dir``'s trace onto ``measured_dir``'s topology and compare
    against what that world actually measured.  Medians across steps on
    both sides (the first step of a fresh program carries its compile).
    ``source_result`` reuses an already-analyzed ``ReplayResult`` for
    ``source_dir`` (the CLI has one in hand) instead of re-stitching.
    Returns the record served under ``validation`` on GET /projection
    and fed to ``hvd_projection_err_pct`` / bench.py."""
    from . import analyze

    src = source_result or analyze(source_dir, plan_search=False)
    tgt = analyze(measured_dir, plan_search=False)
    target_world = source_world_of(tgt)
    if spec is None:
        spec = base_spec_from_env(target_world)
    src_world = source_world_of(src)
    cm = CostModel.from_topology(
        base_spec_from_env(src_world).with_world(src_world))
    synth = synthesized_comm_bytes(src.artifacts)

    def _projected_us(d: StepDAG) -> float:
        pdag, _ = project_dag(d, cm, spec, mode=mode, synth_bytes=synth,
                              source_world=src_world)
        return schedule(pdag).makespan

    projected = statistics.median(_projected_us(d) for d in src.dags)
    measured = statistics.median(d.measured_step_us for d in tgt.dags)
    return {
        "source_dir": src.artifacts.trace_dir,
        "measured_dir": tgt.artifacts.trace_dir,
        "source_world": src_world,
        "target_world": spec.world,
        "spec": spec.to_dict(),
        "projected_step_us": round(projected, 3),
        "measured_step_us": round(measured, 3),
        "err_pct": projection_error_pct(projected, measured)
        if measured > 0 else None,
    }


def live_validation(small: int = 1, big: int = 8, *, steps: int = 7,
                    global_batch: int = 128, in_dim: int = 256,
                    classes: int = 4, width: int = 256,
                    root: Optional[str] = None, seed: int = 0) -> dict:
    """The end-to-end accuracy drive: trace an MLP train step on a
    ``small``-device CPU mesh and again on a ``big``-device mesh, project
    small→big, and return the :func:`validate` record.  Tier-1 pins the
    error band; ``bench.py --child-projection`` reports it as
    ``projection_err_pct``.

    The GLOBAL batch is held fixed across the two worlds.  On real
    hardware the projection's contract is per-rank work held fixed
    (weak scaling, every rank its own chip); the forced CPU mesh runs
    all ``big`` virtual devices on one host engine, so per-rank work
    held fixed would measure core oversubscription, not the twin.
    With the global batch fixed, the one-engine measurement executes
    exactly the work the projection schedules across its parallel
    ranks (the source process's step), and the residual error is the
    mesh-partition + collective overhead the model is supposed to
    approximate — a stable, meaningful band (docs/projection.md
    "Accuracy caveats").

    Each step is timed to completion (``block_until_ready``) and the
    trace artifacts are written directly in the capture layout —
    the in-job timeline's STEP spans cover only the async *dispatch*,
    which is exactly the dishonesty a wall-clock validation must not
    inherit.

    Leaves the hvd world SHUT DOWN (callers re-init as needed)."""
    import tempfile
    import time

    import jax
    import jax.tree_util as jtu
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from ...models.mlp import MLP
    from ...training import init_train_state, make_train_step, shard_batch

    tmpdir = None
    if root is None:
        tmpdir = tempfile.TemporaryDirectory(prefix="hvd_projection_")
        root = tmpdir.name
    devs = jax.devices("cpu")
    if len(devs) < big:
        raise RuntimeError(
            f"live projection validation wants {big} CPU devices "
            f"(xla_force_host_platform_device_count), found {len(devs)}")
    model = MLP(features=(width, classes))
    opt = optax.sgd(0.05)
    rng = np.random.default_rng(seed)

    def loss_fn(logits, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    dirs = {}
    try:
        for tag, ndev in (("source", small), ("target", big)):
            hvd.shutdown()
            hvd.init(devices=devs[:ndev])
            step = make_train_step(apply_fn=model.apply, loss_fn=loss_fn,
                                   optimizer=opt, donate=False)
            state = init_train_state(
                model, opt, np.zeros((2, in_dim), np.float32))
            x = shard_batch(rng.normal(size=(
                global_batch, in_dim)).astype(np.float32))
            y = shard_batch(rng.integers(0, classes, size=(
                global_batch,)).astype(np.int32))
            durs_us = []
            for _ in range(steps):
                t0 = time.perf_counter()
                state, loss = step(state, x, y)
                jax.block_until_ready(loss)
                durs_us.append((time.perf_counter() - t0) * 1e6)
            # capture-layout artifacts: STEP envelopes at the measured
            # wall durations + the gradient manifest (one entry per
            # parameter leaf) the synthesized collective prices
            leaves = jtu.tree_leaves(state.params)
            shapes = {f"g{i}": list(np.shape(v))
                      for i, v in enumerate(leaves)}
            dtypes = {f"g{i}": str(np.asarray(v).dtype)
                      for i, v in enumerate(leaves)}
            d = os.path.join(root, tag)
            dirs[tag] = d
            rank_dir = os.path.join(d, "0")
            os.makedirs(rank_dir, exist_ok=True)
            events, cursor = [], 0.0
            for i, dur in enumerate(durs_us):
                events.append({"name": "STEP", "cat": f"step_{i}",
                               "ph": "X", "ts": cursor, "dur": dur,
                               "pid": 0, "tid": "step"})
                cursor += dur
            for fname, payload in (
                    ("comm.json", events),
                    ("tensor_shapes.json", shapes),
                    ("tensor_dtypes.json", dtypes),
                    ("gradient_name_list.json", sorted(shapes)),
                    ("metadata.json", {"rank": 0, "size": ndev,
                                       "model": "projection-live"})):
                with open(os.path.join(rank_dir, fname), "w") as f:
                    json.dump(payload, f, indent=1)
    finally:
        hvd.shutdown()
    out = validate(dirs["source"], dirs["target"])
    out["steps"] = steps
    out["global_batch"] = global_batch
    if tmpdir is not None:
        tmpdir.cleanup()
    return out


# The serving-plane hook (projected p99 headroom per replica delta)
# lives in utils/slo.py — pure arithmetic with no replay dependencies,
# so the serving autoscaler can consult it without importing this
# stack — and is re-exported above as part of the projection API.


# ---------------------------------------------------------------------------
# gauge export
# ---------------------------------------------------------------------------
def export_projection_gauges(summary: dict,
                             err_pct: Optional[float] = None) -> None:
    """Surface the projection on the metrics plane: per-world
    ``hvd_projection_step_us`` / ``hvd_projection_efficiency`` plus the
    tracked ``hvd_projection_err_pct`` accuracy.  Never raises — the
    twin must not take down the job it describes."""
    try:
        from ... import metrics

        if not metrics.on():
            return
        for row in summary.get("projections", ()):
            world = str(row.get("world"))
            metrics.PROJECTION_STEP_US.labels(world).set(
                float(row["projected_step_us"]))
            if row.get("scaling_efficiency") is not None:
                metrics.PROJECTION_EFFICIENCY.labels(world).set(
                    float(row["scaling_efficiency"]))
        if err_pct is None:
            err_pct = (summary.get("validation") or {}).get("err_pct")
        if err_pct is not None:
            metrics.PROJECTION_ERR_PCT.set(float(err_pct))
    except Exception:  # noqa: BLE001
        pass
