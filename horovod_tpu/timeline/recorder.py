"""Recorder: model-DAG / tensor-shape / gradient-manifest dumps + step hook.

Re-design of the fork's auto-profiling recorders (the byteprofile/dPRO
layer): TF ``Recorder``/``TimelineHook`` (reference
horovod/tensorflow/recorder.py:339-521 dumps per-step Chrome traces,
partition GraphDefs, a networkx DAG as ``dag.gml``, ``tensor_shapes.json``,
``metadata.json``, ``gradient_name_list.json``; :165-193 gradient name
registration) and MXNet ``Recorder`` (reference mxnet/recorder.py:187-302,
DAG from ``symbol.debug_str()``).

TPU-native sources replace framework graph introspection:

* the **DAG** comes from the step function's jaxpr (the XLA-input graph —
  strictly more faithful than TF's partition graphs, since it is exactly
  what gets compiled);
* **tensor shapes** come from jaxpr avals;
* **gradient names** come from pytree paths;
* **per-step device traces** come from ``jax.profiler`` (XLA's own
  profiler), started/stopped by the step window — replacing the patched
  NCCL name-tagging (reference nccl_operations.cc:149-152): collective HLOs
  in the XLA trace already carry source metadata.

Outputs land in ``<dir>/<rank>/`` next to the timeline's ``comm.json``
(fork layout, reference timeline.cc:216).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax

from .. import core
from ..utils import env as env_util
from ..utils.logging import get_logger
from .timeline import timeline

log = get_logger(__name__)


def _gml_escape(s: str) -> str:
    return s.replace('"', "'")


def jaxpr_dag(closed_jaxpr) -> tuple:
    """(nodes, edges) from a ClosedJaxpr: nodes are primitives/inputs/
    outputs with shape/dtype attributes; edges follow var def→use."""
    jaxpr = closed_jaxpr.jaxpr
    nodes: List[Dict[str, Any]] = []
    edges: List[tuple] = []
    var_producer: Dict[Any, int] = {}

    def add_node(label: str, kind: str, aval=None) -> int:
        nid = len(nodes)
        node = {"id": nid, "label": label, "kind": kind}
        if aval is not None and hasattr(aval, "shape"):
            node["shape"] = list(aval.shape)
            node["dtype"] = str(getattr(aval, "dtype", ""))
        nodes.append(node)
        return nid

    for i, v in enumerate(jaxpr.invars):
        nid = add_node(f"input{i}", "input", v.aval)
        var_producer[v] = nid

    for eqn in jaxpr.eqns:
        out_aval = eqn.outvars[0].aval if eqn.outvars else None
        label = str(eqn.primitive.name)
        nid = add_node(label, "op", out_aval)
        for v in eqn.invars:
            if hasattr(v, "aval") and v in var_producer:
                edges.append((var_producer[v], nid))
        for v in eqn.outvars:
            var_producer[v] = nid

    for i, v in enumerate(jaxpr.outvars):
        nid = add_node(f"output{i}", "output",
                       v.aval if hasattr(v, "aval") else None)
        if v in var_producer:
            edges.append((var_producer[v], nid))
    return nodes, edges


def write_gml(nodes: Sequence[dict], edges: Sequence[tuple], path: str) -> None:
    """Minimal GML writer (the reference writes dag.gml via networkx,
    recorder.py:516-521; format kept compatible with nx.read_gml)."""
    with open(path, "w") as f:
        f.write("graph [\n  directed 1\n")
        for n in nodes:
            f.write(f'  node [\n    id {n["id"]}\n'
                    f'    label "{_gml_escape(str(n["label"]))}"\n')
            if "shape" in n:
                f.write(f'    shape "{tuple(n["shape"])}"\n')
            if "dtype" in n:
                f.write(f'    dtype "{n["dtype"]}"\n')
            f.write(f'    kind "{n["kind"]}"\n  ]\n')
        for s, t in edges:
            f.write(f"  edge [\n    source {s}\n    target {t}\n  ]\n")
        f.write("]\n")


def structure_dag(names: Sequence[str]) -> tuple:
    """(nodes, edges) for the aggregation step's own dataflow —
    grad_i → allreduce_i → var_i.  The eager-binding fallback DAG when
    no traced graph is available (TF eager mode, the mxnet fake); same
    node vocabulary as ``jaxpr_dag`` so dag.gml consumers see one
    format."""
    nodes, edges = [], []
    for name in names:
        g = len(nodes)
        nodes.append({"id": g, "label": f"grad/{name}", "kind": "input"})
        a = len(nodes)
        nodes.append({"id": a, "label": f"allreduce/{name}", "kind": "op"})
        v = len(nodes)
        nodes.append({"id": v, "label": name, "kind": "output"})
        edges.extend([(g, a), (a, v)])
    return nodes, edges


def write_gradient_manifest(rec: "Recorder", names: Sequence[str],
                            shapes: Dict[str, list]) -> None:
    """gradient_name_list.json + tensor_shapes.json — the shared artifact
    format both eager bindings dump (reference recorder.py:176-193
    gradient name registration)."""
    with open(rec._path("gradient_name_list.json"), "w") as f:
        json.dump(list(names), f, indent=1)
    with open(rec._path("tensor_shapes.json"), "w") as f:
        json.dump(shapes, f, indent=1)


class Recorder:
    """Capture and dump the model/step structure.

    Usage (mirrors the reference's mandatory Recorder wiring in the fork's
    DistributedTrainer, mxnet/__init__.py:92-134)::

        rec = Recorder(trace_dir)           # or env HVD_TRACE_DIR
        rec.record_step_function(step, state, x, y)   # dag.gml + shapes
        rec.register_gradients(grads_pytree)          # gradient_name_list
        rec.dump_metadata(model="ResNet50", batch=64)
    """

    def __init__(self, trace_dir: Optional[str] = None,
                 rank: Optional[int] = None):
        trace_dir = trace_dir or env_util.get_str(env_util.HVD_TRACE_DIR) \
            or env_util.get_str(env_util.HVD_TIMELINE)
        self.enabled = bool(trace_dir) and env_util.get_bool(
            env_util.HVD_TRACE_ON, True
        )
        self.rank = rank if rank is not None else (
            core.process_rank() if core.is_initialized() else 0
        )
        self.dir = os.path.join(trace_dir, str(self.rank)) if trace_dir else None
        if self.enabled and self.dir:
            os.makedirs(self.dir, exist_ok=True)

    def _path(self, name: str) -> str:
        assert self.dir is not None
        return os.path.join(self.dir, name)

    def record_step_function(self, fn: Callable, *example_args,
                             **example_kwargs) -> None:
        """Trace ``fn`` to a jaxpr and dump dag.gml + tensor_shapes.json
        (reference recorder.py:339-521 equivalents)."""
        if not self.enabled:
            return
        closed = jax.make_jaxpr(fn)(*example_args, **example_kwargs)
        nodes, edges = jaxpr_dag(closed)
        write_gml(nodes, edges, self._path("dag.gml"))
        shapes = {
            f'{n["label"]}.{n["id"]}': n["shape"]
            for n in nodes if "shape" in n
        }
        with open(self._path("tensor_shapes.json"), "w") as f:
            json.dump(shapes, f, indent=1)
        log.debug("recorder: dag.gml with %d nodes, %d edges",
                  len(nodes), len(edges))

    def register_gradients(self, grads: Any) -> None:
        """gradient_name_list.json from pytree paths (reference
        recorder.py:176-193 register_tensors / gradient name manifest).

        Also merges each gradient's shape into ``tensor_shapes.json`` and
        its dtype into ``tensor_dtypes.json``, keyed by manifest name —
        the byte counts the replay engine's what-if cost model
        (timeline/replay/stitcher.py) joins comm events against."""
        if not self.enabled:
            return
        leaves = jax.tree_util.tree_flatten_with_path(grads)[0]
        paths = [
            "gradients/" + "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                                    for k in path)
            for path, _ in leaves
        ]
        with open(self._path("gradient_name_list.json"), "w") as f:
            json.dump(paths, f, indent=1)
        shapes: Dict[str, list] = {}
        dtypes: Dict[str, str] = {}
        for name, (_, leaf) in zip(paths, leaves):
            if hasattr(leaf, "shape"):
                shapes[name] = list(leaf.shape)
                dtypes[name] = str(getattr(leaf, "dtype", "float32"))
        if shapes:
            # merge, don't overwrite: record_step_function and earlier
            # register_gradients calls (second param group, elastic
            # rejoin) contribute keys too — losing a dtype silently
            # falls the stitcher back to the 4-byte default
            for name, payload in (("tensor_shapes.json", shapes),
                                  ("tensor_dtypes.json", dtypes)):
                path = self._path(name)
                if os.path.isfile(path):
                    with open(path) as f:
                        existing = json.load(f)
                    existing.update(payload)
                    payload = existing
                with open(path, "w") as f:
                    json.dump(payload, f, indent=1)

    def dump_metadata(self, **meta: Any) -> None:
        """metadata.json (reference recorder.py metadata dump: model name,
        dtypes, cluster shape...)."""
        if not self.enabled:
            return
        base = {
            "rank": self.rank,
            "size": core.size() if core.is_initialized() else 1,
            "local_size": core.local_size() if core.is_initialized() else 1,
            "platform": core._state.platform,
        }
        base.update(meta)
        with open(self._path("metadata.json"), "w") as f:
            json.dump(base, f, indent=1)


class TimelineHook:
    """Step-driven trace controller (reference tensorflow/recorder.py
    TimelineHook, a ProfilerHook subclass: collects traces only inside the
    [start_step, end_step] window).

    Wrap the training loop::

        hook = TimelineHook(recorder)
        for batch in data:
            with hook.step():
                state, loss = train_step(state, batch)
    """

    def __init__(self, recorder: Recorder,
                 start_step: Optional[int] = None,
                 end_step: Optional[int] = None,
                 xla_profile: bool = False):
        self.recorder = recorder
        self.start_step = start_step if start_step is not None else \
            env_util.get_int(env_util.HVD_TRACE_START_STEP, 0)
        self.end_step = end_step if end_step is not None else \
            env_util.get_int(env_util.HVD_TRACE_END_STEP, 1 << 62)
        self.xla_profile = xla_profile
        self._step = 0
        self._profiling = False
        if self.recorder.enabled:
            timeline.initialize(os.path.dirname(self.recorder.dir))

    def _in_window(self) -> bool:
        return self.start_step <= self._step <= self.end_step

    def step(self):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._step = timeline.record_step(owner="timeline_hook")
            enabled = self.recorder.enabled and self._in_window()
            if enabled and self.xla_profile and not self._profiling:
                jax.profiler.start_trace(self.recorder._path("xla_trace"))
                self._profiling = True
            with timeline.span(f"step_{self._step}", "STEP"):
                yield self._step
            if self._profiling and (
                not self._in_window() or self._step >= self.end_step
            ):
                jax.profiler.stop_trace()
                self._profiling = False

        return ctx()
