"""Collective-traffic report for a compiled SPMD step.

The reference's second headline metric is allreduce *scaling efficiency*
(90% for ResNet-101 on 512 GPUs, reference README.rst:75-77,
docs/benchmarks.rst:12-13), measured on a real cluster.  This repo's
bench host has one chip, so the stand-in is analytical: compile the train
step on a virtual mesh, read the collective instructions out of the
optimized HLO, and model the communication:compute ratio — the quantity
scaling efficiency is made of.

Usage::

    from horovod_tpu.timeline.comm_report import collective_report
    report = collective_report(step, state, x, y)   # step = hvd.spmd(...)
    # {'collectives': {'all-reduce': {'count': 3, 'bytes': ...}, ...},
    #  'flops_per_step': ..., 'scaling_model': {8: 0.97, 64: 0.93, ...}}

``scripts/comm_report.py`` runs it for the headline ResNet-50 step.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, Optional, Tuple

import numpy as np

# HLO collective opcodes and whether their wire volume scales with the
# ring: all-reduce moves 2(n-1)/n of the buffer per link; all-gather and
# reduce-scatter (n-1)/n; collective-permute and all-to-all move the
# full shard once.
_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute",
)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
    "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8, "u64": 8, "c64": 8,
    # fp8 families (quantized-allreduce paths emit these) and c128: a
    # missing entry silently counts the collective as 0 bytes, so the
    # traffic report under-models exactly the payloads compression is
    # supposed to shrink
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1, "f8e3m4": 1,
    "f8e4m3fnuz": 1, "f8e4m3b11fnuz": 1, "f8e5m2fnuz": 1, "f8e8m0fnu": 1,
    "s4": 1, "u4": 1,  # int4 is byte-padded on the wire
    "c128": 16,
}

# instruction result: one or more "dtype[d0,d1]{layout}" entries
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
# layout annotation directly after a dims bracket: TPU optimized HLO
# writes tiled layouts like "f32[128,256]{1,0:T(8,128)}" whose parens
# would abort _INSTR_RE's shape branch — strip them before matching.
_LAYOUT_RE = re.compile(r"(\])\{[^{}]*\}")
# shape group allows one level of tuple nesting: multi-operand async
# starts have shapes like ((f32[...], f32[...]), (f32[...], f32[...]), ...)
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*"
    r"(\((?:[^()]|\([^()]*\))*\)|[^=(]+?)\s+"
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(-start)?\(",
    re.M,
)


def _array_bytes(s: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(s):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _split_top_level(tup: str):
    """Top-level elements of an HLO tuple-shape string
    '(f32[128,256]{1,0}, (b, c), d)' — commas inside (), [] and {} (dims
    and layouts) do not split."""
    inner = tup.strip()
    if inner.startswith("(") and inner.endswith(")"):
        inner = inner[1:-1]
    parts, depth, start = [], 0, 0
    for i, ch in enumerate(inner):
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(inner[start:i])
            start = i + 1
    parts.append(inner[start:])
    return parts


def _shape_bytes(shapes: str, *, payload_only: bool = False) -> int:
    """Bytes of an HLO result-shape string.  ``payload_only``: the shape
    is an async ``-start`` tuple carrying operands AND results —
    ``(operand, result, ctx...)`` or ``((ops...), (results...), ctx)``.
    The payload is the largest top-level element (operand == result for
    all-reduce/permute; the result for all-gather; the operand for
    reduce-scatter — in every case the max, and context scalars lose)."""
    if not payload_only:
        return _array_bytes(shapes)
    return max(
        (_array_bytes(p) for p in _split_top_level(shapes)), default=0
    )


def hlo_collectives(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Count collective instructions and their payload bytes in optimized
    HLO text (``-done`` halves of async pairs are skipped; ``-start``
    tuple shapes count their payload once)."""
    out: Dict[str, Dict[str, int]] = {}
    hlo_text = _LAYOUT_RE.sub(r"\1", hlo_text)
    for m in _INSTR_RE.finditer(hlo_text):
        shapes, op, is_start = m.group(1), m.group(2), bool(m.group(3))
        d = out.setdefault(op, {"count": 0, "bytes": 0})
        d["count"] += 1
        d["bytes"] += _shape_bytes(
            shapes, payload_only=is_start and shapes.startswith("(")
        )
    return out


def _link_volume(op: str, nbytes: int, n: int) -> float:
    """Bytes crossing the busiest ICI link for one ring execution."""
    if n <= 1:
        return 0.0
    if op == "all-reduce":
        return 2.0 * (n - 1) / n * nbytes
    if op in ("all-gather", "reduce-scatter"):
        return (n - 1) / n * nbytes
    if op == "broadcast":
        return float(nbytes)        # pipelined ring bcast: full buffer
    return float(nbytes)  # permute / all-to-all: one shard hop


def _ring_hops(op: str, n: int) -> int:
    """Serialized neighbor exchanges in a 1-D ring execution of ``op`` —
    the latency (α) term's multiplier."""
    if n <= 1:
        return 0
    if op == "all-reduce":
        return 2 * (n - 1)          # reduce-scatter + all-gather phases
    if op in ("all-gather", "reduce-scatter", "broadcast"):
        return n - 1
    return 1                        # permute / all-to-all: one exchange


#: cost curves of the wire formats in ops/compression.py — the itemsize
#: MUST agree with the compressors' ``wire_itemsize``.  ``qd_us_per_mib``
#: models the quantize+dequantize kernel pair per MiB of *uncompressed*
#: payload (bf16 is a pure cast; int8 adds round+clip on the VPU; fp8
#: adds the float-format conversion); ``scale_exchange`` adds one scalar
#: all-reduce's α per call (the per-tensor max-|x| agreement quantizers
#: need — pure latency, the payload is one float).
COMPRESSION_MODEL = {
    "bf16": {"itemsize": 2, "qd_us_per_mib": 0.5, "scale_exchange": False},
    "fp16": {"itemsize": 2, "qd_us_per_mib": 0.5, "scale_exchange": False},
    "int8": {"itemsize": 1, "qd_us_per_mib": 1.0, "scale_exchange": True},
    "fp8": {"itemsize": 1, "qd_us_per_mib": 1.5, "scale_exchange": True},
    "fp8_e4m3": {"itemsize": 1, "qd_us_per_mib": 1.5,
                 "scale_exchange": True},
    "fp8_e5m2": {"itemsize": 1, "qd_us_per_mib": 1.5,
                 "scale_exchange": True},
}

#: modeled cross-host (DCN) link for the two-level shape — an order
#: cheaper than ICI in bandwidth and an order worse in latency; override
#: per job via HVD_REPLAY_DCN_GBPS / HVD_REPLAY_DCN_HOP_US
DEFAULT_DCN_BYTES_PER_SEC = 25e9
DEFAULT_DCN_HOP_LATENCY = 10e-6

#: modeled ICI link (v5e: ~186 GB/s per direction, ~1 µs per neighbor
#: hop) — the ONE place these constants live: the replay CostModel, the
#: SCALING.md tables, and the projection engine all read them from here
DEFAULT_ICI_BYTES_PER_SEC = 186e9
DEFAULT_ICI_HOP_LATENCY = 1e-6


@dataclasses.dataclass(frozen=True)
class TopologySpec:
    """One communication topology — real or hypothetical — as the cost
    model sees it: world size, the ICI/DCN tier split (``local_size``
    ranks share an ICI domain; ``cross_size`` domains meet over DCN),
    per-tier α–β parameters, and the wire-format policy (compression /
    two-level) the runtime would run with.

    This is the single source of topology assumptions: the SCALING.md
    efficiency tables (:func:`model_scaling` / :func:`collective_report`),
    the replay what-ifs (timeline/replay/simulator.py ``CostModel``), and
    the digital-twin projection engine (timeline/replay/projection.py,
    ``hvd_replay --project``) all price collectives through a spec, so a
    docs table and a projection can never disagree on α–β/tier numbers.

    ``two_level`` policy: ``"off"`` always prices the flat ring,
    ``"on"`` prices the hierarchical shape whenever the topology
    decomposes (degrading to flat exactly like the runtime), ``"auto"``
    picks whichever the model says is cheaper — the choice a planner
    would make.  ``flat_fabric`` picks the link the FLAT ring runs at:
    ``"auto"`` uses DCN whenever the spec spans hosts (a flat ring runs
    at its slowest link), ``"ici"`` pins the legacy single-torus
    assumption the SCALING.md base tables are built on."""

    world: int
    local_size: int = 1
    ici_bytes_per_sec: float = DEFAULT_ICI_BYTES_PER_SEC
    ici_hop_latency_us: float = DEFAULT_ICI_HOP_LATENCY * 1e6
    dcn_bytes_per_sec: float = DEFAULT_DCN_BYTES_PER_SEC
    dcn_hop_latency_us: float = DEFAULT_DCN_HOP_LATENCY * 1e6
    compression: Optional[str] = None
    two_level: str = "off"              # "off" | "on" | "auto"
    flat_fabric: str = "auto"           # "auto" | "ici"

    @property
    def cross_size(self) -> int:
        """ICI domains meeting over DCN (1 when the spec doesn't
        decompose — the whole world is one domain)."""
        if self.local_size > 1 and self.world % self.local_size == 0:
            return self.world // self.local_size
        return 1

    def two_level_possible(self) -> bool:
        """Same decomposability rule the runtime's degrade uses
        (parallel/hierarchical.py): >1 rank per ICI domain AND >1
        domain."""
        return (self.local_size > 1 and self.world % self.local_size == 0
                and self.world // self.local_size > 1)

    def spans_dcn(self) -> bool:
        """True when the spec declares more than one host group — the
        flat ring would cross DCN links."""
        return self.cross_size > 1

    def with_world(self, world: int) -> "TopologySpec":
        return dataclasses.replace(self, world=int(world))

    def _flat_params(self) -> Tuple[float, float]:
        """(bytes_per_sec, hop_latency_seconds) the FLAT ring runs at."""
        if self.flat_fabric != "ici" and self.spans_dcn():
            return self.dcn_bytes_per_sec, self.dcn_hop_latency_us * 1e-6
        return self.ici_bytes_per_sec, self.ici_hop_latency_us * 1e-6

    def _flat_us(self, op: str, nbytes: int, *, calls: int = 1,
                 compression: Optional[str] = None,
                 orig_itemsize: int = 4) -> float:
        bw, hop = self._flat_params()
        return predict_collective_us(
            op, nbytes, self.world, calls=calls,
            ici_bytes_per_sec=bw, ici_hop_latency=hop,
            compression=compression, orig_itemsize=orig_itemsize)

    def _two_level_us(self, op: str, nbytes: int, *, calls: int = 1,
                      compression: Optional[str] = None,
                      orig_itemsize: int = 4) -> float:
        return predict_collective_us(
            op, nbytes, self.world, calls=calls,
            ici_bytes_per_sec=self.ici_bytes_per_sec,
            ici_hop_latency=self.ici_hop_latency_us * 1e-6,
            compression=compression, orig_itemsize=orig_itemsize,
            two_level=True, local_size=self.local_size,
            dcn_bytes_per_sec=self.dcn_bytes_per_sec,
            dcn_hop_latency=self.dcn_hop_latency_us * 1e-6)

    def wire_choice(self, op: str, nbytes: int, *, calls: int = 1,
                    compression: Optional[str] = None,
                    orig_itemsize: int = 4) -> Tuple[str, float]:
        """``(wire_format, predicted_us)`` under this spec's policy —
        the decision the projection engine reports per collective.
        ``wire_format`` is ``"flat"`` or ``"two_level"``, suffixed with
        ``+<compression>`` when a wire format compresses."""
        flat = self._flat_us(op, nbytes, calls=calls,
                             compression=compression,
                             orig_itemsize=orig_itemsize)
        can_two = (op == "all-reduce" and self.two_level != "off"
                   and self.two_level_possible())
        if can_two:
            two = self._two_level_us(op, nbytes, calls=calls,
                                     compression=compression,
                                     orig_itemsize=orig_itemsize)
            if self.two_level == "on" or two < flat:
                return self._tag("two_level", compression), two
        return self._tag("flat", compression), flat

    @staticmethod
    def _tag(base: str, compression: Optional[str]) -> str:
        return f"{base}+{compression}" if compression else base

    def predict_us(self, op: str, nbytes: int, *, calls: int = 1,
                   compression: Optional[str] = "__spec__",
                   orig_itemsize: int = 4) -> float:
        """α–β cost of ``op`` under this spec's wire policy (the
        ``wire_choice`` price; ``compression`` defaults to the spec's
        own, pass ``None`` to force uncompressed)."""
        comp = self.compression if compression == "__spec__" else compression
        return self.wire_choice(op, nbytes, calls=calls, compression=comp,
                                orig_itemsize=orig_itemsize)[1]

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["cross_size"] = self.cross_size
        return d

    def describe(self) -> str:
        s = f"world={self.world}"
        if self.local_size > 1:
            s += f" local={self.local_size}x{self.cross_size}"
        if self.two_level != "off":
            s += f" two_level={self.two_level}"
        if self.compression:
            s += f" compression={self.compression}"
        return s


def _compression_spec(compression):
    if not compression or str(compression).lower() in ("none", "ef_none"):
        return None
    key = str(compression).lower()
    if key.startswith("ef_"):
        key = key[3:]               # error feedback rides the same wire
    spec = COMPRESSION_MODEL.get(key)
    if spec is None:
        raise ValueError(
            f"no cost curve for compression {compression!r}; known: "
            f"{', '.join(sorted(COMPRESSION_MODEL))}")
    return spec


def compression_wire_ratio(compression, orig_itemsize: int = 4) -> float:
    """Compressed-to-original wire-byte ratio for a payload of
    ``orig_itemsize``-byte elements (never above 1 — compressing bf16 to
    bf16 is free, not a doubling)."""
    spec = _compression_spec(compression)
    if spec is None:
        return 1.0
    return min(1.0, spec["itemsize"] / max(int(orig_itemsize), 1))


def compression_overhead_us(nbytes: int, compression) -> float:
    """Quantize+dequantize µs for ``nbytes`` of uncompressed payload."""
    spec = _compression_spec(compression)
    if spec is None:
        return 0.0
    return nbytes / 2**20 * spec["qd_us_per_mib"]


def compression_scale_exchange(compression) -> bool:
    spec = _compression_spec(compression)
    return bool(spec and spec["scale_exchange"])


def compression_terms_us(compression, nbytes: int, world: int,
                         hop_latency_us: float,
                         orig_itemsize: int = 4
                         ) -> Tuple[float, float, float]:
    """``(wire_ratio, qd_us, scale_alpha_us)`` — the three compression
    cost terms every pricing site composes identically (the replay
    CostModel's calibrated what-ifs and the projection engine; the
    flat/two-level shapes inside :func:`predict_collective_us` inline
    the same primitives).  One helper so a cost-curve change (a new
    quantizer overhead term, a different scale-exchange shape) cannot
    silently desync the pricing sites."""
    spec = _compression_spec(compression)
    if spec is None:
        return 1.0, 0.0, 0.0
    ratio = compression_wire_ratio(compression, orig_itemsize)
    qd = compression_overhead_us(nbytes, compression)
    scale = (_ring_hops("all-reduce", world) * hop_latency_us
             if spec["scale_exchange"] else 0.0)
    return ratio, qd, scale


def predict_collective_us(
    op: str,
    nbytes: int,
    world: int,
    *,
    calls: int = 1,
    ici_bytes_per_sec: float = DEFAULT_ICI_BYTES_PER_SEC,
    ici_hop_latency: float = DEFAULT_ICI_HOP_LATENCY,
    compression: Optional[str] = None,
    orig_itemsize: int = 4,
    two_level: bool = False,
    local_size: Optional[int] = None,
    dcn_bytes_per_sec: Optional[float] = None,
    dcn_hop_latency: Optional[float] = None,
) -> float:
    """α–β cost of ``calls`` ring executions of ``op`` moving ``nbytes``
    total, in µs — THE cost model: ``collective_report``'s scaling
    curves, the per-tensor table below, and the replay engine's what-if
    simulator (timeline/replay/simulator.py) all call this one function,
    so a what-if and the report can never disagree on predicted cost.

    ``compression`` (a registry name from ops/compression.py) prices the
    wire-efficiency tier: β shrinks by the wire-byte ratio, and the
    quantize/dequantize overhead plus the quantizers' scalar scale
    exchange (one α) are added — compression is NOT free, which is
    exactly why the planner must rank it against fusion on one scale.

    ``two_level=True`` (all-reduce only) prices the hierarchical shape
    (parallel/hierarchical.py ``two_level_allreduce``): a local
    reduce-scatter and all-gather on ICI at full precision, and the
    cross-host all-reduce on the 1/local_size shard over the DCN link —
    with ``compression`` applied to the cross stage only, where it is
    applied in the real path.  Falls back to the flat shape when the
    topology can't decompose (local_size unset/1, or not dividing
    world) — mirroring the runtime's own degrade."""
    spec = _compression_spec(compression)
    ratio = compression_wire_ratio(compression, orig_itemsize)
    scale_hops = _ring_hops("all-reduce", world) if spec \
        and spec["scale_exchange"] else 0

    if two_level and op == "all-reduce" and local_size \
            and local_size > 1 and world % local_size == 0 \
            and world // local_size > 1:
        l, c = int(local_size), world // int(local_size)
        dcn_bw = dcn_bytes_per_sec if dcn_bytes_per_sec is not None \
            else DEFAULT_DCN_BYTES_PER_SEC
        dcn_hop = dcn_hop_latency if dcn_hop_latency is not None \
            else DEFAULT_DCN_HOP_LATENCY
        shard = nbytes / l
        t = (
            # local reduce-scatter + all-gather, full precision on ICI
            _link_volume("reduce-scatter", nbytes, l) / ici_bytes_per_sec
            + _link_volume("all-gather", nbytes, l) / ici_bytes_per_sec
            + calls * 2 * _ring_hops("reduce-scatter", l) * ici_hop_latency
            # cross all-reduce on the (compressed) shard over DCN
            + _link_volume("all-reduce", shard * ratio, c) / dcn_bw
            + calls * _ring_hops("all-reduce", c) * dcn_hop
            # quantize/dequantize the shard; scale exchange rides DCN
            + compression_overhead_us(int(shard), compression) * 1e-6
            + (calls * _ring_hops("all-reduce", c) * dcn_hop
               if spec and spec["scale_exchange"] else 0.0)
        )
        return t * 1e6

    t = (_link_volume(op, nbytes * ratio, world) / ici_bytes_per_sec
         + calls * _ring_hops(op, world) * ici_hop_latency
         + compression_overhead_us(nbytes, compression) * 1e-6
         + calls * scale_hops * ici_hop_latency)
    return t * 1e6


def per_tensor_table(
    tensors: Dict[str, Dict[str, Any]],
    world: int,
    *,
    measured_us: Optional[Dict[str, float]] = None,
    ici_bytes_per_sec: float = DEFAULT_ICI_BYTES_PER_SEC,
    ici_hop_latency: float = DEFAULT_ICI_HOP_LATENCY,
) -> Dict[str, Dict[str, Any]]:
    """Per-tensor cost table: ``tensors`` maps tensor name ->
    ``{"op", "bytes", "calls"}`` (``calls`` defaults to 1) and the result
    adds ``predicted_us`` from :func:`predict_collective_us` plus, when a
    ``measured_us`` map is given (e.g. comm-span durations out of a
    merged trace), ``measured_us`` and ``model_error_pct`` — the
    prediction-vs-reality check that tells you whether a what-if built on
    this model is trustworthy for that tensor."""
    measured_us = measured_us or {}
    table: Dict[str, Dict[str, Any]] = {}
    for name, d in tensors.items():
        op = str(d.get("op", "all-reduce"))
        nbytes = int(d.get("bytes", 0) or 0)
        calls = int(d.get("calls", 1) or 1)
        row: Dict[str, Any] = {
            "op": op,
            "bytes": nbytes,
            "calls": calls,
            "predicted_us": round(predict_collective_us(
                op, nbytes, world, calls=calls,
                ici_bytes_per_sec=ici_bytes_per_sec,
                ici_hop_latency=ici_hop_latency), 3),
        }
        if name in measured_us:
            m = float(measured_us[name])
            row["measured_us"] = round(m, 3)
            if m > 0:
                row["model_error_pct"] = round(
                    (row["predicted_us"] - m) / m * 100.0, 1)
        table[name] = row
    return table


def model_scaling(
    cols: Dict[str, Dict[str, int]],
    t_compute: Optional[float],
    *,
    sizes=(8, 16, 32, 64),
    ici_bytes_per_sec: float = DEFAULT_ICI_BYTES_PER_SEC,
    ici_hop_latency: float = DEFAULT_ICI_HOP_LATENCY,
    compression: Optional[str] = None,
    orig_itemsize: int = 4,
    two_level: bool = False,
    local_size: Optional[int] = None,
    dcn_bytes_per_sec: Optional[float] = None,
    dcn_hop_latency: Optional[float] = None,
):
    """The pure α-β curve: ({n: t_comm_seconds}, {n: efficiency}) from a
    collective profile (``hlo_collectives`` output) and a per-step
    single-chip compute time.  ``compression``/``two_level`` model the
    wire-efficiency tier (docs/compression.md) on the same curve — the
    SCALING.md story of whether 96–99% at 64 chips survives 10× bigger
    gradient payloads.  ``orig_itemsize`` is the payload's element size
    (default f32 = 4): pass 2 for bf16-native gradients, or the wire
    ratio of bf16/int8 compression is overstated (``cols`` aggregates
    bytes only, so the dtype must come from the caller).  Routed
    through one :class:`TopologySpec` per world size (and through
    :func:`predict_collective_us` underneath) so this curve, the replay
    what-ifs, and the ``hvd_replay --project`` projections share one
    arithmetic — a SCALING.md table and a projection can't disagree.
    ``flat_fabric="ici"`` pins the legacy single-torus assumption: the
    DCN link only enters through ``two_level=True``, exactly as these
    tables have always been computed."""
    base = TopologySpec(
        world=0,
        local_size=int(local_size) if local_size else 1,
        ici_bytes_per_sec=ici_bytes_per_sec,
        ici_hop_latency_us=ici_hop_latency * 1e6,
        dcn_bytes_per_sec=dcn_bytes_per_sec
        if dcn_bytes_per_sec is not None else DEFAULT_DCN_BYTES_PER_SEC,
        dcn_hop_latency_us=(dcn_hop_latency if dcn_hop_latency is not None
                            else DEFAULT_DCN_HOP_LATENCY) * 1e6,
        two_level="on" if two_level else "off",
        flat_fabric="ici",
    )
    comm_seconds, scaling = {}, {}
    for n in sizes:
        spec = base.with_world(n)
        t_comm = sum(
            spec.predict_us(
                op, d["bytes"], calls=d["count"],
                # only the gradient all-reduce path compresses; other
                # collectives (batch-stat gathers, permutes) ride as-is
                compression=compression if op == "all-reduce" else None,
                orig_itemsize=orig_itemsize,
            ) * 1e-6
            for op, d in cols.items()
        )
        comm_seconds[n] = round(t_comm, 6)
        scaling[n] = (
            round(t_compute / (t_compute + t_comm), 4)
            if t_compute else None
        )
    return comm_seconds, scaling


def collective_report(
    step_fn,
    *args,
    # None → utils/flops.peak_flops(): the ONE peak constant (v5e
    # 197e12 unless HVD_PEAK_FLOPS overrides) every MFU number divides
    # by — a hardware change can't desync this report from bench.py or
    # the compute-anatomy profiler
    peak_flops: Optional[float] = None,
    ici_bytes_per_sec: float = DEFAULT_ICI_BYTES_PER_SEC,
    ici_hop_latency: float = DEFAULT_ICI_HOP_LATENCY,
    sizes=(8, 16, 32, 64),
    measured_step_seconds: Optional[float] = None,
    compression: Optional[str] = None,
    orig_itemsize: int = 4,
    two_level: bool = False,
    local_size: Optional[int] = None,
    dcn_bytes_per_sec: Optional[float] = None,
    dcn_hop_latency: Optional[float] = None,
    **kwargs,
) -> Dict[str, Any]:
    """Compile ``step_fn`` (a jitted/spmd-wrapped callable) on the current
    mesh and report its collective traffic plus a roofline scaling model.

    The α-β model: per-step compute time = measured single-chip step time
    when given (the honest base — pass the bench number), else
    flops/peak; per-step comm time at world size n =
    Σ_ops [ link_volume(op, bytes, n) / ici_bw            (β, bandwidth)
          + count(op) · ring_hops(op, n) · hop_latency ]  (α, latency);
    efficiency(n) = t_compute / (t_compute + t_comm(n)) — the no-overlap
    bound (XLA overlaps some collectives, so the real curve sits between
    this and 1.0; the reference's 90%-at-512, README.rst:75-77, is the
    same quantity measured).  The α term is why per-tensor collective
    streams (the hierarchical path's one-RS/AG-per-gradient) scale worse
    than fused buckets even at equal bytes — the reference's whole fusion
    rationale (SURVEY §2.1)."""
    import jax

    if peak_flops is None:
        from ..utils.flops import peak_flops as _peak_flops

        peak_flops = _peak_flops()

    lowered = step_fn.lower(*args, **kwargs) if hasattr(step_fn, "lower") \
        else jax.jit(step_fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    txt = compiled.as_text()
    cols = hlo_collectives(txt)

    cost = compiled.cost_analysis()
    if isinstance(cost, list):
        cost = cost[0] if cost else {}
    flops = float((cost or {}).get("flops", 0.0))

    t_compute = measured_step_seconds if measured_step_seconds \
        else (flops / peak_flops if flops else None)
    comm_seconds, scaling = model_scaling(
        cols, t_compute, sizes=sizes,
        ici_bytes_per_sec=ici_bytes_per_sec,
        ici_hop_latency=ici_hop_latency,
        compression=compression, orig_itemsize=orig_itemsize,
        two_level=two_level,
        local_size=local_size, dcn_bytes_per_sec=dcn_bytes_per_sec,
        dcn_hop_latency=dcn_hop_latency,
    )
    return {
        "collectives": cols,
        "total_collective_bytes": sum(d["bytes"] for d in cols.values()),
        "flops_per_step": flops,
        "assumptions": {
            "peak_flops": peak_flops,
            "ici_bytes_per_sec": ici_bytes_per_sec,
            "ici_hop_latency": ici_hop_latency,
            "t_compute_seconds": t_compute,
            "t_compute_source": "measured" if measured_step_seconds
            else "flops/peak",
            "compression": compression or "none",
            "two_level": bool(two_level),
            "local_size": local_size,
            "model": "efficiency = t_compute / (t_compute + t_comm); "
                     "t_comm = bytes-on-busiest-link/bw + "
                     "count*ring_hops*hop_latency; 1-D ring, no overlap",
        },
        "modeled_comm_seconds": comm_seconds,
        "scaling_model": scaling,
    }
