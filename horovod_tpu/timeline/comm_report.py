"""Collective-traffic report for a compiled SPMD step.

The reference's second headline metric is allreduce *scaling efficiency*
(90% for ResNet-101 on 512 GPUs, reference README.rst:75-77,
docs/benchmarks.rst:12-13), measured on a real cluster.  This repo's
bench host has one chip, so the stand-in is analytical: compile the train
step on a virtual mesh, read the collective instructions out of the
optimized HLO, and model the communication:compute ratio — the quantity
scaling efficiency is made of.

Usage::

    from horovod_tpu.timeline.comm_report import collective_report
    report = collective_report(step, state, x, y)   # step = hvd.spmd(...)
    # {'collectives': {'all-reduce': {'count': 3, 'bytes': ...}, ...},
    #  'flops_per_step': ..., 'scaling_model': {8: 0.97, 64: 0.93, ...}}

``scripts/comm_report.py`` runs it for the headline ResNet-50 step.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional

import numpy as np

# HLO collective opcodes and whether their wire volume scales with the
# ring: all-reduce moves 2(n-1)/n of the buffer per link; all-gather and
# reduce-scatter (n-1)/n; collective-permute and all-to-all move the
# full shard once.
_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute",
)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
    "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8, "u64": 8, "c64": 8,
    # fp8 families (quantized-allreduce paths emit these) and c128: a
    # missing entry silently counts the collective as 0 bytes, so the
    # traffic report under-models exactly the payloads compression is
    # supposed to shrink
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1, "f8e3m4": 1,
    "f8e4m3fnuz": 1, "f8e4m3b11fnuz": 1, "f8e5m2fnuz": 1, "f8e8m0fnu": 1,
    "s4": 1, "u4": 1,  # int4 is byte-padded on the wire
    "c128": 16,
}

# instruction result: one or more "dtype[d0,d1]{layout}" entries
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
# layout annotation directly after a dims bracket: TPU optimized HLO
# writes tiled layouts like "f32[128,256]{1,0:T(8,128)}" whose parens
# would abort _INSTR_RE's shape branch — strip them before matching.
_LAYOUT_RE = re.compile(r"(\])\{[^{}]*\}")
# shape group allows one level of tuple nesting: multi-operand async
# starts have shapes like ((f32[...], f32[...]), (f32[...], f32[...]), ...)
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*"
    r"(\((?:[^()]|\([^()]*\))*\)|[^=(]+?)\s+"
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(-start)?\(",
    re.M,
)


def _array_bytes(s: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(s):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _split_top_level(tup: str):
    """Top-level elements of an HLO tuple-shape string
    '(f32[128,256]{1,0}, (b, c), d)' — commas inside (), [] and {} (dims
    and layouts) do not split."""
    inner = tup.strip()
    if inner.startswith("(") and inner.endswith(")"):
        inner = inner[1:-1]
    parts, depth, start = [], 0, 0
    for i, ch in enumerate(inner):
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(inner[start:i])
            start = i + 1
    parts.append(inner[start:])
    return parts


def _shape_bytes(shapes: str, *, payload_only: bool = False) -> int:
    """Bytes of an HLO result-shape string.  ``payload_only``: the shape
    is an async ``-start`` tuple carrying operands AND results —
    ``(operand, result, ctx...)`` or ``((ops...), (results...), ctx)``.
    The payload is the largest top-level element (operand == result for
    all-reduce/permute; the result for all-gather; the operand for
    reduce-scatter — in every case the max, and context scalars lose)."""
    if not payload_only:
        return _array_bytes(shapes)
    return max(
        (_array_bytes(p) for p in _split_top_level(shapes)), default=0
    )


def hlo_collectives(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Count collective instructions and their payload bytes in optimized
    HLO text (``-done`` halves of async pairs are skipped; ``-start``
    tuple shapes count their payload once)."""
    out: Dict[str, Dict[str, int]] = {}
    hlo_text = _LAYOUT_RE.sub(r"\1", hlo_text)
    for m in _INSTR_RE.finditer(hlo_text):
        shapes, op, is_start = m.group(1), m.group(2), bool(m.group(3))
        d = out.setdefault(op, {"count": 0, "bytes": 0})
        d["count"] += 1
        d["bytes"] += _shape_bytes(
            shapes, payload_only=is_start and shapes.startswith("(")
        )
    return out


def _link_volume(op: str, nbytes: int, n: int) -> float:
    """Bytes crossing the busiest ICI link for one ring execution."""
    if n <= 1:
        return 0.0
    if op == "all-reduce":
        return 2.0 * (n - 1) / n * nbytes
    if op in ("all-gather", "reduce-scatter"):
        return (n - 1) / n * nbytes
    if op == "broadcast":
        return float(nbytes)        # pipelined ring bcast: full buffer
    return float(nbytes)  # permute / all-to-all: one shard hop


def _ring_hops(op: str, n: int) -> int:
    """Serialized neighbor exchanges in a 1-D ring execution of ``op`` —
    the latency (α) term's multiplier."""
    if n <= 1:
        return 0
    if op == "all-reduce":
        return 2 * (n - 1)          # reduce-scatter + all-gather phases
    if op in ("all-gather", "reduce-scatter", "broadcast"):
        return n - 1
    return 1                        # permute / all-to-all: one exchange


#: cost curves of the wire formats in ops/compression.py — the itemsize
#: MUST agree with the compressors' ``wire_itemsize``.  ``qd_us_per_mib``
#: models the quantize+dequantize kernel pair per MiB of *uncompressed*
#: payload (bf16 is a pure cast; int8 adds round+clip on the VPU; fp8
#: adds the float-format conversion); ``scale_exchange`` adds one scalar
#: all-reduce's α per call (the per-tensor max-|x| agreement quantizers
#: need — pure latency, the payload is one float).
COMPRESSION_MODEL = {
    "bf16": {"itemsize": 2, "qd_us_per_mib": 0.5, "scale_exchange": False},
    "fp16": {"itemsize": 2, "qd_us_per_mib": 0.5, "scale_exchange": False},
    "int8": {"itemsize": 1, "qd_us_per_mib": 1.0, "scale_exchange": True},
    "fp8": {"itemsize": 1, "qd_us_per_mib": 1.5, "scale_exchange": True},
    "fp8_e4m3": {"itemsize": 1, "qd_us_per_mib": 1.5,
                 "scale_exchange": True},
    "fp8_e5m2": {"itemsize": 1, "qd_us_per_mib": 1.5,
                 "scale_exchange": True},
}

#: modeled cross-host (DCN) link for the two-level shape — an order
#: cheaper than ICI in bandwidth and an order worse in latency; override
#: per job via HVD_REPLAY_DCN_GBPS / HVD_REPLAY_DCN_HOP_US
DEFAULT_DCN_BYTES_PER_SEC = 25e9
DEFAULT_DCN_HOP_LATENCY = 10e-6


def _compression_spec(compression):
    if not compression or str(compression).lower() in ("none", "ef_none"):
        return None
    key = str(compression).lower()
    if key.startswith("ef_"):
        key = key[3:]               # error feedback rides the same wire
    spec = COMPRESSION_MODEL.get(key)
    if spec is None:
        raise ValueError(
            f"no cost curve for compression {compression!r}; known: "
            f"{', '.join(sorted(COMPRESSION_MODEL))}")
    return spec


def compression_wire_ratio(compression, orig_itemsize: int = 4) -> float:
    """Compressed-to-original wire-byte ratio for a payload of
    ``orig_itemsize``-byte elements (never above 1 — compressing bf16 to
    bf16 is free, not a doubling)."""
    spec = _compression_spec(compression)
    if spec is None:
        return 1.0
    return min(1.0, spec["itemsize"] / max(int(orig_itemsize), 1))


def compression_overhead_us(nbytes: int, compression) -> float:
    """Quantize+dequantize µs for ``nbytes`` of uncompressed payload."""
    spec = _compression_spec(compression)
    if spec is None:
        return 0.0
    return nbytes / 2**20 * spec["qd_us_per_mib"]


def compression_scale_exchange(compression) -> bool:
    spec = _compression_spec(compression)
    return bool(spec and spec["scale_exchange"])


def predict_collective_us(
    op: str,
    nbytes: int,
    world: int,
    *,
    calls: int = 1,
    ici_bytes_per_sec: float = 186e9,
    ici_hop_latency: float = 1e-6,
    compression: Optional[str] = None,
    orig_itemsize: int = 4,
    two_level: bool = False,
    local_size: Optional[int] = None,
    dcn_bytes_per_sec: Optional[float] = None,
    dcn_hop_latency: Optional[float] = None,
) -> float:
    """α–β cost of ``calls`` ring executions of ``op`` moving ``nbytes``
    total, in µs — THE cost model: ``collective_report``'s scaling
    curves, the per-tensor table below, and the replay engine's what-if
    simulator (timeline/replay/simulator.py) all call this one function,
    so a what-if and the report can never disagree on predicted cost.

    ``compression`` (a registry name from ops/compression.py) prices the
    wire-efficiency tier: β shrinks by the wire-byte ratio, and the
    quantize/dequantize overhead plus the quantizers' scalar scale
    exchange (one α) are added — compression is NOT free, which is
    exactly why the planner must rank it against fusion on one scale.

    ``two_level=True`` (all-reduce only) prices the hierarchical shape
    (parallel/hierarchical.py ``two_level_allreduce``): a local
    reduce-scatter and all-gather on ICI at full precision, and the
    cross-host all-reduce on the 1/local_size shard over the DCN link —
    with ``compression`` applied to the cross stage only, where it is
    applied in the real path.  Falls back to the flat shape when the
    topology can't decompose (local_size unset/1, or not dividing
    world) — mirroring the runtime's own degrade."""
    spec = _compression_spec(compression)
    ratio = compression_wire_ratio(compression, orig_itemsize)
    scale_hops = _ring_hops("all-reduce", world) if spec \
        and spec["scale_exchange"] else 0

    if two_level and op == "all-reduce" and local_size \
            and local_size > 1 and world % local_size == 0 \
            and world // local_size > 1:
        l, c = int(local_size), world // int(local_size)
        dcn_bw = dcn_bytes_per_sec if dcn_bytes_per_sec is not None \
            else DEFAULT_DCN_BYTES_PER_SEC
        dcn_hop = dcn_hop_latency if dcn_hop_latency is not None \
            else DEFAULT_DCN_HOP_LATENCY
        shard = nbytes / l
        t = (
            # local reduce-scatter + all-gather, full precision on ICI
            _link_volume("reduce-scatter", nbytes, l) / ici_bytes_per_sec
            + _link_volume("all-gather", nbytes, l) / ici_bytes_per_sec
            + calls * 2 * _ring_hops("reduce-scatter", l) * ici_hop_latency
            # cross all-reduce on the (compressed) shard over DCN
            + _link_volume("all-reduce", shard * ratio, c) / dcn_bw
            + calls * _ring_hops("all-reduce", c) * dcn_hop
            # quantize/dequantize the shard; scale exchange rides DCN
            + compression_overhead_us(int(shard), compression) * 1e-6
            + (calls * _ring_hops("all-reduce", c) * dcn_hop
               if spec and spec["scale_exchange"] else 0.0)
        )
        return t * 1e6

    t = (_link_volume(op, nbytes * ratio, world) / ici_bytes_per_sec
         + calls * _ring_hops(op, world) * ici_hop_latency
         + compression_overhead_us(nbytes, compression) * 1e-6
         + calls * scale_hops * ici_hop_latency)
    return t * 1e6


def per_tensor_table(
    tensors: Dict[str, Dict[str, Any]],
    world: int,
    *,
    measured_us: Optional[Dict[str, float]] = None,
    ici_bytes_per_sec: float = 186e9,
    ici_hop_latency: float = 1e-6,
) -> Dict[str, Dict[str, Any]]:
    """Per-tensor cost table: ``tensors`` maps tensor name ->
    ``{"op", "bytes", "calls"}`` (``calls`` defaults to 1) and the result
    adds ``predicted_us`` from :func:`predict_collective_us` plus, when a
    ``measured_us`` map is given (e.g. comm-span durations out of a
    merged trace), ``measured_us`` and ``model_error_pct`` — the
    prediction-vs-reality check that tells you whether a what-if built on
    this model is trustworthy for that tensor."""
    measured_us = measured_us or {}
    table: Dict[str, Dict[str, Any]] = {}
    for name, d in tensors.items():
        op = str(d.get("op", "all-reduce"))
        nbytes = int(d.get("bytes", 0) or 0)
        calls = int(d.get("calls", 1) or 1)
        row: Dict[str, Any] = {
            "op": op,
            "bytes": nbytes,
            "calls": calls,
            "predicted_us": round(predict_collective_us(
                op, nbytes, world, calls=calls,
                ici_bytes_per_sec=ici_bytes_per_sec,
                ici_hop_latency=ici_hop_latency), 3),
        }
        if name in measured_us:
            m = float(measured_us[name])
            row["measured_us"] = round(m, 3)
            if m > 0:
                row["model_error_pct"] = round(
                    (row["predicted_us"] - m) / m * 100.0, 1)
        table[name] = row
    return table


def model_scaling(
    cols: Dict[str, Dict[str, int]],
    t_compute: Optional[float],
    *,
    sizes=(8, 16, 32, 64),
    ici_bytes_per_sec: float = 186e9,
    ici_hop_latency: float = 1e-6,
    compression: Optional[str] = None,
    orig_itemsize: int = 4,
    two_level: bool = False,
    local_size: Optional[int] = None,
    dcn_bytes_per_sec: Optional[float] = None,
    dcn_hop_latency: Optional[float] = None,
):
    """The pure α-β curve: ({n: t_comm_seconds}, {n: efficiency}) from a
    collective profile (``hlo_collectives`` output) and a per-step
    single-chip compute time.  ``compression``/``two_level`` model the
    wire-efficiency tier (docs/compression.md) on the same curve — the
    SCALING.md story of whether 96–99% at 64 chips survives 10× bigger
    gradient payloads.  ``orig_itemsize`` is the payload's element size
    (default f32 = 4): pass 2 for bf16-native gradients, or the wire
    ratio of bf16/int8 compression is overstated (``cols`` aggregates
    bytes only, so the dtype must come from the caller).  Routed
    through :func:`predict_collective_us` so this curve and the replay
    what-ifs share one arithmetic."""
    comm_seconds, scaling = {}, {}
    for n in sizes:
        t_comm = sum(
            predict_collective_us(
                op, d["bytes"], n, calls=d["count"],
                ici_bytes_per_sec=ici_bytes_per_sec,
                ici_hop_latency=ici_hop_latency,
                # only the gradient all-reduce path compresses; other
                # collectives (batch-stat gathers, permutes) ride as-is
                compression=compression if op == "all-reduce" else None,
                orig_itemsize=orig_itemsize,
                two_level=two_level,
                local_size=local_size,
                dcn_bytes_per_sec=dcn_bytes_per_sec,
                dcn_hop_latency=dcn_hop_latency,
            ) * 1e-6
            for op, d in cols.items()
        )
        comm_seconds[n] = round(t_comm, 6)
        scaling[n] = (
            round(t_compute / (t_compute + t_comm), 4)
            if t_compute else None
        )
    return comm_seconds, scaling


def collective_report(
    step_fn,
    *args,
    # None → utils/flops.peak_flops(): the ONE peak constant (v5e
    # 197e12 unless HVD_PEAK_FLOPS overrides) every MFU number divides
    # by — a hardware change can't desync this report from bench.py or
    # the compute-anatomy profiler
    peak_flops: Optional[float] = None,
    ici_bytes_per_sec: float = 186e9,   # v5e: ~186 GB/s per ICI direction
    ici_hop_latency: float = 1e-6,      # ~1 µs per ICI neighbor hop
    sizes=(8, 16, 32, 64),
    measured_step_seconds: Optional[float] = None,
    compression: Optional[str] = None,
    orig_itemsize: int = 4,
    two_level: bool = False,
    local_size: Optional[int] = None,
    dcn_bytes_per_sec: Optional[float] = None,
    dcn_hop_latency: Optional[float] = None,
    **kwargs,
) -> Dict[str, Any]:
    """Compile ``step_fn`` (a jitted/spmd-wrapped callable) on the current
    mesh and report its collective traffic plus a roofline scaling model.

    The α-β model: per-step compute time = measured single-chip step time
    when given (the honest base — pass the bench number), else
    flops/peak; per-step comm time at world size n =
    Σ_ops [ link_volume(op, bytes, n) / ici_bw            (β, bandwidth)
          + count(op) · ring_hops(op, n) · hop_latency ]  (α, latency);
    efficiency(n) = t_compute / (t_compute + t_comm(n)) — the no-overlap
    bound (XLA overlaps some collectives, so the real curve sits between
    this and 1.0; the reference's 90%-at-512, README.rst:75-77, is the
    same quantity measured).  The α term is why per-tensor collective
    streams (the hierarchical path's one-RS/AG-per-gradient) scale worse
    than fused buckets even at equal bytes — the reference's whole fusion
    rationale (SURVEY §2.1)."""
    import jax

    if peak_flops is None:
        from ..utils.flops import peak_flops as _peak_flops

        peak_flops = _peak_flops()

    lowered = step_fn.lower(*args, **kwargs) if hasattr(step_fn, "lower") \
        else jax.jit(step_fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    txt = compiled.as_text()
    cols = hlo_collectives(txt)

    cost = compiled.cost_analysis()
    if isinstance(cost, list):
        cost = cost[0] if cost else {}
    flops = float((cost or {}).get("flops", 0.0))

    t_compute = measured_step_seconds if measured_step_seconds \
        else (flops / peak_flops if flops else None)
    comm_seconds, scaling = model_scaling(
        cols, t_compute, sizes=sizes,
        ici_bytes_per_sec=ici_bytes_per_sec,
        ici_hop_latency=ici_hop_latency,
        compression=compression, orig_itemsize=orig_itemsize,
        two_level=two_level,
        local_size=local_size, dcn_bytes_per_sec=dcn_bytes_per_sec,
        dcn_hop_latency=dcn_hop_latency,
    )
    return {
        "collectives": cols,
        "total_collective_bytes": sum(d["bytes"] for d in cols.values()),
        "flops_per_step": flops,
        "assumptions": {
            "peak_flops": peak_flops,
            "ici_bytes_per_sec": ici_bytes_per_sec,
            "ici_hop_latency": ici_hop_latency,
            "t_compute_seconds": t_compute,
            "t_compute_source": "measured" if measured_step_seconds
            else "flops/peak",
            "compression": compression or "none",
            "two_level": bool(two_level),
            "local_size": local_size,
            "model": "efficiency = t_compute / (t_compute + t_comm); "
                     "t_comm = bytes-on-busiest-link/bw + "
                     "count*ring_hops*hop_latency; 1-D ring, no overlap",
        },
        "modeled_comm_seconds": comm_seconds,
        "scaling_model": scaling,
    }
