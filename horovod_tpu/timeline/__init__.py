from .timeline import Timeline, timeline  # noqa: F401
