from .timeline import Timeline, timeline  # noqa: F401


def __getattr__(name):
    # lazy: merge/replay pull analysis-side deps (and recorder pulls
    # jax) that the hot-path timeline must not import at package load
    if name == "replay":
        import importlib

        return importlib.import_module(".replay", __name__)
    if name in ("Recorder", "TimelineHook"):
        from . import recorder

        return getattr(recorder, name)
    if name == "ComputeProfiler":
        from . import profiler

        return profiler.ComputeProfiler
    if name == "profiler":
        import importlib

        return importlib.import_module(".profiler", __name__)
    raise AttributeError(name)
