"""Per-rank Chrome-trace communication timeline.

Re-design of the fork-modified Timeline (reference
horovod/common/timeline.cc/.h): a dedicated writer thread drains an event
queue (reference uses a boost SPSC lock-free queue, timeline.h:68-70; here a
``queue.SimpleQueue``) and streams Chrome-trace JSON.  Fork behaviors kept:

* **per-rank output** ``<dir>/<rank>/comm.json`` (reference
  timeline.cc:205-228, changed from upstream's single coordinator file —
  operations.cc:395-399);
* **step windowing** via ``HVD_TRACE_START_STEP`` / ``HVD_TRACE_END_STEP``
  (reference BYTEPS_TRACE_START_STEP/END_STEP, timeline.cc:30-31,101-144):
  events are only recorded inside the window, and the file is finalized and
  the writer stopped at the end step;
* the event vocabulary: ``NEGOTIATE_<OP>`` spans, top-level ``ALLREDUCE`` /
  ``ALLGATHER`` / ``BROADCAST`` spans, nested activity spans, and
  ``CYCLE_START`` instants when ``HVD_TIMELINE_MARK_CYCLES`` is set
  (reference common.h:31-59, timeline.cc:377-384).

What changes on TPU: GPU activity timing came from CUDA events drained by
finalizer threads (reference gpu_operations.h:103-111); here device-side
timing comes from the XLA profiler (``jax.profiler``), which the Recorder
layer (timeline/recorder.py) integrates; this timeline covers the host-side
dispatch spans — which is also exactly what the reference timeline measures
for the negotiation phase.
"""

from __future__ import annotations

import contextlib
import json
import os
import queue
import threading
import time
from typing import Optional

from .. import core
from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)

_SHUTDOWN = object()


class _Writer:
    """Background writer thread (analog of TimelineWriter::WriterLoop,
    reference timeline.cc)."""

    def __init__(self, path: str):
        self.path = path
        self.q: queue.SimpleQueue = queue.SimpleQueue()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hvd-timeline-writer")
        self._closed = threading.Event()
        self._thread.start()

    def put(self, ev: dict) -> None:
        if not self._closed.is_set():
            self.q.put(ev)

    def close(self) -> None:
        if not self._closed.is_set():
            self.q.put(_SHUTDOWN)
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        with open(self.path, "w") as f:
            f.write("[\n")
            first = True
            while True:
                item = self.q.get()
                if item is _SHUTDOWN:
                    break
                if not first:
                    f.write(",\n")
                json.dump(item, f)
                first = False
                f.flush()
            f.write("\n]\n")
        self._closed.set()


class _NativeWriter:
    """Adapter over the C++ writer thread (csrc/timeline.cc) — the native
    path, used when build/libhvdcore.so is available; same file format."""

    def __init__(self, path: str):
        from ..runtime import native

        self._lib = native.load()
        self._h = self._lib.hvd_timeline_open(path.encode())
        if not self._h:
            raise RuntimeError(f"native timeline open failed: {path}")

    def put(self, ev: dict) -> None:
        if self._h:
            self._lib.hvd_timeline_event(
                self._h, str(ev.get("name", "")).encode(),
                str(ev.get("cat", "")).encode(),
                str(ev.get("tid", "")).encode(),
                str(ev.get("ph", "X")).encode()[:1],
                float(ev.get("ts", 0.0)), float(ev.get("dur", 0.0)),
                int(ev.get("pid", 0)),
            )

    def close(self) -> None:
        if self._h:
            self._lib.hvd_timeline_close(self._h)
            self._h = None


def _make_writer(path: str):
    """Prefer the native writer; fall back to the Python thread
    (HVD_TIMELINE_PYTHON=1 forces the fallback)."""
    if not env_util.get_bool("HVD_TIMELINE_PYTHON"):
        try:
            return _NativeWriter(path)
        except Exception as e:  # noqa: BLE001
            log.debug("native timeline unavailable (%s); python fallback", e)
    return _Writer(path)


class Timeline:
    """Process-wide timeline recorder; one writer per controller process,
    pid field = rank so merged traces line up per-rank."""

    def __init__(self) -> None:
        self._writer: Optional[_Writer] = None
        self._dir: Optional[str] = None
        self._lock = threading.Lock()
        self._step = 0
        self._stepper: Optional[str] = None
        self._start_step = env_util.get_int(env_util.HVD_TRACE_START_STEP, 0)
        self._end_step = env_util.get_int(env_util.HVD_TRACE_END_STEP, 1 << 62)
        self._mark_cycles = env_util.get_bool(env_util.HVD_TIMELINE_MARK_CYCLES)
        self._origin = time.perf_counter()
        self._atexit_registered = False

    # -- lifecycle ----------------------------------------------------------
    def initialize(self, directory: Optional[str] = None) -> None:
        """Open ``<dir>/<rank>/comm.json`` (reference timeline.cc:205-228).

        When the launcher's rendezvous server is reachable
        (``HVD_METRICS_KV_*`` set), also run the clock-offset handshake
        and drop a ``clock_sync.json`` sidecar next to comm.json — the
        per-rank trace-clock→server-clock offset the cross-rank merge
        and the replay engine use to put every rank on one clock
        (``HVD_REPLAY_CLOCK_SYNC=0`` skips it)."""
        directory = directory or env_util.get_str(env_util.HVD_TIMELINE) or \
            env_util.get_str(env_util.HVD_TRACE_DIR)
        if not directory:
            return
        rank = core.process_rank() if core.is_initialized() else 0
        path = os.path.join(directory, str(rank), "comm.json")
        opened = False
        with self._lock:
            if self._writer is None:
                self._writer = _make_writer(path)
                self._dir = os.path.dirname(path)
                opened = True
                # fresh trace file = fresh step window: an init() after a
                # previous run's auto-close must not inherit its counter
                # (else the new trace instantly re-closes empty)
                self._step = 0
                self._stepper = None
                self._start_step = env_util.get_int(
                    env_util.HVD_TRACE_START_STEP, 0)
                self._end_step = env_util.get_int(
                    env_util.HVD_TRACE_END_STEP, 1 << 62)
                log.debug("timeline → %s", path)
                # finalize the JSON even when the user never calls
                # shutdown() (reference closes via the writer thread at
                # process teardown / end-step auto-close); registered once
                # so init/shutdown cycles don't accumulate handlers
                if not self._atexit_registered:
                    import atexit

                    atexit.register(self.shutdown)
                    self._atexit_registered = True
        if opened:
            # network I/O — after the lock is released, and never fatal
            self._record_clock_sync(os.path.dirname(path), rank)

    def _record_clock_sync(self, rank_dir: str, rank: int) -> None:
        """Estimate this rank's trace-clock→server-clock offset against
        the rendezvous server and persist it as ``clock_sync.json``
        (timeline/replay/clock.py; applied by merge_traces).  Written as
        a sidecar, not a trace event, so it survives the native writer's
        fixed event schema."""
        if not env_util.get_bool(env_util.HVD_REPLAY_CLOCK_SYNC, True):
            return
        addr = env_util.get_str(env_util.HVD_METRICS_KV_ADDR)
        port = env_util.get_int(env_util.HVD_METRICS_KV_PORT, 0)
        if not addr or not port:
            return
        secret_hex = env_util.get_str(env_util.HVD_METRICS_SECRET)
        secret = bytes.fromhex(secret_hex) if secret_hex else None
        try:
            from .replay.clock import estimate_offset

            est = estimate_offset(
                addr, port, secret=secret,
                samples=env_util.get_int(
                    env_util.HVD_REPLAY_CLOCK_SAMPLES, 8),
                local_clock_us=self._ts_us,
            )
            est["rank"] = rank
            with open(os.path.join(rank_dir, "clock_sync.json"), "w") as f:
                json.dump(est, f, indent=1)
            log.debug("clock sync: offset %.1f us (rtt %.1f us)",
                      est["offset_us"], est["rtt_us"])
        except Exception as e:  # noqa: BLE001
            log.debug("clock sync skipped: %s", e)

    def shutdown(self) -> None:
        # flush any open compute-anatomy profiler BEFORE the writer
        # closes: compute.json events share this timeline's clock, and
        # a job torn down mid-window must still land its artifact next
        # to comm.json (timeline/profiler.py)
        try:
            from .profiler import finalize_active

            finalize_active()
        except Exception as e:  # noqa: BLE001
            log.debug("profiler finalize on shutdown failed: %s", e)
        with self._lock:
            if self._writer is not None:
                self._writer.close()
                self._writer = None
                # the live half's post-mortem artifact: a numeric snapshot
                # next to comm.json, so one trace dir carries both the
                # spans and the counters they aggregate into
                if self._dir is not None:
                    try:
                        from ..metrics import dump_metrics_json, registry

                        if registry.enabled:
                            dump_metrics_json(
                                os.path.join(self._dir, "metrics.json")
                            )
                    except Exception as e:  # noqa: BLE001
                        log.debug("metrics.json dump failed: %s", e)
                    self._dir = None

    @property
    def active(self) -> bool:
        """Writer open (regardless of the step window) — callers that
        advance the step counter must keep doing so before the window."""
        return self._writer is not None

    @property
    def enabled(self) -> bool:
        return self._writer is not None and self._in_window()

    def _in_window(self) -> bool:
        return self._start_step <= self._step <= self._end_step

    # -- step windowing (fork: BYTEPS_TRACE_*_STEP) -------------------------
    def record_step(self, owner: str = "default") -> int:
        """Advance the step counter; auto-finalize at the end step
        (reference timeline.cc:101-144).

        ``owner`` dedupes composed steppers: the first component to call
        this (e.g. a ``TimelineHook`` wrapping a ``make_train_step`` loop —
        both record steps) claims the counter; other owners' calls return
        without advancing, so the window isn't double-advanced.
        """
        if self._stepper is None:
            self._stepper = owner
        if owner != self._stepper:
            return self._step
        self._step += 1
        if self._step > self._end_step:
            self.shutdown()
        return self._step

    def arm(self, start_step: int, end_step: int, *,
            current_step: Optional[int] = None,
            directory: Optional[str] = None) -> bool:
        """Move the trace window and (re)open the writer — the
        watchdog's auto-arm seam (observe/autoarm.py).

        ``start_step``/``end_step`` are *global* training-step numbers
        when ``current_step`` (the rank's cadence step) is given; they
        are translated onto this timeline's own counter (which counts
        from writer-open), so every rank's window lands on the same
        training steps regardless of when its writer opened.  Returns
        False when no writer could be opened (no directory anywhere).
        Called from the telemetry flusher thread, never the step
        path."""
        self.initialize(directory)
        with self._lock:
            if self._writer is None:
                return False
            offset = (self._step - int(current_step)
                      if current_step is not None else 0)
            self._start_step = max(int(start_step) + offset,
                                   self._step + 1)
            self._end_step = int(end_step) + offset
        log.info("timeline armed: local steps [%d, %d]",
                 self._start_step, self._end_step)
        return True

    # -- events -------------------------------------------------------------
    def _ts_us(self) -> float:
        return (time.perf_counter() - self._origin) * 1e6

    def _emit(self, ev: dict) -> None:
        w = self._writer
        if w is not None:
            w.put(ev)

    @contextlib.contextmanager
    def span(self, tensor_name: str, activity: str, rank: Optional[int] = None):
        """A complete ('X') event named by tensor with the activity as
        category — the nested-activity form of the reference's
        ActivityStart/ActivityEnd."""
        if not self.enabled:
            yield
            return
        t0 = self._ts_us()
        try:
            yield
        finally:
            self._emit({
                "name": activity,
                "cat": tensor_name,
                "ph": "X",
                "ts": t0,
                "dur": self._ts_us() - t0,
                "pid": rank if rank is not None else (
                    core.process_rank() if core.is_initialized() else 0),
                "tid": tensor_name,
            })

    def negotiate_start(self, tensor_name: str, op: str) -> None:
        """NEGOTIATE_<OP> begin (reference timeline.cc NegotiateStart)."""
        if self.enabled:
            self._emit({"name": f"NEGOTIATE_{op}", "cat": tensor_name,
                        "ph": "B", "ts": self._ts_us(),
                        "pid": core.process_rank() if core.is_initialized() else 0,
                        "tid": tensor_name})

    def negotiate_rank_ready(self, tensor_name: str, rank: int) -> None:
        """Per-rank readiness X event (fork NegotiateSubEvent "Sync",
        reference timeline.cc:250-259, used controller.cc:656-661)."""
        if self.enabled:
            self._emit({"name": f"{rank}", "cat": tensor_name, "ph": "X",
                        "ts": self._ts_us(), "dur": 1,
                        "pid": core.process_rank() if core.is_initialized() else 0,
                        "tid": tensor_name})

    def negotiate_end(self, tensor_name: str, op: str) -> None:
        if self.enabled:
            self._emit({"name": f"NEGOTIATE_{op}", "cat": tensor_name,
                        "ph": "E", "ts": self._ts_us(),
                        "pid": core.process_rank() if core.is_initialized() else 0,
                        "tid": tensor_name})

    def mark_cycle_start(self) -> None:
        """CYCLE_START instant (reference timeline.cc:377-384, gated by
        HOROVOD_TIMELINE_MARK_CYCLES)."""
        if self.enabled and self._mark_cycles:
            self._emit({"name": "CYCLE_START", "ph": "i", "s": "g",
                        "ts": self._ts_us(),
                        "pid": core.process_rank() if core.is_initialized() else 0,
                        "tid": "cycle"})


#: process-wide singleton, auto-enabled when HVD_TIMELINE is set at init
timeline = Timeline()
