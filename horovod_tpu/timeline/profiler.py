"""Compute-anatomy profiler: per-block device-time attribution, roofline
accounting, and host-gap detection.

The trace plane so far answers the *communication* questions (comm.json
spans, the replay engine's {compute, negotiation, comm, idle} split) but
models compute as one opaque serial chain per rank — exactly the gap the
dPRO thesis (profile → DAG → simulate → optimize) says to close with
fine-grained per-operation traces.  This module is the compute half:

* :class:`ComputeProfiler` — a BYTEPS_TRACE-style step window
  (``HVD_PROFILE_START_STEP``/``END_STEP``, defaulting to the timeline's
  ``HVD_TRACE_*`` knobs) during which ``make_train_step`` runs its
  *decomposed* step — forward / backward / grad_allreduce /
  optimizer_update dispatched as separately-jitted programs with a
  device sync at each boundary — so every block's device time is
  host-visible; each block also carries XLA ``cost_analysis()`` flops
  and bytes (extending the single-number path comm_report already
  reads).  ``HVD_PROFILE_XLA=1`` additionally runs a ``jax.profiler``
  trace capture into ``<rank>/xla_trace`` for op-level drill-down;
* :func:`reduce_trace_events` — the parser: a pure function reducing
  Chrome-trace-style events (X spans or B/E pairs, ``STEP`` envelopes)
  into the per-rank anatomy — per-segment device µs / occurrence count /
  flops / bytes, roofline verdict per block
  (:func:`roofline_verdict`), and device-idle-waiting-on-host ("host
  gap") detection from the inter-dispatch gaps inside each step
  envelope.  Pure python over plain dicts, so the fixture corpus below
  keeps it testable on CPU tier-1;
* ``compute.json`` — the per-rank artifact written next to ``comm.json``
  at window end (and at timeline shutdown as a backstop):
  ``{"rank", "clock", "anatomy", "events"}``.  The raw events ride along
  so the cross-rank merge (timeline/merge.py) and the replay stitcher
  (which splits each rank's compute chain into per-segment nodes) can
  place them on the shared clock;
* :func:`aggregate_anatomies` — the cross-rank reduction behind
  ``GET /profile`` on the rendezvous server and ``scripts/hvd_profile.py``:
  per-segment slowest rank, mean/max host gap, mean MFU.

Artifact contract and knob table: docs/profiling.md.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Dict, List, Optional

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)

#: ``cat`` tag on segment events (distinguishes them from STEP envelopes)
SEGMENT_CAT = "compute_segment"
STEP_NAME = "STEP"

#: the per-rank artifact name, next to comm.json
COMPUTE_JSON = "compute.json"

#: merged-trace row group base: compute rows render under pid
#: COMPUTE_PID_BASE + rank so viewers show them as their own process
#: group per rank (timeline/merge.py)
COMPUTE_PID_BASE = 100000


# ---------------------------------------------------------------------------
# roofline accounting
# ---------------------------------------------------------------------------
def roofline_verdict(flops: Optional[float], nbytes: Optional[float],
                     device_us: float, *, peak_flops: float,
                     hbm_bytes_per_sec: float) -> Dict[str, Any]:
    """Price one segment against the roofline.

    The ridge point is ``peak_flops / hbm_bytes_per_sec`` flops/byte: a
    segment whose arithmetic intensity sits at or above it is limited by
    the MXU (``compute-bound``), below it by HBM (``memory-bound``);
    with neither flops nor bytes known the verdict is ``unknown`` (the
    segment still counts device time).  Alongside the verdict: achieved
    FLOP/s and its peak fraction (the segment's MFU), achieved bytes/s
    and its bandwidth fraction — the "how far from the roof" numbers the
    next perf PR needs as targets."""
    out: Dict[str, Any] = {"verdict": "unknown"}
    if device_us <= 0.0:
        return out
    secs = device_us * 1e-6
    if flops is not None:
        out["achieved_flops_per_sec"] = flops / secs
        out["mfu"] = flops / secs / peak_flops
    if nbytes is not None:
        out["achieved_bytes_per_sec"] = nbytes / secs
        out["hbm_fraction"] = nbytes / secs / hbm_bytes_per_sec
    ridge = peak_flops / hbm_bytes_per_sec
    if flops is not None and nbytes is not None:
        if nbytes > 0:
            out["intensity_flops_per_byte"] = flops / nbytes
            out["verdict"] = ("compute-bound"
                              if flops / nbytes >= ridge else "memory-bound")
        elif flops > 0:
            out["verdict"] = "compute-bound"
    elif flops is not None and flops > 0:
        out["verdict"] = "compute-bound"
    elif nbytes is not None and nbytes > 0:
        out["verdict"] = "memory-bound"
    return out


# ---------------------------------------------------------------------------
# the parser: trace events -> anatomy
# ---------------------------------------------------------------------------
def _empty_anatomy(peak_flops: float, hbm_bytes_per_sec: float,
                   gap_threshold_us: float) -> Dict[str, Any]:
    return {
        "steps": 0,
        "wall_us": 0.0,
        "segments": {},
        "host_gap": {"total_us": 0.0, "per_step_us": 0.0, "fraction": 0.0,
                     "spans": [], "flagged": 0},
        "mfu": None,
        "top_segment": None,
        "verdict": "empty",
        "unmatched_spans": 0,
        "peak_flops": peak_flops,
        "hbm_bytes_per_sec": hbm_bytes_per_sec,
        "gap_threshold_us": gap_threshold_us,
    }


def _collect_spans(events: List[dict]):
    """``(steps, segments, unmatched)`` from a trace-event list.

    ``steps``: (start, end) of every STEP X envelope; ``segments``:
    (name, start, end, flops, bytes) for every non-STEP X span plus
    every matched B/E pair (keyed by (name, tid) like the comm
    timeline); ``unmatched``: repeated-B overwrites, stray Es, and
    spans still open at end-of-trace — a truncated capture shows up
    here instead of silently under-counting."""
    steps: List[tuple] = []
    segs: List[tuple] = []
    open_spans: Dict[tuple, tuple] = {}
    unmatched = 0
    for ev in events:
        name = str(ev.get("name", ""))
        ph = ev.get("ph", "X")
        ts = float(ev.get("ts", 0.0))
        args = ev.get("args") or {}
        flops = args.get("flops")
        nbytes = args.get("bytes")
        if name == STEP_NAME:
            if ph == "X":
                steps.append((ts, ts + float(ev.get("dur", 0.0))))
            continue
        if not name:
            continue
        if ph == "X":
            segs.append((name, ts, ts + float(ev.get("dur", 0.0)),
                         flops, nbytes))
        elif ph == "B":
            key = (name, str(ev.get("tid", "")))
            if key in open_spans:
                unmatched += 1          # earlier B never saw its E
            open_spans[key] = (ts, flops, nbytes)
        elif ph == "E":
            key = (name, str(ev.get("tid", "")))
            if key not in open_spans:
                unmatched += 1          # E without a B
                continue
            t0, f0, b0 = open_spans.pop(key)
            segs.append((name, t0, ts, flops if flops is not None else f0,
                         nbytes if nbytes is not None else b0))
    unmatched += len(open_spans)        # dangling Bs
    segs.sort(key=lambda s: s[1])
    steps.sort()
    return steps, segs, unmatched


def reduce_trace_events(
    events: List[dict],
    *,
    peak_flops: Optional[float] = None,
    hbm_bytes_per_sec: Optional[float] = None,
    gap_threshold_us: Optional[float] = None,
    host_bound_fraction: float = env_util.DEFAULT_PROFILE_HOST_BOUND_FRACTION,
) -> Dict[str, Any]:
    """Reduce a captured trace-event stream into the step anatomy.

    Segment totals are summed per name; flops/bytes accumulate only when
    present (an unknown segment name with no cost data still counts its
    device time, verdict ``unknown``).  Host gap = each STEP envelope's
    duration minus the union of segment spans inside it, with individual
    inter-dispatch gaps >= ``gap_threshold_us`` recorded as flagged
    spans.  Without STEP envelopes the segments' own envelope stands in
    as one step; with nothing at all the anatomy is ``verdict: empty``.
    """
    from ..utils import flops as flops_util

    peak = peak_flops if peak_flops is not None else flops_util.peak_flops()
    hbm = hbm_bytes_per_sec if hbm_bytes_per_sec is not None \
        else flops_util.hbm_bytes_per_sec()
    gap_thresh = gap_threshold_us if gap_threshold_us is not None \
        else env_util.get_float(env_util.HVD_PROFILE_GAP_THRESHOLD_US,
                                env_util.DEFAULT_PROFILE_GAP_THRESHOLD_US)

    steps, segs, unmatched = _collect_spans(events)
    if not steps and not segs:
        out = _empty_anatomy(peak, hbm, gap_thresh)
        out["unmatched_spans"] = unmatched
        return out
    if not steps:
        steps = [(min(s[1] for s in segs), max(s[2] for s in segs))]

    # per-name totals
    totals: Dict[str, Dict[str, Any]] = {}
    for name, t0, t1, flops, nbytes in segs:
        d = totals.setdefault(name, {"device_us": 0.0, "count": 0,
                                     "flops": None, "bytes": None})
        d["device_us"] += t1 - t0
        d["count"] += 1
        if flops is not None:
            d["flops"] = (d["flops"] or 0.0) + float(flops)
        if nbytes is not None:
            d["bytes"] = (d["bytes"] or 0.0) + float(nbytes)

    # host gap: per step envelope, uncovered time between dispatches
    wall_us = sum(t1 - t0 for t0, t1 in steps)
    gap_total = 0.0
    flagged: List[dict] = []
    for i, (s0, s1) in enumerate(steps):
        cursor = s0
        inside = [s for s in segs if s[2] > s0 + 1e-9 and s[1] < s1 - 1e-9]
        for _name, t0, t1, _f, _b in inside:
            t0, t1 = max(t0, s0), min(t1, s1)
            if t0 > cursor + 1e-9:
                gap = t0 - cursor
                gap_total += gap
                if gap >= gap_thresh:
                    flagged.append({"step": i, "start_us": round(cursor, 3),
                                    "dur_us": round(gap, 3)})
            cursor = max(cursor, t1)
        if s1 > cursor + 1e-9:
            gap = s1 - cursor
            gap_total += gap
            if gap >= gap_thresh:
                flagged.append({"step": i, "start_us": round(cursor, 3),
                                "dur_us": round(gap, 3)})

    n_steps = len(steps)
    segments: Dict[str, Dict[str, Any]] = {}
    flops_known = 0.0
    any_flops = False
    for name, d in sorted(totals.items(), key=lambda kv: -kv[1]["device_us"]):
        entry: Dict[str, Any] = {
            "device_us": round(d["device_us"], 3),
            "count": d["count"],
            "per_step_us": round(d["device_us"] / n_steps, 3),
            "flops": d["flops"],
            "bytes": d["bytes"],
            "fraction": round(d["device_us"] / wall_us, 4)
            if wall_us > 0 else 0.0,
        }
        entry.update(roofline_verdict(
            d["flops"], d["bytes"], d["device_us"],
            peak_flops=peak, hbm_bytes_per_sec=hbm))
        segments[name] = entry
        if d["flops"] is not None:
            flops_known += d["flops"]
            any_flops = True

    gap_fraction = gap_total / wall_us if wall_us > 0 else 0.0
    top = max(totals, key=lambda n: totals[n]["device_us"]) if totals \
        else None
    verdict = "host-bound" if gap_fraction >= host_bound_fraction else (
        segments[top]["verdict"] if top else "empty")
    mfu = flops_known / (wall_us * 1e-6 * peak) \
        if any_flops and wall_us > 0 else None
    return {
        "steps": n_steps,
        "wall_us": round(wall_us, 3),
        "segments": segments,
        "host_gap": {
            "total_us": round(gap_total, 3),
            "per_step_us": round(gap_total / n_steps, 3),
            "fraction": round(gap_fraction, 4),
            "spans": flagged,
            "flagged": len(flagged),
        },
        "mfu": round(mfu, 4) if mfu is not None else None,
        "top_segment": top,
        "verdict": verdict,
        "unmatched_spans": unmatched,
        "peak_flops": peak,
        "hbm_bytes_per_sec": hbm,
        "gap_threshold_us": gap_thresh,
    }


# ---------------------------------------------------------------------------
# cross-rank aggregation (GET /profile, scripts/hvd_profile.py)
# ---------------------------------------------------------------------------
def aggregate_anatomies(per_rank: Dict[str, dict]) -> Dict[str, Any]:
    """Cross-rank anatomy reduction — ONE implementation shared by the
    rendezvous server's ``GET /profile`` and the CLI, so the live route
    and the offline report can never disagree on who the slowest rank
    is.  Per segment: each rank's device µs, the slowest rank, and the
    max−min spread; plus mean MFU and the worst host gap."""
    segs: Dict[str, Dict[str, float]] = {}
    mfus: Dict[str, float] = {}
    gaps: Dict[str, float] = {}
    verdicts: Dict[str, str] = {}
    for rank, an in sorted(per_rank.items()):
        if not isinstance(an, dict):
            continue
        for name, d in (an.get("segments") or {}).items():
            segs.setdefault(name, {})[rank] = float(d.get("device_us", 0.0))
            verdicts.setdefault(name, d.get("verdict", "unknown"))
        if an.get("mfu") is not None:
            mfus[rank] = float(an["mfu"])
        hg = an.get("host_gap") or {}
        gaps[rank] = float(hg.get("per_step_us", 0.0))
    out_segs: Dict[str, dict] = {}
    for name, by_rank in segs.items():
        slowest = max(by_rank, key=by_rank.get)
        out_segs[name] = {
            "per_rank_device_us": {r: round(v, 3)
                                   for r, v in sorted(by_rank.items())},
            "mean_device_us": round(sum(by_rank.values()) / len(by_rank), 3),
            "slowest_rank": slowest,
            "spread_us": round(max(by_rank.values())
                               - min(by_rank.values()), 3),
            "verdict": verdicts.get(name, "unknown"),
        }
    top = sorted(out_segs, key=lambda n: -out_segs[n]["mean_device_us"])
    return {
        "ranks": sorted(per_rank),
        "segments": out_segs,
        "top_segments": top,
        "mfu": {
            "per_rank": {r: round(v, 4) for r, v in sorted(mfus.items())},
            "mean": round(sum(mfus.values()) / len(mfus), 4)
            if mfus else None,
        },
        "host_gap_per_step_us": {
            "per_rank": {r: round(v, 3) for r, v in sorted(gaps.items())},
            "max_rank": max(gaps, key=gaps.get) if gaps else None,
        },
    }


def load_compute_json(trace_dir: str) -> Dict[int, dict]:
    """rank -> parsed compute.json for every per-rank subdir that has
    one (same directory convention as merge.discover_ranks; a dir
    without any is simply empty — the caller decides whether that is an
    error)."""
    out: Dict[int, dict] = {}
    for entry in sorted(os.listdir(trace_dir)):
        if not entry.isdigit():
            continue
        p = os.path.join(trace_dir, entry, COMPUTE_JSON)
        if not os.path.isfile(p):
            continue
        try:
            with open(p) as f:
                out[int(entry)] = json.load(f)
        except (ValueError, OSError):
            log.warning("profiler: undecodable %s", p)
    return dict(sorted(out.items()))


def own_rank_anatomy(trace_dir: str,
                     rank: Optional[int] = None) -> Optional[dict]:
    """THIS rank's anatomy from an already-written ``compute.json``
    (None when absent/undecodable) — the compute-knob tuner's offline
    plan source (optim/compute_knobs.py): a job restarted over the same
    trace dir can plan compute knobs from its previous incarnation's
    window before its own profiler has run."""
    if rank is None:
        from .. import core

        rank = core.process_rank() if core.is_initialized() else 0
    p = os.path.join(trace_dir, str(rank), COMPUTE_JSON)
    if not os.path.isfile(p):
        return None
    try:
        with open(p) as f:
            return json.load(f).get("anatomy") or None
    except (ValueError, OSError):
        return None


def report_from_dir(trace_dir: str) -> Dict[str, Any]:
    """The step-anatomy report for a whole trace dir: every rank's
    anatomy plus the cross-rank aggregate — scripts/hvd_profile.py's
    payload and the shape ``GET /profile`` serves."""
    per_rank = load_compute_json(trace_dir)
    if not per_rank:
        raise FileNotFoundError(
            f"no <rank>/{COMPUTE_JSON} under {trace_dir} — run with "
            "HVD_PROFILE=1 and a timeline dir first")
    anatomies = {str(r): d.get("anatomy", {}) for r, d in per_rank.items()}
    return {
        "trace_dir": os.path.abspath(trace_dir),
        "ranks": anatomies,
        "aggregate": aggregate_anatomies(anatomies),
    }


# ---------------------------------------------------------------------------
# the live profiler
# ---------------------------------------------------------------------------
#: profilers that started a capture and have not finalized — the
#: timeline-shutdown backstop flushes these (Timeline.shutdown)
_ACTIVE: List["ComputeProfiler"] = []


def finalize_active() -> None:
    """Flush every still-open profiler (called by Timeline.shutdown so
    compute.json lands next to comm.json even when the job never ran
    past the window's end step)."""
    for prof in list(_ACTIVE):
        prof.finalize()


class ComputeProfiler:
    """Step-windowed compute profiler (one per ``make_train_step``).

    ``on_step()`` advances the window; while it returns True the step
    wrapper runs the decomposed per-segment path, timing each block via
    :meth:`run_segment` (dispatch + device, closed by a
    ``block_until_ready`` sync) inside a :meth:`step_span` envelope.
    Past the end step :meth:`finalize` reduces the events, writes
    ``compute.json``, exports the ``hvd_mfu`` /
    ``hvd_step_phase_fraction`` / ``hvd_host_gap_us`` gauges, and pushes
    the anatomy to the rendezvous ``profile`` scope so ``GET /profile``
    aggregates it."""

    def __init__(self, trace_dir: Optional[str] = None,
                 rank: Optional[int] = None,
                 enabled: Optional[bool] = None,
                 start_step: Optional[int] = None,
                 end_step: Optional[int] = None):
        trace_dir = trace_dir or env_util.get_str(env_util.HVD_TIMELINE) \
            or env_util.get_str(env_util.HVD_TRACE_DIR)
        if enabled is None:
            enabled = env_util.get_bool(env_util.HVD_PROFILE)
        if enabled and not trace_dir:
            log.warning("HVD_PROFILE=1 without HVD_TIMELINE/HVD_TRACE_DIR: "
                        "nowhere to write compute.json — profiler disabled")
            enabled = False
        self.enabled = bool(enabled)
        if rank is None:
            from .. import core

            rank = core.process_rank() if core.is_initialized() else 0
        self.rank = rank
        self.dir = os.path.join(trace_dir, str(rank)) if trace_dir else None
        if start_step is None:
            start_step = env_util.get_int(
                env_util.HVD_PROFILE_START_STEP,
                max(env_util.get_int(env_util.HVD_TRACE_START_STEP, 1), 1))
        self.start_step = max(int(start_step), 1)
        if end_step is None:
            end_step = env_util.get_int(
                env_util.HVD_PROFILE_END_STEP,
                env_util.get_int(
                    env_util.HVD_TRACE_END_STEP,
                    self.start_step + env_util.DEFAULT_PROFILE_STEPS - 1))
        self.end_step = int(end_step)
        from ..utils import flops as flops_util

        self.peak_flops = flops_util.peak_flops()
        self.hbm_bytes_per_sec = flops_util.hbm_bytes_per_sec()
        self.gap_threshold_us = env_util.get_float(
            env_util.HVD_PROFILE_GAP_THRESHOLD_US,
            env_util.DEFAULT_PROFILE_GAP_THRESHOLD_US)
        self._xla = env_util.get_bool(env_util.HVD_PROFILE_XLA)
        self._xla_running = False
        self._step = 0
        self._events: List[dict] = []
        self._origin = time.perf_counter()
        self._started = False
        self._finalized = False
        self._in_step = False
        self._finalize_pending = False
        self._clock = None              # latched at capture start
        self.anatomy: Optional[dict] = None

    # -- clock --------------------------------------------------------------
    def _now(self) -> float:
        """µs on the timeline's trace clock when it was recording at
        capture start (so compute.json events land on the same clock as
        comm.json and the per-rank ``clock_sync.json`` offset applies
        to both); the profiler's own origin otherwise.  The source is
        LATCHED at capture start — a timeline auto-closing mid-window
        must not jump the origin between two recorded spans (the
        timeline's ``_ts_us`` keeps ticking after its writer closes)."""
        if self._clock is not None:
            return self._clock()
        return (time.perf_counter() - self._origin) * 1e6

    @property
    def clock_name(self) -> str:
        return "timeline" if self._clock is not None else "local"

    # -- window -------------------------------------------------------------
    @property
    def capturing(self) -> bool:
        return (self.enabled and not self._finalized
                and self.start_step <= self._step <= self.end_step)

    def on_step(self) -> bool:
        """Advance the window; True while this step should run the
        profiled (decomposed) path.  Auto-finalizes past the end step."""
        if not self.enabled or self._finalized:
            return False
        self._step += 1
        if self._step > self.end_step:
            self.finalize()
            return False
        if self._step < self.start_step:
            return False
        if not self._started:
            self._started = True
            _ACTIVE.append(self)
            from .timeline import timeline

            if timeline.active:
                self._clock = timeline._ts_us
            if self._xla and self.dir:
                try:
                    import jax

                    jax.profiler.start_trace(
                        os.path.join(self.dir, "xla_trace"))
                    self._xla_running = True
                except Exception as e:  # noqa: BLE001
                    log.debug("xla trace capture unavailable: %s", e)
        return True

    def arm(self, start_step: int, end_step: int, *,
            current_step: Optional[int] = None,
            trace_dir: Optional[str] = None) -> None:
        """(Re)open the capture window — the watchdog's auto-arm seam
        (observe/autoarm.py).

        ``start_step``/``end_step`` are *global* training-step numbers
        when ``current_step`` (the rank's cadence step) is given: the
        counter is synced to it so a dormant profiler — constructed
        disabled, never advanced — lands the window on the same steps
        as every other rank.  A finalized profiler is reset for a
        fresh capture; its next finalize overwrites compute.json and
        re-pushes the anatomy.  Called from the telemetry flusher
        thread, never the step path; the fields are plain ints/bools,
        so the worst cross-thread interleaving with ``on_step`` is a
        one-step window shift."""
        if current_step is not None:
            self._step = int(current_step)
        if trace_dir and self.dir is None:
            self.dir = os.path.join(trace_dir, str(self.rank))
        self.start_step = max(int(start_step), self._step + 1)
        self.end_step = int(end_step)
        self._finalized = False
        self._finalize_pending = False
        self._started = False
        self._events = []
        self._clock = None
        self.anatomy = None
        self.enabled = True
        log.info("compute profiler armed: steps [%d, %d]",
                 self.start_step, self.end_step)

    # -- recording ----------------------------------------------------------
    @contextlib.contextmanager
    def step_span(self):
        """One STEP envelope in the captured stream — the unit the
        parser computes host gaps inside.  A finalize that lands while
        the step is in flight (e.g. the timeline window auto-closing
        under this very step's ``record_step``) is deferred to the
        span's close so the step's segments make it into the artifact."""
        self._in_step = True
        t0 = self._now()
        try:
            yield
        finally:
            self._events.append({
                "name": STEP_NAME, "cat": f"step_{self._step}", "ph": "X",
                "ts": t0, "dur": self._now() - t0,
                "pid": self.rank, "tid": "step",
            })
            self._in_step = False
            if self._finalize_pending:
                self._finalize_pending = False
                self.finalize()

    def run_segment(self, name: str, fn, *args,
                    flops: Optional[float] = None,
                    nbytes: Optional[float] = None):
        """Run one step block and record its span.  The trailing
        ``block_until_ready`` closes the span at device completion —
        that sync is the decomposed path's honesty (and its documented
        perturbation: only window steps pay it)."""
        t0 = self._now()
        out = fn(*args)
        try:
            import jax

            jax.block_until_ready(out)
        except Exception:  # noqa: BLE001 — non-array outputs time as dispatch
            pass
        ev = {
            "name": name, "cat": SEGMENT_CAT, "ph": "X",
            "ts": t0, "dur": self._now() - t0,
            "pid": self.rank, "tid": "compute",
            "args": {"step": self._step},
        }
        if flops is not None:
            ev["args"]["flops"] = float(flops)
        if nbytes is not None:
            ev["args"]["bytes"] = float(nbytes)
        self._events.append(ev)
        return out

    # -- finalization -------------------------------------------------------
    def finalize(self) -> Optional[dict]:
        """Reduce, persist, export, push — idempotent; deferred to the
        span close when a profiled step is mid-flight."""
        if not self.enabled or self._finalized:
            return self.anatomy
        if self._in_step:
            self._finalize_pending = True
            return self.anatomy
        self._finalized = True
        if self in _ACTIVE:
            _ACTIVE.remove(self)
        if self._xla_running:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001
                log.debug("xla trace stop failed: %s", e)
            self._xla_running = False
        if not self._started:
            return None                  # never captured: no artifact
        self.anatomy = reduce_trace_events(
            self._events,
            peak_flops=self.peak_flops,
            hbm_bytes_per_sec=self.hbm_bytes_per_sec,
            gap_threshold_us=self.gap_threshold_us)
        if self.dir:
            try:
                os.makedirs(self.dir, exist_ok=True)
                with open(os.path.join(self.dir, COMPUTE_JSON), "w") as f:
                    json.dump({
                        "rank": self.rank,
                        "clock": self.clock_name,
                        "anatomy": self.anatomy,
                        "events": self._events,
                    }, f, indent=1)
            except OSError as e:
                log.warning("compute.json write failed: %s", e)
        self._export_gauges()
        self._push_summary()
        log.info("compute profiler: %d step(s) captured, top segment %s "
                 "(%s), mfu %s",
                 self.anatomy["steps"], self.anatomy["top_segment"],
                 self.anatomy["verdict"], self.anatomy["mfu"])
        return self.anatomy

    def _export_gauges(self) -> None:
        try:
            from .. import metrics

            if not metrics.on() or self.anatomy is None:
                return
            from ..metrics import timeseries

            if self.anatomy["mfu"] is not None:
                metrics.MFU.set(self.anatomy["mfu"])
                if timeseries.on():
                    timeseries.record(timeseries.MFU_SERIES,
                                      self.anatomy["mfu"],
                                      step=self._step)
            metrics.HOST_GAP_US.set(
                self.anatomy["host_gap"]["per_step_us"])
            if timeseries.on():
                timeseries.record(timeseries.HOST_GAP_US_SERIES,
                                  self.anatomy["host_gap"]["per_step_us"],
                                  step=self._step)
            for name, d in self.anatomy["segments"].items():
                metrics.STEP_PHASE_FRACTION.labels(name).set(d["fraction"])
            metrics.STEP_PHASE_FRACTION.labels("host_gap").set(
                self.anatomy["host_gap"]["fraction"])
        except Exception as e:  # noqa: BLE001 — metrics must not fail a run
            log.debug("profiler gauge export failed: %s", e)

    def _push_summary(self) -> None:
        """Publish the anatomy under the rendezvous ``profile`` scope
        (key = rank) so the launcher's signed ``GET /profile`` serves
        the cross-rank aggregate.  Same env wiring as the metrics
        pusher; never fatal."""
        addr = env_util.get_str(env_util.HVD_METRICS_KV_ADDR)
        port = env_util.get_int(env_util.HVD_METRICS_KV_PORT, 0)
        if not addr or not port or self.anatomy is None:
            return
        secret_hex = env_util.get_str(env_util.HVD_METRICS_SECRET)
        secret = bytes.fromhex(secret_hex) if secret_hex else None
        try:
            from ..run.http_client import put_profile_summary

            put_profile_summary(addr, port, self.rank, self.anatomy,
                                secret=secret)
        except Exception as e:  # noqa: BLE001
            log.debug("profile push skipped: %s", e)


def from_env(rank: Optional[int] = None) -> Optional[ComputeProfiler]:
    """The training-layer entry: an enabled profiler, or None when
    HVD_PROFILE is off (so the step wrapper pays nothing)."""
    prof = ComputeProfiler(rank=rank)
    return prof if prof.enabled else None


# ---------------------------------------------------------------------------
# fixture: hand-computed ground truth (scripts/hvd_profile.py --check)
# ---------------------------------------------------------------------------
#: fixture roofline constants — ridge = 200e12 / 800e9 = 250 flops/byte
PROFILE_PEAK_FLOPS = 200e12
PROFILE_HBM_BYTES_PER_SEC = 800e9
PROFILE_GAP_THRESHOLD_US = 25.0

#: Two ranks, two 1000 µs steps each.  Rank 0 per step:
#:
#: ::
#:
#:     [forward 0-250][gap 50][backward 300-800][allreduce 800-900]
#:     [optimizer 900-950][gap 50]
#:
#: forward 250 µs @ 10 GF / 20 MB → intensity 500 ≥ ridge →
#: compute-bound, achieved 40 TF/s = 20% of peak; backward 500 µs @
#: 20 GF / 50 MB → intensity 400 → compute-bound; grad_allreduce 100 µs
#: @ 0 F / 50 MB → memory-bound; optimizer_update 50 µs @ 0 F / 30 MB →
#: memory-bound.  Host gap 100 µs/step (2 flagged 50 µs spans), step
#: MFU = 30 GF / (1 ms × 200 TF/s) = 0.15.  Rank 1 is identical except
#: backward runs 550 µs back-to-back with forward (one 50 µs tail gap)
#: — the per-segment slowest rank the aggregate must name.
PROFILE_EXPECTED: Dict[str, Any] = {
    "peak_flops": PROFILE_PEAK_FLOPS,
    "hbm_bytes_per_sec": PROFILE_HBM_BYTES_PER_SEC,
    "gap_threshold_us": PROFILE_GAP_THRESHOLD_US,
    "ranks": {
        "0": {
            "steps": 2, "wall_us": 2000.0, "mfu": 0.15,
            "host_gap_total_us": 200.0, "host_gap_per_step_us": 100.0,
            "host_gap_fraction": 0.1, "flagged_gaps": 4,
            "top_segment": "backward", "verdict": "compute-bound",
            "segments": {
                "forward": {"device_us": 500.0, "count": 2,
                            "fraction": 0.25, "intensity": 500.0,
                            "mfu": 0.2, "verdict": "compute-bound"},
                "backward": {"device_us": 1000.0, "count": 2,
                             "fraction": 0.5, "intensity": 400.0,
                             "mfu": 0.2, "verdict": "compute-bound"},
                "grad_allreduce": {"device_us": 200.0, "count": 2,
                                   "fraction": 0.1,
                                   "verdict": "memory-bound"},
                "optimizer_update": {"device_us": 100.0, "count": 2,
                                     "fraction": 0.05,
                                     "verdict": "memory-bound"},
            },
        },
        "1": {
            "steps": 2, "wall_us": 2000.0, "mfu": 0.15,
            "host_gap_total_us": 100.0, "host_gap_per_step_us": 50.0,
            "host_gap_fraction": 0.05, "flagged_gaps": 2,
            "top_segment": "backward", "verdict": "compute-bound",
            "segments": {
                "forward": {"device_us": 500.0, "count": 2,
                            "fraction": 0.25, "intensity": 500.0,
                            "mfu": 0.2, "verdict": "compute-bound"},
                "backward": {"device_us": 1100.0, "count": 2,
                             "fraction": 0.55, "intensity": 400.0,
                             "verdict": "compute-bound"},
                "grad_allreduce": {"device_us": 200.0, "count": 2,
                                   "fraction": 0.1,
                                   "verdict": "memory-bound"},
                "optimizer_update": {"device_us": 100.0, "count": 2,
                                     "fraction": 0.05,
                                     "verdict": "memory-bound"},
            },
        },
    },
    "slowest": {"backward": "1"},
    "backward_spread_us": 100.0,
    "aggregate_mfu": 0.15,
    "host_gap_max_rank": "0",
}

_FIXTURE_SEGMENTS = {
    # name: (flops, bytes) per occurrence
    "forward": (10e9, 20e6),
    "backward": (20e9, 50e6),
    "grad_allreduce": (0.0, 50e6),
    "optimizer_update": (0.0, 30e6),
}


def profile_fixture_events(rank: int) -> List[dict]:
    """The fixture's raw trace-event stream for one rank (pure python —
    this is the corpus the parser is pinned against on CPU tier-1)."""
    layout = {
        0: (("forward", 0.0, 250.0), ("backward", 300.0, 500.0),
            ("grad_allreduce", 800.0, 100.0),
            ("optimizer_update", 900.0, 50.0)),
        1: (("forward", 0.0, 250.0), ("backward", 250.0, 550.0),
            ("grad_allreduce", 800.0, 100.0),
            ("optimizer_update", 900.0, 50.0)),
    }[rank]
    events: List[dict] = []
    for step in (1, 2):
        o = (step - 1) * 1000.0
        events.append({"name": STEP_NAME, "cat": f"step_{step}", "ph": "X",
                       "ts": o, "dur": 1000.0, "pid": rank, "tid": "step"})
        for name, ts, dur in layout:
            flops, nbytes = _FIXTURE_SEGMENTS[name]
            events.append({
                "name": name, "cat": SEGMENT_CAT, "ph": "X",
                "ts": o + ts, "dur": dur, "pid": rank, "tid": "compute",
                "args": {"step": step, "flops": flops, "bytes": nbytes},
            })
    return events


def write_profile_fixture(trace_dir: str) -> Dict[str, Any]:
    """Materialize the fixture as per-rank ``compute.json`` artifacts
    (events + parser-reduced anatomy) and return
    :data:`PROFILE_EXPECTED` — the corpus ``hvd_profile --check`` and
    the tier-1 tests recover exactly."""
    for rank in (0, 1):
        d = os.path.join(trace_dir, str(rank))
        os.makedirs(d, exist_ok=True)
        events = profile_fixture_events(rank)
        anatomy = reduce_trace_events(
            events, peak_flops=PROFILE_PEAK_FLOPS,
            hbm_bytes_per_sec=PROFILE_HBM_BYTES_PER_SEC,
            gap_threshold_us=PROFILE_GAP_THRESHOLD_US)
        with open(os.path.join(d, COMPUTE_JSON), "w") as f:
            json.dump({"rank": rank, "clock": "fixture",
                       "anatomy": anatomy, "events": events}, f, indent=1)
    return dict(PROFILE_EXPECTED)
