"""Cross-rank trace merge + straggler analysis.

The fork's per-rank layout (``<dir>/<rank>/comm.json``, reference
timeline.cc:205-228) deliberately gives every rank its own file — good
for capture, bad for analysis: N disconnected traces can't answer the
dPRO-style question "which rank is late?".  This module fuses them:

* :func:`merge_traces` — one Chrome trace for the whole job, with each
  event's ``pid`` forced to its rank and ``process_name`` metadata so
  chrome://tracing / Perfetto shows one row group per rank;
* :func:`straggler_report` — per-tensor negotiation-wait spread across
  ranks.  A NEGOTIATE span measures how long a rank waited for the rest
  of the job to reach the same collective (reference timeline.cc
  NegotiateStart/End, controller.cc response assembly): the LAST rank to
  arrive waits the least, so per tensor the rank with the minimum wait
  is the straggler and ``spread = max - min`` is the time it cost the
  others.

``scripts/hvd_trace_merge.py`` is the CLI.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

NEGOTIATE_PREFIX = "NEGOTIATE_"


def load_rank_events(path: str) -> List[dict]:
    """Parse one comm.json leniently: a live (unfinalized) file has no
    closing bracket and may end mid-stream (same contract as
    scripts/trace_summary.py)."""
    with open(path) as f:
        txt = f.read().strip()
    if txt.endswith(","):
        txt = txt[:-1]
    if not txt.endswith("]"):
        txt += "]"
    return json.loads(txt)


def discover_ranks(trace_dir: str) -> Dict[int, str]:
    """rank -> comm.json path for every per-rank subdir that has one."""
    out: Dict[int, str] = {}
    for entry in os.listdir(trace_dir):
        if not entry.isdigit():
            continue
        p = os.path.join(trace_dir, entry, "comm.json")
        if os.path.isfile(p):
            out[int(entry)] = p
    if not out:
        raise FileNotFoundError(
            f"no <rank>/comm.json under {trace_dir}"
        )
    return dict(sorted(out.items()))


def merge_traces(trace_dir: str) -> dict:
    """All ranks' events as ONE Chrome trace (object form, so viewers
    accept it even though per-rank files use the array form): every
    event's ``pid`` is its rank — regardless of what the recording
    process wrote — plus ``process_name``/``process_sort_index``
    metadata per rank."""
    events: List[dict] = []
    for rank, path in discover_ranks(trace_dir).items():
        events.append({"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": f"rank {rank}"}})
        events.append({"name": "process_sort_index", "ph": "M",
                       "pid": rank, "args": {"sort_index": rank}})
        for ev in load_rank_events(path):
            ev = dict(ev)
            ev["pid"] = rank
            events.append(ev)
    return {"traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"source": "hvd_trace_merge",
                          "trace_dir": os.path.abspath(trace_dir)}}


def write_merged(trace_dir: str, out_path: str) -> dict:
    merged = merge_traces(trace_dir)
    d = os.path.dirname(out_path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(merged, f)
    return merged


# ---------------------------------------------------------------------------
# straggler analysis
# ---------------------------------------------------------------------------
def negotiation_waits(events: List[dict]) -> Dict[str, Dict[str, float]]:
    """tensor -> {op, wait_us} from one rank's events: the duration of
    each NEGOTIATE_<OP> B/E pair (first pair per tensor wins; repeated
    negotiations of the same name accumulate)."""
    waits: Dict[str, Dict[str, float]] = {}
    open_spans: Dict[tuple, float] = {}
    for ev in events:
        name = ev.get("name", "")
        if not name.startswith(NEGOTIATE_PREFIX):
            continue
        tensor = ev.get("cat") or ev.get("tid") or ""
        key = (name, tensor)
        ph = ev.get("ph")
        if ph == "B":
            open_spans[key] = float(ev.get("ts", 0.0))
        elif ph == "E" and key in open_spans:
            dur = float(ev.get("ts", 0.0)) - open_spans.pop(key)
            d = waits.setdefault(
                tensor, {"op": name[len(NEGOTIATE_PREFIX):], "wait_us": 0.0}
            )
            d["wait_us"] += dur
        elif ph == "X":
            d = waits.setdefault(
                tensor, {"op": name[len(NEGOTIATE_PREFIX):], "wait_us": 0.0}
            )
            d["wait_us"] += float(ev.get("dur", 0.0))
    return waits


def straggler_report(trace_dir: str, top: Optional[int] = None) -> dict:
    """Per-tensor negotiation-wait spread across ranks.

    For each tensor negotiated on >= 2 ranks:

    * ``per_rank_wait_us`` — each rank's cumulative negotiation wait;
    * ``spread_us`` — max - min across ranks: the time the tensor's
      slowest arrival cost the fastest;
    * ``straggler_rank`` — the rank with the MINIMUM wait (it arrived
      last, so everyone else waited on it);
    * ``max_wait_rank`` — the rank that waited longest (arrived first).

    ``ranks`` summarizes per-rank blame: how many tensors each rank
    stragglered, and its total negotiation wait (a chronically low
    total = chronically late rank).
    """
    per_rank = {rank: negotiation_waits(load_rank_events(path))
                for rank, path in discover_ranks(trace_dir).items()}
    tensors: Dict[str, dict] = {}
    for rank, waits in per_rank.items():
        for tensor, d in waits.items():
            t = tensors.setdefault(tensor, {"op": d["op"], "waits": {}})
            t["waits"][rank] = d["wait_us"]
    rows = []
    straggled = {r: 0 for r in per_rank}
    for tensor, t in tensors.items():
        waits = t["waits"]
        if len(waits) < 2:
            continue
        mx = max(waits, key=waits.get)
        mn = min(waits, key=waits.get)
        spread = waits[mx] - waits[mn]
        straggled[mn] += 1
        rows.append({
            "tensor": tensor,
            "op": t["op"],
            "per_rank_wait_us": {str(r): round(w, 1)
                                 for r, w in sorted(waits.items())},
            "spread_us": round(spread, 1),
            "straggler_rank": mn,
            "max_wait_rank": mx,
        })
    rows.sort(key=lambda r: -r["spread_us"])
    if top:
        rows = rows[:top]
    return {
        "tensors": rows,
        "ranks": {
            str(r): {
                "times_straggler": straggled[r],
                "total_negotiate_wait_us": round(
                    sum(d["wait_us"] for d in per_rank[r].values()), 1),
            }
            for r in per_rank
        },
    }
