"""Cross-rank trace merge + straggler analysis.

The fork's per-rank layout (``<dir>/<rank>/comm.json``, reference
timeline.cc:205-228) deliberately gives every rank its own file — good
for capture, bad for analysis: N disconnected traces can't answer the
dPRO-style question "which rank is late?".  This module fuses them:

* :func:`merge_traces` — one Chrome trace for the whole job, with each
  event's ``pid`` forced to its rank and ``process_name`` metadata so
  chrome://tracing / Perfetto shows one row group per rank.  When every
  rank carries a ``clock_sync.json`` sidecar (written by
  ``Timeline.initialize`` after the offset-estimation handshake against
  the rendezvous server, timeline/replay/clock.py), event timestamps are
  shifted onto one shared clock — the alignment the replay engine's
  cross-rank critical path depends on;
* :func:`straggler_report` — per-tensor negotiation-wait spread across
  ranks.  A NEGOTIATE span measures how long a rank waited for the rest
  of the job to reach the same collective (reference timeline.cc
  NegotiateStart/End, controller.cc response assembly): the LAST rank to
  arrive waits the least, so per tensor the rank with the minimum wait
  is the straggler and ``spread = max - min`` is the time it cost the
  others.

``scripts/hvd_trace_merge.py`` is the CLI.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

NEGOTIATE_PREFIX = "NEGOTIATE_"

#: per-rank clock-offset sidecar written by Timeline.initialize
CLOCK_SYNC_FILE = "clock_sync.json"

#: per-rank compute-anatomy artifact written by the profiler
#: (timeline/profiler.py); its segment events merge into the Chrome
#: trace as their own per-rank row group
COMPUTE_JSON = "compute.json"

#: control-plane flight-recorder dump (``hvd_events --json >
#: <dir>/events.json``, or a raw ``GET /events`` report); its events
#: merge as one row of Chrome instant events above the rank rows
EVENTS_JSON = "events.json"

#: pid of the flight-recorder row — negative so it can never collide
#: with a rank pid or a COMPUTE_PID_BASE row, sorted above rank 0
EVENTS_PID = -1


def load_events_artifact(trace_dir: str) -> List[dict]:
    """The flight-recorder events dumped next to the trace (``{}``-
    tolerant: absent, undecodable, a bare list, or a full ``GET
    /events`` report all work — a trace without one is normal)."""
    p = os.path.join(trace_dir, EVENTS_JSON)
    if not os.path.isfile(p):
        return []
    try:
        with open(p) as f:
            d = json.load(f)
    except (ValueError, OSError):
        return []
    if isinstance(d, dict):
        d = d.get("events") or []
    return [e for e in d if isinstance(e, dict)]


def load_profile_artifact(trace_dir: str, rank: int) -> dict:
    """One rank's parsed ``compute.json`` (``{}`` when absent or
    undecodable — a rank that never profiled is normal, not an error)."""
    p = os.path.join(trace_dir, str(rank), COMPUTE_JSON)
    if not os.path.isfile(p):
        return {}
    try:
        with open(p) as f:
            d = json.load(f)
        return d if isinstance(d, dict) else {}
    except (ValueError, OSError):
        return {}


def load_rank_events(path: str) -> List[dict]:
    """Parse one comm.json leniently: a live (unfinalized) file has no
    closing bracket and may end mid-stream (same contract as
    scripts/trace_summary.py).  A rank that initialized its writer but
    never recorded an event leaves an empty (or whitespace-only, or
    bare-``[``) file — that is an empty trace, not a parse error."""
    with open(path) as f:
        txt = f.read().strip()
    if not txt or txt == "[":
        return []
    if txt.endswith(","):
        txt = txt[:-1]
    if not txt.endswith("]"):
        txt += "]"
    return json.loads(txt)


def discover_ranks(trace_dir: str) -> Dict[int, str]:
    """rank -> comm.json path for every per-rank subdir that has one."""
    out: Dict[int, str] = {}
    for entry in os.listdir(trace_dir):
        if not entry.isdigit():
            continue
        p = os.path.join(trace_dir, entry, "comm.json")
        if os.path.isfile(p):
            out[int(entry)] = p
    if not out:
        raise FileNotFoundError(
            f"no <rank>/comm.json under {trace_dir}"
        )
    return dict(sorted(out.items()))


def load_clock_offsets(trace_dir: str) -> Dict[int, float]:
    """rank -> trace-clock→server-clock offset (µs) from each rank's
    ``clock_sync.json`` sidecar (written by ``Timeline.initialize`` after
    the rendezvous handshake, timeline/replay/clock.py).  Ranks without a
    sidecar are simply absent."""
    out: Dict[int, float] = {}
    for entry in os.listdir(trace_dir):
        if not entry.isdigit():
            continue
        p = os.path.join(trace_dir, entry, CLOCK_SYNC_FILE)
        if not os.path.isfile(p):
            continue
        try:
            with open(p) as f:
                out[int(entry)] = float(json.load(f)["offset_us"])
        except (ValueError, KeyError, TypeError):
            continue
    return out


def clock_shifts(trace_dir: str, ranks) -> tuple:
    """``(aligned, shift_per_rank, offsets)`` — THE alignment policy,
    shared by :func:`merge_traces` and the replay stitcher so the merged
    Chrome trace and the replay DAG built over the same directory can
    never disagree: shifts apply only when EVERY rank has an offset
    (all-or-nothing — mixing aligned and unaligned ranks is worse than
    either), normalized so the earliest-offset rank stays put."""
    offsets = load_clock_offsets(trace_dir)
    aligned = bool(offsets) and all(r in offsets for r in ranks)
    base = min(offsets.values()) if aligned else 0.0
    shift = {r: (offsets[r] - base if aligned else 0.0) for r in ranks}
    return aligned, shift, offsets


def merge_traces(trace_dir: str, align_clocks: bool = True) -> dict:
    """All ranks' events as ONE Chrome trace (object form, so viewers
    accept it even though per-rank files use the array form): every
    event's ``pid`` is its rank — regardless of what the recording
    process wrote — plus ``process_name``/``process_sort_index``
    metadata per rank.

    When ``align_clocks`` and EVERY rank has a ``clock_sync.json``
    sidecar, each event's ``ts`` is shifted by that rank's offset
    (normalized so the earliest rank stays at its original origin) — all
    ranks then share one clock and cross-rank span comparisons are
    meaningful.  With offsets missing for any rank nothing is shifted
    (mixing aligned and unaligned ranks would be worse than either)."""
    ranks = discover_ranks(trace_dir)
    if align_clocks:
        aligned, shift, offsets = clock_shifts(trace_dir, ranks)
    else:
        aligned, shift, offsets = False, {}, {}
    from .profiler import COMPUTE_PID_BASE

    events: List[dict] = []
    for rank, path in ranks.items():
        events.append({"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": f"rank {rank}"}})
        events.append({"name": "process_sort_index", "ph": "M",
                       "pid": rank, "args": {"sort_index": rank}})
        for ev in load_rank_events(path):
            ev = dict(ev)
            ev["pid"] = rank
            if aligned and "ts" in ev:
                ev["ts"] = float(ev["ts"]) + shift[rank]
            events.append(ev)
        # compute-anatomy segments (compute.json): own row group per
        # rank, shifted onto the shared clock exactly like comm events.
        # A 'local'-clock artifact (profiler ran without the timeline)
        # shares no origin with comm.json — merging it would place the
        # rows at nonsense offsets, so it is skipped.
        artifact = load_profile_artifact(trace_dir, rank)
        prof = artifact.get("events", []) \
            if artifact.get("clock") != "local" else []
        if prof:
            cpid = COMPUTE_PID_BASE + rank
            events.append({"name": "process_name", "ph": "M", "pid": cpid,
                           "args": {"name": f"rank {rank} compute"}})
            events.append({"name": "process_sort_index", "ph": "M",
                           "pid": cpid, "args": {"sort_index": rank}})
            for ev in prof:
                ev = dict(ev)
                ev["pid"] = cpid
                if aligned and "ts" in ev:
                    ev["ts"] = float(ev["ts"]) + shift[rank]
                events.append(ev)
    # Control-plane flight-recorder events (events.json): ONE row of
    # Chrome instant events above the rank rows, so "epoch.commit" or
    # "abort.publish" lines up against what the device timelines were
    # doing.  Recorder timestamps are wall-clock seconds while trace
    # spans ride the trace clock; with no cross-clock handshake the
    # merge anchors the EARLIEST recorder event at the earliest trace
    # timestamp and preserves relative spacing — placement is
    # indicative, not sample-exact.
    recorder = [e for e in load_events_artifact(trace_dir)
                if e.get("ts") is not None]
    if recorder and events:
        trace_ts = [float(e["ts"]) for e in events if "ts" in e]
        origin_us = min(trace_ts) if trace_ts else 0.0
        ev_origin_us = min(float(e["ts"]) for e in recorder) * 1e6
        events.append({"name": "process_name", "ph": "M",
                       "pid": EVENTS_PID,
                       "args": {"name": "control plane"}})
        events.append({"name": "process_sort_index", "ph": "M",
                       "pid": EVENTS_PID, "args": {"sort_index": -1}})
        for e in sorted(recorder, key=lambda e: float(e["ts"])):
            events.append({
                "name": e.get("kind") or "event",
                "ph": "i", "s": "g",
                "pid": EVENTS_PID, "tid": 0,
                "ts": origin_us + float(e["ts"]) * 1e6 - ev_origin_us,
                "args": {"id": e.get("id"),
                         "severity": e.get("severity"),
                         "rank": e.get("rank"),
                         "correlation_id": e.get("correlation_id"),
                         "cause_id": e.get("cause_id"),
                         "payload": e.get("payload")},
            })
    return {"traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"source": "hvd_trace_merge",
                          "trace_dir": os.path.abspath(trace_dir),
                          "clock_aligned": aligned,
                          "clock_offsets_us": {str(r): round(o, 3)
                                               for r, o in offsets.items()}}}


def write_merged(trace_dir: str, out_path: str) -> dict:
    merged = merge_traces(trace_dir)
    d = os.path.dirname(out_path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(merged, f)
    return merged


# ---------------------------------------------------------------------------
# straggler analysis
# ---------------------------------------------------------------------------
def negotiation_waits(
    events: List[dict],
) -> tuple:
    """``(waits, unmatched)`` from one rank's events.

    ``waits``: tensor -> {op, wait_us}, the duration of each
    NEGOTIATE_<OP> B/E pair (repeated negotiations of the same name
    accumulate); ``"X"``-phase negotiation events (complete spans, the
    form the native writer emits) contribute their ``dur`` directly.

    ``unmatched``: spans that never paired — a repeated ``"B"`` for the
    same ``(name, tensor)`` key means the earlier span lost its ``"E"``
    (it is counted, not silently overwritten), a stray ``"E"`` has no
    open span, and whatever is still open at end-of-trace leaked.  A
    truncated live trace shows up here instead of silently under-counting
    waits."""
    waits: Dict[str, Dict[str, float]] = {}
    open_spans: Dict[tuple, float] = {}
    unmatched = 0
    for ev in events:
        name = ev.get("name", "")
        if not name.startswith(NEGOTIATE_PREFIX):
            continue
        tensor = ev.get("cat") or ev.get("tid") or ""
        key = (name, tensor)
        ph = ev.get("ph")
        if ph == "B":
            if key in open_spans:
                unmatched += 1  # earlier B never saw its E
            open_spans[key] = float(ev.get("ts", 0.0))
        elif ph == "E":
            if key not in open_spans:
                unmatched += 1  # E without a B (trace started mid-span)
                continue
            dur = float(ev.get("ts", 0.0)) - open_spans.pop(key)
            d = waits.setdefault(
                tensor, {"op": name[len(NEGOTIATE_PREFIX):], "wait_us": 0.0}
            )
            d["wait_us"] += dur
        elif ph == "X":
            d = waits.setdefault(
                tensor, {"op": name[len(NEGOTIATE_PREFIX):], "wait_us": 0.0}
            )
            d["wait_us"] += float(ev.get("dur", 0.0))
    unmatched += len(open_spans)  # still open at end-of-trace
    return waits, unmatched


def straggler_report(trace_dir: str, top: Optional[int] = None) -> dict:
    """Per-tensor negotiation-wait spread across ranks.

    For each tensor negotiated on >= 2 ranks:

    * ``per_rank_wait_us`` — each rank's cumulative negotiation wait;
    * ``spread_us`` — max - min across ranks: the time the tensor's
      slowest arrival cost the fastest;
    * ``straggler_rank`` — the rank with the MINIMUM wait (it arrived
      last, so everyone else waited on it);
    * ``max_wait_rank`` — the rank that waited longest (arrived first).

    ``ranks`` summarizes per-rank blame: how many tensors each rank
    stragglered, its total negotiation wait (a chronically low
    total = chronically late rank), and ``unmatched_spans`` — B/E pairs
    that never closed, the signature of a truncated live trace.

    When any rank carries a ``compute.json`` (the compute-anatomy
    profiler, timeline/profiler.py), ``segments`` extends the straggler
    story to the compute side: per profiled step block, each rank's
    device time, the SLOWEST rank, and the max−min spread — so "rank 3
    is late" localizes to "rank 3's backward is 10% slower", not just a
    negotiation wait.
    """
    per_rank: Dict[int, Dict[str, dict]] = {}
    unmatched: Dict[int, int] = {}
    for rank, path in discover_ranks(trace_dir).items():
        per_rank[rank], unmatched[rank] = negotiation_waits(
            load_rank_events(path))
    tensors: Dict[str, dict] = {}
    for rank, waits in per_rank.items():
        for tensor, d in waits.items():
            t = tensors.setdefault(tensor, {"op": d["op"], "waits": {}})
            t["waits"][rank] = d["wait_us"]
    rows = []
    straggled = {r: 0 for r in per_rank}
    for tensor, t in tensors.items():
        waits = t["waits"]
        if len(waits) < 2:
            continue
        mx = max(waits, key=waits.get)
        mn = min(waits, key=waits.get)
        spread = waits[mx] - waits[mn]
        straggled[mn] += 1
        rows.append({
            "tensor": tensor,
            "op": t["op"],
            "per_rank_wait_us": {str(r): round(w, 1)
                                 for r, w in sorted(waits.items())},
            "spread_us": round(spread, 1),
            "straggler_rank": mn,
            "max_wait_rank": mx,
        })
    rows.sort(key=lambda r: -r["spread_us"])
    if top:
        rows = rows[:top]
    report = {
        "tensors": rows,
        "ranks": {
            str(r): {
                "times_straggler": straggled[r],
                "total_negotiate_wait_us": round(
                    sum(d["wait_us"] for d in per_rank[r].values()), 1),
                "unmatched_spans": unmatched[r],
            }
            for r in per_rank
        },
    }
    segments = segment_straggler_report(trace_dir, per_rank.keys())
    if segments:
        report["segments"] = segments
    report["verdicts"] = straggler_verdicts(report)
    return report


def straggler_verdicts(report: dict, *,
                       skew_threshold: float = 1.3) -> dict:
    """Machine-readable per-rank verdict block from a straggler report —
    the shape the watchdog's drift detector consumes
    (``observe.detectors.straggler_from_verdicts``), so offline trace
    analysis and the live watchdog agree on who is late.

    Each rank gets ``{"verdict": "straggler" | "ok", "skew", "basis"}``:

    * with profiled compute (``segments``), ``skew`` is the rank's
      total segment device time over the cross-rank median
      (basis ``segment_device_us``) — late because *slow*;
    * otherwise ``skew`` is ``1 + times_straggler / contested_tensors``
      (basis ``negotiate_wait``) — a rank that arrived last for every
      contested tensor scores 2.0, one never late scores 1.0.
    """
    verdicts: Dict[str, dict] = {}
    segments = report.get("segments") or {}
    totals: Dict[str, float] = {}
    for seg in segments.values():
        for rank, us in (seg.get("per_rank_device_us") or {}).items():
            totals[str(rank)] = totals.get(str(rank), 0.0) + float(us)
    if len(totals) >= 2:
        ordered = sorted(totals.values())
        mid = len(ordered) // 2
        median = ordered[mid] if len(ordered) % 2 \
            else (ordered[mid - 1] + ordered[mid]) / 2.0
        for rank, total in totals.items():
            ratio = total / median if median > 0 else 1.0
            verdicts[rank] = {
                "verdict": "straggler" if ratio >= skew_threshold else "ok",
                "skew": round(ratio, 4),
                "basis": "segment_device_us",
            }
    contested = len(report.get("tensors") or [])
    for rank, d in (report.get("ranks") or {}).items():
        if rank in verdicts:
            continue
        frac = (d.get("times_straggler", 0) / contested) if contested else 0.0
        verdicts[rank] = {
            "verdict": "straggler" if contested and frac >= 0.5 else "ok",
            "skew": round(1.0 + frac, 4),
            "basis": "negotiate_wait",
        }
    return {"ranks": verdicts, "skew_threshold": skew_threshold}


def segment_straggler_report(trace_dir: str, ranks) -> Dict[str, dict]:
    """Per-compute-segment slowest-rank table from the ranks'
    ``compute.json`` anatomies: ``{segment: {per_rank_device_us,
    slowest_rank, spread_us}}`` (empty when nobody profiled).  The
    reduction is :func:`~horovod_tpu.timeline.profiler
    .aggregate_anatomies` — the same one behind ``GET /profile`` and
    ``hvd_profile`` — so this table can never disagree with them on
    who the slowest rank is."""
    from .profiler import aggregate_anatomies

    anatomies = {}
    for rank in ranks:
        anatomy = load_profile_artifact(trace_dir, rank).get("anatomy")
        if isinstance(anatomy, dict):
            anatomies[str(rank)] = anatomy
    if not anatomies:
        return {}
    agg = aggregate_anatomies(anatomies)
    return {
        name: {
            "per_rank_device_us": s["per_rank_device_us"],
            "slowest_rank": int(s["slowest_rank"]),
            "spread_us": s["spread_us"],
        }
        for name, s in agg["segments"].items()
    }
