"""horovod_tpu.torch: the PyTorch-flavored API surface.

Mirror of horovod/torch (reference horovod/torch/__init__.py,
torch/mpi_ops.py): ``allreduce[_async_]``, ``allgather``, ``broadcast``,
``synchronize``/``poll`` handles, ``DistributedOptimizer`` with
``backward_passes_per_step``, ``broadcast_parameters`` /
``broadcast_optimizer_state``, Compression.

Architecture: the reference routes torch tensors through a C++ extension
(mpi_ops_v2.cc) into the background-thread/NCCL stack; here torch tensors
bridge to the XLA data plane via zero-ceremony numpy interchange and the
eager SPMD programs (horovod_tpu/eager.py), with a ``HandleManager``
mirroring the v2 handle API (reference torch/handle_manager.cc,
mpi_ops.py:72-75).  On this image torch is CPU-only, so the device hop is
host→TPU→host per call — the *contract* (hooks, handles, in-place
semantics) is what this module preserves; torch-on-TPU compute would ride
torch-xla, which is out of scope for the runtime (SURVEY §7.3(4)).
"""

from __future__ import annotations

import concurrent.futures
import threading
from typing import Any, Dict, Optional

import numpy as np

from .. import core, eager
from ..core import Average, Sum, Adasum, Min, Max  # noqa: F401
from ..ops.compression import Compression  # noqa: F401
from ..runtime import eager_controller

init = core.init
shutdown = core.shutdown
rank = core.rank
local_rank = core.local_rank
size = core.size
local_size = core.local_size
cross_rank = core.cross_rank
cross_size = core.cross_size
is_initialized = core.is_initialized
mpi_enabled = core.mpi_enabled
nccl_built = core.nccl_built


class HandleManager:
    """Async-op handle registry (reference torch/handle_manager.cc:
    AllocateHandle/MarkDone/PollHandle/WaitForCompletion + the outputs
    map in torch/mpi_ops.py:72-75).

    Genuinely deferred: ``submit`` hands the collective to a background
    thread (the analog of the reference's background communication thread +
    GPU finalizer threads, operations.cc:333 / thread_pool.cc) so
    reductions overlap the caller's compute; ``poll`` is the real
    completion state and ``wait`` joins the future.  One thread per handle
    — a bounded pool could deadlock across ranks when hook firing order
    differs (every pooled worker blocked in wait_data on names the peer
    hasn't submitted because its own submits are stuck in the queue).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next = 0
        self._futures: Dict[int, concurrent.futures.Future] = {}

    def submit(self, fn, *args) -> int:
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def runner():
            try:
                fut.set_result(fn(*args))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        with self._lock:
            h = self._next
            self._next += 1
            self._futures[h] = fut
        threading.Thread(target=runner, daemon=True,
                         name=f"hvd-eager-{h}").start()
        return h

    def poll(self, handle: int) -> bool:
        with self._lock:
            fut = self._futures.get(handle)
        if fut is None:
            raise ValueError(f"unknown handle {handle}")
        return fut.done()

    def wait(self, handle: int) -> Any:
        with self._lock:
            fut = self._futures.pop(handle, None)
        if fut is None:
            raise ValueError(f"unknown handle {handle}")
        return fut.result()


_handles = HandleManager()


def _to_numpy(tensor) -> np.ndarray:
    if hasattr(tensor, "detach"):
        return tensor.detach().cpu().numpy()
    return np.asarray(tensor)


def _like(tensor, arr: np.ndarray):
    if hasattr(tensor, "detach"):
        import torch as th

        # ascontiguousarray promotes 0-d to 1-d (ndmin=1); reshape to the
        # wire array's own shape so scalars (e.g. BN num_batches_tracked)
        # round-trip — allgather outputs keep their grown dim 0
        return th.from_numpy(
            np.ascontiguousarray(arr)).reshape(arr.shape).to(tensor.dtype)
    return arr


def allreduce_async(tensor, average=None, name=None, op=None,
                    compression=Compression.none):
    """reference torch/mpi_ops.py:94-129 (op/average normalization and the
    divisor trick: Average → Sum + divide).  The reduction runs on the
    handle pool: compression → cross-process sum over the native data
    plane (or multihost_utils on a jax.distributed pod) → decompression."""
    op = _normalize_op(average, op)
    # Snapshot at submit time: _to_numpy aliases the live tensor, and the
    # background thread must not observe later mutations (grad
    # accumulation, zero_grad) racing the wire serialization.
    arr = np.array(_to_numpy(tensor), copy=True)
    # Name allocated in program order on the caller thread so all
    # processes agree even when pool workers race.
    nm = name or eager_controller.next_name("allreduce.torch")

    def work():
        comp, ctx = compression.compress(arr)
        out = eager.process_allreduce(np.asarray(comp), op=op, name=nm)
        out = np.asarray(compression.decompress(out, ctx))
        return _like(tensor, out)

    return _handles.submit(work)


def allreduce(tensor, average=None, name=None, op=None,
              compression=Compression.none):
    return synchronize(
        allreduce_async(tensor, average, name, op, compression)
    )


def allreduce_(tensor, average=None, name=None, op=None):
    """In-place variant (reference mpi_ops.py allreduce_)."""
    out = allreduce(tensor, average, name, op)
    if hasattr(tensor, "copy_"):
        tensor.copy_(out)
        return tensor
    tensor[...] = out
    return tensor


def allgather_async(tensor, name=None):
    arr = _to_numpy(tensor)
    nm = name or eager_controller.next_name("allgather.torch")

    def work():
        return _like(tensor, eager.process_allgather(arr, name=nm))

    return _handles.submit(work)


def allgather(tensor, name=None):
    return synchronize(allgather_async(tensor, name))


def broadcast_async(tensor, root_rank, name=None):
    arr = _to_numpy(tensor)
    nm = name or eager_controller.next_name("broadcast.torch")

    def work():
        return _like(tensor, eager.process_broadcast(arr, root_rank, name=nm))

    return _handles.submit(work)


def broadcast(tensor, root_rank, name=None):
    return synchronize(broadcast_async(tensor, root_rank, name))


def broadcast_(tensor, root_rank, name=None):
    out = broadcast(tensor, root_rank, name)
    if hasattr(tensor, "copy_"):
        tensor.copy_(out)
        return tensor
    tensor[...] = out
    return tensor


def poll(handle: int) -> bool:
    return _handles.poll(handle)


def synchronize(handle: int):
    return _handles.wait(handle)


def join() -> int:
    from ..elastic.join import join as _join

    return _join()


_normalize_op = eager.normalize_op


# ---------------------------------------------------------------------------
# optimizer + parameter sync
# ---------------------------------------------------------------------------
class _DistributedOptimizer:
    """Wraps a torch.optim.Optimizer: async-allreduce each parameter
    gradient as it materializes during backward (grad-accumulator hooks,
    reference torch/__init__.py:122-157), then join the handles in
    ``synchronize()`` before step() — communication overlaps the rest of
    the backward pass via the handle pool."""

    def __init__(self, optimizer, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1, op=Average):
        self._opt = optimizer
        self._compression = compression
        self._op = op
        self.backward_passes_per_step = backward_passes_per_step
        self._counter = 0
        self._param_names = {}
        self._grad_accs = []         # keep accumulators alive (reference :150)
        self._pending = {}           # param id -> (param, handle)
        self._delay = {}             # param id -> remaining backward passes
        if named_parameters is not None:
            for n, p in named_parameters:
                self._param_names[id(p)] = n
        self._register_hooks()

    def _name_of(self, p, fallback_idx: int) -> str:
        return self._param_names.get(id(p), f"param.{fallback_idx}")

    def _register_hooks(self) -> None:
        idx = 0
        for group in self._opt.param_groups:
            for p in group["params"]:
                i = idx
                idx += 1
                if not getattr(p, "requires_grad", False):
                    continue
                try:
                    # the grad-accumulator node fires once p.grad is final
                    # for this backward (reference torch/__init__.py:141-157)
                    acc = p.expand_as(p).grad_fn.next_functions[0][0]
                    acc.register_hook(self._make_hook(p, i))
                    self._grad_accs.append(acc)
                    self._delay[id(p)] = self.backward_passes_per_step
                except (AttributeError, IndexError, RuntimeError, TypeError):
                    pass  # non-autograd tensor: reduced in synchronize()

    def _make_hook(self, p, idx: int):
        def hook(*ignore):
            self._delay[id(p)] -= 1
            if self._delay[id(p)] > 0 or p.grad is None:
                return
            self._delay[id(p)] = self.backward_passes_per_step
            self._pending[id(p)] = (p, allreduce_async(
                p.grad, op=self._op,
                name=f"allreduce.{self._name_of(p, idx)}",
                compression=self._compression,
            ))

        return hook

    def __getattr__(self, item):
        return getattr(self._opt, item)

    def zero_grad(self, *a, **kw):
        return self._opt.zero_grad(*a, **kw)

    def _copy_into(self, g, red) -> None:
        if hasattr(g, "copy_"):
            g.copy_(_like(g, np.asarray(red)))
        else:
            g[...] = red

    def synchronize(self) -> None:
        """Join outstanding gradient handles; reduce any gradient the hooks
        missed (reference torch/__init__.py:159-176 synchronize())."""
        idx = 0
        for group in self._opt.param_groups:
            for p in group["params"]:
                i = idx
                idx += 1
                g = getattr(p, "grad", None)
                if g is None:
                    continue
                if id(p) in self._pending:
                    _, h = self._pending.pop(id(p))
                    self._copy_into(g, _to_numpy(_handles.wait(h)))
                else:
                    # hookless tensor or manually-assigned grad (no backward
                    # ran): same path as the async hook, joined immediately
                    h = allreduce_async(
                        g, op=self._op,
                        name=f"allreduce.{self._name_of(p, i)}",
                        compression=self._compression,
                    )
                    self._copy_into(g, _to_numpy(_handles.wait(h)))

    def step(self, closure=None):
        self._counter += 1
        if self._counter % self.backward_passes_per_step == 0:
            self.synchronize()
            return self._opt.step(closure)
        return None


class _DistributedAdasumOptimizer:
    """Adasum applied to parameter *deltas*, not gradients (reference
    torch/__init__.py:219-387 _DistributedAdasumOptimizer): each step
    snapshots the parameters, lets the wrapped optimizer take its local
    step, Adasum-reduces ``delta = p_after - start`` across ranks, and
    rebases ``p = start + reduced_delta``.  This is the semantically
    correct Adasum composition with stateful optimizers (momentum/Adam):
    the *update direction* is reduced, so per-rank optimizer state stays
    consistent with what was actually applied.

    Deltas reduce asynchronously on the handle pool (one per parameter,
    program-order names) and join before the rebase — the TPU-era stand-in
    for the reference's per-hook overlap."""

    def __init__(self, optimizer, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1):
        self._opt = optimizer
        self._compression = compression
        self.backward_passes_per_step = backward_passes_per_step
        self._counter = 0
        self._param_names = {}
        if named_parameters is not None:
            for n, p in named_parameters:
                self._param_names[id(p)] = n

    def __getattr__(self, item):
        return getattr(self._opt, item)

    def zero_grad(self, *a, **kw):
        return self._opt.zero_grad(*a, **kw)

    def synchronize(self) -> None:
        # deltas only exist after the local step; nothing to pre-join
        # (reference's synchronize() is likewise a no-op, :352)
        pass

    def step(self, closure=None):
        self._counter += 1
        if self._counter % self.backward_passes_per_step != 0:
            return None  # accumulate grads locally, like the grad path

        params = [p for g in self._opt.param_groups for p in g["params"]
                  if getattr(p, "grad", None) is not None]
        starts = {id(p): p.detach().clone() for p in params}
        loss = self._opt.step(closure)

        handles = []
        for i, p in enumerate(params):
            delta = p.detach() - starts[id(p)]
            nm = self._param_names.get(id(p), f"param.{i}")
            handles.append((p, allreduce_async(
                delta, op=Adasum, name=f"adasum.delta.{nm}",
                compression=self._compression,
            )))
        for p, h in handles:
            reduced = _handles.wait(h)  # torch tensor (allreduce_async)
            p.data.copy_(starts[id(p)] + reduced.to(p.dtype))
        return loss


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1, op=Average):
    """op=Adasum returns the delta-optimizer (reference
    torch/__init__.py:389-414 dispatches the same way)."""
    if op == Adasum:
        return _DistributedAdasumOptimizer(
            optimizer, named_parameters, compression,
            backward_passes_per_step,
        )
    return _DistributedOptimizer(
        optimizer, named_parameters, compression,
        backward_passes_per_step, op,
    )


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """In-place parameter broadcast (reference torch/__init__.py:446-478;
    accepts a state_dict or an iterable of (name, tensor))."""
    if hasattr(params, "items"):
        items = list(params.items())
    else:
        items = list(params)
    for _, p in items:
        if hasattr(p, "copy_"):
            broadcast_(p, root_rank)


def broadcast_optimizer_state(optimizer, root_rank: int = 0) -> None:
    """reference torch/__init__.py:480-578: walk optimizer.state_dict(),
    broadcast every tensor entry, scalars via broadcast_object."""
    state = optimizer.state_dict()
    synced = eager.broadcast_object(state, root_rank=root_rank) \
        if core.process_size() > 1 else state
    optimizer.load_state_dict(synced)


def broadcast_object(obj, root_rank: int = 0, name=None):
    return eager.broadcast_object(obj, root_rank=root_rank)
