"""horovod_tpu.torch: the PyTorch-flavored API surface.

Mirror of horovod/torch (reference horovod/torch/__init__.py,
torch/mpi_ops.py): ``allreduce[_async_]``, ``allgather``, ``broadcast``,
``synchronize``/``poll`` handles, ``DistributedOptimizer`` with
``backward_passes_per_step``, ``broadcast_parameters`` /
``broadcast_optimizer_state``, Compression.

Architecture: the reference routes torch tensors through a C++ extension
(mpi_ops_v2.cc) into the background-thread/NCCL stack; here torch tensors
bridge to the XLA data plane via zero-ceremony numpy interchange and the
eager SPMD programs (horovod_tpu/eager.py), with a ``HandleManager``
mirroring the v2 handle API (reference torch/handle_manager.cc,
mpi_ops.py:72-75).  On this image torch is CPU-only, so the device hop is
host→TPU→host per call — the *contract* (hooks, handles, in-place
semantics) is what this module preserves; torch-on-TPU compute would ride
torch-xla, which is out of scope for the runtime (SURVEY §7.3(4)).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import numpy as np

from .. import core, eager
from ..core import Average, Sum, Adasum, Min, Max  # noqa: F401
from ..ops.compression import Compression  # noqa: F401

init = core.init
shutdown = core.shutdown
rank = core.rank
local_rank = core.local_rank
size = core.size
local_size = core.local_size
cross_rank = core.cross_rank
cross_size = core.cross_size
is_initialized = core.is_initialized
mpi_enabled = core.mpi_enabled
nccl_built = core.nccl_built


class HandleManager:
    """Async-op handle registry (reference torch/handle_manager.cc:
    AllocateHandle/MarkDone/PollHandle/WaitForCompletion + the outputs
    map in torch/mpi_ops.py:72-75)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next = 0
        self._results: Dict[int, Any] = {}
        self._done: Dict[int, bool] = {}

    def allocate(self) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._done[h] = False
            return h

    def mark_done(self, handle: int, result: Any) -> None:
        with self._lock:
            self._results[handle] = result
            self._done[handle] = True

    def poll(self, handle: int) -> bool:
        with self._lock:
            return self._done.get(handle, False)

    def wait(self, handle: int) -> Any:
        # JAX dispatch is async under the hood; by the time we store the
        # result it is a future — materialize here (the "synchronize").
        with self._lock:
            if handle not in self._done:
                raise ValueError(f"unknown handle {handle}")
            result = self._results.pop(handle)
            del self._done[handle]
        return result


_handles = HandleManager()


def _to_numpy(tensor) -> np.ndarray:
    if hasattr(tensor, "detach"):
        return tensor.detach().cpu().numpy()
    return np.asarray(tensor)


def _like(tensor, arr: np.ndarray):
    if hasattr(tensor, "detach"):
        import torch as th

        return th.from_numpy(np.ascontiguousarray(arr)).to(tensor.dtype)
    return arr


def _eager_collective(fn, tensor, *fn_args, **fn_kw):
    """Run a host-plane collective on one per-process tensor.  With a
    single controller the process IS every rank's controller, so the
    reduction is the identity family; multi-process goes through the
    process-plane collectives (eager.py)."""
    arr = _to_numpy(tensor)
    return fn(arr, *fn_args, **fn_kw)


def allreduce_async(tensor, average=None, name=None, op=None):
    """reference torch/mpi_ops.py:94-129 (op/average normalization and the
    divisor trick: Average → Sum + divide)."""
    op = _normalize_op(average, op)
    h = _handles.allocate()

    arr = _to_numpy(tensor)
    if core.process_size() == 1:
        out = arr if op != Sum else arr * core.process_size()
    else:
        gathered = eager.allgather_object(arr)
        stacked = np.stack(gathered)
        out = stacked.mean(0) if op == Average else stacked.sum(0)
    _handles.mark_done(h, _like(tensor, out))
    return h


def allreduce(tensor, average=None, name=None, op=None,
              compression=Compression.none):
    return synchronize(allreduce_async(tensor, average, name, op))


def allreduce_(tensor, average=None, name=None, op=None):
    """In-place variant (reference mpi_ops.py allreduce_)."""
    out = allreduce(tensor, average, name, op)
    if hasattr(tensor, "copy_"):
        tensor.copy_(out)
        return tensor
    tensor[...] = out
    return tensor


def allgather_async(tensor, name=None):
    h = _handles.allocate()
    arr = _to_numpy(tensor)
    if core.process_size() == 1:
        out = arr
    else:
        out = np.concatenate(eager.allgather_object(arr), axis=0)
    _handles.mark_done(h, _like(tensor, out))
    return h


def allgather(tensor, name=None):
    return synchronize(allgather_async(tensor, name))


def broadcast_async(tensor, root_rank, name=None):
    h = _handles.allocate()
    arr = _to_numpy(tensor)
    out = eager.broadcast_object(arr, root_rank=root_rank) \
        if core.process_size() > 1 else arr
    _handles.mark_done(h, _like(tensor, out))
    return h


def broadcast(tensor, root_rank, name=None):
    return synchronize(broadcast_async(tensor, root_rank, name))


def broadcast_(tensor, root_rank, name=None):
    out = broadcast(tensor, root_rank, name)
    if hasattr(tensor, "copy_"):
        tensor.copy_(out)
        return tensor
    tensor[...] = out
    return tensor


def poll(handle: int) -> bool:
    return _handles.poll(handle)


def synchronize(handle: int):
    return _handles.wait(handle)


def join() -> int:
    from ..elastic.join import join as _join

    return _join()


def _normalize_op(average, op):
    """reference mpi_ops.py handle_average_backwards_compatibility."""
    if average is not None and op is not None:
        raise ValueError("cannot specify both average and op")
    if op is not None:
        return op
    if average is False:
        return Sum
    return Average


# ---------------------------------------------------------------------------
# optimizer + parameter sync
# ---------------------------------------------------------------------------
class _DistributedOptimizer:
    """Wraps a torch.optim.Optimizer: allreduce each parameter gradient
    before step() (reference torch/__init__.py:122-217; the per-parameter
    backward hooks collapse to a pre-step sweep here because the host
    collective is synchronous — overlap belongs to the compiled plane)."""

    def __init__(self, optimizer, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1, op=Average):
        self._opt = optimizer
        self._compression = compression
        self._op = op
        self.backward_passes_per_step = backward_passes_per_step
        self._counter = 0

    def __getattr__(self, item):
        return getattr(self._opt, item)

    def zero_grad(self, *a, **kw):
        return self._opt.zero_grad(*a, **kw)

    def synchronize(self) -> None:
        """Allreduce all gradients now (reference torch/__init__.py:159-176
        synchronize())."""
        for group in self._opt.param_groups:
            for p in group["params"]:
                if getattr(p, "grad", None) is not None:
                    g = p.grad
                    comp, ctx = self._compression.compress(_to_numpy(g))
                    if core.process_size() > 1:
                        gathered = eager.allgather_object(np.asarray(comp))
                        stacked = np.stack(gathered)
                        red = stacked.mean(0) if self._op == Average \
                            else stacked.sum(0)
                    else:
                        red = np.asarray(comp)
                    red = self._compression.decompress(red, ctx)
                    if hasattr(g, "copy_"):
                        import torch as th

                        g.copy_(th.from_numpy(
                            np.ascontiguousarray(red)).to(g.dtype))
                    else:
                        g[...] = red

    def step(self, closure=None):
        self._counter += 1
        if self._counter % self.backward_passes_per_step == 0:
            self.synchronize()
            return self._opt.step(closure)
        return None


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1, op=Average):
    return _DistributedOptimizer(
        optimizer, named_parameters, compression,
        backward_passes_per_step, op,
    )


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """In-place parameter broadcast (reference torch/__init__.py:446-478;
    accepts a state_dict or an iterable of (name, tensor))."""
    if hasattr(params, "items"):
        items = list(params.items())
    else:
        items = list(params)
    for _, p in items:
        if hasattr(p, "copy_"):
            broadcast_(p, root_rank)


def broadcast_optimizer_state(optimizer, root_rank: int = 0) -> None:
    """reference torch/__init__.py:480-578: walk optimizer.state_dict(),
    broadcast every tensor entry, scalars via broadcast_object."""
    state = optimizer.state_dict()
    synced = eager.broadcast_object(state, root_rank=root_rank) \
        if core.process_size() > 1 else state
    optimizer.load_state_dict(synced)


def broadcast_object(obj, root_rank: int = 0, name=None):
    return eager.broadcast_object(obj, root_rank=root_rank)
