"""Pipeline parallelism: GPipe-style microbatch pipeline over a mesh axis.

The reference implements data parallelism only (SURVEY §2.6: PP
"absent") — this completes the TPU build's parallelism layer (dp / tp /
sp / pp) on the same collective substrate: stages are ranks along a
``pp`` mesh axis, every tick each rank applies its stage to the resident
activation and the results rotate one hop over ICI via ``ppermute`` —
the neighbor-only traffic pattern pipelining was designed for.

Formulation (the "circulating buffer" SPMD pipeline): all stages share
one activation shape; with S stages and M microbatches the loop runs
``T = M + S - 1`` ticks.  Rank 0 injects microbatch ``t`` at tick ``t``;
rank ``S-1`` banks its output for microbatch ``t-(S-1)``; a final psum
over the pp axis replicates the collected outputs (only the last rank's
buffer is nonzero).  The schedule is a ``lax.scan`` — compiled control
flow, no Python loop over ticks — and is differentiable end-to-end
(``ppermute``'s transpose is the inverse permutation, so gradients
counter-rotate through the pipeline automatically).

Bubble fraction is the usual (S-1)/(M+S-1); pick M >> S.

Verification: the handoff ``ppermute`` lowers to a SendRecv event in
the schedule model checker (``hvd_verify``, HVD013) under the
``axis:<name>`` group of the pp axis; the micro-batch ``lax.scan``
unrolls to HVD_VERIFY_LOOP_BOUND and is surfaced in the report's
``loop_bounds`` field.  Repo self-verify (tests/test_hvd_verify.py)
keeps this module finding-free — the rotation is unconditional on every
stage rank, so every send has its matching recv.
"""

from __future__ import annotations

import warnings
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def _to_varying(x, axis):
    """Mark ``x`` varying over ``axis`` for the replication checker
    (pcast on current jax; pvary on older releases)."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis,), to="varying")
    return lax.pvary(x, (axis,))


def _vma_state(x, axis) -> str:
    """'on' when the replication checker recorded ``x`` as varying over
    ``axis`` (shard_map check_vma=True), 'off' when the checker is
    demonstrably disabled, 'unknown' when this JAX can't tell (no false
    alarms in that case)."""
    from ..utils import jax_compat

    if getattr(lax, "pvary", None) is jax_compat._compat_pvary:
        # the compat identity shim means NO VMA machinery exists on this
        # JAX: the backward psum→pbroadcast rewrite cannot happen
        # (measured: gradients scale by the stage count) — warn loudly
        return "off"
    if not hasattr(jax, "typeof"):
        return "unknown"
    try:
        vma = getattr(jax.typeof(x), "vma", None)
    except Exception:
        return "unknown"
    if vma is None:
        return "unknown"
    return "on" if axis in vma else "off"


def pipeline_apply(stage_fn: Callable, stage_params, x_mbs, *,
                   axis: str = "pp"):
    """Run ``x_mbs`` microbatches through the S-stage pipeline.

    Args:
      stage_fn: ``(stage_params, x) -> y`` with ``y.shape == x.shape``
        (one pipeline stage; this rank's slice of the layer stack).
      stage_params: THIS rank's stage parameters (stack the per-stage
        pytrees on a leading axis sharded over ``axis`` and index
        ``[0]`` inside the shard_map, as the tests do).
      x_mbs: ``[M, microbatch, ...]`` microbatches, replicated across the
        pp axis (only rank 0 reads them).
      axis: the pipeline mesh axis.

    Returns ``[M, microbatch, ...]`` outputs, replicated across ``axis``.

    .. warning:: The enclosing ``shard_map`` MUST run with
       ``check_vma=True`` (the default).  Under ``check_vma=False`` the
       final psum's transpose is not rewritten to a pbroadcast and the
       backward pass mis-scales gradients by the pipeline size — a
       warning is emitted when the checker is detected off, but the
       forward values are identical, so there is no runtime error.
    """
    s = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    m = x_mbs.shape[0]
    ticks = m + s - 1
    perm = [(i, (i + 1) % s) for i in range(s)]

    def tick(carry, t):
        state, outbuf = carry
        # rank 0 injects microbatch t (clipped reads past the end feed
        # junk whose pipeline exit lands outside the valid window)
        inject = _to_varying(x_mbs[jnp.clip(t, 0, m - 1)], axis)
        inp = jnp.where(idx == 0, inject, state)
        out = stage_fn(stage_params, inp)
        pos = t - (s - 1)
        valid = (idx == s - 1) & (pos >= 0)
        outbuf = jnp.where(
            valid, outbuf.at[jnp.clip(pos, 0, m - 1)].set(out), outbuf
        )
        state = lax.ppermute(out, axis, perm)
        return (state, outbuf), None

    # NB: the region must run with replication checking ON
    # (shard_map(check_vma=True), the default): the final psum's
    # transpose is then the correct pbroadcast.  Under check_vma=False
    # the backward pass mis-scales (measured) — hence the explicit
    # pvary marking on the carries and the injected microbatch.
    state0 = _to_varying(jnp.zeros_like(x_mbs[0]), axis)
    if _vma_state(state0, axis) == "off":
        warnings.warn(
            "pipeline_apply requires shard_map(check_vma=True): the "
            "replication checker is off in this trace, so gradients "
            "through the pipeline will be mis-scaled by the stage count",
            stacklevel=2,
        )
    outbuf0 = _to_varying(jnp.zeros_like(x_mbs), axis)
    (_, outbuf), _ = lax.scan(tick, (state0, outbuf0),
                              jnp.arange(ticks))
    # only the last rank banked outputs; replicate them
    return lax.psum(outbuf, axis)


def stack_stage_params(per_stage_params):
    """Stack S per-stage pytrees on a new leading axis (shard it over the
    pp axis; each rank then indexes ``[0]`` to get its stage)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *per_stage_params
    )
