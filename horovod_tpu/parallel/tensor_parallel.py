"""Tensor (model) parallelism, GSPMD-style: shard the parameters, let
XLA insert the collectives.

The reference implements data parallelism only (SURVEY §2.6: TP "absent;
not required for parity") — this module is the TPU build's beyond-parity
model-parallel layer, done the way the hardware wants it: a STANDARD
dense model + sharding annotations.  Under ``jit`` over a (dp, tp) mesh,
a kernel sharded ``P(None, "tp")`` makes the activation tp-sharded
(column parallel, no communication), the next kernel sharded
``P("tp", None)`` contracts over the sharded dimension and XLA inserts
exactly one ``psum`` over tp (row parallel) — Megatron's f/g operators,
derived by the partitioner, with gradients correct by construction (no
hand-written transpose rules, unlike a shard_map formulation where the
psum transpose depends on replication checking).

Usage::

    mesh = Mesh(devices.reshape(dp, tp), ("dp", "tp"))
    params = model.init(...)                       # plain flax MLP/GPT
    params = shard_tp_params(params, mesh, rules=TP_MLP_RULES)
    step = jax.jit(train_step, ...)                # nothing TP-specific
    # batch sharded P("dp"); XLA partitions compute + grads

``TP_MLP_RULES`` maps parameter path suffixes to PartitionSpecs; extend
with your model's layer names (attention qkv → column, out-proj → row).

Verification: this island is deliberately INVISIBLE to the schedule
model checker — GSPMD derives the tp collectives inside the partitioner,
so there is no ``lax.psum`` in this source for ``hvd_verify`` to lower
(its ``axis:`` group coverage sees explicit collectives only).  That is
a feature, not a gap: per-rank schedule divergence cannot be authored
here because XLA emits one identical program for every mesh member.
The runtime sanitizer likewise only guards the eager control plane, not
the compiled step.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ParallelMLP(nn.Module):
    """A plain two-layer MLP whose parameter NAMES match
    :data:`TP_MLP_RULES` — the TP behavior comes entirely from the
    sharding annotations applied by :func:`shard_tp_params`."""

    hidden: int
    out: int
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    activation: Callable = nn.gelu

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.hidden, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="up")(x)
        h = self.activation(h)
        return nn.Dense(self.out, dtype=self.dtype,
                        param_dtype=self.param_dtype, name="down")(h)


# path-suffix -> spec builder (axis name substituted in)
TP_MLP_RULES = {
    "up/kernel": lambda tp: P(None, tp),      # column parallel
    "up/bias": lambda tp: P(tp),              # follows the output shard
    "down/kernel": lambda tp: P(tp, None),    # row parallel (psum here)
    "down/bias": lambda tp: P(),              # replicated, post-reduction
}

# attention projections follow the same pattern: qkv fused or per-head
# kernels are column parallel over heads, the output projection is row
# parallel.  DenseGeneral kernels are [d, heads, head_dim] / [heads,
# head_dim, d], so the head axis is the tp-sharded one.
TP_ATTENTION_RULES = {
    "query/kernel": lambda tp: P(None, tp, None),
    "key/kernel": lambda tp: P(None, tp, None),
    "value/kernel": lambda tp: P(None, tp, None),
    "query/bias": lambda tp: P(tp, None),
    "key/bias": lambda tp: P(tp, None),
    "value/bias": lambda tp: P(tp, None),
    "out/kernel": lambda tp: P(tp, None, None),
    "out/bias": lambda tp: P(),
}


def _path_name(path) -> str:
    return "/".join(
        getattr(p, "key", getattr(p, "name", str(p))) for p in path
    )


def shard_tp_params(params, mesh: Mesh, *, rules: Dict[str, Callable],
                    axis: str = "tp", default: Optional[P] = None):
    """device_put every parameter with its TP sharding.

    ``rules``: path-suffix -> (axis_name -> PartitionSpec).  Leaves with
    no matching rule get ``default`` (replicated if None).  Returns the
    sharded pytree; run the training step under plain ``jax.jit`` — the
    partitioner propagates these shardings through the graph."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    out = []
    for path, leaf in flat:
        name = _path_name(path)
        spec = None
        for suffix, builder in rules.items():
            if name.endswith(suffix):
                spec = builder(axis)
                break
        if spec is None:
            spec = default if default is not None else P()
        out.append(jax.device_put(leaf, NamedSharding(mesh, spec)))
    return jax.tree_util.tree_unflatten(treedef, out)


def tp_constraint(x, mesh: Mesh, spec: P):
    """``with_sharding_constraint`` under an explicit mesh — pin an
    activation's layout at a TP boundary when the partitioner needs the
    hint (e.g. force the MLP output replicated before a residual add)."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
