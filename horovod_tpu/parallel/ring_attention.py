"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no model or attention code (SURVEY §5: "long-context /
sequence parallelism: absent"), but its communication shapes are exactly
what ring attention is built on — the reduce-scatter/allgather
decomposition of hierarchical allreduce (reference
nccl_operations.cc:241-246) and Adasum's distance-doubling exchanges
(adasum/adasum.h:167-195).  This module adds the long-context layer the
TPU build treats as first-class, on the same collective backend:

* :func:`ring_attention` — blockwise attention with the K/V shards rotating
  around the ring via ``lax.ppermute`` (one hop per step, rides ICI
  neighbor links), accumulating with an online-softmax (the
  numerically-stable streaming form), so sequence length scales linearly
  with rank count while activation memory stays per-shard.  Causal masking
  is applied from global block positions.
* :func:`ulysses_attention` — the all-to-all alternative: switch from
  sequence-sharded to head-sharded with one ``all_to_all``, run full local
  attention per head group, and switch back.  Cheaper for moderate
  sequence lengths when head count ≥ ranks.

Both run inside ``hvd.spmd`` regions on the flat mesh axis and compose
with the data-parallel dimension by using a 2-D (dp, sp) mesh.

Verification: every K/V rotation ``ppermute`` is a SendRecv event in
the schedule checker (HVD013) and the Ulysses ``all_to_all`` a
collective under the ``axis:<name>`` group; the rotations run
unconditionally on every ring member each scan step, which is exactly
what keeps repo self-verify finding-free here.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import core
from ..ops import flash_attention as fa


def _axis(axis=None):
    """Resolve the sequence-parallel mesh axis.  ``axis`` explicit wins —
    that is how SP composes with DP on a 2-D (dp, sp) mesh: shard the
    batch over dp, the sequence over sp, and pass ``axis="sp"`` here.
    Default: the framework's single SPMD rank axis."""
    if axis is not None:
        return axis
    axes = core._spmd_axes()
    if axes is None:
        raise RuntimeError("ring attention must run inside an SPMD region")
    if len(axes) != 1:
        raise NotImplementedError(
            "pass axis= to pick the sequence axis of a multi-axis mesh"
        )
    return axes[0]


def _block_attn(q, k, v, *, scale, mask=None):
    """One q-block × kv-block partial attention, returning the streaming
    triple (unnormalized out, row max, row sumexp) in f32."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)                       # [b,h,q]
    # guard fully-masked rows (all -inf)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1)                       # [b,h,q]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(jnp.float32)
    return o, m_safe, l


def _merge(o1, m1, l1, o2, m2, l2):
    """Merge two streaming-softmax partials (flash-attention combine)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    l = l1 * a1 + l2 * a2
    # broadcast [b,h,q] → [b,q,h,1]
    b1 = jnp.transpose(a1, (0, 2, 1))[..., None]
    b2 = jnp.transpose(a2, (0, 2, 1))[..., None]
    o = o1 * b1 + o2 * b2
    return o, m, l


def ring_attention(q, k, v, *, causal: bool = False,
                   scale: Optional[float] = None,
                   impl: str = "xla",
                   block_q: int = fa.DEFAULT_BLOCK_Q,
                   block_k: int = fa.DEFAULT_BLOCK_K,
                   interpret: Optional[bool] = None,
                   axis: Optional[str] = None):
    """Attention over a sequence sharded across ranks.

    Args:
      q, k, v: per-rank shards ``[batch, seq_local, heads, head_dim]``;
        global sequence = ``seq_local * axis_size``, shard r owns
        positions ``[r*seq_local, (r+1)*seq_local)``.
      causal: apply causal masking in *global* positions.
      scale: logit scale; default ``1/sqrt(head_dim)``.
      impl: ``"xla"`` (lax einsums, XLA fuses) or ``"pallas"`` (flash
        kernels on the MXU per hop, custom VJP rotating gradients around
        the ring; see :mod:`horovod_tpu.ops.flash_attention`).
      axis: sequence mesh axis; default = the global rank axis.  Pass
        the sp axis name to compose with data parallelism on a 2-D
        (dp, sp) mesh.

    Returns the attention output for the local q shard, same shape/dtype
    as ``q``.
    """
    if impl == "pallas":
        axis = _axis(axis)
        if scale is None:
            scale = 1.0 / float(np.sqrt(q.shape[-1]))
        fn = _ring_pallas_fn(
            axis, lax.axis_size(axis), bool(causal), float(scale),
            int(block_q), int(block_k), fa._resolve_interpret(interpret),
        )
        out = fn(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                 jnp.swapaxes(v, 1, 2))
        return jnp.swapaxes(out, 1, 2)
    if impl != "xla":
        raise ValueError(f"unknown impl {impl!r} (want 'xla' or 'pallas')")
    axis = _axis(axis)
    n = lax.axis_size(axis)
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    seq_local = q.shape[1]
    my = lax.axis_index(axis)

    # neighbor ring: step s receives the kv block originally on rank
    # (my - 1 - ...) — we rotate kv by one hop each iteration.
    perm = [(i, (i + 1) % n) for i in range(n)]

    def causal_mask(kv_owner):
        if not causal:
            return None
        q_pos = my * seq_local + jnp.arange(seq_local)          # [q]
        k_pos = kv_owner * seq_local + jnp.arange(seq_local)    # [k]
        return (q_pos[:, None] >= k_pos[None, :])[None, None]   # [1,1,q,k]

    def body(carry, _):
        o, m, l, kc, vc, owner = carry
        po, pm, pl = _block_attn(q, kc, vc, scale=scale,
                                 mask=causal_mask(owner))
        o, m, l = _merge(o, m, l, po, pm, pl)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        owner = (owner - 1) % n
        return (o, m, l, kc, vc, owner), None

    o0 = jnp.zeros(q.shape[:1] + q.shape[1:], jnp.float32)
    m0 = jnp.full((q.shape[0], q.shape[2], seq_local), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((q.shape[0], q.shape[2], seq_local), jnp.float32)

    (o, m, l, _, _, _), _ = lax.scan(
        body, (o0, m0, l0, k, v, my), None, length=n
    )
    denom = jnp.transpose(l, (0, 2, 1))[..., None]
    return (o / jnp.maximum(denom, 1e-30)).astype(q.dtype)


# ---------------------------------------------------------------------------
# pallas ring: flash kernels per hop, gradients rotate with their kv shards
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _ring_pallas_fn(axis, n, causal, scale, block_q, block_k, interpret):
    """Differentiable ring attention in ``[b,h,s,d]`` layout.

    Forward: scan ``n`` hops; each hop runs the Pallas partial kernel on the
    resident kv shard (global-position causal offsets), merges the streaming
    triple, and rotates kv one neighbor over ICI.  Backward: a second ring
    pass where dk/dv accumulators travel *with* their kv shards, so after n
    hops each rank holds exactly the gradient of its own shard.
    """
    kw = dict(causal=causal, scale=scale, block_q=block_q, block_k=block_k,
              interpret=interpret)
    perm = tuple((i, (i + 1) % n) for i in range(n))

    def fwd_scan(q, k, v):
        b, h, seq, d = q.shape
        my = lax.axis_index(axis)

        def body(carry, _):
            o, m, l, kc, vc, owner = carry
            po, pm, plv = fa.mha_partial(q, kc, vc, my * seq, owner * seq,
                                         **kw)
            m_new = jnp.maximum(m, pm)
            a1 = jnp.exp(m - m_new)
            a2 = jnp.exp(pm - m_new)
            o = o * a1 + po * a2
            l = l * a1 + plv * a2
            kc = lax.ppermute(kc, axis, perm)
            vc = lax.ppermute(vc, axis, perm)
            return (o, m_new, l, kc, vc, (owner - 1) % n), None

        o0 = jnp.zeros((b, h, seq, d), jnp.float32)
        m0 = jnp.full((b, h, seq, 1), fa.NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, seq, 1), jnp.float32)
        (o, m, l, _, _, _), _ = lax.scan(
            body, (o0, m0, l0, k, v, my), None, length=n
        )
        l_safe = jnp.maximum(l, 1e-30)
        out = (o / l_safe).astype(q.dtype)
        lse = m + jnp.log(l_safe)
        return out, lse

    @jax.custom_vjp
    def f(q, k, v):
        out, _ = fwd_scan(q, k, v)
        return out

    def fwd(q, k, v):
        out, lse = fwd_scan(q, k, v)
        return out, (q, k, v, out, lse)

    def bwd(res, do):
        q, k, v, out, lse = res
        seq = q.shape[2]
        my = lax.axis_index(axis)
        delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1, keepdims=True)

        def body(carry, _):
            dq, kc, vc, dkc, dvc, owner = carry
            q_off = my * seq
            kv_off = owner * seq
            dq = dq + fa.mha_bwd_dq(q, kc, vc, do, lse, delta, q_off,
                                    kv_off, **kw)
            dkb, dvb = fa.mha_bwd_dkv(q, kc, vc, do, lse, delta, q_off,
                                      kv_off, **kw)
            dkc = dkc + dkb
            dvc = dvc + dvb
            kc = lax.ppermute(kc, axis, perm)
            vc = lax.ppermute(vc, axis, perm)
            dkc = lax.ppermute(dkc, axis, perm)
            dvc = lax.ppermute(dvc, axis, perm)
            return (dq, kc, vc, dkc, dvc, (owner - 1) % n), None

        (dq, _, _, dk, dv, _), _ = lax.scan(
            body,
            (jnp.zeros(q.shape, jnp.float32), k, v,
             jnp.zeros(k.shape, jnp.float32),
             jnp.zeros(v.shape, jnp.float32), my),
            None, length=n,
        )
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    f.defvjp(fwd, bwd)
    return f


def ulysses_attention(q, k, v, *, causal: bool = False,
                      scale: Optional[float] = None,
                      impl: str = "xla",
                      block_q: int = fa.DEFAULT_BLOCK_Q,
                      block_k: int = fa.DEFAULT_BLOCK_K,
                      interpret: Optional[bool] = None,
                      axis: Optional[str] = None):
    """All-to-all ("Ulysses") sequence parallelism.

    Per-rank inputs ``[batch, seq_local, heads, head_dim]`` with
    ``heads % axis_size == 0``: one all_to_all reshards to
    ``[batch, seq_global, heads/axis_size, head_dim]``, full attention
    runs locally on the head subset, and a second all_to_all restores
    sequence sharding.  ``axis``: as in :func:`ring_attention` — pass the
    sp axis of a (dp, sp) mesh to compose with data parallelism.
    """
    axis = _axis(axis)
    n = lax.axis_size(axis)
    b, s_local, h, d = q.shape
    if h % n:
        raise ValueError(f"heads {h} not divisible by ranks {n}")
    scale = scale if scale is not None else 1.0 / np.sqrt(d)

    def to_heads(x):
        # split heads across ranks, gather sequence
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_seq(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)   # [b, s_g, h/n, d]
    sg = qh.shape[1]
    if impl == "pallas":
        oh = fa.flash_attention(qh, kh, vh, causal=causal, scale=scale,
                                block_q=block_q, block_k=block_k,
                                interpret=interpret)
    elif impl == "xla":
        oh = fa.softmax_attention(qh, kh, vh, causal=causal, scale=scale)
    else:
        raise ValueError(f"unknown impl {impl!r} (want 'xla' or 'pallas')")
    return to_seq(oh).astype(q.dtype)
