"""Expert parallelism: mixture-of-experts with all-to-all token dispatch.

The reference implements data parallelism only (SURVEY §2.6: EP
"absent") — this is the last letter of the TPU build's parallelism layer
(dp / tp / sp / pp / ep), in the GShard/Mesh-TensorFlow formulation that
XLA compiles well: static capacity-bounded dispatch tensors (no
data-dependent shapes), einsum dispatch/combine, and ONE ``all_to_all``
each way over the ``ep`` mesh axis to move token buffers between the
ranks that hold the tokens and the ranks that hold the experts.

Layout (inside a shard_map over ``axis``): each rank holds ``n_local``
tokens and ``experts_per_rank`` experts; E = ep_size *
experts_per_rank.  Top-1 routing with per-expert capacity C — tokens
beyond capacity are dropped (standard GShard semantics; size C
generously for tests).

Verification: the dispatch/combine ``all_to_all`` pair is modelled by
the schedule checker under the ``axis:<ep>`` group; the untiled
split-axis-0 contract (leading dispatch dimension == ep axis size) is
HVD015's axis-shape check — a literal capacity reshape that contradicts
a literal mesh declaration is flagged statically.  This module's
dispatch tensors are shaped by the symbolic axis size, so the contract
holds by construction.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def top1_dispatch(gates: jnp.ndarray, capacity: int):
    """Build static dispatch/combine tensors from router probabilities.

    Args:
      gates: ``[n, E]`` router probabilities (softmax output).
      capacity: per-expert buffer size C.

    Returns ``(dispatch [n, E, C] bool-ish f32, combine [n, E, C] f32)``:
    token t goes to slot ``position(t)`` of its argmax expert unless the
    expert is over capacity; combine carries the gate probability.
    """
    n, e = gates.shape
    expert = jnp.argmax(gates, axis=-1)                     # [n]
    # Buffer positions are computed in int32: a low-precision cumsum
    # (e.g. bf16 gates) saturates at 256 tokens and collides slots.
    onehot_i = jax.nn.one_hot(expert, e, dtype=jnp.int32)   # [n, E]
    pos = (jnp.cumsum(onehot_i, axis=0) - onehot_i) * onehot_i  # [n, E]
    pos = jnp.sum(pos, axis=-1)                             # [n] int32
    keep = pos < capacity
    onehot = onehot_i.astype(gates.dtype)                   # [n, E]
    gate = jnp.max(gates * onehot, axis=-1) * keep          # [n]
    pos_oh = jax.nn.one_hot(pos, capacity, dtype=gates.dtype)  # [n, C]
    dispatch = onehot[:, :, None] * pos_oh[:, None, :] * keep[:, None, None]
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def moe_apply(expert_fn: Callable, expert_params, x, router_kernel, *,
              capacity: int, axis: str = "ep"):
    """One EP MoE layer inside a shard_map over ``axis``.

    Args:
      expert_fn: ``(params_for_one_expert, tokens [m, d]) -> [m, d]``.
      expert_params: THIS rank's experts, stacked ``[experts_per_rank,
        ...]`` (vmapped over).
      x: this rank's tokens ``[n_local, d]``.
      router_kernel: ``[d, E]`` routing weights (replicated; E = ep *
        experts_per_rank).
      capacity: per-expert, per-source-rank buffer size.

    Returns ``[n_local, d]`` with each token's expert output weighted by
    its gate (dropped tokens contribute zero, as in GShard top-1).
    """
    ep = lax.axis_size(axis)
    _, d = x.shape
    e = router_kernel.shape[-1]
    if e % ep:
        raise ValueError(f"experts {e} not divisible by ep={ep}")
    per_rank = e // ep

    gates = jax.nn.softmax(
        (x.astype(jnp.float32) @ router_kernel.astype(jnp.float32)), axis=-1
    ).astype(x.dtype)
    dispatch, combine = top1_dispatch(gates, capacity)

    # gather token buffers per expert: [E, C, d]
    expert_in = jnp.einsum("nd,nec->ecd", x, dispatch)
    # reshape to [ep, per_rank, C, d] and all_to_all the ep dim: after
    # the exchange this rank holds, for ITS experts, every source rank's
    # buffers: [ep(src), per_rank, C, d]
    expert_in = expert_in.reshape(ep, per_rank, capacity, d)
    expert_in = lax.all_to_all(expert_in, axis, split_axis=0,
                               concat_axis=0, tiled=False)
    # run this rank's experts on [src*ep buffers x C] tokens each
    flat = jnp.moveaxis(expert_in, 1, 0).reshape(
        per_rank, ep * capacity, d
    )
    out = jax.vmap(expert_fn)(expert_params, flat)     # [per_rank, ep*C, d]
    out = jnp.moveaxis(
        out.reshape(per_rank, ep, capacity, d), 0, 1
    )                                                  # [ep, per_rank, C, d]
    # route back: inverse all_to_all
    out = lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                         tiled=False)
    out = out.reshape(e, capacity, d)
    # combine on the token side
    return jnp.einsum("ecd,nec->nd", out, combine.astype(out.dtype))
