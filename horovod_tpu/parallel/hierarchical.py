"""Hierarchical (two-level) collectives: local stage + cross stage.

Re-design of NCCLHierarchicalAllreduce (reference
horovod/common/ops/nccl_operations.cc:171-372: NCCL ReduceScatter inside the
node → per-local-rank parallel cross-node MPI_Allreduce on a host buffer →
NCCL Allgather back, remainder handled via NCCL Reduce/Bcast) and
MPIHierarchicalAllgather (mpi_operations.cc), built on the LOCAL/CROSS
communicator split (common.h:110-114).

TPU mapping: "local" = devices connected by ICI within a slice, "cross" =
slices connected by DCN.  The same reduce_scatter → cross-allreduce →
all_gather decomposition applies, with ``axis_index_groups`` on the flat
mesh (so it composes with the 1-D rank model) — each cross-stage psum moves
1/local_size of the data, and the local stages ride ICI.

Enabled per-call or via ``HVD_HIERARCHICAL_ALLREDUCE=1`` (reference knob
HOROVOD_HIERARCHICAL_ALLREDUCE, common.h:72; autotuned by
parameter_manager.cc — ours is a candidate knob in optim/autotune.py).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .. import core
from ..core import Average, Sum
from ..utils import env as env_util


def _local_groups() -> list:
    ls = core.local_size()
    return [list(range(n * ls, (n + 1) * ls)) for n in range(core.cross_size())]


def _cross_groups_for_chunk() -> list:
    ls = core.local_size()
    return [
        [n * ls + r for n in range(core.cross_size())] for r in range(ls)
    ]


def hierarchical_allreduce(tensor, *, op: str = Average):
    """Two-level allreduce on the flat 1-D mesh.

    reduce_scatter over the local group (ICI) → psum over the cross group
    (DCN) on the 1/local_size shard → all_gather over the local group —
    exactly the reference's three phases (nccl_operations.cc:241-287), but
    the "host buffer" hop disappears: the cross psum runs device-to-device.
    """
    axes = core._spmd_axes()
    if axes is None or len(axes) != 1:
        raise RuntimeError(
            "hierarchical_allreduce runs on the flat mesh inside hvd.spmd"
        )
    axis = axes[0]
    if op == core.Adasum:
        from ..ops.adasum import adasum_allreduce

        return adasum_allreduce(tensor, hierarchical=True)
    ls = core.local_size()
    if ls == 1 or core.cross_size() == 1:
        out = lax.psum(tensor, axis)
        return out / core.size() if op == Average else out

    orig_shape = tensor.shape
    flat = tensor.reshape(-1)
    # Pad to a multiple of local_size so the scatter is even — the analog of
    # the fusion-threshold divisibility rounding (reference
    # controller.cc:357-375).
    n = flat.shape[0]
    pad = (-n) % ls
    if pad:
        flat = jnp.pad(flat, (0, pad))

    shard = lax.psum_scatter(
        flat, axis, scatter_dimension=0, tiled=True,
        axis_index_groups=_local_groups(),
    )
    shard = lax.psum(shard, axis, axis_index_groups=_cross_groups_for_chunk())
    full = lax.all_gather(
        shard, axis, axis=0, tiled=True, axis_index_groups=_local_groups()
    )
    if pad:
        full = full[:n]
    out = full.reshape(orig_shape)
    if op == Average:
        out = out / core.size()
    return out


def hierarchical_allgather(tensor):
    """Two-level allgather: gather inside the local group, then exchange the
    node blocks across (reference MPIHierarchicalAllgather,
    mpi_operations.cc — node-leader gather through an MPI shared-memory
    window + cross allgather; here both stages are XLA all_gathers)."""
    axes = core._spmd_axes()
    if axes is None or len(axes) != 1:
        raise RuntimeError(
            "hierarchical_allgather runs on the flat mesh inside hvd.spmd"
        )
    axis = axes[0]
    local = lax.all_gather(
        tensor, axis, axis=0, tiled=True, axis_index_groups=_local_groups()
    )
    # Every local rank now holds the node block; one cross-group allgather
    # (per local rank, in parallel) assembles the global concatenation.
    out = lax.all_gather(
        local, axis, axis=0, tiled=True,
        axis_index_groups=_cross_groups_for_chunk(),
    )
    return out


def use_hierarchical_default() -> bool:
    return env_util.get_bool(env_util.HVD_HIERARCHICAL_ALLREDUCE, False)
