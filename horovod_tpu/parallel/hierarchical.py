"""Hierarchical (two-level) collectives: local stage + cross stage.

Re-design of NCCLHierarchicalAllreduce (reference
horovod/common/ops/nccl_operations.cc:171-372: NCCL ReduceScatter inside the
node → per-local-rank parallel cross-node MPI_Allreduce on a host buffer →
NCCL Allgather back, remainder handled via NCCL Reduce/Bcast) and
MPIHierarchicalAllgather (mpi_operations.cc), built on the LOCAL/CROSS
communicator split (common.h:110-114).

TPU mapping: "local" = devices connected by ICI within a slice, "cross" =
slices connected by DCN.  The same reduce_scatter → cross-allreduce →
all_gather decomposition applies, with ``axis_index_groups`` on the flat
mesh (so it composes with the 1-D rank model) — each cross-stage psum moves
1/local_size of the data, and the local stages ride ICI.

Enabled per-call or via ``HVD_HIERARCHICAL_ALLREDUCE=1`` (reference knob
HOROVOD_HIERARCHICAL_ALLREDUCE, common.h:72; autotuned by
parameter_manager.cc — ours is a candidate knob in optim/autotune.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax.numpy as jnp
from jax import lax

from .. import core
from ..core import Average, Sum
from ..ops.compression import Compression, ErrorFeedback, _compressible
from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)


def _local_groups() -> list:
    ls = core.local_size()
    return [list(range(n * ls, (n + 1) * ls)) for n in range(core.cross_size())]


def _cross_groups_for_chunk() -> list:
    ls = core.local_size()
    return [
        [n * ls + r for n in range(core.cross_size())] for r in range(ls)
    ]


# ---------------------------------------------------------------------------
# group identity surfaced to dispatch (the sanitizer/model-checker seam)
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class DispatchStage:
    """One stage of a hierarchical dispatch, as the group/epoch-aware
    sanitizer fingerprints it (analysis/sanitizer.py): the op kind, the
    communication-group label, and the group's member ranks."""

    op: str
    group: str
    peers: Tuple[int, ...]


def process_group_members(rank: int, size: int,
                          local_size: int) -> Tuple[Tuple[int, ...],
                                                    Tuple[int, ...]]:
    """(local members, cross members) of ``rank`` on the flat 1-D rank
    line — the pure topology shared by the device-plane groups above and
    the process-plane sanitizer stage plan below."""
    node, chunk = divmod(rank, local_size)
    local = tuple(range(node * local_size, (node + 1) * local_size))
    cross = tuple(n * local_size + chunk
                  for n in range(size // local_size))
    return local, cross


def process_stage_plan(op: str = "allreduce", *,
                       rank: Optional[int] = None,
                       size: Optional[int] = None,
                       local_size: Optional[int] = None
                       ) -> Optional[List[DispatchStage]]:
    """The per-group dispatch sequence a two-level collective issues on
    ``rank``, over *controller processes* — what the sanitizer must
    fingerprint so the intra-host and cross-host stages check against
    their own groups instead of the flat world.  None when the process
    topology is trivial (single host, single process per host, or an
    uneven split): the dispatch is then one flat-world collective."""
    if rank is None:
        rank = core.process_rank()
    if size is None:
        size = core.process_size()
    if local_size is None:
        local_size = env_util.get_int(env_util.HVD_LOCAL_SIZE, 0) or 1
    if size <= 1 or local_size <= 1 or local_size >= size \
            or size % local_size:
        return None
    local, cross = process_group_members(rank, size, local_size)
    node, chunk = divmod(rank, local_size)
    return [
        DispatchStage("reducescatter", f"local:{node}", local),
        DispatchStage(op, f"cross:{chunk}", cross),
        DispatchStage("allgather", f"local:{node}", local),
    ]


def hierarchical_allreduce(tensor, *, op: str = Average):
    """Two-level allreduce on the flat 1-D mesh.

    reduce_scatter over the local group (ICI) → psum over the cross group
    (DCN) on the 1/local_size shard → all_gather over the local group —
    exactly the reference's three phases (nccl_operations.cc:241-287), but
    the "host buffer" hop disappears: the cross psum runs device-to-device.
    """
    axes = core._spmd_axes()
    if axes is None or len(axes) != 1:
        raise RuntimeError(
            "hierarchical_allreduce runs on the flat mesh inside hvd.spmd"
        )
    axis = axes[0]
    if op == core.Adasum:
        from ..ops.adasum import adasum_allreduce

        return adasum_allreduce(tensor, hierarchical=True)
    ls = core.local_size()
    if ls == 1 or core.cross_size() == 1:
        out = lax.psum(tensor, axis)
        return out / core.size() if op == Average else out

    orig_shape = tensor.shape
    flat = tensor.reshape(-1)
    # Pad to a multiple of local_size so the scatter is even — the analog of
    # the fusion-threshold divisibility rounding (reference
    # controller.cc:357-375).
    n = flat.shape[0]
    pad = (-n) % ls
    if pad:
        flat = jnp.pad(flat, (0, pad))

    _record_stage_inventory(flat)
    shard = lax.psum_scatter(
        flat, axis, scatter_dimension=0, tiled=True,
        axis_index_groups=_local_groups(),
    )
    shard = lax.psum(shard, axis, axis_index_groups=_cross_groups_for_chunk())
    full = lax.all_gather(
        shard, axis, axis=0, tiled=True, axis_index_groups=_local_groups()
    )
    if pad:
        full = full[:n]
    out = full.reshape(orig_shape)
    if op == Average:
        out = out / core.size()
    return out


def _record_stage_inventory(flat) -> None:
    """Group-labelled traced inventory for the three hierarchical stages
    (runs at trace time, once per compile).  Labels are the group
    *families* (``local`` / ``cross``) — the same vocabulary hvd_verify
    projects statically; the sanitizer's runtime fingerprints key the
    concrete instances (``local:<node>``, ``cross:<chunk>``,
    process_stage_plan).  The user-facing ``allreduce`` dispatch itself
    is already counted once by collectives.allreduce — these ride the
    separate ``hvd_collectives_traced_group_total`` counter only."""
    try:
        from .. import metrics as _metrics

        _metrics.record_traced_group("reducescatter", "local")
        _metrics.record_traced_group("allreduce", "cross")
        _metrics.record_traced_group("allgather", "local")
    except Exception:  # noqa: BLE001 — accounting never breaks tracing
        pass


def _count_two_level_fallback(reason: str) -> None:
    """Bump ``hvd_two_level_fallbacks_total`` and warn.  Runs at trace
    time (topology is static under jit), so the counter counts fallback
    *decisions* — once per compiled program, not per step."""
    log.warning(
        "two_level_allreduce falling back to flat allreduce: %s", reason)
    try:
        from .. import metrics

        if metrics.on():
            metrics.TWO_LEVEL_FALLBACKS.inc()
    except Exception:  # noqa: BLE001 — accounting never breaks the step
        pass


def two_level_allreduce(tensor, *, op: str = Average,
                        compression=Compression.none):
    """Two-level allreduce with the compressed payload on the cross
    (DCN) stage — the unification of ``hierarchical_allreduce`` with
    the compression tier (docs/compression.md):

    1. **local reduce-scatter** over the ICI group at full precision
       (ICI bandwidth is ~an order cheaper than DCN; quantizing here
       would spend accuracy where bytes are cheap);
    2. **cross allreduce** on the 1/local_size shard, quantized with
       ``compression`` (headroom for ``cross_size`` summands —
       ops/compression.py) — this is the stage whose bytes dominate at
       scale, and exactly where the 4–8× payload cut lands;
    3. **local all-gather** of the dequantized shard.

    Degrades to a FLAT (single-level, still compressed) allreduce
    instead of raising mid-step when the topology can't support the
    decomposition — trivial local/cross groups, or a non-power-of-two
    cross-host group (the constraint this path shares with Adasum's
    VHDD pairing, ops/adasum.py ``_check_cross_pow2``: the autotuner
    flips ops freely between the two, so both must accept the same
    worlds).  Fallbacks bump ``hvd_two_level_fallbacks_total``.

    :class:`ErrorFeedback` compression degrades to its inner stateless
    compressor here: the residual pytree is full-tensor-shaped while
    the quantization error lives on the 1/local_size shard; the local
    stages being exact keeps the uncompensated error at 1/local_size
    of the flat path's.
    """
    axes = core._spmd_axes()
    if axes is None or len(axes) != 1:
        raise RuntimeError(
            "two_level_allreduce runs on the flat mesh inside hvd.spmd"
        )
    axis = axes[0]
    if op == core.Adasum:
        from ..ops.adasum import adasum_allreduce

        return adasum_allreduce(tensor, hierarchical=True)
    if op not in (Average, Sum):
        raise ValueError("two_level_allreduce supports Sum/Average/Adasum")
    if isinstance(compression, ErrorFeedback):
        compression = compression.compressor
    ls = core.local_size()
    cs = core.cross_size()

    def _flat():
        c, ctx = compression.compress_for(tensor, core.size()) \
            if hasattr(compression, "compress_for") \
            else compression.compress(tensor)
        out = lax.psum(c, axis)
        if op == Average:
            out = out / core.size()
        return compression.decompress(out, ctx)

    if ls == 1 or cs == 1:
        # trivial decomposition: all wire is one level anyway
        _count_two_level_fallback(
            f"trivial topology (local_size={ls}, cross_size={cs})")
        return _flat()
    if cs & (cs - 1):
        _count_two_level_fallback(
            f"cross-host group of {cs} is not a power of two")
        return _flat()
    if not _compressible(tensor):
        # int/bool/complex payloads ride the uncompressed two-level shape
        return hierarchical_allreduce(tensor, op=op)

    orig_shape = tensor.shape
    flat = tensor.reshape(-1)
    n = flat.shape[0]
    pad = (-n) % ls
    if pad:
        flat = jnp.pad(flat, (0, pad))

    _record_stage_inventory(flat)
    shard = lax.psum_scatter(
        flat, axis, scatter_dimension=0, tiled=True,
        axis_index_groups=_local_groups(),
    )
    c, ctx = compression.compress_for(shard, cs) \
        if hasattr(compression, "compress_for") \
        else compression.compress(shard)
    red = lax.psum(c, axis, axis_index_groups=_cross_groups_for_chunk())
    shard = compression.decompress(red, ctx)
    full = lax.all_gather(
        shard, axis, axis=0, tiled=True, axis_index_groups=_local_groups()
    )
    if pad:
        full = full[:n]
    out = full.reshape(orig_shape)
    if op == Average:
        out = out / core.size()
    return out


def use_two_level_default() -> bool:
    return env_util.get_bool(env_util.HVD_TWO_LEVEL_ALLREDUCE, False)


def hierarchical_allgather(tensor):
    """Two-level allgather: gather inside the local group, then exchange the
    node blocks across (reference MPIHierarchicalAllgather,
    mpi_operations.cc — node-leader gather through an MPI shared-memory
    window + cross allgather; here both stages are XLA all_gathers)."""
    axes = core._spmd_axes()
    if axes is None or len(axes) != 1:
        raise RuntimeError(
            "hierarchical_allgather runs on the flat mesh inside hvd.spmd"
        )
    axis = axes[0]
    local = lax.all_gather(
        tensor, axis, axis=0, tiled=True, axis_index_groups=_local_groups()
    )
    # Every local rank now holds the node block; one cross-group allgather
    # (per local rank, in parallel) assembles the global concatenation.
    out = lax.all_gather(
        local, axis, axis=0, tiled=True,
        axis_index_groups=_cross_groups_for_chunk(),
    )
    return out


def use_hierarchical_default() -> bool:
    return env_util.get_bool(env_util.HVD_HIERARCHICAL_ALLREDUCE, False)
