"""ResNet family (flax/linen), TPU-first.

The reference has no model code at all — its benchmarks instantiate Keras
``applications.ResNet50`` (reference
examples/tensorflow2_synthetic_benchmark.py:64) and tf_cnn_benchmarks
(docs/benchmarks.rst:15-63).  This module provides the equivalent model
family natively so the framework's headline benchmark (ResNet-50 synthetic,
BASELINE.md) is self-contained.

TPU-first choices:

* **NHWC** layouts and 3x3/1x1 convs that XLA tiles directly onto the MXU;
* **bf16 compute, f32 params** (``dtype``/``param_dtype`` split) — the MXU's
  native mixed precision, no loss scaling needed;
* BatchNorm statistics are per-replica (Horovod-style data parallelism does
  not sync BN; cross-replica stats would add per-step collectives);
* no Python control flow in the forward pass — fully unrollable for jit.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import jax.numpy as jnp
from flax import linen as nn
from jax import lax

ModuleDef = Any


class SpaceToDepthConvInit(nn.Module):
    """The ResNet stem (7x7 stride-2 conv) computed as a 4x4 stride-1
    conv on space-to-depth-transformed input — mathematically identical
    output, but the MXU sees 12 input channels instead of 3 and no
    stride (the MLPerf TPU ResNet trick).  Holds the SAME (7,7,Cin,F)
    kernel parameter as the plain conv, so checkpoints interchange;
    the 4x4x(4Cin) kernel is derived in-graph (tiny, XLA folds it)."""

    features: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        if h % 2 or w % 2:
            raise ValueError(
                f"space_to_depth stem needs even spatial dims, got "
                f"{(h, w)}; use stem='conv' for odd input sizes"
            )
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (7, 7, c, self.features), self.param_dtype,
        ).astype(self.dtype)
        x = x.astype(self.dtype)
        # space-to-depth(2): y[p,q,(a,b,ch)] = x[2p+a, 2q+b, ch]
        y = x.reshape(b, h // 2, 2, w // 2, 2, c) \
             .transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)
        # out(i,j) = sum_{u,v} x[u,v] K[u-2i+3, v-2j+3]; with u=2p+a the
        # kernel index is 2(p-i)+a+3 = 2P+a-1 for P=p-i+2 in [0,4) — pad
        # one leading zero row/col so it becomes K8[2P+a, 2Q+b]
        k8 = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))
        kp = k8.reshape(4, 2, 4, 2, c, self.features) \
               .transpose(0, 2, 1, 3, 4, 5) \
               .reshape(4, 4, 4 * c, self.features)
        return lax.conv_general_dilated(
            y, kp, (1, 1), [(2, 1), (2, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )


class PallasConvBN3x3(nn.Module):
    """Fused stride-1 3x3 conv + BatchNorm + ReLU over the Pallas kernels
    (ops/conv_bn.py): train mode runs the conv+stats-epilogue kernel with
    the full-BN-backward custom VJP; eval mode runs the folded-affine
    kernel.  The round-4 conv+BN experiment module (docs/PERF.md) —
    selected by ``ResNet(conv_bn="pallas")``; its parameter layout is its
    own (kernel/scale/bias + batch_stats mean/var), so checkpoints do NOT
    interchange with the (Conv, BatchNorm) pair it replaces."""

    features: int
    train: bool
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x):
        from ..ops.conv_bn import conv3x3_bn_relu, conv3x3_bn_relu_train

        cin = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (3, 3, cin, self.features), self.param_dtype,
        )
        gamma = self.param("scale", nn.initializers.ones,
                           (self.features,), self.param_dtype)
        beta = self.param("bias", nn.initializers.zeros,
                          (self.features,), self.param_dtype)
        ra_mean = self.variable(
            "batch_stats", "mean",
            lambda: jnp.zeros((self.features,), jnp.float32))
        ra_var = self.variable(
            "batch_stats", "var",
            lambda: jnp.ones((self.features,), jnp.float32))
        k = kernel.astype(self.dtype)
        x = x.astype(self.dtype)
        if self.train:
            out, mean, var = conv3x3_bn_relu_train(
                x, k, gamma.astype(jnp.float32), beta.astype(jnp.float32),
                self.epsilon,
            )
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var
        else:
            scale = gamma * (lax.rsqrt(ra_var.value + self.epsilon))
            bias = beta - ra_mean.value * scale
            out = conv3x3_bn_relu(x, k, scale, bias)
        return out


class BatchNormReLU(nn.Module):
    """BatchNorm + ReLU with the elementwise apply fused into one Pallas
    pass (ops/elementwise.py ``scale_bias_relu``) — the compute tier's
    norm+activation join, selected by ``ResNet(norm_act="pallas")``.

    The per-channel statistics (a tiny reduction XLA handles well) and
    the folded ``scale``/``bias`` stay in jnp; the [B,H,W,C]-sized
    normalize+activate traffic — the HBM-bound part — runs as the single
    fused kernel.  Gradients flow through batch mean/var exactly like
    ``flax.linen.BatchNorm`` (the folded affine is a function of the
    batch stats, so autodiff chains the kernel's dscale/dbias back
    through them).  Parameter names inside the module mirror
    ``BatchNorm``'s (params scale/bias, batch_stats mean/var), but the
    module path differs — like ``conv_bn="pallas"``, checkpoints do NOT
    interchange with the pair it replaces."""

    use_running_average: bool
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x):
        from ..ops.elementwise import scale_bias_relu

        c = x.shape[-1]
        gamma = self.param("scale", nn.initializers.ones, (c,),
                           self.param_dtype)
        beta = self.param("bias", nn.initializers.zeros, (c,),
                          self.param_dtype)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((c,), jnp.float32))
        x = x.astype(self.dtype)
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32)
            axes = tuple(range(x.ndim - 1))
            mean = xf.mean(axis=axes)
            var = jnp.maximum(
                (xf * xf).mean(axis=axes) - mean * mean, 0.0)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * \
                    lax.stop_gradient(mean)
                ra_var.value = m * ra_var.value + (1 - m) * \
                    lax.stop_gradient(var)
        scale = gamma.astype(jnp.float32) * lax.rsqrt(var + self.epsilon)
        bias = beta.astype(jnp.float32) - mean * scale
        return scale_bias_relu(x, scale, bias)


def _norm_relu(norm, norm_relu, y):
    """Every ``norm()(y); relu(y)`` pair in the blocks goes through
    here: XLA's own elementwise fusion by default, or the single-pass
    Pallas norm+activation join when a ``BatchNormReLU`` partial is
    wired in (``norm_act="pallas"``)."""
    if norm_relu is not None:
        return norm_relu()(y)
    return nn.relu(norm()(y))


def _residual_join(residual, y, kind: str):
    """The block output ``relu(residual + y)``: XLA elementwise fusion by
    default, or the Pallas single-pass kernel (the docs/PERF.md §56×56
    experiment — measured by scripts/pallas_residual_experiment.py)."""
    if kind == "pallas":
        from ..ops.elementwise import residual_relu

        return residual_relu(residual, y)
    return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    join: str = "xla"  # "xla" | "pallas"
    fused: ModuleDef = None  # PallasConvBN3x3 partial (conv_bn="pallas")
    norm_relu: ModuleDef = None  # BatchNormReLU partial (norm_act="pallas")

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = _norm_relu(self.norm, self.norm_relu, y)
        if self.fused is not None and self.strides == 1:
            # the 3x3+BN+ReLU as one fused Pallas op (stride-1 blocks;
            # stride-2 stage entries keep the XLA pair)
            y = self.fused(features=self.filters)(y)
        else:
            y = self.conv(self.filters, (3, 3),
                          strides=(self.strides,) * 2)(y)
            y = _norm_relu(self.norm, self.norm_relu, y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides,) * 2,
                name="conv_proj",
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return _residual_join(residual, y, self.join)


class BasicBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    join: str = "xla"  # "xla" | "pallas"
    fused: ModuleDef = None  # PallasConvBN3x3 partial (conv_bn="pallas")
    norm_relu: ModuleDef = None  # BatchNormReLU partial (norm_act="pallas")

    @nn.compact
    def __call__(self, x):
        residual = x
        if self.fused is not None and self.strides == 1:
            # first 3x3+BN+ReLU fused; the second conv's BN has no ReLU
            # before the join, so it stays on the XLA pair
            y = self.fused(features=self.filters)(x)
        else:
            y = self.conv(self.filters, (3, 3),
                          strides=(self.strides,) * 2)(x)
            y = _norm_relu(self.norm, self.norm_relu, y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides,) * 2,
                name="conv_proj",
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return _residual_join(residual, y, self.join)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    stem: str = "conv"  # "conv" | "space_to_depth" (same params/output)
    residual_join: str = "xla"  # "xla" | "pallas" (same math, see blocks)
    conv_bn: str = "xla"  # "xla" | "pallas" (fused 3x3+BN+ReLU, see blocks)
    norm_act: str = "xla"  # "xla" | "pallas" (fused BN-apply+ReLU join)

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, param_dtype=self.param_dtype,
        )
        fused = None
        if self.conv_bn == "pallas":
            fused = partial(
                PallasConvBN3x3, train=train, dtype=self.dtype,
                param_dtype=self.param_dtype,
            )
        elif self.conv_bn != "xla":
            raise ValueError(
                f"unknown conv_bn {self.conv_bn!r} (want 'xla' or "
                "'pallas')"
            )
        norm_relu = None
        if self.norm_act == "pallas":
            norm_relu = partial(
                BatchNormReLU, use_running_average=not train,
                dtype=self.dtype, param_dtype=self.param_dtype,
            )
        elif self.norm_act != "xla":
            raise ValueError(
                f"unknown norm_act {self.norm_act!r} (want 'xla' or "
                "'pallas')"
            )
        x = x.astype(self.dtype)
        if self.stem == "space_to_depth":
            x = SpaceToDepthConvInit(
                features=self.num_filters, dtype=self.dtype,
                param_dtype=self.param_dtype, name="conv_init",
            )(x)
        elif self.stem == "conv":
            x = conv(self.num_filters, (7, 7), strides=(2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        else:
            raise ValueError(
                f"unknown stem {self.stem!r} (want 'conv' or "
                "'space_to_depth')"
            )
        if norm_relu is not None:
            x = norm_relu(name="bn_init")(x)
        else:
            x = norm(name="bn_init")(x)
            x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=self.num_filters * 2 ** i,
                    strides=strides, conv=conv, norm=norm,
                    join=self.residual_join, fused=fused,
                    norm_relu=norm_relu,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=self.param_dtype)(x)
        # logits in f32 for a numerically stable softmax/loss
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)

MODELS = {
    "ResNet18": ResNet18,
    "ResNet34": ResNet34,
    "ResNet50": ResNet50,
    "ResNet101": ResNet101,
    "ResNet152": ResNet152,
}
