"""ResNet family (flax/linen), TPU-first.

The reference has no model code at all — its benchmarks instantiate Keras
``applications.ResNet50`` (reference
examples/tensorflow2_synthetic_benchmark.py:64) and tf_cnn_benchmarks
(docs/benchmarks.rst:15-63).  This module provides the equivalent model
family natively so the framework's headline benchmark (ResNet-50 synthetic,
BASELINE.md) is self-contained.

TPU-first choices:

* **NHWC** layouts and 3x3/1x1 convs that XLA tiles directly onto the MXU;
* **bf16 compute, f32 params** (``dtype``/``param_dtype`` split) — the MXU's
  native mixed precision, no loss scaling needed;
* BatchNorm statistics are per-replica (Horovod-style data parallelism does
  not sync BN; cross-replica stats would add per-step collectives);
* no Python control flow in the forward pass — fully unrollable for jit.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides,) * 2)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides,) * 2,
                name="conv_proj",
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides,) * 2)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides,) * 2,
                name="conv_proj",
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, param_dtype=self.param_dtype,
        )
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), strides=(2, 2),
                 padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=self.num_filters * 2 ** i,
                    strides=strides, conv=conv, norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=self.param_dtype)(x)
        # logits in f32 for a numerically stable softmax/loss
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)

MODELS = {
    "ResNet18": ResNet18,
    "ResNet34": ResNet34,
    "ResNet50": ResNet50,
    "ResNet101": ResNet101,
    "ResNet152": ResNet152,
}
