"""BERT-style Transformer encoder (flax/linen), TPU-first.

Present because the driver's benchmark configs include "Adasum allreduce on
BERT-base" (BASELINE.json) and the fork's sweep scripts profile BERT
(reference examples/test_bert.sh) — the reference itself ships no model
code.  bf16 compute / f32 params; attention as einsums that map straight
onto the MXU; optional sequence parallelism via
horovod_tpu.parallel.ring_attention.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax.numpy as jnp
import numpy as np
from flax import linen as nn


class SelfAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # Optional override for the core attention computation, signature
    # (q, k, v, mask) -> out.  parallel/ring_attention.py plugs in here for
    # sequence-parallel execution.
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, mask=None):
        d = x.shape[-1]
        assert d % self.num_heads == 0
        head_dim = d // self.num_heads
        dense = lambda name: nn.DenseGeneral(
            (self.num_heads, head_dim), dtype=self.dtype,
            param_dtype=self.param_dtype, name=name, axis=-1,
        )
        q, k, v = dense("query")(x), dense("key")(x), dense("value")(x)
        if self.attention_fn is not None:
            out = self.attention_fn(q, k, v, mask)
        else:
            scale = 1.0 / np.sqrt(head_dim)
            logits = jnp.einsum("...qhd,...khd->...hqk", q, k) * scale
            if mask is not None:
                logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
            probs = nn.softmax(logits.astype(jnp.float32), axis=-1).astype(
                self.dtype
            )
            out = jnp.einsum("...hqk,...khd->...qhd", probs, v)
        return nn.DenseGeneral(
            d, axis=(-2, -1), dtype=self.dtype, param_dtype=self.param_dtype,
            name="out",
        )(out)


class EncoderLayer(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, mask=None):
        h = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype)(x)
        h = SelfAttention(
            self.num_heads, dtype=self.dtype, param_dtype=self.param_dtype,
            attention_fn=self.attention_fn,
        )(h, mask)
        x = x + h
        h = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype)(x)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype,
                     param_dtype=self.param_dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(x.shape[-1], dtype=self.dtype,
                     param_dtype=self.param_dtype)(h)
        return x + h


class BertEncoder(nn.Module):
    """Pre-LN BERT-style encoder over token ids."""

    vocab_size: int = 30522
    hidden_dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, ids, mask=None):
        x = nn.Embed(self.vocab_size, self.hidden_dim,
                     param_dtype=self.param_dtype, dtype=self.dtype)(ids)
        pos = nn.Embed(self.max_len, self.hidden_dim,
                       param_dtype=self.param_dtype, dtype=self.dtype)(
            jnp.arange(ids.shape[-1])[None, :]
        )
        x = x + pos
        for _ in range(self.num_layers):
            x = EncoderLayer(
                self.num_heads, self.mlp_dim, dtype=self.dtype,
                param_dtype=self.param_dtype, attention_fn=self.attention_fn,
            )(x, mask)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype)(x)
        return x.astype(jnp.float32)


def bert_base(**kw):
    return BertEncoder(**kw)


def bert_tiny(**kw):
    """4-layer/128-dim variant for tests and CPU dry-runs."""
    kw.setdefault("vocab_size", 1024)
    kw.setdefault("hidden_dim", 128)
    kw.setdefault("num_layers", 4)
    kw.setdefault("num_heads", 4)
    kw.setdefault("mlp_dim", 256)
    kw.setdefault("max_len", 512)
    return BertEncoder(**kw)
