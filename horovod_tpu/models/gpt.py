"""GPT-style decoder-only Transformer LM (flax/linen), TPU-first.

The long-context model family: causal attention defaults to the Pallas
flash kernels (ops/flash_attention.py) on TPU, and any attention override
— ring or Ulysses sequence parallelism with ``causal=True`` — plugs into
``attention_fn`` exactly as in the BERT encoder.  The reference ships no
model code (SURVEY §5); this family exists so the framework's benchmark
and long-context claims are self-contained.

TPU-first choices: bf16 compute / f32 params; pre-LN; attention and MLP
as einsums on the MXU; weight-tied LM head (one embedding matrix);
no Python control flow in the forward pass."""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from .bert import EncoderLayer


def causal_flash_attention_fn(q, k, v, mask):
    """Default causal core: flash kernels on TPU, interpreter off-TPU
    (ops/flash_attention.py resolves per mesh platform)."""
    from ..ops.flash_attention import flash_attention

    return flash_attention(q, k, v, causal=True)


class GPT(nn.Module):
    """Decoder-only LM over token ids -> logits ``[b, s, vocab]``.

    ``attention_fn(q, k, v, mask)`` must apply causal masking itself
    (the default does; for sequence parallelism pass e.g.
    ``lambda q, k, v, m: ring_attention(q, k, v, causal=True,
    axis="sp")``)."""

    vocab_size: int = 50257
    hidden_dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 1024
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attention_fn: Optional[Callable] = None
    # offset of this shard's first token in the global sequence — nonzero
    # under sequence parallelism, where position embeddings must be global
    def position_ids(self, ids, seq_offset):
        return seq_offset + jnp.arange(ids.shape[-1])[None, :]

    @nn.compact
    def __call__(self, ids, seq_offset: int = 0):
        attn = self.attention_fn or causal_flash_attention_fn
        embed = nn.Embed(self.vocab_size, self.hidden_dim,
                         param_dtype=self.param_dtype, dtype=self.dtype,
                         name="wte")
        x = embed(ids)
        x = x + nn.Embed(self.max_len, self.hidden_dim,
                         param_dtype=self.param_dtype, dtype=self.dtype,
                         name="wpe")(self.position_ids(ids, seq_offset))
        for _ in range(self.num_layers):
            x = EncoderLayer(
                self.num_heads, self.mlp_dim, dtype=self.dtype,
                param_dtype=self.param_dtype, attention_fn=attn,
            )(x)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype)(x)
        # weight-tied LM head: logits = x @ wte^T, f32 for the softmax
        logits = embed.attend(x.astype(self.param_dtype))
        return logits.astype(jnp.float32)


def gpt2_small(**kw):
    return GPT(**kw)


def gpt_tiny(**kw):
    """4-layer/128-dim variant for tests and CPU dry-runs."""
    kw.setdefault("vocab_size", 1024)
    kw.setdefault("hidden_dim", 128)
    kw.setdefault("num_layers", 4)
    kw.setdefault("num_heads", 4)
    kw.setdefault("mlp_dim", 256)
    kw.setdefault("max_len", 512)
    return GPT(**kw)


def next_token_loss(logits, ids):
    """Shifted cross-entropy: predict ids[t+1] from position t."""
    logp = nn.log_softmax(logits[:, :-1])
    tgt = ids[:, 1:]
    ll = jnp.take_along_axis(logp, tgt[..., None], axis=-1)
    return -jnp.mean(ll)
