"""Inception V3 (flax/linen), TPU-first.

Inception V3 headlines the reference's published scaling table
(reference README.rst:75-77, docs/benchmarks.rst:12-13: 90% scaling
efficiency at 512 GPUs) and its benchmark scripts instantiate the Keras
application (reference examples/tensorflow2_synthetic_benchmark.py
``getattr(applications, args.model)``).  This is the standard published
architecture (Szegedy et al. 2015, "Rethinking the Inception
Architecture") built natively: the factorized 7x1/1x7 and 3x1/1x3
branches are exactly the mix of skinny convolutions that exercises MXU
tiling differently from ResNet's uniform 3x3s.

Same TPU conventions as models/resnet.py: NHWC, bf16 compute with f32
params, BN statistics per replica, no Python control flow in the
forward pass.  Input: 299x299x3 (the canonical shape; any spatial size
>= 75 works).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Tuple

import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any


class ConvBN(nn.Module):
    """conv + BN + ReLU, the Inception building block (all ~94 convs)."""

    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    train: bool = True

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(
            self.features, self.kernel, strides=self.strides,
            padding=self.padding, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype,
        )(x)
        x = nn.BatchNorm(
            use_running_average=not self.train, momentum=0.9,
            epsilon=1e-3, dtype=self.dtype, param_dtype=self.param_dtype,
        )(x)
        return nn.relu(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    conv: ModuleDef

    @nn.compact
    def __call__(self, x):
        b1 = self.conv(64, (1, 1))(x)
        b5 = self.conv(48, (1, 1))(x)
        b5 = self.conv(64, (5, 5))(b5)
        b3 = self.conv(64, (1, 1))(x)
        b3 = self.conv(96, (3, 3))(b3)
        b3 = self.conv(96, (3, 3))(b3)
        bp = self.conv(self.pool_features, (1, 1))(_avg_pool_same(x))
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    """Grid reduction 35x35 -> 17x17."""

    conv: ModuleDef

    @nn.compact
    def __call__(self, x):
        b3 = self.conv(384, (3, 3), strides=(2, 2), padding="VALID")(x)
        bd = self.conv(64, (1, 1))(x)
        bd = self.conv(96, (3, 3))(bd)
        bd = self.conv(96, (3, 3), strides=(2, 2), padding="VALID")(bd)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    """Factorized 7x7 branches (1x7 / 7x1)."""

    c7: int
    conv: ModuleDef

    @nn.compact
    def __call__(self, x):
        c7 = self.c7
        b1 = self.conv(192, (1, 1))(x)
        b7 = self.conv(c7, (1, 1))(x)
        b7 = self.conv(c7, (1, 7))(b7)
        b7 = self.conv(192, (7, 1))(b7)
        bd = self.conv(c7, (1, 1))(x)
        bd = self.conv(c7, (7, 1))(bd)
        bd = self.conv(c7, (1, 7))(bd)
        bd = self.conv(c7, (7, 1))(bd)
        bd = self.conv(192, (1, 7))(bd)
        bp = self.conv(192, (1, 1))(_avg_pool_same(x))
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    """Grid reduction 17x17 -> 8x8."""

    conv: ModuleDef

    @nn.compact
    def __call__(self, x):
        b3 = self.conv(192, (1, 1))(x)
        b3 = self.conv(320, (3, 3), strides=(2, 2), padding="VALID")(b3)
        b7 = self.conv(192, (1, 1))(x)
        b7 = self.conv(192, (1, 7))(b7)
        b7 = self.conv(192, (7, 1))(b7)
        b7 = self.conv(192, (3, 3), strides=(2, 2), padding="VALID")(b7)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    """Expanded filter banks (split 1x3 / 3x1 outputs concatenated)."""

    conv: ModuleDef

    @nn.compact
    def __call__(self, x):
        b1 = self.conv(320, (1, 1))(x)
        b3 = self.conv(384, (1, 1))(x)
        b3 = jnp.concatenate([
            self.conv(384, (1, 3))(b3),
            self.conv(384, (3, 1))(b3),
        ], axis=-1)
        bd = self.conv(448, (1, 1))(x)
        bd = self.conv(384, (3, 3))(bd)
        bd = jnp.concatenate([
            self.conv(384, (1, 3))(bd),
            self.conv(384, (3, 1))(bd),
        ], axis=-1)
        bp = self.conv(192, (1, 1))(_avg_pool_same(x))
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, dtype=self.dtype,
                       param_dtype=self.param_dtype, train=train)

        def c(features, kernel, **kw):
            return conv(features=features, kernel=kernel, **kw)

        x = x.astype(self.dtype)
        # stem: 299 -> 35x35x192
        x = c(32, (3, 3), strides=(2, 2), padding="VALID")(x)
        x = c(32, (3, 3), padding="VALID")(x)
        x = c(64, (3, 3))(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = c(80, (1, 1), padding="VALID")(x)
        x = c(192, (3, 3), padding="VALID")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        # 35x35 stage
        x = InceptionA(pool_features=32, conv=c)(x)
        x = InceptionA(pool_features=64, conv=c)(x)
        x = InceptionA(pool_features=64, conv=c)(x)
        x = InceptionB(conv=c)(x)
        # 17x17 stage
        x = InceptionC(c7=128, conv=c)(x)
        x = InceptionC(c7=160, conv=c)(x)
        x = InceptionC(c7=160, conv=c)(x)
        x = InceptionC(c7=192, conv=c)(x)
        x = InceptionD(conv=c)(x)
        # 8x8 stage
        x = InceptionE(conv=c)(x)
        x = InceptionE(conv=c)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=self.param_dtype)(x)
        return x.astype(jnp.float32)
