"""Vision Transformer (flax/linen), TPU-first.

Rounds out the image-model registry with the attention-based family the
reference era predates: the scaling-table models (ResNet/VGG/Inception,
reference README.rst:75-77) are all convolutional, while modern TPU
image workloads are ViTs.  Reuses the shared Transformer encoder layer
(models/bert.py EncoderLayer), so the same ``attention_fn`` plug-in used
for sequence parallelism works here too.

TPU-first choices: bf16 compute / f32 params; patchify as a single
strided conv (one MXU-friendly matmul per patch grid); learnable class
token + position embeddings; pre-LN encoder; no dropout (the synthetic
benchmarks measure compute, and deterministic forward keeps the
``train`` flag shape-stable for XLA).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax.numpy as jnp
from flax import linen as nn

from .bert import EncoderLayer


class ViT(nn.Module):
    """ViT over NHWC images -> logits ``[b, num_classes]``.

    Matches the image-registry call convention
    (``model.apply(vars, x, train=...)``); ``train`` is accepted for
    interface parity and ignored (no BN, no dropout).
    """

    num_classes: int = 1000
    patch_size: int = 16
    hidden_dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        b, h, w, _ = x.shape
        p = self.patch_size
        assert h % p == 0 and w % p == 0, \
            f"image {h}x{w} not divisible by patch {p}"
        x = nn.Conv(self.hidden_dim, kernel_size=(p, p), strides=(p, p),
                    dtype=self.dtype, param_dtype=self.param_dtype,
                    name="patch_embed")(x.astype(self.dtype))
        x = x.reshape(b, -1, self.hidden_dim)          # [b, hw/p^2, d]

        cls = self.param("cls", nn.initializers.zeros,
                         (1, 1, self.hidden_dim), self.param_dtype)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (b, 1, self.hidden_dim)).astype(
                self.dtype), x], axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, x.shape[1], self.hidden_dim), self.param_dtype)
        x = x + pos.astype(self.dtype)

        for _ in range(self.num_layers):
            x = EncoderLayer(
                self.num_heads, self.mlp_dim, dtype=self.dtype,
                param_dtype=self.param_dtype,
                attention_fn=self.attention_fn,
            )(x)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=self.param_dtype, name="head")(x[:, 0])


# standard variants (Dosovitskiy et al. table 1 shapes)
ViT_S16 = partial(ViT, patch_size=16, hidden_dim=384, num_layers=12,
                  num_heads=6, mlp_dim=1536)
ViT_B16 = partial(ViT, patch_size=16, hidden_dim=768, num_layers=12,
                  num_heads=12, mlp_dim=3072)
ViT_L16 = partial(ViT, patch_size=16, hidden_dim=1024, num_layers=24,
                  num_heads=16, mlp_dim=4096)
