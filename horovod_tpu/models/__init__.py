"""Image-classification model registry: the families the reference's
benchmark scripts instantiate from Keras applications (reference
examples/tensorflow2_synthetic_benchmark.py:64 getattr(applications,
args.model); the published scaling table covers Inception V3,
ResNet-101, and VGG-16, reference README.rst:75-77).  The transformer
families live in their submodules (models/gpt.py, models/bert.py) with
their own benchmark harnesses — they take token inputs, not images."""

from .inception import InceptionV3  # noqa: F401
from .resnet import MODELS as _RESNET_MODELS
from .resnet import (  # noqa: F401
    ResNet18, ResNet34, ResNet50, ResNet101, ResNet152,
)
from .vgg import VGG11, VGG16, VGG19  # noqa: F401
from .vit import ViT, ViT_S16, ViT_B16, ViT_L16  # noqa: F401

# the --model CLI registry; spread from resnet.MODELS (kept for
# backwards compatibility) so the two can never diverge
MODELS = {
    **_RESNET_MODELS,
    "VGG11": VGG11,
    "VGG16": VGG16,
    "VGG19": VGG19,
    "InceptionV3": InceptionV3,
    "ViT-S16": ViT_S16,
    "ViT-B16": ViT_B16,
    "ViT-L16": ViT_L16,
}

# registry names whose init() carries no "batch_stats" collection —
# harnesses pass has_batch_stats accordingly (single site: update here
# when adding a BN-free model)
BATCH_STATS_FREE = frozenset({"ViT-S16", "ViT-B16", "ViT-L16"})
