"""VGG family (flax/linen), TPU-first.

VGG-16 is one of the three models in the reference's published scaling
table (reference README.rst:75-77, docs/benchmarks.rst:12-13: 68%
scaling efficiency at 512 GPUs — the hardest of the three because its
~138M dense-heavy parameters make the gradient allreduce enormous).
Providing it natively keeps that benchmark reproducible here: the
~500 MB of fp32 gradients per step is exactly the payload that stresses
the fused-bucket allreduce.

Same TPU conventions as models/resnet.py: NHWC, bf16 compute with f32
params, no Python control flow in the forward pass.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn

# channels per conv, "M" = 2x2 maxpool (the classic configurations)
_CFGS = {
    11: (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    16: (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
         512, 512, 512, "M", 512, 512, 512, "M"),
    19: (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"),
}


class VGG(nn.Module):
    cfg: Sequence
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    batch_norm: bool = False  # the classic nets are BN-free
    # 0.0 by default so the model drops into make_train_step (which
    # passes no 'dropout' rng — synthetic benchmarks don't regularize);
    # pass 0.5 + rngs={'dropout': key} at apply time for classic VGG
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, kernel_size=(3, 3), padding=1,
                       dtype=self.dtype, param_dtype=self.param_dtype)
        x = x.astype(self.dtype)
        for item in self.cfg:
            if item == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = conv(features=item)(x)
                if self.batch_norm:
                    x = nn.BatchNorm(
                        use_running_average=not train, momentum=0.9,
                        epsilon=1e-5, dtype=self.dtype,
                        param_dtype=self.param_dtype,
                    )(x)
                x = nn.relu(x)
        x = x.reshape(x.shape[0], -1)
        dense = partial(nn.Dense, dtype=self.dtype,
                        param_dtype=self.param_dtype)
        x = nn.relu(dense(4096)(x))
        if self.dropout:
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.relu(dense(4096)(x))
        if self.dropout:
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = dense(self.num_classes)(x)
        return x.astype(jnp.float32)


VGG11 = partial(VGG, cfg=_CFGS[11])
VGG16 = partial(VGG, cfg=_CFGS[16])
VGG19 = partial(VGG, cfg=_CFGS[19])
