"""Small MLP / convnet for MNIST-class examples and tests — the model behind
the examples/mnist.py end-to-end slice (the reference's
examples/tensorflow2_mnist.py uses an equivalent little convnet)."""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn


class MLP(nn.Module):
    features: Sequence[int] = (128, 64, 10)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for i, f in enumerate(self.features):
            x = nn.Dense(f, dtype=self.dtype)(x)
            if i < len(self.features) - 1:
                x = nn.relu(x)
        return x


class ConvNet(nn.Module):
    """The examples/tensorflow2_mnist.py-shaped convnet."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)
