"""Client for the rendezvous KV store (reference
horovod/run/http/http_client.py: read_data_from_kvstore /
put_data_into_kvstore).

Transient-failure policy: every request to the rendezvous server crosses
a real network on a pod, so idempotent requests (GET/DELETE — the server
is a plain KV store) are retried with exponential backoff + jitter on
``URLError`` and 5xx responses.  PUTs are retried only when the caller
opts in (``retry=True``) — the store's PUTs are last-writer-wins
overwrites, so opting in is safe for keys with a single writer (the
abort flag, heartbeat leases).  Knobs: ``HVD_HTTP_RETRIES`` (default 2
retries after the first attempt) and ``HVD_HTTP_BACKOFF_MS`` (default
50 ms base, doubled per attempt).  Retries surface as the
``hvd_http_retries_total`` counter.  The ``HVD_FAULT_SPEC`` harness's
``http_drop`` faults inject here (elastic/faults.py) so the retry path
itself is testable.

Control-plane tier additions (docs/control_plane.md):

* **Keep-alive pooling** — requests ride one persistent
  ``http.client.HTTPConnection`` per (thread, host:port) instead of a
  fresh TCP connect per call; a connection the server closed while idle
  is replaced with one silent fresh-connection retry (the send never
  reached the application layer, so even POSTs are safe).  Reuses
  surface as ``hvd_http_reuse_total``; ``HVD_HTTP_KEEPALIVE=0`` turns
  pooling off.
* **Ordered failover** — when ``HVD_RENDEZVOUS_ADDRS`` lists the target
  among several ``host:port`` entries, a request whose transport
  retries are exhausted moves on to the next address (the warm standby,
  run/journal.py), and the first live address is remembered so later
  requests skip the dead primary.  Failovers surface as
  ``hvd_cp_failovers_total``.
* **Batch surface** — :func:`put_batch` (the relay tree's upstream
  ``PUT /batch`` leg), :func:`get_scope` (cursor-based scope reads),
  and :func:`put_kv_reply` (a PUT that returns the server's JSON reply,
  e.g. the heartbeat's piggybacked abort verdict).
"""

from __future__ import annotations

import http.client
import io
import json as _json
import random
import socket
import threading
import time
import urllib.error
import urllib.request
from base64 import b64decode, b64encode
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils import env as env_util
from ..utils.logging import get_logger
from .http_server import SECRET_HEADER, sign

log = get_logger(__name__)

#: methods safe to retry without opt-in: the server's GET/DELETE are
#: idempotent (reads and prefix-deletes of a plain KV store)
_IDEMPOTENT_METHODS = ("GET", "DELETE")

#: transport errors that mean a pooled connection went stale while idle
#: (the server closed it between requests).  The request never reached
#: the application layer, so one silent fresh-connection retry — outside
#: the caller's retry budget — is safe for every method.  A *timeout* is
#: deliberately absent: the server may have processed a timed-out
#: request, so it surfaces as a normal URLError.
_STALE_ERRORS = (ConnectionResetError, BrokenPipeError,
                 http.client.RemoteDisconnected,
                 http.client.CannotSendRequest)

_pool_local = threading.local()


def _record_retry() -> None:
    """Count one retried request; never raises (the metrics plane must
    not take down a rendezvous request)."""
    try:
        from .. import metrics

        if metrics.on():
            metrics.HTTP_RETRIES.inc()
    except Exception:  # noqa: BLE001
        pass


def _record_counter(name: str) -> None:
    try:
        from .. import metrics

        if metrics.on():
            getattr(metrics, name).inc()
    except Exception:  # noqa: BLE001
        pass


class _Response:
    """Minimal reply object (context manager + ``read``), covering what
    callers used from urllib's response: the whole body is already read
    so the underlying connection can go back to the pool."""

    def __init__(self, status: int, data: bytes, headers):
        self.status = status
        self.code = status
        self.headers = headers
        self._data = data

    def read(self) -> bytes:
        return self._data

    def __enter__(self) -> "_Response":
        return self

    def __exit__(self, *exc) -> bool:
        return False


def _pool() -> Dict[Tuple[str, int], http.client.HTTPConnection]:
    conns = getattr(_pool_local, "conns", None)
    if conns is None:
        conns = _pool_local.conns = {}
    return conns


def reset_pool() -> None:
    """Drop this thread's pooled connections (tests / post-fork)."""
    conns = getattr(_pool_local, "conns", None)
    if conns:
        for c in conns.values():
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        conns.clear()


def _send_once(method: str, addr: str, port: int, path: str,
               body: bytes, secret: Optional[bytes],
               timeout: float) -> _Response:
    """One request over a pooled (or fresh) connection.  Raises
    ``urllib.error.HTTPError`` on non-2xx and ``urllib.error.URLError``
    on transport failure — the same surface urlopen gave callers."""
    keepalive = env_util.get_bool(env_util.HVD_HTTP_KEEPALIVE, True)
    pool = _pool() if keepalive else None
    key = (addr, int(port))
    url = f"http://{addr}:{port}{path}"
    payload = body if method in ("PUT", "POST") else None
    headers = {}
    if secret is not None:
        headers[SECRET_HEADER] = sign(secret, path, body)
    if not keepalive:
        headers["Connection"] = "close"
    for fresh_retry in (False, True):
        conn = pool.pop(key, None) if pool is not None else None
        reused = conn is not None
        if conn is None:
            conn = http.client.HTTPConnection(addr, int(port),
                                              timeout=timeout)
        elif conn.sock is not None:
            conn.sock.settimeout(timeout)
        try:
            if conn.sock is None:
                conn.connect()
                # Nagle + delayed-ACK on a persistent connection turns
                # every small request/reply exchange into ~40 ms; the
                # control plane lives on small exchanges
                conn.sock.setsockopt(socket.IPPROTO_TCP,
                                     socket.TCP_NODELAY, 1)
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
        except _STALE_ERRORS as e:
            conn.close()
            if reused and not fresh_retry:
                continue  # the keep-alive race: one silent fresh retry
            raise urllib.error.URLError(e)
        except (OSError, http.client.HTTPException) as e:
            conn.close()
            raise urllib.error.URLError(e)
        if pool is not None and not resp.will_close:
            pool[key] = conn
        else:
            conn.close()
        if reused:
            _record_counter("HTTP_REUSE")
        if 200 <= resp.status < 300:
            return _Response(resp.status, data, resp.headers)
        raise urllib.error.HTTPError(url, resp.status, resp.reason,
                                     resp.headers, io.BytesIO(data))
    raise urllib.error.URLError(socket.error("unreachable"))  # pragma: no cover


def failover_targets(
        addr: str, port: int) -> Optional[List[Tuple[str, int]]]:
    """The ordered address list from ``HVD_RENDEZVOUS_ADDRS`` when the
    requested endpoint belongs to it (None otherwise — requests to
    endpoints outside the list, e.g. a per-host relay, never fail
    over)."""
    raw = env_util.get_str(env_util.HVD_RENDEZVOUS_ADDRS)
    if not raw:
        return None
    targets: List[Tuple[str, int]] = []
    for tok in raw.split(","):
        tok = tok.strip()
        if not tok or ":" not in tok:
            continue
        host, _, p = tok.rpartition(":")
        try:
            targets.append((host, int(p)))
        except ValueError:
            continue
    if len(targets) < 2 or (addr, int(port)) not in targets:
        return None
    return targets


_active_lock = threading.Lock()
_active_target: Dict[Tuple, int] = {}


def _request(method: str, addr: str, port: int, path: str,
             body: bytes = b"", secret: Optional[bytes] = None,
             timeout: float = 10.0, retries: Optional[int] = None):
    """One HTTP request with bounded retries and ordered failover.
    ``retries=None`` applies the default policy: ``HVD_HTTP_RETRIES``
    for idempotent methods, 0 for PUTs (callers opt in via an explicit
    count).  When the target is part of ``HVD_RENDEZVOUS_ADDRS``, a
    target whose transport retries are exhausted is abandoned for the
    next address in the list (starting from the last known-live one);
    HTTP error replies (4xx/5xx) are real answers from a live server
    and never fail over."""
    if retries is None:
        retries = env_util.get_int(env_util.HVD_HTTP_RETRIES,
                                   env_util.DEFAULT_HTTP_RETRIES) \
            if method in _IDEMPOTENT_METHODS else 0
    backoff = env_util.get_float(env_util.HVD_HTTP_BACKOFF_MS,
                                 env_util.DEFAULT_HTTP_BACKOFF_MS) / 1000.0
    targets = failover_targets(addr, port)
    if targets is None:
        order: List[Tuple[str, int]] = [(addr, int(port))]
    else:
        key = tuple(targets)
        with _active_lock:
            start = _active_target.get(key, 0)
        order = [targets[(start + i) % len(targets)]
                 for i in range(len(targets))]
    last_err: Optional[BaseException] = None
    for ti, (t_addr, t_port) in enumerate(order):
        attempt = 0
        while True:
            try:
                from ..elastic import faults

                faults.on_http(path)  # inside the loop: drops exercise retries
                resp = _send_once(method, t_addr, t_port, path, body,
                                  secret, timeout)
                if targets is not None:
                    with _active_lock:
                        _active_target[tuple(targets)] = targets.index(
                            (t_addr, t_port))
                return resp
            except urllib.error.HTTPError as e:
                # 4xx (404 rendezvous-miss, 401 bad secret) is a real
                # answer, not a transient — only server errors are
                # retried, and an erroring-but-live server is never
                # abandoned for a standby
                if e.code < 500 or attempt >= retries:
                    raise
            except urllib.error.URLError as e:
                last_err = e
                if attempt >= retries:
                    break  # transport dead past the budget: next target
            attempt += 1
            _record_retry()
            # full jitter on top of the doubling base: concurrent ranks
            # hammering a recovering server must not re-synchronize
            time.sleep(backoff * (2 ** (attempt - 1))
                       + random.uniform(0.0, backoff))
        if ti + 1 < len(order):
            _record_counter("CP_FAILOVERS")
            log.warning("rendezvous %s:%d unreachable; failing over to "
                        "%s:%d", t_addr, t_port, *order[ti + 1])
    assert last_err is not None
    raise last_err


def put_kv(addr: str, port: int, scope: str, key: str, value: bytes,
           secret: Optional[bytes] = None, retry: bool = False,
           timeout: float = 10.0) -> None:
    """PUT one key.  ``retry=True`` opts this (non-idempotent but
    last-writer-wins) write into the transient-failure retry policy —
    use it for single-writer keys like the abort flag."""
    retries = env_util.get_int(env_util.HVD_HTTP_RETRIES,
                               env_util.DEFAULT_HTTP_RETRIES) if retry else 0
    with _request("PUT", addr, port, f"/{scope}/{key}", value, secret,
                  timeout=timeout, retries=retries):
        pass


def get_kv(addr: str, port: int, scope: str, key: str,
           secret: Optional[bytes] = None,
           wait: bool = False, timeout: float = 60.0) -> Optional[bytes]:
    """GET, optionally polling until the key appears (rendezvous wait).
    The poll backs off from 50 ms toward a 1 s cap so a long rendezvous
    wait is tens of requests, not ``timeout / 0.1`` of them."""
    deadline = time.monotonic() + timeout
    delay = 0.05
    while True:
        try:
            with _request("GET", addr, port, f"/{scope}/{key}",
                          secret=secret) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404 and wait and time.monotonic() < deadline:
                time.sleep(delay)
                delay = min(delay * 1.5, 1.0)
                continue
            if e.code == 404:
                return None
            raise


def put_kv_reply(addr: str, port: int, scope: str, key: str, value: bytes,
                 secret: Optional[bytes] = None, retry: bool = False,
                 timeout: float = 10.0) -> Optional[dict]:
    """PUT one key and return the server's JSON reply (None when the
    reply carries no body — a pre-control-plane server).  The heartbeat
    rides this: a ``/health/<rank>`` renewal's reply carries the abort
    verdict, collapsing renew + abort-poll into one round trip."""
    retries = env_util.get_int(env_util.HVD_HTTP_RETRIES,
                               env_util.DEFAULT_HTTP_RETRIES) if retry else 0
    with _request("PUT", addr, port, f"/{scope}/{key}", value, secret,
                  timeout=timeout, retries=retries) as resp:
        data = resp.read()
    if not data:
        return None
    try:
        return _json.loads(data)
    except (ValueError, TypeError):
        return None


def put_batch(addr: str, port: int,
              entries: Sequence[Tuple[str, bytes]],
              secret: Optional[bytes] = None, retry: bool = False,
              timeout: float = 30.0) -> dict:
    """One ``PUT /batch`` carrying many KV entries — the relay tree's
    upstream leg (run/relay.py).  ``entries`` is ``[(path, value),
    ...]`` with full ``/scope/key`` paths.  Returns the server reply
    (``{"server_id", "abort", "applied", "skipped"}``).  Safe to opt
    into retries for last-writer-wins keys (leases, snapshots,
    fingerprints) — exactly what rides the relay."""
    body = _json.dumps({"entries": [
        {"p": p, "v": b64encode(v).decode()} for p, v in entries]}).encode()
    retries = env_util.get_int(env_util.HVD_HTTP_RETRIES,
                               env_util.DEFAULT_HTTP_RETRIES) if retry else 0
    with _request("PUT", addr, port, "/batch", body, secret,
                  timeout=timeout, retries=retries) as resp:
        return _json.loads(resp.read().decode())


def get_scope(addr: str, port: int, scope: str,
              since: Optional[int] = None,
              secret: Optional[bytes] = None,
              timeout: float = 10.0) -> dict:
    """Scope-level batch read (``GET /scope/<name>?since=V``): returns
    ``{"server_id", "version", "full", "entries": {key: bytes},
    "removed": [keys]}`` — only the keys changed after ``since`` unless
    the server answers with a full resync.  One round trip replaces a
    GET per key (the sanitizer's peer polls ride this)."""
    # ``since`` is always sent (-1 = full fetch): its presence is what
    # routes the request to the batch reader on the server
    path = f"/scope/{scope}?since={-1 if since is None else int(since)}"
    with _request("GET", addr, port, path, secret=secret,
                  timeout=timeout) as resp:
        out = _json.loads(resp.read().decode())
    out["entries"] = {k: b64decode(v)
                      for k, v in (out.get("entries") or {}).items()}
    return out


def delete_scope(addr: str, port: int, scope: str,
                 secret: Optional[bytes] = None) -> None:
    with _request("DELETE", addr, port, f"/{scope}", secret=secret):
        pass


def delete_kv(addr: str, port: int, scope: str, key: str,
              secret: Optional[bytes] = None) -> None:
    """Delete one key (the server's DELETE matches exact paths as well as
    scope prefixes) — used by the sanitizer to garbage-collect old
    fingerprints."""
    with _request("DELETE", addr, port, f"/{scope}/{key}", secret=secret):
        pass


def push_shard(addr: str, port: int, key: str, data: bytes,
               secret: Optional[bytes] = None,
               timeout: float = 30.0) -> None:
    """Upload one snapshot shard to a peer worker's shard server
    (``PUT /shard/<gen>.<src_rank>.<idx>``) — the replication write of
    the peer state plane (elastic/peerstate.py).  Retries ride the
    standard transient-failure policy; shard writes are idempotent
    (same bytes, content-checksummed at restore)."""
    put_kv(addr, port, "shard", key, data, secret=secret, retry=True,
           timeout=timeout)


def pull_shard(addr: str, port: int, key: str,
               secret: Optional[bytes] = None,
               timeout: float = 30.0) -> Optional[bytes]:
    """Fetch one snapshot shard from a peer worker's shard server
    (``GET /shard/<gen>.<src_rank>.<idx>``); None when the peer does not
    hold it.  The caller verifies the manifest checksum and tries the
    next replica on mismatch (elastic/peerstate.py)."""
    return get_kv(addr, port, "shard", key, secret=secret, wait=False,
                  timeout=timeout)


def get_peerstate(addr: str, port: int, secret: Optional[bytes] = None,
                  timeout: float = 10.0) -> dict:
    """The peer-state-plane table from ``GET /peerstate``: registered
    shard-server endpoints, per-generation manifest/commit coverage, and
    the newest fully-committed generation restore would target
    (docs/fault_tolerance.md#the-peer-state-plane)."""
    import json

    with _request("GET", addr, port, "/peerstate", secret=secret,
                  timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def get_sanitizer(addr: str, port: int,
                  secret: Optional[bytes] = None) -> dict:
    """The collective-sanitizer fingerprint table from ``GET /sanitizer``:
    published fingerprints grouped by sequence number, then rank — the
    live who-is-ahead view while chasing a divergence."""
    import json

    with _request("GET", addr, port, "/sanitizer", secret=secret) as resp:
        return json.loads(resp.read().decode())


def get_health(addr: str, port: int, secret: Optional[bytes] = None,
               timeout: float = 10.0) -> dict:
    """The failure-domain liveness view from ``GET /health``: per-rank
    heartbeat lease age + live/stale/dead verdict (computed on the
    server's clock) and the job-wide abort flag (None when unset)."""
    import json

    with _request("GET", addr, port, "/health", secret=secret,
                  timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def get_membership(addr: str, port: int, secret: Optional[bytes] = None,
                   timeout: float = 10.0) -> dict:
    """The elastic-membership table from ``GET /membership``: the
    committed epoch record (``epoch``/``world``/``controller_addr``),
    pending rejoin announcements, per-epoch ready acks, and the
    flapping-host blocklist (docs/fault_tolerance.md)."""
    import json

    with _request("GET", addr, port, "/membership", secret=secret,
                  timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def get_clock(addr: str, port: int, secret: Optional[bytes] = None,
              timeout: float = 2.0) -> float:
    """The rendezvous server's monotonic clock (µs) from ``GET /clock`` —
    one leg of the replay engine's offset-estimation handshake
    (timeline/replay/clock.py estimates rtt/offset around this call)."""
    import json

    with _request("GET", addr, port, "/clock", secret=secret,
                  timeout=timeout) as resp:
        return float(json.loads(resp.read().decode())["server_us"])


def put_replay_summary(addr: str, port: int, summary: dict,
                       secret: Optional[bytes] = None) -> None:
    """Publish a replay summary (scripts/hvd_replay.py output) so
    ``GET /replay`` on the rendezvous server serves it."""
    import json

    put_kv(addr, port, "replay", "summary",
           json.dumps(summary).encode(), secret=secret)


def put_projection_summary(addr: str, port: int, summary: dict,
                           secret: Optional[bytes] = None) -> None:
    """Publish a digital-twin projection summary (``hvd_replay
    --project`` output, docs/projection.md) so ``GET /projection`` on
    the rendezvous server serves it.  Single writer, last-writer-wins →
    safe to retry."""
    import json

    put_kv(addr, port, "projection", "summary",
           json.dumps(summary).encode(), secret=secret, retry=True)


def get_projection(addr: str, port: int,
                   secret: Optional[bytes] = None,
                   timeout: float = 10.0) -> Optional[dict]:
    """The latest topology-projected summary from ``GET /projection``
    (None if nothing has been published yet): per-target projected step
    time / efficiency / wire formats plus the tracked
    projected-vs-measured accuracy record."""
    import json

    try:
        with _request("GET", addr, port, "/projection", secret=secret,
                      timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None
        raise


def put_autotune_plan(addr: str, port: int, seq: int, record: dict,
                      secret: Optional[bytes] = None) -> None:
    """Publish one profile-guided plan record (applied / verified /
    rolled_back — optim/profile_guided.py) under the rendezvous
    ``autotune`` scope so ``GET /autotune`` renders the per-plan table.
    Single writer (the tuner), last-writer-wins → safe to retry."""
    import json

    put_kv(addr, port, "autotune", f"plan.{int(seq)}",
           json.dumps(record).encode(), secret=secret, retry=True)


def put_profile_summary(addr: str, port: int, rank, summary: dict,
                        secret: Optional[bytes] = None) -> None:
    """Publish one rank's compute-anatomy summary (timeline/profiler.py
    window anatomy) under the rendezvous ``profile`` scope so
    ``GET /profile`` renders the cross-rank aggregate.  Single writer
    per key (the rank), last-writer-wins → safe to retry."""
    import json

    put_kv(addr, port, "profile", str(rank),
           json.dumps(summary).encode(), secret=secret, retry=True)


def get_profile(addr: str, port: int, secret: Optional[bytes] = None,
                timeout: float = 10.0) -> dict:
    """The aggregated compute-anatomy report from ``GET /profile``:
    per-rank anatomies plus the cross-rank aggregate (per-segment
    slowest rank, mean MFU, worst host gap — docs/profiling.md)."""
    import json

    with _request("GET", addr, port, "/profile", secret=secret,
                  timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def get_timeseries(addr: str, port: int, secret: Optional[bytes] = None,
                   timeout: float = 10.0) -> dict:
    """The telemetry time-series table from ``GET /timeseries``:
    per-rank ring-buffer histories plus the cross-rank summary
    (docs/observe.md) — the watchdog's and ``hvd_watch``'s read."""
    import json

    with _request("GET", addr, port, "/timeseries", secret=secret,
                  timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def get_alerts(addr: str, port: int, secret: Optional[bytes] = None,
               timeout: float = 10.0) -> dict:
    """The watchdog alert log from ``GET /alerts``, newest first
    (docs/observe.md alert schema)."""
    import json

    with _request("GET", addr, port, "/alerts", secret=secret,
                  timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def get_events(addr: str, port: int, secret: Optional[bytes] = None,
               since_ts: Optional[float] = None,
               kind: Optional[str] = None,
               timeout: float = 10.0) -> dict:
    """The control-plane flight-recorder log from ``GET /events``,
    oldest first (observe/events.py event schema), with the server's
    incarnation id + scope version for cursor/restart detection.
    ``since_ts``/``kind`` filter server-side (hvd_events --follow)."""
    import json
    from urllib.parse import urlencode

    params = {}
    if since_ts is not None:
        params["since_ts"] = repr(float(since_ts))
    if kind:
        params["kind"] = kind
    path = "/events" + (f"?{urlencode(params)}" if params else "")
    with _request("GET", addr, port, path, secret=secret,
                  timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def get_autotune(addr: str, port: int, secret: Optional[bytes] = None,
                 timeout: float = 10.0) -> dict:
    """The profile-guided tuning table from ``GET /autotune``: every
    pushed plan record plus the latest predicted/realized speedup pair
    (docs/autotune.md artifact contract)."""
    import json

    with _request("GET", addr, port, "/autotune", secret=secret,
                  timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def get_replay(addr: str, port: int,
               secret: Optional[bytes] = None) -> Optional[dict]:
    """The latest replay summary from ``GET /replay`` (None if nothing
    has been published yet)."""
    import json

    try:
        with _request("GET", addr, port, "/replay", secret=secret) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None
        raise


def _post_json(addr: str, port: int, path: str, payload: dict,
               secret: Optional[bytes] = None,
               timeout: float = 30.0, retries: int = 0) -> dict:
    """One signed JSON POST to a serving route.  POSTs default to no
    transient retries (a retried /infer would double-submit); routes
    that are idempotent server-side (result posts — the broker counts
    and ignores duplicate completions) opt in via ``retries``.
    4xx/5xx replies that carry a JSON body are surfaced as
    RuntimeError with the server's error."""
    import json

    body = json.dumps(payload).encode()
    try:
        with _request("POST", addr, port, path, body, secret,
                      timeout=timeout, retries=retries) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        try:
            detail = json.loads(e.read().decode()).get("error")
        except Exception:  # noqa: BLE001
            detail = None
        raise RuntimeError(
            f"POST {path} -> {e.code}"
            + (f": {detail}" if detail else "")) from e


def post_infer(addr: str, port: int, inputs,
               secret: Optional[bytes] = None,
               timeout: float = 30.0) -> dict:
    """One inference request through the serving front-end's signed
    ``POST /infer`` (docs/inference.md request schema): returns
    ``{"id", "outputs", "latency_ms", "replica"}``; raises
    RuntimeError carrying the server's error on 503 (queue full),
    504 (request timeout), or 500 (replica failure)."""
    import numpy as np

    return _post_json(addr, port, "/infer",
                      {"inputs": np.asarray(inputs).tolist()},
                      secret=secret, timeout=timeout)


def get_serving(addr: str, port: int, secret: Optional[bytes] = None,
                timeout: float = 10.0) -> dict:
    """The serving status page from ``GET /serving``: broker window
    stats (queue depth, windowed p50/p99), SLO knobs, and the
    autoscaler's world/events when one is attached."""
    import json

    with _request("GET", addr, port, "/serving", secret=secret,
                  timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def serve_pull(addr: str, port: int, replica_id: str, max_batch: int,
               wait_ms: float = 0.0, secret: Optional[bytes] = None,
               timeout: float = 40.0) -> dict:
    """Remote-replica pull (serving/replica.py RemoteSource): long-poll
    up to ``wait_ms`` for a batch of pending requests."""
    return _post_json(addr, port, "/serving/pull",
                      {"replica_id": str(replica_id),
                       "max_batch": int(max_batch),
                       "wait_ms": float(wait_ms)},
                      secret=secret, timeout=timeout)


def serve_result(addr: str, port: int, replica_id: str, results,
                 secret: Optional[bytes] = None,
                 timeout: float = 30.0) -> dict:
    """Remote-replica completion post: ``results`` is a list of
    ``{"id", "output"}`` (or ``{"id", "error"}``) records.  Retried on
    transient failures — safe because the broker resolves each request
    exactly once and drops duplicates — so one flaky connection doesn't
    strand a computed answer."""
    return _post_json(addr, port, "/serving/result",
                      {"replica_id": str(replica_id),
                       "results": list(results)},
                      secret=secret, timeout=timeout,
                      retries=env_util.get_int(
                          env_util.HVD_HTTP_RETRIES,
                          env_util.DEFAULT_HTTP_RETRIES))


class RemoteStore:
    """The RendezvousServer's in-process store surface (put / get /
    delete / scope_items / clear_scope / health_report / ...) over
    HTTP, with its own ordered failover across ``addrs``.

    This is what detaches the :class:`~horovod_tpu.elastic.driver.
    ElasticDriver` from the rendezvous process: pointed at
    ``[(primary), (standby)]`` it keeps committing epochs through a
    primary death (docs/control_plane.md), with the server-side epoch
    fence surfacing as :class:`~horovod_tpu.run.http_server.
    EpochFencedError` exactly like the in-process path."""

    def __init__(self, addrs: Sequence[Tuple[str, int]],
                 secret: Optional[bytes] = None):
        self.addrs: List[Tuple[str, int]] = [
            (a, int(p)) for a, p in addrs]
        if not self.addrs:
            raise ValueError("RemoteStore needs at least one address")
        self.secret = secret
        self._active = 0
        self._lock = threading.Lock()

    @property
    def active_addr(self) -> Tuple[str, int]:
        with self._lock:
            return self.addrs[self._active]

    def _call(self, fn):
        """Run ``fn(addr, port)`` against the active address, walking
        the list on transport failure (HTTP error replies are real
        answers from a live server and never fail over)."""
        with self._lock:
            start = self._active
        last_err: Optional[BaseException] = None
        for i in range(len(self.addrs)):
            idx = (start + i) % len(self.addrs)
            addr, port = self.addrs[idx]
            try:
                out = fn(addr, port)
            except urllib.error.HTTPError:
                raise
            except (urllib.error.URLError, OSError) as e:
                last_err = e
                if i + 1 < len(self.addrs):
                    _record_counter("CP_FAILOVERS")
                    log.warning("control store %s:%d unreachable; trying "
                                "%s:%d", addr, port,
                                *self.addrs[(idx + 1) % len(self.addrs)])
                continue
            with self._lock:
                self._active = idx
            return out
        assert last_err is not None
        raise last_err

    def put(self, scope: str, key: str, value: bytes) -> None:
        def go(addr, port):
            try:
                put_kv(addr, port, scope, key, value, secret=self.secret,
                       retry=True)
            except urllib.error.HTTPError as e:
                if e.code == 409:
                    from .http_server import EpochFencedError

                    raise EpochFencedError(
                        e.read().decode() or "epoch write fenced")
                raise
        self._call(go)

    def get(self, scope: str, key: str) -> Optional[bytes]:
        return self._call(lambda a, p: get_kv(a, p, scope, key,
                                              secret=self.secret))

    def delete(self, scope: str, key: str) -> None:
        self._call(lambda a, p: delete_kv(a, p, scope, key, self.secret))

    def clear_scope(self, scope: str) -> None:
        self._call(lambda a, p: delete_scope(a, p, scope,
                                             secret=self.secret))

    def scope_items(self, scope: str) -> Dict[str, bytes]:
        out = self._call(lambda a, p: get_scope(a, p, scope,
                                                secret=self.secret))
        return out["entries"]

    def scope_since(self, scope: str,
                    since: Optional[int] = None) -> dict:
        return self._call(lambda a, p: get_scope(a, p, scope, since=since,
                                                 secret=self.secret))

    def health_report(self) -> dict:
        return self._call(lambda a, p: get_health(a, p,
                                                  secret=self.secret))

    def membership_report(self) -> dict:
        return self._call(lambda a, p: get_membership(a, p,
                                                      secret=self.secret))


def get_metrics(addr: str, port: int, secret: Optional[bytes] = None,
                json_form: bool = False) -> str:
    """Scrape the launcher's aggregated metrics: Prometheus text from
    ``GET /metrics`` (or the merged JSON snapshots from
    ``GET /metrics.json``), signed like every other rendezvous request."""
    path = "/metrics.json" if json_form else "/metrics"
    with _request("GET", addr, port, path, secret=secret) as resp:
        return resp.read().decode()
