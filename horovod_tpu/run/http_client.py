"""Client for the rendezvous KV store (reference
horovod/run/http/http_client.py: read_data_from_kvstore /
put_data_into_kvstore)."""

from __future__ import annotations

import time
import urllib.error
import urllib.request
from typing import Optional

from .http_server import SECRET_HEADER, sign


def _request(method: str, addr: str, port: int, path: str,
             body: bytes = b"", secret: Optional[bytes] = None,
             timeout: float = 10.0):
    url = f"http://{addr}:{port}{path}"
    req = urllib.request.Request(url, data=body if method == "PUT" else None,
                                 method=method)
    if secret is not None:
        req.add_header(SECRET_HEADER, sign(secret, path, body))
    return urllib.request.urlopen(req, timeout=timeout)


def put_kv(addr: str, port: int, scope: str, key: str, value: bytes,
           secret: Optional[bytes] = None) -> None:
    with _request("PUT", addr, port, f"/{scope}/{key}", value, secret):
        pass


def get_kv(addr: str, port: int, scope: str, key: str,
           secret: Optional[bytes] = None,
           wait: bool = False, timeout: float = 60.0) -> Optional[bytes]:
    """GET, optionally polling until the key appears (rendezvous wait)."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            with _request("GET", addr, port, f"/{scope}/{key}",
                          secret=secret) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404 and wait and time.monotonic() < deadline:
                time.sleep(0.1)
                continue
            if e.code == 404:
                return None
            raise


def delete_scope(addr: str, port: int, scope: str,
                 secret: Optional[bytes] = None) -> None:
    with _request("DELETE", addr, port, f"/{scope}", secret=secret):
        pass


def delete_kv(addr: str, port: int, scope: str, key: str,
              secret: Optional[bytes] = None) -> None:
    """Delete one key (the server's DELETE matches exact paths as well as
    scope prefixes) — used by the sanitizer to garbage-collect old
    fingerprints."""
    with _request("DELETE", addr, port, f"/{scope}/{key}", secret=secret):
        pass


def get_sanitizer(addr: str, port: int,
                  secret: Optional[bytes] = None) -> dict:
    """The collective-sanitizer fingerprint table from ``GET /sanitizer``:
    published fingerprints grouped by sequence number, then rank — the
    live who-is-ahead view while chasing a divergence."""
    import json

    with _request("GET", addr, port, "/sanitizer", secret=secret) as resp:
        return json.loads(resp.read().decode())


def get_clock(addr: str, port: int, secret: Optional[bytes] = None,
              timeout: float = 2.0) -> float:
    """The rendezvous server's monotonic clock (µs) from ``GET /clock`` —
    one leg of the replay engine's offset-estimation handshake
    (timeline/replay/clock.py estimates rtt/offset around this call)."""
    import json

    with _request("GET", addr, port, "/clock", secret=secret,
                  timeout=timeout) as resp:
        return float(json.loads(resp.read().decode())["server_us"])


def put_replay_summary(addr: str, port: int, summary: dict,
                       secret: Optional[bytes] = None) -> None:
    """Publish a replay summary (scripts/hvd_replay.py output) so
    ``GET /replay`` on the rendezvous server serves it."""
    import json

    put_kv(addr, port, "replay", "summary",
           json.dumps(summary).encode(), secret=secret)


def get_replay(addr: str, port: int,
               secret: Optional[bytes] = None) -> Optional[dict]:
    """The latest replay summary from ``GET /replay`` (None if nothing
    has been published yet)."""
    import json

    try:
        with _request("GET", addr, port, "/replay", secret=secret) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None
        raise


def get_metrics(addr: str, port: int, secret: Optional[bytes] = None,
                json_form: bool = False) -> str:
    """Scrape the launcher's aggregated metrics: Prometheus text from
    ``GET /metrics`` (or the merged JSON snapshots from
    ``GET /metrics.json``), signed like every other rendezvous request."""
    path = "/metrics.json" if json_form else "/metrics"
    with _request("GET", addr, port, path, secret=secret) as resp:
        return resp.read().decode()
