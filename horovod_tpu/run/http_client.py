"""Client for the rendezvous KV store (reference
horovod/run/http/http_client.py: read_data_from_kvstore /
put_data_into_kvstore).

Transient-failure policy: every request to the rendezvous server crosses
a real network on a pod, so idempotent requests (GET/DELETE — the server
is a plain KV store) are retried with exponential backoff + jitter on
``URLError`` and 5xx responses.  PUTs are retried only when the caller
opts in (``retry=True``) — the store's PUTs are last-writer-wins
overwrites, so opting in is safe for keys with a single writer (the
abort flag, heartbeat leases).  Knobs: ``HVD_HTTP_RETRIES`` (default 2
retries after the first attempt) and ``HVD_HTTP_BACKOFF_MS`` (default
50 ms base, doubled per attempt).  Retries surface as the
``hvd_http_retries_total`` counter.  The ``HVD_FAULT_SPEC`` harness's
``http_drop`` faults inject here (elastic/faults.py) so the retry path
itself is testable.
"""

from __future__ import annotations

import random
import time
import urllib.error
import urllib.request
from typing import Optional

from ..utils import env as env_util
from .http_server import SECRET_HEADER, sign

#: methods safe to retry without opt-in: the server's GET/DELETE are
#: idempotent (reads and prefix-deletes of a plain KV store)
_IDEMPOTENT_METHODS = ("GET", "DELETE")


def _record_retry() -> None:
    """Count one retried request; never raises (the metrics plane must
    not take down a rendezvous request)."""
    try:
        from .. import metrics

        if metrics.on():
            metrics.HTTP_RETRIES.inc()
    except Exception:  # noqa: BLE001
        pass


def _request(method: str, addr: str, port: int, path: str,
             body: bytes = b"", secret: Optional[bytes] = None,
             timeout: float = 10.0, retries: Optional[int] = None):
    """One HTTP request with bounded retries.  ``retries=None`` applies
    the default policy: ``HVD_HTTP_RETRIES`` for idempotent methods,
    0 for PUTs (callers opt in via an explicit count)."""
    if retries is None:
        retries = env_util.get_int(env_util.HVD_HTTP_RETRIES,
                                   env_util.DEFAULT_HTTP_RETRIES) \
            if method in _IDEMPOTENT_METHODS else 0
    backoff = env_util.get_float(env_util.HVD_HTTP_BACKOFF_MS,
                                 env_util.DEFAULT_HTTP_BACKOFF_MS) / 1000.0
    url = f"http://{addr}:{port}{path}"
    attempt = 0
    while True:
        req = urllib.request.Request(
            url, data=body if method in ("PUT", "POST") else None,
            method=method,
        )
        if secret is not None:
            req.add_header(SECRET_HEADER, sign(secret, path, body))
        try:
            from ..elastic import faults

            faults.on_http(path)  # inside the loop: drops exercise retries
            return urllib.request.urlopen(req, timeout=timeout)
        except urllib.error.HTTPError as e:
            # 4xx (404 rendezvous-miss, 401 bad secret) is a real answer,
            # not a transient — only server errors are retried
            if e.code < 500 or attempt >= retries:
                raise
        except urllib.error.URLError:
            if attempt >= retries:
                raise
        attempt += 1
        _record_retry()
        # full jitter on top of the doubling base: concurrent ranks
        # hammering a recovering server must not re-synchronize
        time.sleep(backoff * (2 ** (attempt - 1))
                   + random.uniform(0.0, backoff))


def put_kv(addr: str, port: int, scope: str, key: str, value: bytes,
           secret: Optional[bytes] = None, retry: bool = False,
           timeout: float = 10.0) -> None:
    """PUT one key.  ``retry=True`` opts this (non-idempotent but
    last-writer-wins) write into the transient-failure retry policy —
    use it for single-writer keys like the abort flag."""
    retries = env_util.get_int(env_util.HVD_HTTP_RETRIES,
                               env_util.DEFAULT_HTTP_RETRIES) if retry else 0
    with _request("PUT", addr, port, f"/{scope}/{key}", value, secret,
                  timeout=timeout, retries=retries):
        pass


def get_kv(addr: str, port: int, scope: str, key: str,
           secret: Optional[bytes] = None,
           wait: bool = False, timeout: float = 60.0) -> Optional[bytes]:
    """GET, optionally polling until the key appears (rendezvous wait).
    The poll backs off from 50 ms toward a 1 s cap so a long rendezvous
    wait is tens of requests, not ``timeout / 0.1`` of them."""
    deadline = time.monotonic() + timeout
    delay = 0.05
    while True:
        try:
            with _request("GET", addr, port, f"/{scope}/{key}",
                          secret=secret) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404 and wait and time.monotonic() < deadline:
                time.sleep(delay)
                delay = min(delay * 1.5, 1.0)
                continue
            if e.code == 404:
                return None
            raise


def delete_scope(addr: str, port: int, scope: str,
                 secret: Optional[bytes] = None) -> None:
    with _request("DELETE", addr, port, f"/{scope}", secret=secret):
        pass


def delete_kv(addr: str, port: int, scope: str, key: str,
              secret: Optional[bytes] = None) -> None:
    """Delete one key (the server's DELETE matches exact paths as well as
    scope prefixes) — used by the sanitizer to garbage-collect old
    fingerprints."""
    with _request("DELETE", addr, port, f"/{scope}/{key}", secret=secret):
        pass


def get_sanitizer(addr: str, port: int,
                  secret: Optional[bytes] = None) -> dict:
    """The collective-sanitizer fingerprint table from ``GET /sanitizer``:
    published fingerprints grouped by sequence number, then rank — the
    live who-is-ahead view while chasing a divergence."""
    import json

    with _request("GET", addr, port, "/sanitizer", secret=secret) as resp:
        return json.loads(resp.read().decode())


def get_health(addr: str, port: int, secret: Optional[bytes] = None,
               timeout: float = 10.0) -> dict:
    """The failure-domain liveness view from ``GET /health``: per-rank
    heartbeat lease age + live/stale/dead verdict (computed on the
    server's clock) and the job-wide abort flag (None when unset)."""
    import json

    with _request("GET", addr, port, "/health", secret=secret,
                  timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def get_membership(addr: str, port: int, secret: Optional[bytes] = None,
                   timeout: float = 10.0) -> dict:
    """The elastic-membership table from ``GET /membership``: the
    committed epoch record (``epoch``/``world``/``controller_addr``),
    pending rejoin announcements, per-epoch ready acks, and the
    flapping-host blocklist (docs/fault_tolerance.md)."""
    import json

    with _request("GET", addr, port, "/membership", secret=secret,
                  timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def get_clock(addr: str, port: int, secret: Optional[bytes] = None,
              timeout: float = 2.0) -> float:
    """The rendezvous server's monotonic clock (µs) from ``GET /clock`` —
    one leg of the replay engine's offset-estimation handshake
    (timeline/replay/clock.py estimates rtt/offset around this call)."""
    import json

    with _request("GET", addr, port, "/clock", secret=secret,
                  timeout=timeout) as resp:
        return float(json.loads(resp.read().decode())["server_us"])


def put_replay_summary(addr: str, port: int, summary: dict,
                       secret: Optional[bytes] = None) -> None:
    """Publish a replay summary (scripts/hvd_replay.py output) so
    ``GET /replay`` on the rendezvous server serves it."""
    import json

    put_kv(addr, port, "replay", "summary",
           json.dumps(summary).encode(), secret=secret)


def put_projection_summary(addr: str, port: int, summary: dict,
                           secret: Optional[bytes] = None) -> None:
    """Publish a digital-twin projection summary (``hvd_replay
    --project`` output, docs/projection.md) so ``GET /projection`` on
    the rendezvous server serves it.  Single writer, last-writer-wins →
    safe to retry."""
    import json

    put_kv(addr, port, "projection", "summary",
           json.dumps(summary).encode(), secret=secret, retry=True)


def get_projection(addr: str, port: int,
                   secret: Optional[bytes] = None,
                   timeout: float = 10.0) -> Optional[dict]:
    """The latest topology-projected summary from ``GET /projection``
    (None if nothing has been published yet): per-target projected step
    time / efficiency / wire formats plus the tracked
    projected-vs-measured accuracy record."""
    import json

    try:
        with _request("GET", addr, port, "/projection", secret=secret,
                      timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None
        raise


def put_autotune_plan(addr: str, port: int, seq: int, record: dict,
                      secret: Optional[bytes] = None) -> None:
    """Publish one profile-guided plan record (applied / verified /
    rolled_back — optim/profile_guided.py) under the rendezvous
    ``autotune`` scope so ``GET /autotune`` renders the per-plan table.
    Single writer (the tuner), last-writer-wins → safe to retry."""
    import json

    put_kv(addr, port, "autotune", f"plan.{int(seq)}",
           json.dumps(record).encode(), secret=secret, retry=True)


def put_profile_summary(addr: str, port: int, rank, summary: dict,
                        secret: Optional[bytes] = None) -> None:
    """Publish one rank's compute-anatomy summary (timeline/profiler.py
    window anatomy) under the rendezvous ``profile`` scope so
    ``GET /profile`` renders the cross-rank aggregate.  Single writer
    per key (the rank), last-writer-wins → safe to retry."""
    import json

    put_kv(addr, port, "profile", str(rank),
           json.dumps(summary).encode(), secret=secret, retry=True)


def get_profile(addr: str, port: int, secret: Optional[bytes] = None,
                timeout: float = 10.0) -> dict:
    """The aggregated compute-anatomy report from ``GET /profile``:
    per-rank anatomies plus the cross-rank aggregate (per-segment
    slowest rank, mean MFU, worst host gap — docs/profiling.md)."""
    import json

    with _request("GET", addr, port, "/profile", secret=secret,
                  timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def get_autotune(addr: str, port: int, secret: Optional[bytes] = None,
                 timeout: float = 10.0) -> dict:
    """The profile-guided tuning table from ``GET /autotune``: every
    pushed plan record plus the latest predicted/realized speedup pair
    (docs/autotune.md artifact contract)."""
    import json

    with _request("GET", addr, port, "/autotune", secret=secret,
                  timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def get_replay(addr: str, port: int,
               secret: Optional[bytes] = None) -> Optional[dict]:
    """The latest replay summary from ``GET /replay`` (None if nothing
    has been published yet)."""
    import json

    try:
        with _request("GET", addr, port, "/replay", secret=secret) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None
        raise


def _post_json(addr: str, port: int, path: str, payload: dict,
               secret: Optional[bytes] = None,
               timeout: float = 30.0, retries: int = 0) -> dict:
    """One signed JSON POST to a serving route.  POSTs default to no
    transient retries (a retried /infer would double-submit); routes
    that are idempotent server-side (result posts — the broker counts
    and ignores duplicate completions) opt in via ``retries``.
    4xx/5xx replies that carry a JSON body are surfaced as
    RuntimeError with the server's error."""
    import json

    body = json.dumps(payload).encode()
    try:
        with _request("POST", addr, port, path, body, secret,
                      timeout=timeout, retries=retries) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        try:
            detail = json.loads(e.read().decode()).get("error")
        except Exception:  # noqa: BLE001
            detail = None
        raise RuntimeError(
            f"POST {path} -> {e.code}"
            + (f": {detail}" if detail else "")) from e


def post_infer(addr: str, port: int, inputs,
               secret: Optional[bytes] = None,
               timeout: float = 30.0) -> dict:
    """One inference request through the serving front-end's signed
    ``POST /infer`` (docs/inference.md request schema): returns
    ``{"id", "outputs", "latency_ms", "replica"}``; raises
    RuntimeError carrying the server's error on 503 (queue full),
    504 (request timeout), or 500 (replica failure)."""
    import numpy as np

    return _post_json(addr, port, "/infer",
                      {"inputs": np.asarray(inputs).tolist()},
                      secret=secret, timeout=timeout)


def get_serving(addr: str, port: int, secret: Optional[bytes] = None,
                timeout: float = 10.0) -> dict:
    """The serving status page from ``GET /serving``: broker window
    stats (queue depth, windowed p50/p99), SLO knobs, and the
    autoscaler's world/events when one is attached."""
    import json

    with _request("GET", addr, port, "/serving", secret=secret,
                  timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def serve_pull(addr: str, port: int, replica_id: str, max_batch: int,
               wait_ms: float = 0.0, secret: Optional[bytes] = None,
               timeout: float = 40.0) -> dict:
    """Remote-replica pull (serving/replica.py RemoteSource): long-poll
    up to ``wait_ms`` for a batch of pending requests."""
    return _post_json(addr, port, "/serving/pull",
                      {"replica_id": str(replica_id),
                       "max_batch": int(max_batch),
                       "wait_ms": float(wait_ms)},
                      secret=secret, timeout=timeout)


def serve_result(addr: str, port: int, replica_id: str, results,
                 secret: Optional[bytes] = None,
                 timeout: float = 30.0) -> dict:
    """Remote-replica completion post: ``results`` is a list of
    ``{"id", "output"}`` (or ``{"id", "error"}``) records.  Retried on
    transient failures — safe because the broker resolves each request
    exactly once and drops duplicates — so one flaky connection doesn't
    strand a computed answer."""
    return _post_json(addr, port, "/serving/result",
                      {"replica_id": str(replica_id),
                       "results": list(results)},
                      secret=secret, timeout=timeout,
                      retries=env_util.get_int(
                          env_util.HVD_HTTP_RETRIES,
                          env_util.DEFAULT_HTTP_RETRIES))


def get_metrics(addr: str, port: int, secret: Optional[bytes] = None,
                json_form: bool = False) -> str:
    """Scrape the launcher's aggregated metrics: Prometheus text from
    ``GET /metrics`` (or the merged JSON snapshots from
    ``GET /metrics.json``), signed like every other rendezvous request."""
    path = "/metrics.json" if json_form else "/metrics"
    with _request("GET", addr, port, path, secret=secret) as resp:
        return resp.read().decode()
