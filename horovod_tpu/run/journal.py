"""Mutation journal + warm-standby rendezvous server (HA control plane).

Every subsystem built in PRs 1-12 — elastic membership, coordinated
abort, autotune plans, serving state — lives in the launcher's
rendezvous KV store, which made the launcher a single point of failure:
its death killed an ``--elastic`` job that was otherwise perfectly able
to continue.  This module is the survivability half of the control-plane
tier (docs/control_plane.md):

* :class:`Journal` — an append-only JSONL log of KV mutations.  The
  primary :class:`~horovod_tpu.run.http_server.RendezvousServer` (given
  ``journal_path``, usually via ``HVD_RENDEZVOUS_JOURNAL``) appends one
  record per put/delete/scope-clear **under the owning shard's lock**,
  so the log is a faithful per-key linearization.  High-churn,
  reconstructible scopes (``metrics``, ``sanitizer``, ``profile``,
  ``health``) are excluded by default: leases re-renew within one
  heartbeat interval of a failover and snapshots re-push, so journaling
  them would only bloat the log.
* :class:`JournalTailer` / :func:`read_entries` — replay: a tailer
  thread follows the journal (including across partial trailing lines
  mid-append) and applies each record to a store.
* :class:`StandbyServer` — a full RendezvousServer that tails the
  primary's journal into its own sharded store.  It serves the same
  HTTP surface with the same secret; when the primary dies, clients
  walk the ordered ``HVD_RENDEZVOUS_ADDRS`` list (run/http_client.py
  failover) and land here with membership epochs, the abort flag, and
  autotune/serving state intact.  Split-brain is prevented by **epoch
  fencing** in the server itself: ``/membership/epoch`` writes that do
  not advance the committed epoch are rejected with 409, so a stale
  primary resurrected after a takeover cannot roll the world back.

Run a standby out-of-process with ``scripts/hvd_standby.py`` (the
journal path must be reachable from both hosts — shared filesystem or a
synced copy).
"""

from __future__ import annotations

import base64
import json
import os
import threading
import time
from typing import List, Optional, Tuple

from ..utils.logging import get_logger
from .store import split_path

log = get_logger(__name__)

#: scopes whose traffic is high-churn and reconstructible after a
#: failover (leases re-renew, snapshots re-push, fingerprints re-check).
#: ``shard`` is raw peer-snapshot bytes (elastic/peerstate.py): they
#: live on the PEER workers' shard servers, are re-pushed by the next
#: snapshot, and must never bloat a journal — only their manifests
#: (the journaled ``peerstate`` scope) need to survive a failover.
JOURNAL_EXCLUDED_SCOPES = frozenset(
    {"metrics", "sanitizer", "profile", "health", "shard"})


class Journal:
    """Append-only JSONL journal of KV mutations.

    One record per line: ``{"op": "put"|"del"|"clear", "p": path,
    "t": wall-clock, ["v": base64 value]}``.  ``record`` is called with
    the owning shard lock held (run/store.py), so per-key ordering in
    the file matches the store; the internal lock serializes appends
    across shards."""

    def __init__(self, path: str,
                 exclude: frozenset = JOURNAL_EXCLUDED_SCOPES):
        self.path = str(path)
        self.exclude = frozenset(exclude)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(self.path, "ab")
        self._closed = False
        self.records = 0

    def record(self, op: str, path: str,
               value: Optional[bytes] = None) -> None:
        if split_path(path)[0] in self.exclude:
            return
        rec = {"op": op, "p": path, "t": time.time()}
        if value is not None:
            rec["v"] = base64.b64encode(value).decode()
        line = (json.dumps(rec) + "\n").encode()
        with self._lock:
            if self._closed:
                # a straggling keep-alive handler thread after stop():
                # the mutation is lost WITH the server, which is fine —
                # raising here would 500 a teardown-window request
                return
            self._f.write(line)
            self._f.flush()
            self.records += 1

    def close(self) -> None:
        with self._lock:
            self._closed = True
            try:
                self._f.close()
            except ValueError:
                pass


def read_entries(path: str, offset: int = 0) -> Tuple[List[dict], int]:
    """Read complete journal records from ``offset``; returns the
    decoded records and the new offset.  A partial trailing line (the
    primary mid-append) is left for the next call; a corrupt complete
    line is skipped with a warning rather than wedging the tailer."""
    try:
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read()
    except FileNotFoundError:
        return [], offset
    if not data:
        return [], offset
    end = data.rfind(b"\n")
    if end < 0:
        return [], offset
    entries: List[dict] = []
    for line in data[:end].split(b"\n"):
        if not line.strip():
            continue
        try:
            entries.append(json.loads(line))
        except ValueError:
            log.warning("journal: skipping corrupt record at ~%d bytes",
                        offset)
    return entries, offset + end + 1


def apply_entry(store, rec: dict) -> None:
    """Apply one journal record to a ShardedKVStore.  Epoch writes are
    fenced at replay time too: a journal poisoned by a stale writer (a
    resurrected primary appending a regressed commit) must not roll a
    standby's committed epoch back — the skip mirrors the 409 the live
    surface would have answered."""
    op = rec.get("op")
    path = rec.get("p")
    if not isinstance(path, str):
        return
    value = None
    if "v" in rec:
        try:
            value = base64.b64decode(rec["v"])
        except (ValueError, TypeError):
            return
    if op == "put" and value is not None:
        from .http_server import EPOCH_PATH, _epoch_of

        if path == EPOCH_PATH:
            cur_raw = store.get(EPOCH_PATH)
            if cur_raw is not None:
                cur, new = _epoch_of(cur_raw), _epoch_of(value)
                if cur is not None and (new is None or new < cur):
                    log.warning("journal replay: skipping regressed "
                                "membership epoch write (%s < %s)", new, cur)
                    return
    store.apply_replayed(op, path, value)


def replay(path: str, store) -> int:
    """Replay a whole journal into ``store``; returns the record count
    (the fast-recovery path and the unit-test surface)."""
    entries, _ = read_entries(path)
    for rec in entries:
        apply_entry(store, rec)
    return len(entries)


class JournalTailer(threading.Thread):
    """Follow a growing journal file, applying records to ``store``."""

    def __init__(self, path: str, store, poll_seconds: float = 0.05):
        super().__init__(daemon=True, name="hvd-journal-tailer")
        self.path = str(path)
        self.store = store
        self.poll_seconds = float(poll_seconds)
        self.offset = 0
        self.applied = 0
        self._stop_event = threading.Event()

    def catch_up(self) -> int:
        """Apply everything currently in the journal; returns how many
        records were applied this call."""
        entries, self.offset = read_entries(self.path, self.offset)
        for rec in entries:
            apply_entry(self.store, rec)
        self.applied += len(entries)
        return len(entries)

    def run(self) -> None:
        while not self._stop_event.is_set():
            if not self.catch_up():
                self._stop_event.wait(self.poll_seconds)
        self.catch_up()  # drain what arrived before the stop

    def stop(self) -> None:
        self._stop_event.set()


class StandbyServer:
    """A warm-standby rendezvous server: tails the primary's journal
    into its own store and serves the identical HTTP surface, so
    clients that fail over via ``HVD_RENDEZVOUS_ADDRS`` resume against
    live membership/abort/autotune state."""

    def __init__(self, journal_path: str, secret: Optional[bytes] = None,
                 port: int = 0, poll_seconds: float = 0.05):
        from .http_server import RendezvousServer

        # the standby never journals: replaying a replayed journal into
        # a third server is an operator decision, not a default loop
        self.server = RendezvousServer(secret=secret, port=port)
        self.tailer = JournalTailer(journal_path, self.server.store,
                                    poll_seconds=poll_seconds)

    @property
    def port(self) -> int:
        return self.server.port

    @property
    def applied(self) -> int:
        return self.tailer.applied

    def start(self) -> int:
        self.tailer.catch_up()  # warm before serving
        self.tailer.start()
        port = self.server.start()
        log.info("standby rendezvous on port %d (journal %s, %d records "
                 "replayed)", port, self.tailer.path, self.applied)
        return port

    def stop(self) -> None:
        self.tailer.stop()
        self.server.stop()
