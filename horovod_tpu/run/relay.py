"""Per-host relay: aggregate control-plane traffic into batched PUTs.

Every rank used to talk to the launcher's rendezvous server directly —
heartbeat renewals, metric snapshots, sanitizer fingerprints — which
put O(ranks) (sanitizer: O(ranks x groups)) requests per interval on
one ``ThreadingHTTPServer``.  The relay tree collapses that to
O(hosts) (docs/control_plane.md): **local rank 0 on each host** runs a
:class:`RelayDaemon` (elected through the same ``HVD_LOCAL_RANK``
topology ``two_level_allreduce`` computes with), local ranks send their
batchable PUTs to it over loopback, and a flusher thread ships the
coalesced buffer upstream as one signed ``PUT /batch`` every
``HVD_RELAY_FLUSH_MS``.

Semantics that make the aggregation safe:

* Only **last-writer-wins** scopes are buffered (``health``,
  ``metrics``, ``sanitizer``): coalescing the buffer to the latest
  value per key is exactly the store's own PUT semantics.  Everything
  else (membership acks, abort flags, serving traffic) passes through
  to the primary synchronously, and GET/DELETE are forwarded verbatim.
* The upstream ``/batch`` reply carries the job-wide **abort flag**;
  the relay caches it and answers local ``/health/`` renewals with it,
  so a rank's one buffered round trip still answers "is the job
  aborting" — the verdict is at most one flush interval staler than a
  direct renewal's.
* Clients **fall back** to the primary when the relay is unreachable
  (:func:`control_endpoint` / :func:`mark_relay_failed`,
  ``hvd_relay_fallbacks_total``): a dead relay degrades to PR 4's
  per-rank traffic, never to silence.

The relay finds its upstream from the ordinary rendezvous wiring
(``HVD_METRICS_KV_ADDR``/``PORT``) and publishes its own address under
the ``relay`` KV scope (key = host slug) for local peers to discover;
upstream flushes ride the failover-aware client, so a relay keeps
working across a warm-standby takeover (``HVD_RENDEZVOUS_ADDRS``).
"""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.error
from http.server import BaseHTTPRequestHandler
from typing import Dict, Optional, Tuple

from ..utils import env as env_util
from ..utils.logging import get_logger
from .http_server import SECRET_HEADER, QuietThreadingHTTPServer, sign

log = get_logger(__name__)

#: KV scope where each host's relay publishes its address
RELAY_SCOPE = "relay"

#: scopes whose PUTs are buffered + batched (last-writer-wins keys with
#: a single writer per key); everything else passes through.  The
#: timeseries scope qualifies because relay-routed history pushes are
#: full self-contained snapshots (metrics/timeseries.py disables the
#: append-delta protocol behind a relay for exactly this reason).  The
#: events scope qualifies because every flight-recorder event carries a
#: unique per-process key (observe/events.py), so coalescing to the
#: latest value per key can never merge two distinct events.
BATCH_SCOPES = frozenset({"health", "metrics", "sanitizer", "timeseries",
                          "events"})


def host_slug() -> str:
    """Stable per-host identity for relay election/discovery: the cross
    (host) index when the launcher exported one, else the hostname."""
    cross = env_util.get_str(env_util.HVD_CROSS_RANK)
    if cross is not None:
        return f"node{cross}"
    return socket.gethostname() or "localhost"


def _record(name: str, n: int = 1) -> None:
    try:
        from .. import metrics

        if metrics.on():
            getattr(metrics, name).inc(n)
    except Exception:  # noqa: BLE001 — metrics must not fail the relay
        pass


class _RelayHandler(BaseHTTPRequestHandler):
    """The relay's local HTTP surface: the same KV wire protocol as the
    rendezvous server, so ``put_kv``/``get_kv`` work unchanged against
    it — buffered for batch scopes, proxied for everything else."""

    protocol_version = "HTTP/1.1"
    timeout = 65
    disable_nagle_algorithm = True  # same reasoning as KVStoreHandler

    def _daemon(self) -> "RelayDaemon":
        d = self.server.relay_daemon  # type: ignore[attr-defined]
        if d._stop_event.is_set():
            # stop() ran but this keep-alive connection's handler thread
            # is still alive: a PUT buffered now would never be flushed
            # (the final drain already ran), so the stopped relay must
            # look DEAD to pooled clients — connection aborted routes
            # them through mark_relay_failed to the primary
            raise ConnectionAbortedError("relay daemon stopped")
        return d

    def _verify(self, body: bytes = b"") -> bool:
        secret = self._daemon().secret
        if secret is None:
            return True
        got = self.headers.get(SECRET_HEADER, "")
        import hmac as _hmac

        return _hmac.compare_digest(got, sign(secret, self.path, body))

    def _reply(self, code: int, body: bytes = b"",
               content_type: Optional[str] = None) -> None:
        self.send_response(code)
        if content_type:
            self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _forward(self, method: str, body: bytes = b"") -> None:
        """Pass one request through to the primary, mirroring its
        status and body (the non-batchable traffic path)."""
        d = self._daemon()
        from . import http_client

        try:
            with http_client._request(method, d.upstream_addr,
                                      d.upstream_port, self.path, body,
                                      d.secret) as resp:
                self._reply(resp.status, resp.read())
        except urllib.error.HTTPError as e:
            self._reply(e.code, e.read())
        except urllib.error.URLError:
            self._reply(502, json.dumps(
                {"error": "relay: upstream unreachable"}).encode(),
                content_type="application/json")

    def do_PUT(self) -> None:  # noqa: N802
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._verify(body):
            self._reply(401)
            return
        d = self._daemon()
        scope = self.path.lstrip("/").split("/", 1)[0]
        if scope in d.batch_scopes:
            d.buffer(self.path, body)
            reply: Dict[str, object] = {"relay": True}
            if scope == "health":
                # the batched round trip's abort piggyback, served from
                # the cache the last upstream flush refreshed
                reply["abort"] = d.abort_cache
                reply["server_id"] = d.upstream_id
            self._reply(200, json.dumps(reply).encode(),
                        content_type="application/json")
            return
        self._forward("PUT", body)

    def do_GET(self) -> None:  # noqa: N802
        if not self._verify():
            self._reply(401)
            return
        self._forward("GET")

    def do_DELETE(self) -> None:  # noqa: N802
        if not self._verify():
            self._reply(401)
            return
        self._forward("DELETE")

    def do_POST(self) -> None:  # noqa: N802
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._verify(body):
            self._reply(401)
            return
        self._forward("POST", body)

    def log_message(self, fmt, *args):
        log.debug("relay: " + fmt, *args)


class RelayDaemon:
    """One host's control-plane aggregator (see module docstring)."""

    def __init__(self, upstream_addr: str, upstream_port: int,
                 secret: Optional[bytes] = None, port: Optional[int] = None,
                 flush_ms: Optional[float] = None,
                 batch_scopes: frozenset = BATCH_SCOPES):
        self.upstream_addr = upstream_addr
        self.upstream_port = int(upstream_port)
        self.secret = secret
        self.batch_scopes = frozenset(batch_scopes)
        self.flush_seconds = float(
            flush_ms if flush_ms is not None
            else env_util.get_float(env_util.HVD_RELAY_FLUSH_MS,
                                    env_util.DEFAULT_RELAY_FLUSH_MS)) / 1000.0
        listen_port = int(port if port is not None
                          else env_util.get_int(env_util.HVD_RELAY_PORT, 0))
        self._httpd = QuietThreadingHTTPServer(
            ("0.0.0.0", listen_port), _RelayHandler)
        self._httpd.relay_daemon = self  # type: ignore[attr-defined]
        self._buffer: Dict[str, bytes] = {}
        self._buffer_lock = threading.Lock()
        self.abort_cache: Optional[object] = None
        self.upstream_id: Optional[str] = None
        self.flushes = 0
        self.entries_flushed = 0
        self.flush_errors = 0
        self._stop_event = threading.Event()
        self._serve_thread: Optional[threading.Thread] = None
        self._flush_thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def buffer(self, path: str, value: bytes) -> None:
        """Coalesce one batchable PUT (latest value per key wins — the
        store's own last-writer-wins semantics)."""
        with self._buffer_lock:
            self._buffer[path] = value

    def pending(self) -> int:
        with self._buffer_lock:
            return len(self._buffer)

    def flush_now(self) -> bool:
        """Ship the buffered entries upstream as one ``PUT /batch``
        (also refreshing the abort cache); returns success.  On failure
        the entries are restored — without clobbering anything newer —
        for the next flush to carry."""
        with self._buffer_lock:
            entries = list(self._buffer.items())
            self._buffer.clear()
        from .http_client import put_batch

        try:
            reply = put_batch(self.upstream_addr, self.upstream_port,
                              entries, secret=self.secret, retry=True)
        except Exception as e:  # noqa: BLE001 — the flusher must survive
            self.flush_errors += 1
            log.debug("relay flush failed (%d entries kept): %s",
                      len(entries), e)
            with self._buffer_lock:
                for path, value in entries:
                    self._buffer.setdefault(path, value)
            return False
        self.abort_cache = reply.get("abort")
        self.upstream_id = reply.get("server_id")
        self.flushes += 1
        self.entries_flushed += len(entries)
        _record("RELAY_FLUSHES")
        if entries:
            _record("RELAY_ENTRIES", len(entries))
        return True

    def _flush_loop(self) -> None:
        # idle ticks skip the upstream request unless the abort cache
        # has gone stale (one heartbeat interval): a quiet host costs
        # O(1/interval) upstream requests, a busy one O(1/flush)
        stale_after = max(self.flush_seconds * 2.0, env_util.get_float(
            env_util.HVD_HEARTBEAT_INTERVAL_SECONDS,
            env_util.DEFAULT_HEARTBEAT_INTERVAL_SECONDS))
        last_contact = 0.0
        while not self._stop_event.wait(self.flush_seconds):
            now = time.monotonic()
            if self.pending() or now - last_contact > stale_after:
                if self.flush_now():
                    last_contact = now
        self.flush_now()  # final drain

    def start(self) -> int:
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="hvd-relay")
        self._serve_thread.start()
        self._flush_thread = threading.Thread(
            target=self._flush_loop, daemon=True, name="hvd-relay-flush")
        self._flush_thread.start()
        log.info("relay daemon for host %s on port %d (upstream %s:%d, "
                 "flush %.0f ms)", host_slug(), self.port,
                 self.upstream_addr, self.upstream_port,
                 self.flush_seconds * 1e3)
        return self.port

    def publish(self, addr: Optional[str] = None) -> None:
        """Announce this relay under ``/relay/<host>`` so local peers
        discover it (retry=True: single writer, last-writer-wins)."""
        from .http_client import put_kv

        record = json.dumps({
            "addr": addr or "127.0.0.1",
            "port": self.port,
            "host": host_slug(),
        }).encode()
        put_kv(self.upstream_addr, self.upstream_port, RELAY_SCOPE,
               host_slug(), record, secret=self.secret, retry=True)

    def stop(self) -> None:
        self._stop_event.set()
        if self._flush_thread is not None:
            self._flush_thread.join(timeout=5)
        self._httpd.shutdown()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5)
        # release the port: pooled keep-alive clients must see a dead
        # relay as connection-refused (→ their permanent direct
        # fallback), not a silent accept-less bind
        self._httpd.server_close()


# ---------------------------------------------------------------------------
# process-wide wiring: election (local rank 0) + client-side routing
# ---------------------------------------------------------------------------
_daemon: Optional[RelayDaemon] = None
_endpoint: Optional[Tuple[str, int, bool]] = None
_resolve_lock = threading.Lock()


def enabled() -> bool:
    return env_util.get_bool(env_util.HVD_RELAY)


def start_from_env() -> Optional[RelayDaemon]:
    """Elect + start this host's relay: runs on local rank 0 when
    ``HVD_RELAY=1`` and the launcher rendezvous is wired; no-op (and
    None) everywhere else.  Called by ``core.init()``."""
    global _daemon
    if not enabled() or _daemon is not None:
        return _daemon
    local_rank = env_util.get_int(env_util.HVD_LOCAL_RANK,
                                  env_util.get_int(env_util.HVD_PROCESS_ID,
                                                   0))
    if local_rank != 0:
        return None
    addr = env_util.get_str(env_util.HVD_METRICS_KV_ADDR)
    port = env_util.get_int(env_util.HVD_METRICS_KV_PORT, 0)
    if not addr or not port:
        return None
    secret_hex = env_util.get_str(env_util.HVD_METRICS_SECRET)
    secret = bytes.fromhex(secret_hex) if secret_hex else None
    daemon = RelayDaemon(addr, port, secret=secret)
    daemon.start()
    try:
        daemon.publish()
    except Exception as e:  # noqa: BLE001 — peers fall back to direct
        log.warning("relay address publish failed: %s", e)
    _daemon = daemon
    return daemon


def instance() -> Optional[RelayDaemon]:
    return _daemon


def stop() -> None:
    """Stop this process's relay daemon and drop the cached endpoint
    (core.shutdown / tests)."""
    global _daemon, _endpoint
    with _resolve_lock:
        if _daemon is not None:
            _daemon.stop()
            _daemon = None
        _endpoint = None


def control_endpoint() -> Optional[Tuple[str, int, bool]]:
    """(addr, port, via_relay) that batchable control-plane writes
    should target: this host's relay when one is discoverable, else the
    primary rendezvous directly; None when no rendezvous is wired at
    all.  Resolved once and cached; :func:`mark_relay_failed` drops a
    dead relay back to the direct path."""
    global _endpoint
    if _endpoint is not None:
        return _endpoint
    addr = env_util.get_str(env_util.HVD_METRICS_KV_ADDR)
    port = env_util.get_int(env_util.HVD_METRICS_KV_PORT, 0)
    if not addr or not port:
        return None
    with _resolve_lock:
        if _endpoint is not None:
            return _endpoint
        resolved: Tuple[str, int, bool] = (addr, port, False)
        if enabled():
            if _daemon is not None:
                resolved = ("127.0.0.1", _daemon.port, True)
            else:
                secret_hex = env_util.get_str(env_util.HVD_METRICS_SECRET)
                secret = bytes.fromhex(secret_hex) if secret_hex else None
                from .http_client import get_kv

                try:
                    raw = get_kv(addr, port, RELAY_SCOPE, host_slug(),
                                 secret=secret, wait=True, timeout=5.0)
                except Exception as e:  # noqa: BLE001
                    log.debug("relay discovery failed: %s", e)
                    raw = None
                if raw is not None:
                    try:
                        rec = json.loads(raw)
                        resolved = (str(rec["addr"]), int(rec["port"]), True)
                    except (ValueError, TypeError, KeyError):
                        log.warning("undecodable relay record for host %s; "
                                    "using the primary directly", host_slug())
        _endpoint = resolved
        return resolved


def control_put(direct_addr: str, direct_port: int, scope: str, key: str,
                value: bytes, secret: Optional[bytes] = None,
                want_reply: bool = False):
    """PUT one batchable control-plane key through this host's relay
    when one is resolved, falling back — permanently, via
    :func:`mark_relay_failed` — to the direct path when the relay is
    unreachable.  The ONE copy of the routing that the heartbeat, the
    metrics pusher, and the sanitizer share, so none of them can drift
    into silently losing its traffic behind a dead relay.  Returns the
    parsed JSON reply when ``want_reply`` (relay replies carry
    ``{"relay": true}`` so callers can tell which path answered)."""
    from .http_client import put_kv, put_kv_reply

    def send(addr, port):
        if want_reply:
            return put_kv_reply(addr, port, scope, key, value,
                                secret=secret)
        return put_kv(addr, port, scope, key, value, secret=secret)

    ep = control_endpoint()
    if ep is not None and ep[2]:
        try:
            return send(ep[0], ep[1])
        except (urllib.error.URLError, OSError):
            mark_relay_failed()
    return send(direct_addr, direct_port)


def mark_relay_failed() -> None:
    """A client's request to the relay failed at the transport level:
    fall back to the primary for the rest of this incarnation (the
    pass-through guarantee — a dead relay must not silence a host)."""
    global _endpoint
    with _resolve_lock:
        if _endpoint is not None and _endpoint[2]:
            log.warning("relay at %s:%d unreachable; falling back to the "
                        "primary rendezvous", _endpoint[0], _endpoint[1])
            _record("RELAY_FALLBACKS")
            addr = env_util.get_str(env_util.HVD_METRICS_KV_ADDR)
            port = env_util.get_int(env_util.HVD_METRICS_KV_PORT, 0)
            _endpoint = (addr, port, False) if addr and port else None


def _reset_for_tests() -> None:
    global _daemon, _endpoint
    with _resolve_lock:
        _daemon = None
        _endpoint = None
