"""Sharded key-value store backing the rendezvous server.

PRs 1-12 funneled every control-plane family — health leases, membership
epochs, sanitizer fingerprints, autotune plans, metric snapshots,
serving pulls — through ONE ``dict`` guarded by ONE lock inside
``RendezvousServer``.  At thousand-rank worlds that lock is the
contention point: a sanitizer fingerprint storm serializes behind
heartbeat renewals which serialize behind a 100 KiB metrics snapshot
PUT.  This module replaces it (docs/control_plane.md):

* **Hash-sharded values.**  Keys (``/scope/key`` paths) are distributed
  over ``HVD_CP_SHARDS`` independent ``dict``+lock shards by CRC32 of
  the full path, so traffic in different scopes — and different keys of
  one hot scope — stops contending.  Single-key operations take exactly
  one shard lock; whole-store snapshots (the report builders) take each
  shard lock in turn, never all at once.
* **Per-scope versioning.**  Every mutation bumps its scope's version
  counter and records the key's version (deletes leave bounded
  tombstones), which is what makes the batch read protocol possible:
  ``GET /scope/<name>?since=V`` returns only the keys that changed
  after ``V`` plus the keys removed since — one HTTP round trip instead
  of one per key, with a ``full`` resync answer whenever the cursor
  predates the retained history (server restart, scope clear, pruned
  tombstones).
* **Journal hook.**  When the owning server was given a mutation
  journal (run/journal.py), every put/delete/clear is appended under
  the shard lock, so the journal is a faithful per-key linearization a
  warm-standby server can replay.

The store is process-internal: the HTTP surface, HMAC auth, and the
lease-time stamping stay in run/http_server.py.
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, List, Optional, Tuple

from ..utils import env as env_util

#: tombstones retained per scope before the oldest half is pruned (a
#: pruned window forces ``full`` resync for cursors that predate it)
TOMBSTONE_LIMIT = 1024


def split_path(path: str) -> Tuple[str, str]:
    """``/scope/key...`` → ``(scope, key)`` (key may contain slashes;
    a bare ``/scope`` yields an empty key)."""
    parts = path.lstrip("/").split("/", 1)
    scope = parts[0]
    key = parts[1] if len(parts) > 1 else ""
    return scope, key


class _ScopeMeta:
    """Per-scope version bookkeeping (guarded by the store's meta lock):
    ``version`` is the scope's mutation counter, ``keys`` maps live key →
    version-of-last-write, ``tombs`` maps deleted key → version-of-delete,
    and ``floor`` is the version below which history was discarded (scope
    clear or tombstone pruning) — a ``since`` cursor under the floor can
    only be answered with a full resync."""

    __slots__ = ("version", "keys", "tombs", "floor")

    def __init__(self) -> None:
        self.version = 0
        self.keys: Dict[str, int] = {}
        self.tombs: Dict[str, int] = {}
        self.floor = 0


class ShardedKVStore:
    """N-way sharded path → bytes store with per-scope change tracking."""

    def __init__(self, shards: Optional[int] = None, journal=None):
        n = int(shards if shards is not None
                else env_util.get_int(env_util.HVD_CP_SHARDS,
                                      env_util.DEFAULT_CP_SHARDS))
        self.num_shards = max(n, 1)
        self._shards: List[Dict[str, bytes]] = [
            {} for _ in range(self.num_shards)]
        self._locks = [threading.Lock() for _ in range(self.num_shards)]
        self._meta: Dict[str, _ScopeMeta] = {}
        self._meta_lock = threading.Lock()
        self.journal = journal

    # -- internals -----------------------------------------------------------
    def _shard_of(self, path: str) -> int:
        return zlib.crc32(path.encode()) % self.num_shards

    def _bump(self, path: str, *, delete: bool = False) -> None:
        """Record one mutation in the scope's version history."""
        scope, key = split_path(path)
        with self._meta_lock:
            meta = self._meta.get(scope)
            if meta is None:
                meta = self._meta[scope] = _ScopeMeta()
            meta.version += 1
            if delete:
                meta.keys.pop(key, None)
                meta.tombs[key] = meta.version
                if len(meta.tombs) > TOMBSTONE_LIMIT:
                    # prune the oldest half; cursors older than the
                    # highest pruned version fall back to a full resync
                    drop = sorted(meta.tombs.items(),
                                  key=lambda kv: kv[1])[:len(meta.tombs) // 2]
                    for k, ver in drop:
                        del meta.tombs[k]
                        meta.floor = max(meta.floor, ver)
            else:
                meta.tombs.pop(key, None)
                meta.keys[key] = meta.version

    def _journal(self, op: str, path: str,
                 value: Optional[bytes] = None) -> None:
        if self.journal is not None:
            self.journal.record(op, path, value)

    # -- point operations ----------------------------------------------------
    def get(self, path: str) -> Optional[bytes]:
        i = self._shard_of(path)
        with self._locks[i]:
            return self._shards[i].get(path)

    def put(self, path: str, value: bytes) -> None:
        i = self._shard_of(path)
        with self._locks[i]:
            self._shards[i][path] = value
            self._journal("put", path, value)
        self._bump(path)

    def pop(self, path: str) -> Optional[bytes]:
        i = self._shard_of(path)
        with self._locks[i]:
            old = self._shards[i].pop(path, None)
            if old is not None:
                self._journal("del", path)
        if old is not None:
            self._bump(path, delete=True)
        return old

    def __contains__(self, path: str) -> bool:
        return self.get(path) is not None

    def __len__(self) -> int:
        return sum(len(s) for s in self._shards)

    # -- bulk operations -----------------------------------------------------
    def items(self) -> Dict[str, bytes]:
        """A loosely consistent whole-store snapshot (shard locks taken
        one at a time) — the report builders' input."""
        out: Dict[str, bytes] = {}
        for i in range(self.num_shards):
            with self._locks[i]:
                out.update(self._shards[i])
        return out

    def prefix_items(self, prefix: str) -> Dict[str, bytes]:
        out: Dict[str, bytes] = {}
        for i in range(self.num_shards):
            with self._locks[i]:
                for k, v in self._shards[i].items():
                    if k.startswith(prefix):
                        out[k] = v
        return out

    def delete_matching(self, path: str) -> List[str]:
        """The HTTP DELETE semantics: drop the exact key and every key
        under ``path + '/'``.  Returns the deleted paths."""
        prefix = path.rstrip("/") + "/"
        deleted: List[str] = []
        for i in range(self.num_shards):
            with self._locks[i]:
                shard = self._shards[i]
                hits = [k for k in shard
                        if k.startswith(prefix) or k == path]
                for k in hits:
                    del shard[k]
                    self._journal("del", k)
                deleted.extend(hits)
        for k in deleted:
            self._bump(k, delete=True)
        return deleted

    def clear_scope(self, scope: str) -> None:
        """Drop every key under ``scope`` and reset its change history
        (readers' ``since`` cursors are invalidated → full resync).

        Every shard lock is held (in index order) across the delete AND
        the journal append: a concurrent put journals under its shard's
        lock, so no put can land between the clear emptying the shards
        and the clear reaching the journal — the replayed order matches
        what the primary's store actually observed.  Lock order is
        always shards (ascending) then the journal's internal lock, the
        same order a single put uses, so the two cannot deadlock."""
        prefix = f"/{scope}/"
        for lock in self._locks:
            lock.acquire()
        try:
            for shard in self._shards:
                for k in [k for k in shard if k.startswith(prefix)]:
                    del shard[k]
            self._journal("clear", f"/{scope}")
        finally:
            for lock in reversed(self._locks):
                lock.release()
        with self._meta_lock:
            meta = self._meta.get(scope)
            if meta is not None:
                meta.version += 1
                meta.floor = meta.version
                meta.keys.clear()
                meta.tombs.clear()

    def apply_replayed(self, op: str, path: str,
                       value: Optional[bytes]) -> None:
        """Apply one journal entry on a standby (never re-journaled —
        the standby's store has no journal attached by construction)."""
        if op == "put" and value is not None:
            self.put(path, value)
        elif op == "del":
            self.pop(path)
        elif op == "clear":
            self.clear_scope(split_path(path)[0])

    # -- the batch-read protocol --------------------------------------------
    def scope_version(self, scope: str) -> int:
        with self._meta_lock:
            meta = self._meta.get(scope)
            return meta.version if meta is not None else 0

    def scope_since(self, scope: str,
                    since: Optional[int] = None) -> Dict[str, object]:
        """The ``GET /scope/<name>?since=V`` answer: ``{"version",
        "full", "entries": {key: bytes}, "removed": [keys]}``.

        ``since=None`` (or a cursor outside the retained history — under
        the pruning floor, or AHEAD of the current version, which means
        the cursor came from a different server incarnation) returns a
        full snapshot with ``full=True``; otherwise only the keys whose
        last write is newer than ``since`` plus the tombstoned keys."""
        prefix = f"/{scope}/"
        with self._meta_lock:
            meta = self._meta.get(scope)
            if meta is None:
                return {"version": 0, "full": True, "entries": {},
                        "removed": []}
            version = meta.version
            full = (since is None or since < meta.floor or since > version)
            if full:
                wanted = None
                removed: List[str] = []
            else:
                wanted = [k for k, ver in meta.keys.items() if ver > since]
                removed = [k for k, ver in meta.tombs.items() if ver > since]
        entries: Dict[str, bytes] = {}
        if wanted is None:
            entries = {k[len(prefix):]: v
                       for k, v in self.prefix_items(prefix).items()}
        else:
            for key in wanted:
                val = self.get(prefix + key)
                if val is not None:
                    entries[key] = val
        return {"version": version, "full": bool(full),
                "entries": entries, "removed": sorted(removed)}
