"""``tpurun`` — the launcher CLI.

Re-design of ``horovodrun`` (reference horovod/run/run.py:395-615 arg
groups, :696-740 host parsing, :839-861 _launch_job; gloo_run's per-slot
env + ssh fan-out + output capture + failure kill at
run/gloo_run.py:142-288) for TPU pods:

* one worker **process per host** (each controller owns that host's chips —
  the JAX multi-controller model), not one per slot;
* rendezvous = the HTTP KV store (run/http_server.py) + ``jax.distributed``
  (HVD_COORDINATOR_ADDR), replacing Gloo's HTTPStore/full-mesh bootstrap;
* remote execution via ssh command lines (generated identically for
  string-assertion tests, reference test/test_run.py:259-362 asserts the
  mpirun command line with a mocked runner);
* local hosts ("localhost"/"127.0.0.1") spawn subprocesses directly;
* any worker exiting non-zero kills the whole job
  (reference gloo_run.py:253-259); SIGINT/SIGTERM propagate.

Also provides the in-process API ``horovod_tpu.run.run(fn, ...)``
(reference run/run.py:870-956 func mode: cloudpickled fn shipped through
the KV store, results collected back).
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import random
import secrets as _secrets
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from ..utils import env as env_util
from ..utils.logging import get_logger
from . import config_parser
from .hosts import HostInfo, SlotInfo, allocate_slots, parse_hostfile, parse_hosts
from .http_server import RendezvousServer

log = get_logger(__name__)

LOCAL_HOSTS = ("localhost", "127.0.0.1")


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        "tpurun", description="Launch a horovod_tpu training job",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    parser.add_argument("-v", "--version", action="store_true")
    parser.add_argument("-np", "--num-proc", type=int, dest="np",
                        help="total number of ranks (chips)")
    parser.add_argument("-H", "--hosts", dest="hosts",
                        help="host names and slot counts, e.g. h1:8,h2:8")
    parser.add_argument("--hostfile", dest="hostfile",
                        help="hostfile with lines 'host slots=N'")
    parser.add_argument("--tpu", action="store_true", dest="tpu",
                        help="resolve hosts from TPU pod metadata "
                             "(HVD_TPU_HOSTS / TPU_WORKER_HOSTNAMES / "
                             "GCE metadata) instead of -H")
    parser.add_argument("--output-filename", dest="output_filename",
                        help="per-rank stdout/stderr capture directory")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--config-file", dest="config_file",
                        help="YAML config overriding CLI defaults")
    parser.add_argument("--start-timeout", type=int, default=600)
    parser.add_argument("--ssh-port", type=int, dest="ssh_port")
    parser.add_argument("--disable-cache", action="store_true")
    parser.add_argument("--restarts", type=int, dest="restarts", default=0,
                        help="supervised-restart budget: relaunch the whole "
                             "job up to N times after a failure, with "
                             "exponential backoff; HVD_RESTART_COUNT is "
                             "exported so ElasticState.resume() restores "
                             "the latest checkpoint (docs/fault_tolerance.md)")
    parser.add_argument("--elastic", action="store_true", dest="elastic",
                        help="elastic membership: on a worker failure, "
                             "shrink the world and let survivors rebuild "
                             "in process (no relaunch) instead of killing "
                             "the job; spare hosts that announce at the "
                             "rendezvous are admitted at epoch boundaries "
                             "(docs/fault_tolerance.md).  Composes with "
                             "--restarts: a full relaunch only happens "
                             "when the world would drop below --min-np")
    parser.add_argument("--min-np", type=int, dest="min_np",
                        help="elastic floor: give the job up (fail-stop) "
                             "when the world would shrink below this many "
                             "workers (default 1; HVD_ELASTIC_MIN_NP)")
    parser.add_argument("--serve", action="store_true", dest="serve",
                        help="serving plane: attach the inference "
                             "request router to the launcher rendezvous "
                             "server (signed POST /infer, GET /serving) "
                             "and export the HVD_SERVE_* knobs to "
                             "workers, which pull request batches as "
                             "continuous-batching replicas "
                             "(docs/inference.md).  With --elastic "
                             "+ --serve-autoscale, queue depth and "
                             "p99-vs-SLO headroom grow/shrink the "
                             "replica fleet through membership epochs")
    parser.add_argument("--serve-max-batch", type=int,
                        dest="serve_max_batch",
                        help="continuous batcher admission cap "
                             "(HVD_SERVE_MAX_BATCH)")
    parser.add_argument("--serve-max-wait-ms", type=float,
                        dest="serve_max_wait_ms",
                        help="batch flush deadline from first admit "
                             "(HVD_SERVE_MAX_WAIT_MS)")
    parser.add_argument("--serve-slo-ms", type=float,
                        dest="serve_slo_ms",
                        help="p99 latency objective the autoscaler "
                             "defends (HVD_SERVE_SLO_MS)")
    parser.add_argument("--serve-autoscale", action="store_true",
                        dest="serve_autoscale",
                        help="let the serving autoscaler commit "
                             "grow/shrink membership epochs from load "
                             "(needs --elastic; spares announced via "
                             "join_world are held for it)")
    parser.add_argument("--relay", action="store_true", dest="relay",
                        help="control-plane relay tree: local rank 0 on "
                             "each host aggregates heartbeat renewals, "
                             "metric snapshots, and sanitizer "
                             "fingerprints into batched upstream PUTs "
                             "(HVD_RELAY=1, docs/control_plane.md) — "
                             "steady-state rendezvous traffic drops from "
                             "O(ranks) to O(hosts) requests per interval")
    parser.add_argument("--journal", dest="journal", metavar="PATH",
                        help="append every rendezvous KV mutation to this "
                             "file (HVD_RENDEZVOUS_JOURNAL) so a warm "
                             "standby (scripts/hvd_standby.py) can replay "
                             "it and take over on launcher death; pair "
                             "with HVD_RENDEZVOUS_ADDRS listing "
                             "primary,standby for client failover")
    parser.add_argument("--controller", dest="controller",
                        choices=["auto", "xla", "native"], default="auto",
                        help="eager control plane: 'native' runs the C++ "
                             "negotiation controller (multi-process jobs "
                             "get it by default); 'xla' relies on the "
                             "compiled schedule only")
    parser.add_argument("--dry-run", action="store_true", dest="dry_run",
                        help="print the worker launch plan (env + command "
                             "per process) without spawning anything")
    parser.add_argument("-cb", "--check-build", action="store_true",
                        dest="check_build",
                        help="print available frameworks / controllers / "
                             "tensor operations and exit (reference "
                             "horovodrun --check-build)")
    parser.add_argument("--network-interface", dest="network_interface",
                        help="network interface(s) the host data plane "
                             "advertises on workers (reference "
                             "--network-interface; the first name that "
                             "resolves on each worker wins)")

    group_params = parser.add_argument_group("tuneable parameter arguments")
    group_params.add_argument("--fusion-threshold-mb", type=float,
                              dest="fusion_threshold_mb")
    group_params.add_argument("--cycle-time-ms", type=float,
                              dest="cycle_time_ms")
    group_params.add_argument("--cache-capacity", type=int,
                              dest="cache_capacity")
    group_params.add_argument("--hierarchical-allreduce", action="store_true",
                              dest="hierarchical_allreduce")
    group_params.add_argument("--hierarchical-allgather", action="store_true",
                              dest="hierarchical_allgather")
    group_params.add_argument("--compression", dest="compression",
                              choices=["none", "bf16", "fp16", "int8",
                                       "fp8", "fp8_e5m2"],
                              help="gradient wire format (error-feedback "
                                   "residual carried for the quantized "
                                   "formats; docs/compression.md)")
    group_params.add_argument("--no-error-feedback", action="store_true",
                              dest="no_error_feedback",
                              help="drop the error-feedback residual "
                                   "carry (debug; quantized formats "
                                   "then bias the gradient)")
    group_params.add_argument("--two-level-allreduce", action="store_true",
                              dest="two_level_allreduce",
                              help="ICI reduce-scatter + compressed DCN "
                                   "all-reduce + ICI all-gather gradient "
                                   "path (docs/compression.md)")
    group_params.add_argument("--ring-min-bytes", type=int,
                              dest="ring_min_bytes",
                              help="host-plane payloads at or above this "
                                   "ride the peer ring; below it the "
                                   "coordinator star wins on latency "
                                   "(calibrate with scripts/"
                                   "host_plane_bench.py --crossover)")

    group_at = parser.add_argument_group("autotune arguments")
    group_at.add_argument("--autotune", action="store_true")
    group_at.add_argument("--autotune-log-file", dest="autotune_log_file")
    group_at.add_argument("--autotune-warmup-samples", type=int,
                          dest="autotune_warmup_samples")
    group_at.add_argument("--autotune-steps-per-sample", type=int,
                          dest="autotune_steps_per_sample")
    group_at.add_argument("--autotune-bayes-opt-max-samples", type=int,
                          dest="autotune_bayes_opt_max_samples")
    group_at.add_argument("--autotune-gaussian-process-noise", type=float,
                          dest="autotune_gaussian_process_noise")
    group_at.add_argument("--profile-guided", action="store_true",
                          dest="profile_guided",
                          help="close the replay->autotune loop: plan "
                               "fusion buckets from the job's own trace "
                               "window, apply live, verify predicted vs "
                               "realized (docs/autotune.md; needs "
                               "--timeline-filename)")
    group_at.add_argument("--autotune-window-steps", type=int,
                          dest="autotune_window_steps")
    group_at.add_argument("--autotune-guard-band-pct", type=float,
                          dest="autotune_guard_band_pct")

    group_tl = parser.add_argument_group("timeline arguments")
    group_tl.add_argument("--timeline-filename", dest="timeline_filename")
    group_tl.add_argument("--timeline-mark-cycles", action="store_true",
                          dest="timeline_mark_cycles")
    group_tl.add_argument("--trace-start-step", type=int,
                          dest="trace_start_step")
    group_tl.add_argument("--trace-end-step", type=int, dest="trace_end_step")

    group_st = parser.add_argument_group("stall check arguments")
    group_st.add_argument("--no-stall-check", action="store_true",
                          dest="no_stall_check")
    group_st.add_argument("--stall-check-warning-time-seconds", type=int,
                          dest="stall_check_warning_time_seconds")
    group_st.add_argument("--stall-check-shutdown-time-seconds", type=int,
                          dest="stall_check_shutdown_time_seconds")

    group_log = parser.add_argument_group("logging arguments")
    group_log.add_argument("--log-level", dest="log_level",
                           choices=["trace", "debug", "info", "warning",
                                    "error", "fatal"])
    group_log.add_argument("--log-hide-timestamp", action="store_true",
                           dest="log_hide_timestamp")

    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the training command")

    args = parser.parse_args(argv)

    if args.config_file:
        import yaml

        with open(args.config_file) as f:
            cfg = yaml.safe_load(f) or {}
        explicit = _explicit_dests(argv if argv is not None else sys.argv[1:],
                                   parser)
        config_parser.set_args_from_config(args, cfg, explicit)
    return args


def _explicit_dests(argv: List[str], parser: argparse.ArgumentParser) -> set:
    """Which dests the user passed on the command line (so YAML doesn't
    override them — reference run/run.py:609-613 override_args)."""
    explicit = set()
    opts = {}
    for action in parser._actions:  # noqa: SLF001
        for opt in action.option_strings:
            opts[opt] = action.dest
    for tok in argv:
        key = tok.split("=")[0]
        if key in opts:
            explicit.add(opts[key])
    return explicit


def _resolve_hosts(args) -> List[HostInfo]:
    if args.hostfile:
        return parse_hostfile(args.hostfile)
    if args.hosts:
        return parse_hosts(args.hosts)
    if getattr(args, "tpu", False):
        # pod-slice host resolution from TPU metadata/env (SURVEY §7.1's
        # replacement for the reference's ssh/NIC probing,
        # reference run/run.py:62-115,198-268)
        from .discovery import discover_tpu_hosts

        found = discover_tpu_hosts()
        if found:
            return found
        raise RuntimeError(
            "--tpu: no pod hosts discoverable (HVD_TPU_HOSTS / "
            "TPU_WORKER_HOSTNAMES / metadata server all empty)"
        )
    # default: all local slots on this machine
    np = args.np or 1
    return [HostInfo("localhost", np)]


def worker_envs(slots: List[SlotInfo], base_env: Dict[str, str],
                coordinator: str, *, controller: str = "auto",
                controller_addr: Optional[str] = None,
                elastic: bool = False) -> List[Dict[str, str]]:
    """Per-host worker env dicts (reference gloo_run.py:210-216 sets
    HOROVOD_RANK/SIZE/LOCAL_RANK/... per slot; here per host-process, with
    the slot table embedded for the chips it owns).

    ``controller``: the eager control plane.  'auto' = native for
    multi-process jobs (the reference always stands up its controller,
    operations.cc:596-640), xla for single-process.  The native controller
    server runs inside process 0 (runtime/eager_controller.py); workers
    dial ``controller_addr``.
    """
    hosts: Dict[str, List[SlotInfo]] = {}
    for s in slots:
        hosts.setdefault(s.hostname, []).append(s)
    if controller == "auto":
        controller = "native" if len(hosts) > 1 else "xla"
    envs = []
    for pid, (hostname, host_slots) in enumerate(hosts.items()):
        first = host_slots[0]
        env = dict(base_env)
        env.update({
            env_util.HVD_RANK: str(first.rank),
            env_util.HVD_SIZE: str(first.size),
            env_util.HVD_LOCAL_RANK: "0",
            env_util.HVD_LOCAL_SIZE: str(len(host_slots)),
            env_util.HVD_CROSS_RANK: str(first.cross_rank),
            env_util.HVD_CROSS_SIZE: str(first.cross_size),
            env_util.HVD_NUM_PROCESSES: str(len(hosts)),
            env_util.HVD_PROCESS_ID: str(pid),
            env_util.HVD_CONTROLLER: controller,
            env_util.HVD_CPU_OPERATIONS: "xla",
        })
        if elastic:
            # membership identity: the worker id survives epoch changes
            # while HVD_PROCESS_ID is re-assigned densely per epoch
            env[env_util.HVD_ELASTIC] = "1"
            env[env_util.HVD_ELASTIC_WORKER_ID] = str(pid)
        if controller == "native" and controller_addr:
            env["HVD_CONTROLLER_ADDR"] = controller_addr
            # the launcher hosts the server (port 0 bound locally — no
            # remote-port race); workers are clients only
            env["HVD_CONTROLLER_SERVER"] = "external"
            # the address peers dial for THIS worker's ring listener:
            # the launcher knows each worker's host; self-resolution
            # (gethostname) can pick a wrong interface on multi-NIC VMs
            env["HVD_RING_HOST"] = hostname
        if len(hosts) > 1:
            env[env_util.HVD_COORDINATOR_ADDR] = coordinator
        envs.append(env)
    return envs


def ssh_command(hostname: str, env: Dict[str, str], command: List[str],
                ssh_port: Optional[int] = None, cwd: Optional[str] = None) -> str:
    """The remote launch line (reference gloo_run.py:142-259 ssh fan-out;
    kept as a pure string builder so tests can assert it without a
    cluster, reference test/test_run.py:259-362)."""
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in sorted(env.items())
    )
    cd = f"cd {shlex.quote(cwd)} > /dev/null 2>&1 && " if cwd else ""
    port = f" -p {ssh_port}" if ssh_port else ""
    inner = f"{cd}env {exports} {' '.join(shlex.quote(c) for c in command)}"
    return (
        f"ssh -o PasswordAuthentication=no -o StrictHostKeyChecking=no"
        f"{port} {hostname} {shlex.quote(inner)}"
    )


class _Job:
    def __init__(self) -> None:
        self.procs: List[subprocess.Popen] = []
        self.failed: Optional[int] = None
        self.interrupted = False  # operator signal: never auto-restart
        self._lock = threading.Lock()

    def _signal_survivors(self, sig) -> int:
        alive = 0
        with self._lock:
            for p in self.procs:
                if p.poll() is None:
                    alive += 1
                    try:
                        p.send_signal(sig)
                    except OSError:
                        pass
        return alive

    def all_exited(self) -> bool:
        with self._lock:
            return all(p.poll() is not None for p in self.procs)

    def kill_all(self, sig=signal.SIGTERM, *, grace: Optional[float] = None,
                 escalate: bool = True) -> None:
        """Terminate every live worker, escalating SIGTERM→SIGKILL after
        ``grace`` seconds (``HVD_TERM_GRACE_SECONDS``, default 5).  A
        worker wedged in a collective ignores SIGTERM; without the
        escalation the launcher used to leak it."""
        if not self._signal_survivors(sig):
            return
        if not escalate or sig == signal.SIGKILL:
            return
        if grace is None:
            grace = env_util.get_float(env_util.HVD_TERM_GRACE_SECONDS,
                                       env_util.DEFAULT_TERM_GRACE_SECONDS)
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline:
            if self.all_exited():
                return
            time.sleep(0.1)
        survivors = self._signal_survivors(signal.SIGKILL)
        if survivors:
            log.warning("%d worker(s) ignored SIGTERM for %.1fs; sent "
                        "SIGKILL", survivors, grace)


def _supervise(job: _Job, rdv_server: Optional[RendezvousServer],
               poll_interval: float = 0.2) -> int:
    """Event-driven wait on the worker set: react to the FIRST failure,
    whichever rank it is (the old loop blocked in ``procs[0].wait()``, so
    a crashed rank 3 went unnoticed while rank 0 idled in a collective).

    On a failure: publish the coordinated-abort flag on the rendezvous
    server (each rank's heartbeat polls it and raises HorovodAbortError
    at the next dispatch — elastic/heartbeat.py), give survivors one
    heartbeat window to exit with that root cause, then escalate
    SIGTERM→SIGKILL on whatever is left."""
    procs = job.procs
    while True:
        states = [p.poll() for p in procs]
        failures = [(pid, c) for pid, c in enumerate(states)
                    if c is not None and c != 0]
        if failures:
            pid, code = failures[0]
            log.error("worker %d exited with code %d; aborting job",
                      pid, code)
            job.failed = pid
            hb_interval = env_util.get_float(
                env_util.HVD_HEARTBEAT_INTERVAL_SECONDS,
                env_util.DEFAULT_HEARTBEAT_INTERVAL_SECONDS)
            if rdv_server is not None:
                # note: ..elastic re-exports the abort() FUNCTION over the
                # submodule attribute, so names are imported directly
                from ..elastic.abort import ABORT_KEY, ABORT_SCOPE, make_flag

                flag = make_flag(
                    f"worker {pid} exited with code {code}",
                    rank=pid, source="launcher",
                )
                # flight recorder: the publish event rides the flag so
                # observers chain onto it, and the restart loop chains
                # restart.attempt onto it too (observe/events.py)
                try:
                    from ..observe import events as events_mod

                    eid = events_mod.record_event(
                        "abort.publish", severity="critical",
                        payload={"reason": flag["reason"],
                                 "source": "launcher",
                                 "exit_code": code},
                        rank=pid)
                    if eid:
                        flag["event_id"] = eid
                        corr = events_mod.correlation_of(eid)
                        if corr:
                            flag["correlation_id"] = corr
                        job.abort_event_id = eid
                except Exception:  # noqa: BLE001 — best-effort
                    pass
                rdv_server.put(ABORT_SCOPE, ABORT_KEY,
                               json.dumps(flag).encode())
                # survivors poll the flag once per heartbeat interval and
                # raise at their next step/dispatch seam; the exit budget
                # is two intervals plus the term grace (a rank mid-save
                # needs the slack), matching the documented bound of
                # 2 x HVD_HEARTBEAT_INTERVAL_SECONDS + grace
                grace = env_util.get_float(
                    env_util.HVD_TERM_GRACE_SECONDS,
                    env_util.DEFAULT_TERM_GRACE_SECONDS)
                deadline = time.monotonic() + 2.0 * hb_interval + grace
                while time.monotonic() < deadline and not job.all_exited():
                    time.sleep(0.1)
            job.kill_all()
            return code
        if all(c == 0 for c in states):
            return 0
        time.sleep(poll_interval)


def _launch_attempt(args, hosts: List[str], envs: List[Dict[str, str]],
                    rdv_server: Optional[RendezvousServer],
                    attempt: int = 0, driver=None) -> int:
    """Spawn one incarnation of the worker set and supervise it to exit.
    With an elastic ``driver`` the supervision is membership-driven
    (shrink/grow instead of kill-on-first-failure)."""
    job = _Job()

    def handler(signum, frame):
        job.interrupted = True
        job.kill_all(signal.SIGTERM)

    old_int = signal.signal(signal.SIGINT, handler)
    old_term = signal.signal(signal.SIGTERM, handler)

    threads = []
    try:
        for pid, hostname in enumerate(hosts):
            wenv = envs[pid]
            if hostname in LOCAL_HOSTS:
                full_env = dict(os.environ)
                full_env.update(wenv)
                proc = subprocess.Popen(
                    args.command, env=full_env,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True,
                )
            else:
                cmd = ssh_command(hostname, wenv, args.command,
                                  ssh_port=args.ssh_port, cwd=os.getcwd())
                proc = subprocess.Popen(
                    cmd, shell=True,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True,
                )
            job.procs.append(proc)

            t = threading.Thread(
                target=_pump_output,
                args=(proc, pid, args.output_filename, attempt),
                daemon=True,
            )
            t.start()
            threads.append(t)

        rc = driver.supervise(job) if driver is not None \
            else _supervise(job, rdv_server)
        for t in threads:
            t.join(timeout=5)
        if job.interrupted and rc == 0:
            rc = 130  # operator interrupt must not read as success
        args._interrupted = job.interrupted  # noqa: SLF001 — restart gate
        args._abort_event_id = getattr(  # noqa: SLF001 — restart.attempt
            job, "abort_event_id", None)  # chains onto this publish
        return rc
    finally:
        signal.signal(signal.SIGINT, old_int)
        signal.signal(signal.SIGTERM, old_term)


def launch_job(args, slots: List[SlotInfo], env: Dict[str, str]) -> int:
    """Stand up the job's rendezvous plane, then spawn + supervise the
    worker set, relaunching up to ``--restarts`` times on failure
    (reference gloo_run.py:142-259, plus the failure-domain runtime of
    docs/fault_tolerance.md)."""
    hosts = sorted({s.hostname for s in slots},
                   key=[s.hostname for s in slots].index)
    coordinator = f"{socket.gethostname()}:{env_util.get_int('HVD_COORD_PORT', 0) or _free_port()}"

    # Rendezvous/aggregation point: the launcher hosts one server that
    # carries metrics pushes (GET /metrics), sanitizer fingerprints,
    # heartbeat leases + the abort flag (GET /health), and replay
    # summaries.  It exists whenever metrics OR heartbeats want it.
    rdv_server = None
    metrics_on = env_util.parse_bool(
        env.get(env_util.HVD_METRICS, os.environ.get(env_util.HVD_METRICS)),
        True,
    )
    heartbeat_on = not env_util.parse_bool(
        env.get(env_util.HVD_HEARTBEAT_DISABLE,
                os.environ.get(env_util.HVD_HEARTBEAT_DISABLE)),
        False,
    )
    # An operator-provided HVD_METRICS_KV_ADDR means an external
    # aggregation server: forward the operator's values untouched.
    external_sink = env.get(
        env_util.HVD_METRICS_KV_ADDR,
        os.environ.get(env_util.HVD_METRICS_KV_ADDR),
    )
    if not getattr(args, "dry_run", False) and (metrics_on or heartbeat_on) \
            and not external_sink:
        # operator-provided secret (hex) wins so their tooling can sign
        # scrapes; otherwise generate one and LOG it — a secret nobody
        # knows makes the advertised endpoint unusable
        secret_hex = env.get(env_util.HVD_METRICS_SECRET,
                             os.environ.get(env_util.HVD_METRICS_SECRET))
        try:
            rdv_secret = bytes.fromhex(secret_hex) if secret_hex \
                else _secrets.token_bytes(16)
        except ValueError:
            raise ValueError(
                f"{env_util.HVD_METRICS_SECRET} must be hex, got "
                f"{secret_hex!r}"
            )
        journal_path = getattr(args, "journal", None) \
            or env.get(env_util.HVD_RENDEZVOUS_JOURNAL,
                       os.environ.get(env_util.HVD_RENDEZVOUS_JOURNAL))
        rdv_server = RendezvousServer(secret=rdv_secret,
                                      journal_path=journal_path)
        rdv_port = rdv_server.start()
        # flight recorder: launcher-side events land straight in the
        # journaled `events` scope (observe/events.py, GET /events)
        from ..observe import events as events_mod

        events_mod.attach_server(rdv_server)
        rdv_host = "127.0.0.1" if all(h in LOCAL_HOSTS for h in hosts) \
            else socket.gethostname()
        env = dict(env)
        env[env_util.HVD_METRICS_KV_ADDR] = rdv_host
        env[env_util.HVD_METRICS_KV_PORT] = str(rdv_port)
        env[env_util.HVD_METRICS_SECRET] = rdv_secret.hex()
        # ordered failover list for workers: the operator's
        # primary,standby list wins (warm standby via --journal +
        # scripts/hvd_standby.py); otherwise advertise the primary so
        # every client resolves addresses one way
        env.setdefault(
            env_util.HVD_RENDEZVOUS_ADDRS,
            os.environ.get(env_util.HVD_RENDEZVOUS_ADDRS)
            or f"{rdv_host}:{rdv_port}")
        if journal_path:
            log.info("rendezvous journal at %s (standby: "
                     "scripts/hvd_standby.py --journal %s)",
                     journal_path, journal_path)
        if metrics_on:
            # never echo an operator-provided credential into job logs; a
            # generated one must be printed or the endpoint is unusable
            secret_expr = "bytes.fromhex(os.environ['HVD_METRICS_SECRET'])" \
                if secret_hex else f"bytes.fromhex('{rdv_secret.hex()}')"
            log.info(
                "metrics: signed GET http://%s:%d/metrics aggregates all "
                "ranks — e.g. horovod_tpu.run.http_client.get_metrics("
                "'%s', %d, secret=%s)",
                rdv_host, rdv_port, rdv_host, rdv_port,
                secret_expr,
            )
        if heartbeat_on:
            log.info("health: GET http://%s:%d/health reports per-rank "
                     "lease verdicts", rdv_host, rdv_port)
    if getattr(args, "relay", False):
        env = dict(env)
        env[env_util.HVD_RELAY] = "1"

    controller = getattr(args, "controller", "auto") or "auto"
    if controller == "auto":
        controller = "native" if len(hosts) > 1 else "xla"

    if getattr(args, "dry_run", False):
        controller_addr = "<launcher>:<bound-at-launch>" \
            if controller == "native" else None
        envs = worker_envs(slots, env, coordinator, controller=controller,
                           controller_addr=controller_addr,
                           elastic=bool(getattr(args, "elastic", False)))
        for pid, hostname in enumerate(hosts):
            print(f"[dry-run] process {pid} on {hostname}:")
            for k in sorted(set(envs[pid]) - set(env)):
                print(f"  {k}={envs[pid][k]}")
            print(f"  command: {' '.join(args.command)}")
        return 0

    elastic = bool(getattr(args, "elastic", False))
    elastic_store = rdv_server
    if elastic and rdv_server is None:
        # an operator-provided external rendezvous (HVD_METRICS_KV_ADDR
        # + optional HVD_RENDEZVOUS_ADDRS failover list): the driver
        # commits epochs over HTTP instead of in-process — this is the
        # HA deployment where the rendezvous outlives the launcher
        # (docs/control_plane.md)
        ext_port = env.get(env_util.HVD_METRICS_KV_PORT,
                           os.environ.get(env_util.HVD_METRICS_KV_PORT))
        if not external_sink or not ext_port:
            raise RuntimeError(
                "--elastic needs the launcher rendezvous plane: re-enable "
                f"{env_util.HVD_METRICS} or heartbeats, or point "
                f"{env_util.HVD_METRICS_KV_ADDR}/PORT at an external "
                "rendezvous server"
            )
        from .http_client import RemoteStore

        addrs_raw = env.get(env_util.HVD_RENDEZVOUS_ADDRS,
                            os.environ.get(env_util.HVD_RENDEZVOUS_ADDRS))
        addrs = []
        for tok in (addrs_raw or "").split(","):
            tok = tok.strip()
            if tok and ":" in tok:
                host, _, p = tok.rpartition(":")
                try:
                    addrs.append((host, int(p)))
                except ValueError:
                    pass
        if not addrs:
            addrs = [(external_sink, int(ext_port))]
        secret_hex = env.get(env_util.HVD_METRICS_SECRET,
                             os.environ.get(env_util.HVD_METRICS_SECRET))
        elastic_store = RemoteStore(
            addrs, secret=bytes.fromhex(secret_hex) if secret_hex else None)
        log.info("elastic: driving membership through the external "
                 "rendezvous at %s", addrs)
    serve = bool(getattr(args, "serve", False)) \
        or env_util.parse_bool(env.get(env_util.HVD_SERVE), False)
    serve_broker = None
    if serve:
        if rdv_server is None:
            raise RuntimeError(
                "--serve needs the launcher rendezvous plane: re-enable "
                f"{env_util.HVD_METRICS} or heartbeats, and unset any "
                f"external {env_util.HVD_METRICS_KV_ADDR} sink"
            )
        from ..serving.broker import RequestBroker
        from ..serving.frontend import ServingFrontend

        env = dict(env)
        env[env_util.HVD_SERVE] = "1"
        serve_broker = RequestBroker()
        serve_frontend = ServingFrontend(serve_broker)
        rdv_server.attach_serving(serve_frontend)
        log.info(
            "serving: signed POST http://%s:%d/infer routes requests to "
            "the replica fleet; GET http://%s:%d/serving is the status "
            "page (docs/inference.md)",
            env[env_util.HVD_METRICS_KV_ADDR], rdv_server.port,
            env[env_util.HVD_METRICS_KV_ADDR], rdv_server.port,
        )
    # Online anomaly watchdog (observe/watchdog.py, HVD_WATCH=0
    # disables): detectors over the flushed telemetry history, alerts
    # on GET /alerts, auto-armed trace+profile windows on confirmed
    # step-time/straggler regressions.
    watchdog = None
    if rdv_server is not None:
        from ..observe import watchdog as watchdog_mod

        watchdog = watchdog_mod.start_from_env(rdv_server)
        if watchdog is not None:
            log.info("watchdog: GET http://%s:%d/alerts is the alert "
                     "log (docs/observe.md)",
                     env[env_util.HVD_METRICS_KV_ADDR], rdv_server.port)
    restarts = getattr(args, "restarts", 0) or 0
    backoff_base = env_util.get_float(env_util.HVD_RESTART_BACKOFF_SECONDS,
                                      env_util.DEFAULT_RESTART_BACKOFF_SECONDS)
    attempt = 0
    try:
        while True:
            # The native controller server is per-incarnation: a failed
            # attempt leaves half-negotiated state behind, and a restart
            # must rendezvous from scratch.  Elastic jobs go further —
            # the driver owns a fresh ControllerServer per membership
            # EPOCH, so the launcher-level server is skipped entirely.
            ctrl_server = None
            controller_addr = None
            driver = None
            ctrl_host = "127.0.0.1" \
                if all(h in LOCAL_HOSTS for h in hosts) \
                else socket.gethostname()
            if elastic:
                from ..elastic.driver import ElasticDriver

                driver = ElasticDriver(
                    elastic_store, [str(i) for i in range(len(hosts))],
                    min_np=getattr(args, "min_np", None)
                    or env_util.get_int(env_util.HVD_ELASTIC_MIN_NP, 1),
                    controller=controller, controller_host=ctrl_host,
                )
                controller_addr = driver.controller_addr
                if watchdog is not None:
                    # critical straggler alerts can feed this attempt's
                    # driver removal path (HVD_WATCH_EVICT=1)
                    watchdog.attach_driver(driver)
                if serve_broker is not None:
                    # a lossily-removed replica's in-flight requests go
                    # back to the queue for a survivor (zero-drop-on-
                    # crash; drained removals already completed theirs)
                    driver.on_remove = (
                        lambda w, drained, _b=serve_broker:
                        None if drained else _b.requeue(w))
                autoscale = bool(getattr(args, "serve_autoscale", False)) \
                    or env_util.parse_bool(
                        env.get(env_util.HVD_SERVE_AUTOSCALE), False)
                if serve_broker is not None and autoscale:
                    from ..serving.autoscaler import ServingAutoscaler

                    autoscaler = ServingAutoscaler(driver, serve_broker)
                    driver.attach_autoscaler(autoscaler)
                    serve_frontend.autoscaler = autoscaler
                    log.info("serving: autoscaler attached — announced "
                             "spares are held and admitted under load")
            elif controller == "native":
                from ..runtime.controller import ControllerServer

                ctrl_server = ControllerServer(len(hosts), port=0)
                controller_addr = f"{ctrl_host}:{ctrl_server.port}"
            env_attempt = dict(env)
            env_attempt[env_util.HVD_RESTART_COUNT] = str(attempt)
            envs = worker_envs(
                slots, env_attempt, coordinator,
                controller=controller, controller_addr=controller_addr,
                elastic=elastic,
            )
            try:
                rc = _launch_attempt(args, hosts, envs, rdv_server,
                                     attempt=attempt, driver=driver)
            finally:
                if driver is not None:
                    log.info("elastic: final epoch %d, world %s",
                             driver.epoch, driver.world)
                    driver.shutdown()
                if ctrl_server is not None:
                    log.info(
                        "controller: %d cycles, %d cache hits, %d stall "
                        "warnings", ctrl_server.cycles,
                        ctrl_server.cache_hits, ctrl_server.stall_warnings,
                    )
                    ctrl_server.stop()
            if rc == 0 or attempt >= restarts \
                    or getattr(args, "_interrupted", False):
                if rc != 0 and getattr(args, "_interrupted", False):
                    log.info("job interrupted by operator signal; not "
                             "restarting")
                return rc
            attempt += 1
            from .. import metrics as metrics_mod

            if metrics_mod.on():
                metrics_mod.RESTARTS.inc()
            # flight recorder: chain the relaunch onto whichever abort
            # ended the attempt — the launcher's own publish, or the
            # elastic driver's give-up (observe/events.py)
            try:
                from ..observe import events as events_mod

                events_mod.record_event(
                    "restart.attempt", severity="warning",
                    payload={"attempt": attempt, "restarts": restarts,
                             "exit_code": rc},
                    cause_id=getattr(driver, "last_giveup_event_id", None)
                    or getattr(args, "_abort_event_id", None))
            except Exception:  # noqa: BLE001 — best-effort
                pass
            delay = backoff_base * (2 ** (attempt - 1)) \
                + random.uniform(0.0, backoff_base)
            log.warning(
                "restarting job (attempt %d/%d) in %.1fs after exit code "
                "%d; workers resume from their latest checkpoint "
                "(HVD_RESTART_COUNT=%d)", attempt, restarts, delay, rc,
                attempt,
            )
            time.sleep(delay)
            if elastic_store is not None:
                # a stale abort flag, dead lease, or last-attempt
                # membership record must not kill the fresh incarnation
                # at its first heartbeat (works through RemoteStore for
                # an external rendezvous too)
                from .http_server import (
                    ABORT_SCOPE,
                    HEALTH_SCOPE,
                    MEMBERSHIP_SCOPE,
                )

                try:
                    elastic_store.clear_scope(ABORT_SCOPE)
                    elastic_store.clear_scope(HEALTH_SCOPE)
                    elastic_store.clear_scope(MEMBERSHIP_SCOPE)
                except Exception as e:  # noqa: BLE001 — an unreachable
                    log.warning(         # external store: workers' epoch
                        "restart scope reset failed: %s", e)  # filter copes
    finally:
        if watchdog is not None:
            watchdog.stop()
            log.info("watchdog: %d alert(s), %d armed window(s), %d "
                     "eviction(s)", watchdog.alerts_emitted, watchdog.arms,
                     watchdog.evictions)
        if rdv_server is not None:
            rdv_server.stop()


def _pump_output(proc: subprocess.Popen, pid: int,
                 output_dir: Optional[str], attempt: int = 0) -> None:
    """Tag each line with the worker index (mpirun --tag-output style,
    reference mpi_run.py:115-149) and/or tee to per-rank files
    (reference gloo_run.py output capture).  Restart attempts get their
    own files — truncating rank.N.txt on relaunch would destroy the very
    crash diagnostics the restart was for."""
    sink = None
    if output_dir:
        os.makedirs(output_dir, exist_ok=True)
        name = f"rank.{pid}.txt" if attempt == 0 \
            else f"rank.{pid}.restart{attempt}.txt"
        sink = open(os.path.join(output_dir, name), "w")
    assert proc.stdout is not None
    for line in proc.stdout:
        sys.stdout.write(f"[{pid}]<stdout>: {line}")
        sys.stdout.flush()
        if sink:
            sink.write(line)
            sink.flush()
    if sink:
        sink.close()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def check_build() -> str:
    """Availability report (reference run/run.py:289-324 check_build):
    frameworks are import-probed, the controllers/ops reflect this
    build's architecture — XLA collectives over ICI/DCN plus the native
    C++ control/host plane in place of MPI/Gloo/NCCL."""
    import importlib.util

    from .. import __version__
    from ..runtime import native

    def mark(ok):
        return "X" if ok else " "

    def has(mod):
        return importlib.util.find_spec(mod) is not None

    native_ok = native.available()
    return f"""\
horovod_tpu v{__version__}:

Available Frameworks:
    [{mark(has('jax'))}] JAX / flax
    [{mark(has('tensorflow'))}] TensorFlow
    [{mark(has('torch'))}] PyTorch
    [{mark(has('mxnet'))}] MXNet
    [{mark(has('pyspark'))}] Spark

Available Controllers:
    [{mark(has('jax'))}] XLA (compiled SPMD schedule)
    [{mark(native_ok)}] native (C++ TCP negotiation, csrc/controller.cc)

Available Tensor Operations:
    [{mark(has('jax'))}] XLA collectives (ICI/DCN)
    [{mark(native_ok)}] native peer ring (host plane, csrc/ring.cc)
    [{mark(native_ok)}] coordinator star (host plane)"""


def run_commandline(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    if args.version:
        from .. import __version__

        print(__version__)
        return 0
    if getattr(args, "check_build", False):
        print(check_build())
        return 0
    if not args.command:
        print("tpurun: no command given", file=sys.stderr)
        return 2
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    hosts = _resolve_hosts(args)
    np = args.np or sum(h.slots for h in hosts)
    slots = allocate_slots(hosts, np)
    env = config_parser.env_from_args(args)
    if args.verbose:
        env[env_util.HVD_LOG_LEVEL] = env.get(env_util.HVD_LOG_LEVEL, "debug")
    return launch_job(args, slots, env)


# ---------------------------------------------------------------------------
# function mode: horovod_tpu.run.run(fn, args=(), np=...)
# ---------------------------------------------------------------------------
def run(fn, args=(), kwargs=None, np: int = 1,
        extra_env: Optional[Dict[str, str]] = None):
    """Run ``fn(*args, **kwargs)`` on ``np`` local worker processes and
    return the per-process results (reference run/run.py:870-956: the fn is
    pickled, shipped through the KV store, executed by each rank, results
    collected back through the KV store)."""
    import cloudpickle

    kwargs = kwargs or {}
    extra_env = dict(extra_env or {})
    secret = _secrets.token_bytes(16)
    server = RendezvousServer(
        secret=secret,
        journal_path=extra_env.get(
            env_util.HVD_RENDEZVOUS_JOURNAL,
            os.environ.get(env_util.HVD_RENDEZVOUS_JOURNAL)))
    port = server.start()
    # same flight-recorder wiring as launch_job (observe/events.py)
    from ..observe import events as _events_mod

    _events_mod.attach_server(server)
    # Multi-process workers need an eager transport: default to a
    # parent-hosted native controller on loopback (bound to port 0 — no
    # races) unless the caller or environment configured the controller.
    ctrl_server = None
    user_controller = extra_env.get(
        env_util.HVD_CONTROLLER, os.environ.get(env_util.HVD_CONTROLLER)
    )
    if np > 1 and user_controller is None \
            and not os.environ.get("HVD_CONTROLLER_ADDR"):
        from ..runtime.controller import ControllerServer

        ctrl_server = ControllerServer(np, port=0)
        extra_env[env_util.HVD_CONTROLLER] = "native"
        extra_env["HVD_CONTROLLER_ADDR"] = f"127.0.0.1:{ctrl_server.port}"
        extra_env["HVD_CONTROLLER_SERVER"] = "external"
    # Live metrics: point workers' pushers at this server, so a scrape of
    # GET /metrics here aggregates every rank while fn runs (the final
    # snapshot is pushed by task_fn regardless).
    extra_env.setdefault(env_util.HVD_METRICS_KV_ADDR, "127.0.0.1")
    extra_env.setdefault(env_util.HVD_METRICS_KV_PORT, str(port))
    extra_env.setdefault(env_util.HVD_METRICS_SECRET, secret.hex())
    # cloudpickle so lambdas/closures ship (reference run/common/util/codec.py
    # uses base64-cloudpickle for the same purpose)
    server.put("job", "fn", cloudpickle.dumps((fn, args, kwargs)))

    # same always-on watchdog as launch_job (HVD_WATCH=0 disables)
    from ..observe import watchdog as watchdog_mod

    watchdog = watchdog_mod.start_from_env(server)

    procs = []
    try:
        for pid in range(np):
            env = dict(os.environ)
            env.update(extra_env)
            env.update({
                "HVD_RUN_KV_ADDR": "127.0.0.1",
                "HVD_RUN_KV_PORT": str(port),
                "HVD_RUN_SECRET": secret.hex(),
                "HVD_RUN_PID": str(pid),
                "HVD_RUN_NP": str(np),
                env_util.HVD_RANK: str(pid),
                env_util.HVD_SIZE: str(np),
                env_util.HVD_NUM_PROCESSES: str(np),
                env_util.HVD_PROCESS_ID: str(pid),
            })
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "horovod_tpu.run.task_fn"], env=env,
            ))
        # Supervise like launch_job: react to the FIRST failure, whichever
        # worker it is — a rank-order wait would hang here forever while a
        # surviving worker blocks in a collective its dead peer never
        # joins.  The abort flag goes onto this server so the survivors'
        # heartbeats surface the root cause before the escalating kill.
        while True:
            states = [p.poll() for p in procs]
            if all(c is not None for c in states):
                rcs = states
                break
            failures = [(pid, c) for pid, c in enumerate(states)
                        if c is not None and c != 0]
            if failures:
                bad_pid, code = failures[0]
                log.error("function-mode worker %d exited with code %d; "
                          "aborting job", bad_pid, code)
                from ..elastic.abort import ABORT_KEY, ABORT_SCOPE, make_flag

                flag = make_flag(
                    f"worker {bad_pid} exited with code {code}",
                    rank=bad_pid, source="launcher",
                )
                try:
                    eid = _events_mod.record_event(
                        "abort.publish", severity="critical",
                        payload={"reason": flag["reason"],
                                 "source": "launcher",
                                 "exit_code": code},
                        rank=bad_pid)
                    if eid:
                        flag["event_id"] = eid
                        corr = _events_mod.correlation_of(eid)
                        if corr:
                            flag["correlation_id"] = corr
                except Exception:  # noqa: BLE001 — best-effort
                    pass
                server.put(ABORT_SCOPE, ABORT_KEY,
                           json.dumps(flag).encode())
                hb_interval = env_util.get_float(
                    env_util.HVD_HEARTBEAT_INTERVAL_SECONDS,
                    env_util.DEFAULT_HEARTBEAT_INTERVAL_SECONDS)
                grace = env_util.get_float(
                    env_util.HVD_TERM_GRACE_SECONDS,
                    env_util.DEFAULT_TERM_GRACE_SECONDS)
                deadline = time.monotonic() + 2.0 * hb_interval + grace
                while time.monotonic() < deadline \
                        and any(p.poll() is None for p in procs):
                    time.sleep(0.1)
                kill_job = _Job()
                kill_job.procs = procs
                kill_job.kill_all()
                rcs = [p.wait() for p in procs]
                break
            time.sleep(0.1)
        if any(rcs):
            # surface the tracebacks the workers published before exiting
            errors = []
            for pid in range(np):
                blob = server.get("result", str(pid))
                if blob is not None:
                    payload = pickle.loads(blob)
                    if payload.get("error"):
                        errors.append(f"[worker {pid}] {payload['error']}")
            raise RuntimeError(
                "function-mode workers failed: rcs=%s\n%s"
                % (rcs, "\n".join(errors))
            )
        results = []
        for pid in range(np):
            blob = server.get("result", str(pid))
            if blob is None:
                raise RuntimeError(f"worker {pid} returned no result")
            payload = pickle.loads(blob)
            if payload.get("error"):
                raise RuntimeError(
                    f"worker {pid} raised: {payload['error']}"
                )
            results.append(payload["value"])
        return results
    finally:
        # escalating teardown: SIGTERM, grace, then SIGKILL — a worker
        # wedged in a collective ignores SIGTERM and would leak
        if any(p.poll() is None for p in procs):
            grace_job = _Job()
            grace_job.procs = procs
            grace_job.kill_all()
        if watchdog is not None:
            watchdog.stop()
        if ctrl_server is not None:
            ctrl_server.stop()
        server.stop()


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
