"""``tpurun`` — the launcher CLI.

Re-design of ``horovodrun`` (reference horovod/run/run.py:395-615 arg
groups, :696-740 host parsing, :839-861 _launch_job; gloo_run's per-slot
env + ssh fan-out + output capture + failure kill at
run/gloo_run.py:142-288) for TPU pods:

* one worker **process per host** (each controller owns that host's chips —
  the JAX multi-controller model), not one per slot;
* rendezvous = the HTTP KV store (run/http_server.py) + ``jax.distributed``
  (HVD_COORDINATOR_ADDR), replacing Gloo's HTTPStore/full-mesh bootstrap;
* remote execution via ssh command lines (generated identically for
  string-assertion tests, reference test/test_run.py:259-362 asserts the
  mpirun command line with a mocked runner);
* local hosts ("localhost"/"127.0.0.1") spawn subprocesses directly;
* any worker exiting non-zero kills the whole job
  (reference gloo_run.py:253-259); SIGINT/SIGTERM propagate.

Also provides the in-process API ``horovod_tpu.run.run(fn, ...)``
(reference run/run.py:870-956 func mode: cloudpickled fn shipped through
the KV store, results collected back).
"""

from __future__ import annotations

import argparse
import os
import pickle
import secrets as _secrets
import shlex
import signal
import socket
import subprocess
import sys
import threading
from typing import Dict, List, Optional

from ..utils import env as env_util
from ..utils.logging import get_logger
from . import config_parser
from .hosts import HostInfo, SlotInfo, allocate_slots, parse_hostfile, parse_hosts
from .http_server import RendezvousServer

log = get_logger(__name__)

LOCAL_HOSTS = ("localhost", "127.0.0.1")


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        "tpurun", description="Launch a horovod_tpu training job",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    parser.add_argument("-v", "--version", action="store_true")
    parser.add_argument("-np", "--num-proc", type=int, dest="np",
                        help="total number of ranks (chips)")
    parser.add_argument("-H", "--hosts", dest="hosts",
                        help="host names and slot counts, e.g. h1:8,h2:8")
    parser.add_argument("--hostfile", dest="hostfile",
                        help="hostfile with lines 'host slots=N'")
    parser.add_argument("--tpu", action="store_true", dest="tpu",
                        help="resolve hosts from TPU pod metadata "
                             "(HVD_TPU_HOSTS / TPU_WORKER_HOSTNAMES / "
                             "GCE metadata) instead of -H")
    parser.add_argument("--output-filename", dest="output_filename",
                        help="per-rank stdout/stderr capture directory")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--config-file", dest="config_file",
                        help="YAML config overriding CLI defaults")
    parser.add_argument("--start-timeout", type=int, default=600)
    parser.add_argument("--ssh-port", type=int, dest="ssh_port")
    parser.add_argument("--disable-cache", action="store_true")
    parser.add_argument("--controller", dest="controller",
                        choices=["auto", "xla", "native"], default="auto",
                        help="eager control plane: 'native' runs the C++ "
                             "negotiation controller (multi-process jobs "
                             "get it by default); 'xla' relies on the "
                             "compiled schedule only")
    parser.add_argument("--dry-run", action="store_true", dest="dry_run",
                        help="print the worker launch plan (env + command "
                             "per process) without spawning anything")
    parser.add_argument("-cb", "--check-build", action="store_true",
                        dest="check_build",
                        help="print available frameworks / controllers / "
                             "tensor operations and exit (reference "
                             "horovodrun --check-build)")
    parser.add_argument("--network-interface", dest="network_interface",
                        help="network interface(s) the host data plane "
                             "advertises on workers (reference "
                             "--network-interface; the first name that "
                             "resolves on each worker wins)")

    group_params = parser.add_argument_group("tuneable parameter arguments")
    group_params.add_argument("--fusion-threshold-mb", type=float,
                              dest="fusion_threshold_mb")
    group_params.add_argument("--cycle-time-ms", type=float,
                              dest="cycle_time_ms")
    group_params.add_argument("--cache-capacity", type=int,
                              dest="cache_capacity")
    group_params.add_argument("--hierarchical-allreduce", action="store_true",
                              dest="hierarchical_allreduce")
    group_params.add_argument("--hierarchical-allgather", action="store_true",
                              dest="hierarchical_allgather")
    group_params.add_argument("--ring-min-bytes", type=int,
                              dest="ring_min_bytes",
                              help="host-plane payloads at or above this "
                                   "ride the peer ring; below it the "
                                   "coordinator star wins on latency "
                                   "(calibrate with scripts/"
                                   "host_plane_bench.py --crossover)")

    group_at = parser.add_argument_group("autotune arguments")
    group_at.add_argument("--autotune", action="store_true")
    group_at.add_argument("--autotune-log-file", dest="autotune_log_file")
    group_at.add_argument("--autotune-warmup-samples", type=int,
                          dest="autotune_warmup_samples")
    group_at.add_argument("--autotune-steps-per-sample", type=int,
                          dest="autotune_steps_per_sample")
    group_at.add_argument("--autotune-bayes-opt-max-samples", type=int,
                          dest="autotune_bayes_opt_max_samples")
    group_at.add_argument("--autotune-gaussian-process-noise", type=float,
                          dest="autotune_gaussian_process_noise")

    group_tl = parser.add_argument_group("timeline arguments")
    group_tl.add_argument("--timeline-filename", dest="timeline_filename")
    group_tl.add_argument("--timeline-mark-cycles", action="store_true",
                          dest="timeline_mark_cycles")
    group_tl.add_argument("--trace-start-step", type=int,
                          dest="trace_start_step")
    group_tl.add_argument("--trace-end-step", type=int, dest="trace_end_step")

    group_st = parser.add_argument_group("stall check arguments")
    group_st.add_argument("--no-stall-check", action="store_true",
                          dest="no_stall_check")
    group_st.add_argument("--stall-check-warning-time-seconds", type=int,
                          dest="stall_check_warning_time_seconds")
    group_st.add_argument("--stall-check-shutdown-time-seconds", type=int,
                          dest="stall_check_shutdown_time_seconds")

    group_log = parser.add_argument_group("logging arguments")
    group_log.add_argument("--log-level", dest="log_level",
                           choices=["trace", "debug", "info", "warning",
                                    "error", "fatal"])
    group_log.add_argument("--log-hide-timestamp", action="store_true",
                           dest="log_hide_timestamp")

    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the training command")

    args = parser.parse_args(argv)

    if args.config_file:
        import yaml

        with open(args.config_file) as f:
            cfg = yaml.safe_load(f) or {}
        explicit = _explicit_dests(argv if argv is not None else sys.argv[1:],
                                   parser)
        config_parser.set_args_from_config(args, cfg, explicit)
    return args


def _explicit_dests(argv: List[str], parser: argparse.ArgumentParser) -> set:
    """Which dests the user passed on the command line (so YAML doesn't
    override them — reference run/run.py:609-613 override_args)."""
    explicit = set()
    opts = {}
    for action in parser._actions:  # noqa: SLF001
        for opt in action.option_strings:
            opts[opt] = action.dest
    for tok in argv:
        key = tok.split("=")[0]
        if key in opts:
            explicit.add(opts[key])
    return explicit


def _resolve_hosts(args) -> List[HostInfo]:
    if args.hostfile:
        return parse_hostfile(args.hostfile)
    if args.hosts:
        return parse_hosts(args.hosts)
    if getattr(args, "tpu", False):
        # pod-slice host resolution from TPU metadata/env (SURVEY §7.1's
        # replacement for the reference's ssh/NIC probing,
        # reference run/run.py:62-115,198-268)
        from .discovery import discover_tpu_hosts

        found = discover_tpu_hosts()
        if found:
            return found
        raise RuntimeError(
            "--tpu: no pod hosts discoverable (HVD_TPU_HOSTS / "
            "TPU_WORKER_HOSTNAMES / metadata server all empty)"
        )
    # default: all local slots on this machine
    np = args.np or 1
    return [HostInfo("localhost", np)]


def worker_envs(slots: List[SlotInfo], base_env: Dict[str, str],
                coordinator: str, *, controller: str = "auto",
                controller_addr: Optional[str] = None) -> List[Dict[str, str]]:
    """Per-host worker env dicts (reference gloo_run.py:210-216 sets
    HOROVOD_RANK/SIZE/LOCAL_RANK/... per slot; here per host-process, with
    the slot table embedded for the chips it owns).

    ``controller``: the eager control plane.  'auto' = native for
    multi-process jobs (the reference always stands up its controller,
    operations.cc:596-640), xla for single-process.  The native controller
    server runs inside process 0 (runtime/eager_controller.py); workers
    dial ``controller_addr``.
    """
    hosts: Dict[str, List[SlotInfo]] = {}
    for s in slots:
        hosts.setdefault(s.hostname, []).append(s)
    if controller == "auto":
        controller = "native" if len(hosts) > 1 else "xla"
    envs = []
    for pid, (hostname, host_slots) in enumerate(hosts.items()):
        first = host_slots[0]
        env = dict(base_env)
        env.update({
            env_util.HVD_RANK: str(first.rank),
            env_util.HVD_SIZE: str(first.size),
            env_util.HVD_LOCAL_RANK: "0",
            env_util.HVD_LOCAL_SIZE: str(len(host_slots)),
            env_util.HVD_CROSS_RANK: str(first.cross_rank),
            env_util.HVD_CROSS_SIZE: str(first.cross_size),
            env_util.HVD_NUM_PROCESSES: str(len(hosts)),
            env_util.HVD_PROCESS_ID: str(pid),
            env_util.HVD_CONTROLLER: controller,
            env_util.HVD_CPU_OPERATIONS: "xla",
        })
        if controller == "native" and controller_addr:
            env["HVD_CONTROLLER_ADDR"] = controller_addr
            # the launcher hosts the server (port 0 bound locally — no
            # remote-port race); workers are clients only
            env["HVD_CONTROLLER_SERVER"] = "external"
            # the address peers dial for THIS worker's ring listener:
            # the launcher knows each worker's host; self-resolution
            # (gethostname) can pick a wrong interface on multi-NIC VMs
            env["HVD_RING_HOST"] = hostname
        if len(hosts) > 1:
            env[env_util.HVD_COORDINATOR_ADDR] = coordinator
        envs.append(env)
    return envs


def ssh_command(hostname: str, env: Dict[str, str], command: List[str],
                ssh_port: Optional[int] = None, cwd: Optional[str] = None) -> str:
    """The remote launch line (reference gloo_run.py:142-259 ssh fan-out;
    kept as a pure string builder so tests can assert it without a
    cluster, reference test/test_run.py:259-362)."""
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in sorted(env.items())
    )
    cd = f"cd {shlex.quote(cwd)} > /dev/null 2>&1 && " if cwd else ""
    port = f" -p {ssh_port}" if ssh_port else ""
    inner = f"{cd}env {exports} {' '.join(shlex.quote(c) for c in command)}"
    return (
        f"ssh -o PasswordAuthentication=no -o StrictHostKeyChecking=no"
        f"{port} {hostname} {shlex.quote(inner)}"
    )


class _Job:
    def __init__(self) -> None:
        self.procs: List[subprocess.Popen] = []
        self.failed: Optional[int] = None
        self._lock = threading.Lock()

    def kill_all(self, sig=signal.SIGTERM) -> None:
        with self._lock:
            for p in self.procs:
                if p.poll() is None:
                    try:
                        p.send_signal(sig)
                    except OSError:
                        pass


def launch_job(args, slots: List[SlotInfo], env: Dict[str, str]) -> int:
    """Spawn workers, capture output, propagate failure
    (reference gloo_run.py:142-259)."""
    hosts = sorted({s.hostname for s in slots},
                   key=[s.hostname for s in slots].index)
    coordinator = f"{socket.gethostname()}:{env_util.get_int('HVD_COORD_PORT', 0) or _free_port()}"

    # Metrics aggregation point: the launcher hosts a rendezvous server
    # that ranks push registry snapshots to; GET /metrics (signed) on it
    # serves the whole job's Prometheus page (docs/metrics.md).
    metrics_server = None
    metrics_on = env_util.parse_bool(
        env.get(env_util.HVD_METRICS, os.environ.get(env_util.HVD_METRICS)),
        True,
    )
    # An operator-provided HVD_METRICS_KV_ADDR means an external
    # aggregation server: forward the operator's values untouched.
    external_sink = env.get(
        env_util.HVD_METRICS_KV_ADDR,
        os.environ.get(env_util.HVD_METRICS_KV_ADDR),
    )
    if not getattr(args, "dry_run", False) and metrics_on \
            and not external_sink:
        # operator-provided secret (hex) wins so their tooling can sign
        # scrapes; otherwise generate one and LOG it — a secret nobody
        # knows makes the advertised endpoint unusable
        secret_hex = env.get(env_util.HVD_METRICS_SECRET,
                             os.environ.get(env_util.HVD_METRICS_SECRET))
        try:
            metrics_secret = bytes.fromhex(secret_hex) if secret_hex \
                else _secrets.token_bytes(16)
        except ValueError:
            raise ValueError(
                f"{env_util.HVD_METRICS_SECRET} must be hex, got "
                f"{secret_hex!r}"
            )
        metrics_server = RendezvousServer(secret=metrics_secret)
        metrics_port = metrics_server.start()
        metrics_host = "127.0.0.1" if all(h in LOCAL_HOSTS for h in hosts) \
            else socket.gethostname()
        env = dict(env)
        env[env_util.HVD_METRICS_KV_ADDR] = metrics_host
        env[env_util.HVD_METRICS_KV_PORT] = str(metrics_port)
        env[env_util.HVD_METRICS_SECRET] = metrics_secret.hex()
        # never echo an operator-provided credential into job logs; a
        # generated one must be printed or the endpoint is unusable
        secret_expr = "bytes.fromhex(os.environ['HVD_METRICS_SECRET'])" \
            if secret_hex else f"bytes.fromhex('{metrics_secret.hex()}')"
        log.info(
            "metrics: signed GET http://%s:%d/metrics aggregates all "
            "ranks — e.g. horovod_tpu.run.http_client.get_metrics("
            "'%s', %d, secret=%s)",
            metrics_host, metrics_port, metrics_host, metrics_port,
            secret_expr,
        )

    controller = getattr(args, "controller", "auto") or "auto"
    if controller == "auto":
        controller = "native" if len(hosts) > 1 else "xla"
    # The launcher hosts the native controller server (the reference hosts
    # its rendezvous on the launcher the same way, gloo_run.py:262-288):
    # bind port 0 locally, point workers at this machine.
    ctrl_server = None
    controller_addr = None
    if controller == "native" and not getattr(args, "dry_run", False):
        from ..runtime.controller import ControllerServer

        ctrl_server = ControllerServer(len(hosts), port=0)
        ctrl_host = "127.0.0.1" if all(h in LOCAL_HOSTS for h in hosts) \
            else socket.gethostname()
        controller_addr = f"{ctrl_host}:{ctrl_server.port}"
    elif controller == "native":
        controller_addr = "<launcher>:<bound-at-launch>"
    envs = worker_envs(
        slots, env, coordinator,
        controller=controller, controller_addr=controller_addr,
    )

    if getattr(args, "dry_run", False):
        for pid, hostname in enumerate(hosts):
            print(f"[dry-run] process {pid} on {hostname}:")
            for k in sorted(set(envs[pid]) - set(env)):
                print(f"  {k}={envs[pid][k]}")
            print(f"  command: {' '.join(args.command)}")
        return 0

    job = _Job()

    def handler(signum, frame):
        job.kill_all(signal.SIGTERM)

    old_int = signal.signal(signal.SIGINT, handler)
    old_term = signal.signal(signal.SIGTERM, handler)

    threads = []
    try:
        for pid, hostname in enumerate(hosts):
            wenv = envs[pid]
            if hostname in LOCAL_HOSTS:
                full_env = dict(os.environ)
                full_env.update(wenv)
                proc = subprocess.Popen(
                    args.command, env=full_env,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True,
                )
            else:
                cmd = ssh_command(hostname, wenv, args.command,
                                  ssh_port=args.ssh_port, cwd=os.getcwd())
                proc = subprocess.Popen(
                    cmd, shell=True,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True,
                )
            job.procs.append(proc)

            t = threading.Thread(
                target=_pump_output,
                args=(proc, pid, args.output_filename),
                daemon=True,
            )
            t.start()
            threads.append(t)

        rc = 0
        for pid, proc in enumerate(job.procs):
            code = proc.wait()
            if code != 0 and rc == 0:
                rc = code
                log.error("worker %d exited with code %d; terminating job",
                          pid, code)
                job.kill_all()
        for t in threads:
            t.join(timeout=5)
        return rc
    finally:
        signal.signal(signal.SIGINT, old_int)
        signal.signal(signal.SIGTERM, old_term)
        if ctrl_server is not None:
            log.info(
                "controller: %d cycles, %d cache hits, %d stall warnings",
                ctrl_server.cycles, ctrl_server.cache_hits,
                ctrl_server.stall_warnings,
            )
            ctrl_server.stop()
        if metrics_server is not None:
            metrics_server.stop()


def _pump_output(proc: subprocess.Popen, pid: int,
                 output_dir: Optional[str]) -> None:
    """Tag each line with the worker index (mpirun --tag-output style,
    reference mpi_run.py:115-149) and/or tee to per-rank files
    (reference gloo_run.py output capture)."""
    sink = None
    if output_dir:
        os.makedirs(output_dir, exist_ok=True)
        sink = open(os.path.join(output_dir, f"rank.{pid}.txt"), "w")
    assert proc.stdout is not None
    for line in proc.stdout:
        sys.stdout.write(f"[{pid}]<stdout>: {line}")
        sys.stdout.flush()
        if sink:
            sink.write(line)
            sink.flush()
    if sink:
        sink.close()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def check_build() -> str:
    """Availability report (reference run/run.py:289-324 check_build):
    frameworks are import-probed, the controllers/ops reflect this
    build's architecture — XLA collectives over ICI/DCN plus the native
    C++ control/host plane in place of MPI/Gloo/NCCL."""
    import importlib.util

    from .. import __version__
    from ..runtime import native

    def mark(ok):
        return "X" if ok else " "

    def has(mod):
        return importlib.util.find_spec(mod) is not None

    native_ok = native.available()
    return f"""\
horovod_tpu v{__version__}:

Available Frameworks:
    [{mark(has('jax'))}] JAX / flax
    [{mark(has('tensorflow'))}] TensorFlow
    [{mark(has('torch'))}] PyTorch
    [{mark(has('mxnet'))}] MXNet
    [{mark(has('pyspark'))}] Spark

Available Controllers:
    [{mark(has('jax'))}] XLA (compiled SPMD schedule)
    [{mark(native_ok)}] native (C++ TCP negotiation, csrc/controller.cc)

Available Tensor Operations:
    [{mark(has('jax'))}] XLA collectives (ICI/DCN)
    [{mark(native_ok)}] native peer ring (host plane, csrc/ring.cc)
    [{mark(native_ok)}] coordinator star (host plane)"""


def run_commandline(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    if args.version:
        from .. import __version__

        print(__version__)
        return 0
    if getattr(args, "check_build", False):
        print(check_build())
        return 0
    if not args.command:
        print("tpurun: no command given", file=sys.stderr)
        return 2
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    hosts = _resolve_hosts(args)
    np = args.np or sum(h.slots for h in hosts)
    slots = allocate_slots(hosts, np)
    env = config_parser.env_from_args(args)
    if args.verbose:
        env[env_util.HVD_LOG_LEVEL] = env.get(env_util.HVD_LOG_LEVEL, "debug")
    return launch_job(args, slots, env)


# ---------------------------------------------------------------------------
# function mode: horovod_tpu.run.run(fn, args=(), np=...)
# ---------------------------------------------------------------------------
def run(fn, args=(), kwargs=None, np: int = 1,
        extra_env: Optional[Dict[str, str]] = None):
    """Run ``fn(*args, **kwargs)`` on ``np`` local worker processes and
    return the per-process results (reference run/run.py:870-956: the fn is
    pickled, shipped through the KV store, executed by each rank, results
    collected back through the KV store)."""
    import cloudpickle

    kwargs = kwargs or {}
    extra_env = dict(extra_env or {})
    secret = _secrets.token_bytes(16)
    server = RendezvousServer(secret=secret)
    port = server.start()
    # Multi-process workers need an eager transport: default to a
    # parent-hosted native controller on loopback (bound to port 0 — no
    # races) unless the caller or environment configured the controller.
    ctrl_server = None
    user_controller = extra_env.get(
        env_util.HVD_CONTROLLER, os.environ.get(env_util.HVD_CONTROLLER)
    )
    if np > 1 and user_controller is None \
            and not os.environ.get("HVD_CONTROLLER_ADDR"):
        from ..runtime.controller import ControllerServer

        ctrl_server = ControllerServer(np, port=0)
        extra_env[env_util.HVD_CONTROLLER] = "native"
        extra_env["HVD_CONTROLLER_ADDR"] = f"127.0.0.1:{ctrl_server.port}"
        extra_env["HVD_CONTROLLER_SERVER"] = "external"
    # Live metrics: point workers' pushers at this server, so a scrape of
    # GET /metrics here aggregates every rank while fn runs (the final
    # snapshot is pushed by task_fn regardless).
    extra_env.setdefault(env_util.HVD_METRICS_KV_ADDR, "127.0.0.1")
    extra_env.setdefault(env_util.HVD_METRICS_KV_PORT, str(port))
    extra_env.setdefault(env_util.HVD_METRICS_SECRET, secret.hex())
    # cloudpickle so lambdas/closures ship (reference run/common/util/codec.py
    # uses base64-cloudpickle for the same purpose)
    server.put("job", "fn", cloudpickle.dumps((fn, args, kwargs)))

    procs = []
    try:
        for pid in range(np):
            env = dict(os.environ)
            env.update(extra_env)
            env.update({
                "HVD_RUN_KV_ADDR": "127.0.0.1",
                "HVD_RUN_KV_PORT": str(port),
                "HVD_RUN_SECRET": secret.hex(),
                "HVD_RUN_PID": str(pid),
                "HVD_RUN_NP": str(np),
                env_util.HVD_RANK: str(pid),
                env_util.HVD_SIZE: str(np),
                env_util.HVD_NUM_PROCESSES: str(np),
                env_util.HVD_PROCESS_ID: str(pid),
            })
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "horovod_tpu.run.task_fn"], env=env,
            ))
        rcs = [p.wait() for p in procs]
        if any(rcs):
            # surface the tracebacks the workers published before exiting
            errors = []
            for pid in range(np):
                blob = server.get("result", str(pid))
                if blob is not None:
                    payload = pickle.loads(blob)
                    if payload.get("error"):
                        errors.append(f"[worker {pid}] {payload['error']}")
            raise RuntimeError(
                "function-mode workers failed: rcs=%s\n%s"
                % (rcs, "\n".join(errors))
            )
        results = []
        for pid in range(np):
            blob = server.get("result", str(pid))
            if blob is None:
                raise RuntimeError(f"worker {pid} returned no result")
            payload = pickle.loads(blob)
            if payload.get("error"):
                raise RuntimeError(
                    f"worker {pid} raised: {payload['error']}"
                )
            results.append(payload["value"])
        return results
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        if ctrl_server is not None:
            ctrl_server.stop()
        server.stop()


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
