"""HTTP key-value store + rendezvous server.

Re-design of the launcher-side rendezvous service (reference
horovod/run/http/http_server.py: ``KVStoreHandler`` with GET/PUT of
scope/key → bytes at :33-102, ``RendezvousServer`` where DELETE finalizes;
used by Gloo's HTTPStore from C++ during hvd.init, reference
gloo/gloo_context.cc:56-76, and by func-mode result collection,
run/run.py:813-832).

Here the same server bootstraps multi-host jobs: workers publish their
host/port and read the coordinator address before ``jax.distributed``
takes over, and ``tpurun``'s function-mode ships pickled fns/results
through it.  Requests carry an HMAC signature derived from the job secret
(reference run/common/util/secret.py:26-30) — unauthenticated requests are
rejected.

It is also the job's metrics aggregation point: workers push JSON
registry snapshots into the ``metrics`` scope (horovod_tpu/metrics/
push.py), and a signed ``GET /metrics`` renders every rank's snapshot —
plus the launcher's own registry — as one Prometheus text page
(``GET /metrics.json`` serves the raw merged snapshots).  The collective
sanitizer (analysis/sanitizer.py, HVD_SANITIZER=1) publishes per-dispatch
fingerprints into the ``sanitizer`` scope; ``GET /sanitizer`` renders
the live table grouped by sequence number then rank.

The failure-domain runtime rides it too (docs/fault_tolerance.md): ranks
renew heartbeat leases under ``/health/<rank>`` (stamped on the server's
clock at receipt), ``GET /health`` reports per-rank lease age with
live/stale/dead verdicts plus the job-wide abort flag, and the
``/abort/flag`` key is the coordinated-abort protocol's single source of
truth.

**Control-plane tier (docs/control_plane.md).**  The store behind this
surface is the sharded :class:`~horovod_tpu.run.store.ShardedKVStore`
(``HVD_CP_SHARDS`` independent dict+lock shards with per-scope change
tracking), and three wire additions make thousand-rank worlds cheap and
survivable:

* ``PUT /batch`` — one signed request carrying many KV entries
  (``{"entries": [{"p": "/scope/key", "v": <base64>}, ...]}``), the
  upstream leg of the per-host relay tree (run/relay.py).  The reply
  carries the job-wide abort flag and the ``server_id``.
* ``GET /scope/<name>?since=V`` — scope-level batch read: only the keys
  changed after version ``V`` (plus removals), with a full-resync
  answer when the cursor predates the retained history.  The path
  prefix ``/scope/`` is reserved — a KV scope literally named "scope"
  cannot be served.
* a ``PUT`` under ``/health/`` answers with the abort verdict in the
  response body, collapsing the heartbeat's renew + abort-poll pair
  into one round trip (elastic/heartbeat.py).

Writes to ``/membership/epoch`` are **fenced**: an epoch that does not
advance the committed one is rejected (HTTP 409 /
:class:`EpochFencedError`), so a stale primary resurrected after a
warm-standby takeover (run/journal.py) cannot roll the world back.
``server_id`` (a per-incarnation random token carried in mutating
replies and scope reads) is how clients detect a failover and resync
their delta/cursor state.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import socket
import threading
import time
import uuid
from base64 import b64decode, b64encode
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from ..utils.logging import get_logger
from .store import ShardedKVStore

log = get_logger(__name__)

SECRET_HEADER = "X-Hvd-Signature"

METRICS_SCOPE = "metrics"
_METRICS_PREFIX = f"/{METRICS_SCOPE}/"

# collective-sanitizer fingerprints (analysis/sanitizer.py): keys are
# "<seq>.<rank>" → JSON fingerprint; GET /sanitizer renders the table
SANITIZER_SCOPE = "sanitizer"
_SANITIZER_PREFIX = f"/{SANITIZER_SCOPE}/"

# replay-engine summary (timeline/replay/): scripts/hvd_replay.py pushes
# its JSON summary here; GET /replay serves the latest one.  GET /clock
# is the offset-estimation handshake the per-rank timelines use at init.
REPLAY_SCOPE = "replay"
REPLAY_SUMMARY_KEY = "summary"

# digital-twin projection (timeline/replay/projection.py,
# docs/projection.md): `hvd_replay --project --push` publishes the
# topology-projected summary (per-target step time / efficiency / wire
# formats + the tracked projected-vs-measured accuracy record) here;
# GET /projection serves the latest one.
PROJECTION_SCOPE = "projection"
PROJECTION_SUMMARY_KEY = "summary"

# compute-anatomy profiler (timeline/profiler.py): each rank pushes its
# window anatomy under profile/<rank> at finalize; GET /profile renders
# the per-rank anatomies plus the cross-rank aggregate (per-segment
# slowest rank, mean MFU, worst host gap — docs/profiling.md)
PROFILE_SCOPE = "profile"
_PROFILE_PREFIX = f"/{PROFILE_SCOPE}/"

# profile-guided autotune loop (optim/profile_guided.py): the tuner (or
# scripts/hvd_autotune.py --push) publishes one record per plan event
# under plan.<n>; GET /autotune renders the per-plan table plus the
# latest predicted/realized speedup pair (docs/autotune.md contract)
AUTOTUNE_SCOPE = "autotune"
_AUTOTUNE_PREFIX = f"/{AUTOTUNE_SCOPE}/"
AUTOTUNE_PLAN_PREFIX = "plan."

# always-on telemetry time-series (metrics/timeseries.py): each rank's
# flusher lands its ring-buffer history under timeseries/<rank> — full
# snapshots or append-deltas merged server-side — and GET /timeseries
# renders the per-rank series (docs/observe.md)
TIMESERIES_SCOPE = "timeseries"
_TIMESERIES_PREFIX = f"/{TIMESERIES_SCOPE}/"

# online anomaly watchdog (horovod_tpu/observe/): alert records live
# under alerts/<id> (GET /alerts renders them newest-first), and the
# auto-arm broadcast — the KV-broadcast trace+profile start step every
# rank applies consistently — lives at observe/arm
ALERTS_SCOPE = "alerts"
_ALERTS_PREFIX = f"/{ALERTS_SCOPE}/"
OBSERVE_SCOPE = "observe"
ARM_KEY = "arm"

# control-plane flight recorder (observe/events.py): every lifecycle
# actor's structured events land under events/<id> (journaled — the
# audit trail survives warm-standby failover); GET /events renders them
# oldest-first with the scope version for cursor reads
EVENTS_SCOPE = "events"
_EVENTS_PREFIX = f"/{EVENTS_SCOPE}/"

# failure-domain runtime (elastic/heartbeat.py, elastic/abort.py): ranks
# renew leases under /health/<rank>; the server stamps each PUT on ITS
# clock and GET /health renders per-rank lease age + live/stale/dead
# verdicts.  The job-wide abort flag lives at /abort/flag.
HEALTH_SCOPE = "health"
_HEALTH_PREFIX = f"/{HEALTH_SCOPE}/"
ABORT_SCOPE = "abort"
ABORT_KEY = "flag"
# Spare-side liveness (elastic/membership.join_world ↔ driver.spares):
# a worker the driver HOLDS as a spare renews an announce-keyed lease at
# health/spare.<worker> between epoch waits.  The key is non-numeric on
# purpose — the driver's rank-lease expiry loop skips it — but the same
# STALE/DEAD verdict machinery applies, so a spare that dies while held
# is purged before admission instead of stalling a stability timeout.
SPARE_PREFIX = "spare."

# elastic membership (elastic/membership.py, elastic/driver.py): the
# committed epoch record lives at /membership/epoch; workers announce
# rejoin candidacy under announce.<worker>, acknowledge a rebuilt epoch
# under ready.<epoch>.<worker>, and rank 0 broadcasts the live training
# state under state.<epoch>.  GET /membership renders the whole table.
MEMBERSHIP_SCOPE = "membership"
_MEMBERSHIP_PREFIX = f"/{MEMBERSHIP_SCOPE}/"
EPOCH_KEY = "epoch"
BLOCKLIST_KEY = "blocklist"
ANNOUNCE_PREFIX = "announce."
READY_PREFIX = "ready."
STATE_PREFIX = "state."
# lossless scale-down handshake (elastic/driver.py remove(drain=True) ↔
# the departing worker): the driver requests under drain.<worker>, the
# worker stops pulling, finishes in flight, and acks under
# drain_ack.<worker>; only then is the shrink epoch committed.
DRAIN_PREFIX = "drain."
DRAIN_ACK_PREFIX = "drain_ack."
# a worker that received a preemption notice (cloud maintenance, or a
# kind=preempt fault) publishes it under preempt.<worker>; the elastic
# driver's poll turns the notice into a planned drain+snapshot
# (elastic/driver.preempt) instead of waiting for the lease to die.
PREEMPT_PREFIX = "preempt."

EPOCH_PATH = f"/{MEMBERSHIP_SCOPE}/{EPOCH_KEY}"

# peer-replicated state plane (elastic/peerstate.py,
# docs/fault_tolerance.md#the-peer-state-plane): each worker registers
# its shard-server endpoint under peerstate/addr.<worker>; per-rank
# snapshot manifests land at manifest.<gen>.<rank> with PR 5-style
# commit markers at commit.<gen>.<rank> gating which generation restore
# may target.  The scope is journaled, so the warm-standby/fencing
# machinery is the consistency story.  Raw shard BYTES never touch this
# server — they live on the peer workers' own shard servers under
# shard/<gen>.<src_rank>.<idx>.  GET /peerstate renders the table.
PEERSTATE_SCOPE = "peerstate"
_PEERSTATE_PREFIX = f"/{PEERSTATE_SCOPE}/"
PEER_ADDR_PREFIX = "addr."
SNAPSHOT_MANIFEST_PREFIX = "manifest."
SNAPSHOT_COMMIT_PREFIX = "commit."
SHARD_SCOPE = "shard"

# serving plane (horovod_tpu/serving/, docs/inference.md): tpurun
# --serve attaches a ServingFrontend to this server — signed POST
# /infer (one inference request), POST /serving/pull + /serving/result
# (the remote-replica protocol), GET /serving (status page).

#: lease-age verdict thresholds, in units of the lease's own renewal
#: interval: a rank is ``stale`` past STALE_FACTOR missed intervals and
#: ``dead`` past DEAD_FACTOR — the server-side lease expiry.
STALE_FACTOR = 2.0
DEAD_FACTOR = 4.0


#: the batched-write route (one request, many KV entries) and the
#: reserved scope-read route prefix (GET /scope/<name>?since=V)
BATCH_PATH = "/batch"
SCOPE_ROUTE_PREFIX = "/scope/"


class EpochFencedError(RuntimeError):
    """A ``/membership/epoch`` write did not advance the committed
    epoch.  Raised on the in-process path; the HTTP surface answers
    409.  This is the split-brain fence: after a standby takeover, a
    resurrected stale primary (or a partitioned driver) cannot commit a
    regressed world."""


def sign(secret: bytes, path: str, body: bytes = b"") -> str:
    mac = hmac.new(secret, path.encode() + b"|" + body, hashlib.sha256)
    return mac.hexdigest()


def build_health_report(store: Dict[str, bytes],
                        lease_times: Dict[str, float],
                        now: Optional[float] = None) -> Dict[str, object]:
    """Per-rank lease ages and verdicts from a store snapshot, computed on
    the SERVER clock (lease expiry is server-side: a rank whose clock
    drifts — or whose process died — cannot keep its own lease alive).
    Shared by the GET /health handler and the in-process
    :meth:`RendezvousServer.health_report` the elastic driver polls."""
    now = time.monotonic() if now is None else now
    leases = {k[len(_HEALTH_PREFIX):]: v for k, v in store.items()
              if k.startswith(_HEALTH_PREFIX)}
    abort_raw = store.get(f"/{ABORT_SCOPE}/{ABORT_KEY}")
    ranks: Dict[str, object] = {}
    for rank, raw in leases.items():
        try:
            lease = json.loads(raw)
        except (ValueError, TypeError):
            lease = {}
        age = now - lease_times.get(_HEALTH_PREFIX + rank, now)
        interval = float(lease.get("interval", 0.0)) or 1.0
        if age <= STALE_FACTOR * interval:
            verdict = "live"
        elif age <= DEAD_FACTOR * interval:
            verdict = "stale"
        else:
            verdict = "dead"
        ranks[rank] = {
            "age_seconds": round(age, 3),
            "interval": interval,
            "count": lease.get("count"),
            "pid": lease.get("pid"),
            "verdict": verdict,
        }
    abort = None
    if abort_raw is not None:
        try:
            abort = json.loads(abort_raw)
        except (ValueError, TypeError):
            abort = {"reason": "<undecodable abort flag>"}
    return {"ranks": ranks, "abort": abort}


def build_membership_report(store: Dict[str, bytes]) -> Dict[str, object]:
    """The elastic-membership table from a store snapshot: the committed
    epoch record, pending rejoin announcements, per-epoch ready acks, and
    the flapping-host blocklist (GET /membership)."""

    def _load(raw):
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except (ValueError, TypeError):
            return "<undecodable>"

    keys = {k[len(_MEMBERSHIP_PREFIX):]: v for k, v in store.items()
            if k.startswith(_MEMBERSHIP_PREFIX)}
    announces = {k[len(ANNOUNCE_PREFIX):]: _load(v)
                 for k, v in keys.items() if k.startswith(ANNOUNCE_PREFIX)}
    ready: Dict[str, list] = {}
    for k in keys:
        if k.startswith(READY_PREFIX):
            epoch, _, worker = k[len(READY_PREFIX):].partition(".")
            ready.setdefault(epoch, []).append(worker)
    for workers in ready.values():
        workers.sort()
    # "drain_ack." keys never match the "drain." prefix (they diverge
    # at the underscore), so one startswith per family suffices
    drains = {k[len(DRAIN_PREFIX):]: _load(v) for k, v in keys.items()
              if k.startswith(DRAIN_PREFIX)}
    drain_acks = {k[len(DRAIN_ACK_PREFIX):]: _load(v)
                  for k, v in keys.items()
                  if k.startswith(DRAIN_ACK_PREFIX)}
    preempts = {k[len(PREEMPT_PREFIX):]: _load(v) for k, v in keys.items()
                if k.startswith(PREEMPT_PREFIX)}
    return {
        "epoch": _load(keys.get(EPOCH_KEY)),
        "announces": announces,
        "ready": ready,
        "blocklist": _load(keys.get(BLOCKLIST_KEY)) or [],
        "drains": drains,
        "drain_acks": drain_acks,
        "preempts": preempts,
    }


def build_peerstate_report(store: Dict[str, bytes]) -> Dict[str, object]:
    """The peer-state-plane table from a store snapshot (GET
    /peerstate): registered shard-server endpoints, per-generation
    manifest/commit coverage, and the newest fully-committed generation
    — the one :meth:`~horovod_tpu.elastic.peerstate.PeerSnapshotManager.
    restore` would target.  A generation counts as committed only when
    every rank of its recorded world wrote both manifest and marker."""

    def _load(raw):
        try:
            return json.loads(raw)
        except (ValueError, TypeError):
            return "<undecodable>"

    keys = {k[len(_PEERSTATE_PREFIX):]: v for k, v in store.items()
            if k.startswith(_PEERSTATE_PREFIX)}
    addrs = {k[len(PEER_ADDR_PREFIX):]: _load(v)
             for k, v in keys.items() if k.startswith(PEER_ADDR_PREFIX)}
    gens: Dict[int, Dict[str, object]] = {}
    for k, v in keys.items():
        for prefix, field in ((SNAPSHOT_MANIFEST_PREFIX, "manifests"),
                              (SNAPSHOT_COMMIT_PREFIX, "commits")):
            if not k.startswith(prefix):
                continue
            gen_s, _, rank_s = k[len(prefix):].partition(".")
            if not (gen_s.isdigit() and rank_s.isdigit()):
                continue
            rec = gens.setdefault(int(gen_s),
                                  {"manifests": {}, "commits": []})
            if field == "manifests":
                rec["manifests"][rank_s] = _load(v)
            else:
                rec["commits"].append(int(rank_s))
    newest_committed = None
    for gen, rec in sorted(gens.items(), reverse=True):
        rec["commits"] = sorted(rec["commits"])
        root = rec["manifests"].get("0")
        world = (root or {}).get("world_size") if isinstance(root, dict) \
            else None
        world = int(world) if world else len(rec["manifests"])
        rec["world_size"] = world
        rec["committed"] = bool(world) and all(
            str(r) in rec["manifests"] and r in rec["commits"]
            for r in range(world))
        if rec["committed"] and newest_committed is None:
            newest_committed = gen
    return {
        "addrs": addrs,
        "generations": {str(g): r for g, r in sorted(gens.items())},
        "newest_committed": newest_committed,
    }


def build_profile_report(store: Dict[str, bytes]) -> Dict[str, object]:
    """The compute-anatomy table from a store snapshot: every pushed
    per-rank anatomy plus the cross-rank aggregate, computed by the SAME
    :func:`~horovod_tpu.timeline.profiler.aggregate_anatomies` the
    offline CLI uses (``GET /profile``, docs/profiling.md)."""
    per_rank: Dict[str, object] = {}
    for k, v in store.items():
        if not k.startswith(_PROFILE_PREFIX):
            continue
        rank = k[len(_PROFILE_PREFIX):]
        try:
            per_rank[rank] = json.loads(v)
        except (ValueError, TypeError):
            per_rank[rank] = "<undecodable>"
    valid = {r: a for r, a in per_rank.items() if isinstance(a, dict)}
    aggregate = None
    if valid:
        from ..timeline.profiler import aggregate_anatomies

        aggregate = aggregate_anatomies(valid)
    return {"ranks": per_rank, "aggregate": aggregate}


def build_timeseries_report(store: Dict[str, bytes]) -> Dict[str, object]:
    """The time-series table from a store snapshot: each pushed rank's
    series (samples as ``[step, value]`` pairs, oldest first) plus a
    cross-rank summary — per series, every rank's latest value and
    sample count — so one ``GET /timeseries`` answers both "show me the
    history" and "which ranks are reporting" (docs/observe.md)."""
    ranks: Dict[str, object] = {}
    for k, v in store.items():
        if not k.startswith(_TIMESERIES_PREFIX):
            continue
        rank = k[len(_TIMESERIES_PREFIX):]
        try:
            doc = json.loads(v)
            ranks[rank] = doc if isinstance(doc, dict) \
                else "<undecodable>"
        except (ValueError, TypeError):
            ranks[rank] = "<undecodable>"
    summary: Dict[str, Dict[str, object]] = {}
    for rank, doc in ranks.items():
        if not isinstance(doc, dict):
            continue
        for name, entry in (doc.get("series") or {}).items():
            if not isinstance(entry, dict):
                continue
            samples = entry.get("samples") or []
            s = summary.setdefault(name, {"ranks": {}})
            last = samples[-1] if samples else None
            s["ranks"][rank] = {
                "count": len(samples),
                "last_step": entry.get("last_step"),
                "last": last[1] if isinstance(last, (list, tuple))
                and len(last) == 2 else None,
            }
    return {"ranks": ranks, "summary": summary}


def build_alerts_report(store: Dict[str, bytes]) -> Dict[str, object]:
    """The watchdog's alert log from a store snapshot, newest first —
    ``GET /alerts``'s body.  Each record is the detector-emitted
    ``{severity, signal, evidence, window}`` dict plus the ids/stamps
    and any auto-arm / attribution enrichment the watchdog attached
    (observe/watchdog.py, docs/observe.md)."""
    alerts = []
    for k, v in store.items():
        if not k.startswith(_ALERTS_PREFIX):
            continue
        key = k[len(_ALERTS_PREFIX):]
        try:
            rec = json.loads(v)
        except (ValueError, TypeError):
            rec = {"id": key, "error": "<undecodable>"}
        if isinstance(rec, dict):
            rec.setdefault("id", key)
        alerts.append(rec)

    def _order(rec):
        try:
            return int(rec.get("id"))
        except (ValueError, TypeError, AttributeError):
            return -1

    alerts.sort(key=_order, reverse=True)
    counts: Dict[str, int] = {}
    for rec in alerts:
        if isinstance(rec, dict) and rec.get("signal"):
            counts[rec["signal"]] = counts.get(rec["signal"], 0) + 1
    return {"alerts": alerts, "counts": counts}


def build_events_report(store: Dict[str, bytes],
                        since_ts: Optional[float] = None,
                        kind: Optional[str] = None) -> Dict[str, object]:
    """The flight-recorder log from a store snapshot, oldest first —
    ``GET /events``'s body.  Each record is the emitter's ``{id, ts,
    host, rank, kind, severity, correlation_id, cause_id, payload}``
    (observe/events.py).  ``since_ts``/``kind`` filter server-side so a
    following console doesn't re-ship the whole log every poll."""
    records = []
    for k, v in store.items():
        if not k.startswith(_EVENTS_PREFIX):
            continue
        key = k[len(_EVENTS_PREFIX):]
        try:
            rec = json.loads(v)
        except (ValueError, TypeError):
            rec = {"id": key, "error": "<undecodable>"}
        if isinstance(rec, dict):
            rec.setdefault("id", key)
        records.append(rec)
    if since_ts is not None:
        records = [r for r in records
                   if isinstance(r, dict)
                   and (r.get("ts") or 0.0) > since_ts]
    if kind:
        records = [r for r in records if isinstance(r, dict)
                   and str(r.get("kind", "")).startswith(kind)]
    records.sort(key=lambda r: ((r.get("ts") or 0.0)
                                if isinstance(r, dict) else 0.0,
                                str(r.get("id"))
                                if isinstance(r, dict) else ""))
    counts: Dict[str, int] = {}
    for rec in records:
        if isinstance(rec, dict) and rec.get("kind"):
            counts[rec["kind"]] = counts.get(rec["kind"], 0) + 1
    return {"events": records, "counts": counts}


def build_autotune_report(store: Dict[str, bytes]) -> Dict[str, object]:
    """The profile-guided tuning table from a store snapshot: every
    pushed plan record in sequence order, the latest record as
    ``current``, and the headline predicted/realized speedup pair —
    ``GET /autotune``'s body (docs/autotune.md)."""
    plans = []
    for k, v in store.items():
        if not k.startswith(_AUTOTUNE_PREFIX):
            continue
        key = k[len(_AUTOTUNE_PREFIX):]
        if not key.startswith(AUTOTUNE_PLAN_PREFIX):
            continue
        seq_s = key[len(AUTOTUNE_PLAN_PREFIX):]
        try:
            seq = int(seq_s)
        except ValueError:
            continue
        try:
            rec = json.loads(v)
        except (ValueError, TypeError):
            rec = "<undecodable>"
        plans.append({"seq": seq, "record": rec})
    plans.sort(key=lambda p: p["seq"])
    current = plans[-1]["record"] if plans else None
    report: Dict[str, object] = {"plans": plans, "current": current}
    if isinstance(current, dict):
        report["predicted_speedup_pct"] = current.get(
            "predicted_speedup_pct")
        report["realized_speedup_pct"] = current.get(
            "realized_speedup_pct")
        report["outcome"] = current.get("outcome")
    return report


def _decode_abort(store) -> Optional[object]:
    """The job-wide abort flag, parsed (None when unset) — piggybacked
    on health-renewal and /batch replies so one round trip answers both
    "lease renewed" and "is the job aborting"."""
    raw = store.get(f"/{ABORT_SCOPE}/{ABORT_KEY}")
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except (ValueError, TypeError):
        return {"reason": "<undecodable abort flag>"}


def _epoch_of(value: bytes) -> Optional[int]:
    try:
        rec = json.loads(value)
        return int(rec.get("epoch"))
    except (ValueError, TypeError, AttributeError):
        return None


def apply_put(httpd, path: str, value: bytes) -> None:
    """One KV write — the single choke point shared by ``do_PUT``,
    ``PUT /batch``, and the in-process :meth:`RendezvousServer.put`:
    fences ``/membership/epoch`` regressions (:class:`EpochFencedError`)
    and stamps health leases on the server's clock."""
    store = httpd.store
    if path == EPOCH_PATH:
        # check-then-put under one lock: two concurrent writers (the
        # live driver and a partitioned stale one — the very race the
        # fence exists for) must serialize, or both could pass the
        # check against the same committed epoch
        with httpd.fence_lock:
            new = _epoch_of(value)
            cur_raw = store.get(EPOCH_PATH)
            if cur_raw is not None:
                cur = _epoch_of(cur_raw)
                if cur is not None and (new is None or new < cur):
                    raise EpochFencedError(
                        f"membership epoch write ({new}) does not advance "
                        f"the committed epoch ({cur}); rejected by the "
                        "split-brain fence")
            store.put(path, value)
        return
    store.put(path, value)
    if path.startswith(_HEALTH_PREFIX):
        # the lease stamp: receipt on the SERVER clock, so age /
        # expiry never depend on worker clocks (GET /health)
        with httpd.lock:
            httpd.lease_times[path] = time.monotonic()


class _DeltaResync(Exception):
    """A metrics delta PUT cannot be merged (unknown base incarnation
    or no stored snapshot): the pusher must resend a full snapshot."""


def _parse_metrics_delta(body: bytes) -> Optional[dict]:
    """Decode a metrics-scope PUT body as a delta payload, or None for
    a plain full snapshot.  Deltas are written with ``__delta__`` as
    the first key (metrics/push.py), so the cheap prefix check keeps
    full-snapshot PUTs off the JSON parser twice."""
    if b'"__delta__"' not in body[:32]:
        return None
    try:
        payload = json.loads(body)
    except (ValueError, TypeError):
        return None
    if isinstance(payload, dict) and payload.get("__delta__"):
        return payload
    return None


def _merge_metrics_delta(store, path: str, delta: dict,
                         server_id: str) -> bytes:
    """Merge a delta push into the stored full snapshot; raises
    :class:`_DeltaResync` when the delta's base incarnation is not this
    server (restart/failover) or there is nothing to merge into."""
    if delta.get("base_id") != server_id:
        raise _DeltaResync()
    cur_raw = store.get(path)
    if cur_raw is None:
        raise _DeltaResync()
    try:
        cur = json.loads(cur_raw)
    except (ValueError, TypeError):
        raise _DeltaResync()
    fams = cur.get("metrics")
    if not isinstance(fams, dict):
        raise _DeltaResync()
    changed = delta.get("metrics")
    if isinstance(changed, dict):
        fams.update(changed)
    for name in delta.get("removed") or ():
        fams.pop(name, None)
    cur["ts"] = delta.get("ts", time.time())
    return json.dumps(cur).encode()


def _parse_ts_delta(body: bytes) -> Optional[dict]:
    """Decode a timeseries-scope PUT body as an append-delta payload,
    or None for a full snapshot.  Same cheap-prefix contract as
    :func:`_parse_metrics_delta` (``__tsdelta__`` is written first,
    metrics/timeseries.py)."""
    if b'"__tsdelta__"' not in body[:32]:
        return None
    try:
        payload = json.loads(body)
    except (ValueError, TypeError):
        return None
    if isinstance(payload, dict) and payload.get("__tsdelta__"):
        return payload
    return None


def _merge_ts_delta(store, path: str, delta: dict,
                    server_id: str) -> bytes:
    """Append a timeseries delta into the stored per-rank document;
    raises :class:`_DeltaResync` when the delta's base incarnation is
    not this server or there is nothing to append into.  Each series is
    trimmed to ``HVD_TIMESERIES_SERVER_CAP`` samples — the server-side
    bound that keeps an always-on history from growing a per-rank doc
    without limit."""
    from ..utils import env as env_util

    if delta.get("base_id") != server_id:
        raise _DeltaResync()
    cur_raw = store.get(path)
    if cur_raw is None:
        raise _DeltaResync()
    try:
        cur = json.loads(cur_raw)
    except (ValueError, TypeError):
        raise _DeltaResync()
    series = cur.get("series")
    if not isinstance(series, dict):
        raise _DeltaResync()
    cap = env_util.get_int(env_util.HVD_TIMESERIES_SERVER_CAP,
                           env_util.DEFAULT_TIMESERIES_SERVER_CAP)
    for name, entry in (delta.get("series") or {}).items():
        if not isinstance(entry, dict):
            continue
        dst = series.setdefault(name, {"samples": []})
        samples = dst.get("samples")
        if not isinstance(samples, list):
            samples = dst["samples"] = []
        new = [s for s in entry.get("samples") or ()
               if isinstance(s, (list, tuple)) and len(s) == 2]
        samples.extend([list(s) for s in new])
        if len(samples) > cap:
            del samples[:len(samples) - cap]
        dst["seq"] = entry.get("seq", dst.get("seq"))
        if entry.get("dropped"):
            dst["dropped"] = dst.get("dropped", 0) + int(entry["dropped"])
        if new:
            dst["last_step"] = new[-1][0]
    cur["ts"] = time.time()
    return json.dumps(cur).encode()


class KVStoreHandler(BaseHTTPRequestHandler):
    """GET /scope/key → 200 bytes | 404; PUT stores; DELETE /scope
    finalizes the scope (rendezvous complete)."""

    protocol_version = "HTTP/1.1"
    # reap idle keep-alive connections (run/http_client.py pools one
    # connection per client thread) instead of holding a server thread
    # per dead client forever
    timeout = 65
    # small replies written in several send() calls + Nagle + the
    # client's delayed ACK = ~40 ms per exchange on a keep-alive
    # connection; the control plane lives on small exchanges
    disable_nagle_algorithm = True

    def _count(self) -> None:
        if getattr(self.server, "rdv_dead", False):
            # stop() ran but this keep-alive connection's handler thread
            # is still alive: a stopped server must look DEAD to pooled
            # clients (connection aborted → their failover path), not
            # like a live store serving a stale world
            raise ConnectionAbortedError("rendezvous server stopped")
        with self.server.count_lock:  # type: ignore[attr-defined]
            self.server.requests_served += 1  # type: ignore[attr-defined]

    def _verify(self, body: bytes = b"") -> bool:
        secret = self.server.secret  # type: ignore[attr-defined]
        if secret is None:
            return True
        got = self.headers.get(SECRET_HEADER, "")
        want = sign(secret, self.path, body)
        return hmac.compare_digest(got, want)

    def _reply(self, code: int, body: bytes = b"",
               content_type: Optional[str] = None) -> None:
        self.send_response(code)
        if content_type:
            self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _rank_snapshots(self):
        """(extra_labels, snapshot) per pushed rank, rank-ordered, plus
        the launcher's own in-process registry last."""
        from ..metrics.registry import registry

        store: ShardedKVStore = self.server.store  # type: ignore
        pushed = {k[len(_METRICS_PREFIX):]: v
                  for k, v in store.prefix_items(_METRICS_PREFIX).items()}
        snaps = []
        for rank in sorted(pushed, key=lambda r: (not r.isdigit(), int(r)
                                                  if r.isdigit() else 0, r)):
            try:
                snaps.append(({"rank": rank}, json.loads(pushed[rank])))
            except (ValueError, TypeError):
                log.warning("metrics: undecodable snapshot from rank %s",
                            rank)
        snaps.append(({"rank": "launcher"}, registry.snapshot()))
        return snaps

    def _sanitizer_table(self) -> Dict[str, Dict[str, Dict[str, object]]]:
        """Published collective fingerprints partitioned by communication
        group, then ``<epoch>.<seq>``, then rank:
        ``{"world": {"0.5": {"0": {...}, "1": {...}}}}`` — the live view
        of which rank is ahead/behind *within each group* when the
        sanitizer (or an operator) is chasing a divergence.  Keys are
        ``<group>.<epoch>.<seq>.<rank>`` (analysis/sanitizer.py); legacy
        two-part ``<seq>.<rank>`` keys render under ``world`` epoch 0."""
        store: ShardedKVStore = self.server.store  # type: ignore
        raw = {k[len(_SANITIZER_PREFIX):]: v
               for k, v in store.prefix_items(_SANITIZER_PREFIX).items()}
        table: Dict[str, Dict[str, Dict[str, object]]] = {}
        for key, val in raw.items():
            parts = key.split(".")
            if len(parts) == 4:
                group, epoch, seq, rank = parts
            elif len(parts) == 2:
                group, epoch = "world", "0"
                seq, rank = parts
            else:
                continue
            try:
                decoded: object = json.loads(val)
            except (ValueError, TypeError):
                decoded = "<undecodable>"
            table.setdefault(group, {}).setdefault(
                f"{epoch}.{seq}", {})[rank] = decoded
        return table

    def _health_report(self) -> Dict[str, object]:
        """Per-rank lease ages and verdicts plus the abort flag, so one
        GET answers both "who is alive" and "is the job aborting"."""
        with self.server.lock:  # type: ignore
            lease_times = dict(self.server.lease_times)  # type: ignore
        return build_health_report(
            self.server.store.items(), lease_times)  # type: ignore

    def do_GET(self) -> None:  # noqa: N802
        self._count()
        if not self._verify():
            self._reply(401)
            return
        path, _, query = self.path.partition("?")
        path = path.rstrip("/")
        if path.startswith(SCOPE_ROUTE_PREFIX) and "since=" in query:
            # scope-level batch read with a change cursor: GET
            # /scope/<name>?since=V (docs/control_plane.md).  The
            # ``since`` parameter is what selects this route — clients
            # always send one (-1 = full) — so a plain GET of a KV key
            # under a scope literally named "scope" still works.
            from urllib.parse import parse_qs

            scope = path[len(SCOPE_ROUTE_PREFIX):]
            since = None
            vals = parse_qs(query).get("since")
            if vals:
                try:
                    since = int(vals[0])
                except ValueError:
                    since = None
            res = self.server.store.scope_since(scope, since)  # type: ignore
            body = json.dumps({
                "server_id": self.server.server_id,  # type: ignore
                "version": res["version"],
                "full": res["full"],
                "entries": {k: b64encode(v).decode()
                            for k, v in res["entries"].items()},
                "removed": res["removed"],
            }).encode()
            self._reply(200, body, content_type="application/json")
            return
        if path == "/health":
            self._reply(200, json.dumps(self._health_report()).encode(),
                        content_type="application/json")
            return
        if path == "/membership":
            store = self.server.store.items()  # type: ignore
            self._reply(200, json.dumps(build_membership_report(store))
                        .encode(), content_type="application/json")
            return
        if path == "/peerstate":
            store = self.server.store.items()  # type: ignore
            self._reply(200, json.dumps(build_peerstate_report(store))
                        .encode(), content_type="application/json")
            return
        if path == "/serving":
            frontend = getattr(self.server, "serving_frontend", None)
            if frontend is None:
                self._reply(404)
                return
            try:
                body = json.dumps(frontend.report()).encode()
            except Exception as e:  # noqa: BLE001 — status page must
                body = json.dumps(  # not 500 the whole server
                    {"error": f"{type(e).__name__}: {e}"}).encode()
            self._reply(200, body, content_type="application/json")
            return
        # Aggregated metrics routes.  No key collision with the KV store:
        # stored keys are always two-part /scope/key paths.
        if path == "/metrics":
            from ..metrics.registry import render_prometheus

            body = render_prometheus(self._rank_snapshots()).encode()
            self._reply(200, body,
                        content_type="text/plain; version=0.0.4")
            return
        if path == "/metrics.json":
            merged = {labels["rank"]: snap
                      for labels, snap in self._rank_snapshots()}
            self._reply(200, json.dumps(merged).encode(),
                        content_type="application/json")
            return
        if path == "/sanitizer":
            self._reply(200, json.dumps(self._sanitizer_table()).encode(),
                        content_type="application/json")
            return
        if path == "/clock":
            # one leg of the NTP-style offset handshake
            # (timeline/replay/clock.py): the server's monotonic clock in
            # µs — only server-relative consistency matters, every rank
            # estimates its offset against this same process clock
            body = json.dumps({"server_us": time.perf_counter() * 1e6})
            self._reply(200, body.encode(),
                        content_type="application/json")
            return
        if path == "/replay":
            val = self.server.store.get(  # type: ignore
                f"/{REPLAY_SCOPE}/{REPLAY_SUMMARY_KEY}")
            if val is None:
                self._reply(404)
            else:
                self._reply(200, val, content_type="application/json")
            return
        if path == "/projection":
            val = self.server.store.get(  # type: ignore
                f"/{PROJECTION_SCOPE}/{PROJECTION_SUMMARY_KEY}")
            if val is None:
                self._reply(404)
            else:
                self._reply(200, val, content_type="application/json")
            return
        if path == "/autotune":
            store = self.server.store.items()  # type: ignore
            self._reply(200, json.dumps(build_autotune_report(store))
                        .encode(), content_type="application/json")
            return
        if path == "/profile":
            store = self.server.store.items()  # type: ignore
            self._reply(200, json.dumps(build_profile_report(store))
                        .encode(), content_type="application/json")
            return
        if path == "/timeseries":
            store = self.server.store.items()  # type: ignore
            self._reply(200, json.dumps(build_timeseries_report(store))
                        .encode(), content_type="application/json")
            return
        if path == "/alerts":
            store = self.server.store.items()  # type: ignore
            report = build_alerts_report(store)
            # the report carries the incarnation id so a following
            # console (hvd_watch --follow) can tell a restarted server
            # from a quiet one instead of re-printing old alerts
            report["server_id"] = self.server.server_id  # type: ignore
            self._reply(200, json.dumps(report).encode(),
                        content_type="application/json")
            return
        if path == "/events":
            from urllib.parse import parse_qs

            qs = parse_qs(query)
            since_ts = None
            vals = qs.get("since_ts")
            if vals:
                try:
                    since_ts = float(vals[0])
                except ValueError:
                    since_ts = None
            kind = (qs.get("kind") or [None])[0]
            store = self.server.store.items()  # type: ignore
            report = build_events_report(store, since_ts=since_ts,
                                         kind=kind)
            report["server_id"] = self.server.server_id  # type: ignore
            report["version"] = \
                self.server.store.scope_since(  # type: ignore
                    EVENTS_SCOPE, None)["version"]
            self._reply(200, json.dumps(report).encode(),
                        content_type="application/json")
            return
        val = self.server.store.get(self.path)  # type: ignore
        if val is None:
            self._reply(404)
        else:
            self._reply(200, val)

    def do_POST(self) -> None:  # noqa: N802
        """Serving-plane routes (horovod_tpu/serving/frontend.py): the
        KV store itself has no POST surface, so every POST belongs to
        the attached ServingFrontend — 503 when none is attached (the
        job was not launched with ``tpurun --serve``)."""
        self._count()
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._verify(body):
            self._reply(401)
            return
        frontend = getattr(self.server, "serving_frontend", None)
        path = self.path.rstrip("/")
        routes = {} if frontend is None else {
            "/infer": frontend.handle_infer,
            "/serving/pull": frontend.handle_pull,
            "/serving/result": frontend.handle_result,
        }
        handler = routes.get(path)
        if handler is None:
            if path in ("/infer", "/serving/pull", "/serving/result"):
                self._reply(503, json.dumps(
                    {"error": "no serving plane attached (launch with "
                              "tpurun --serve)"}).encode(),
                    content_type="application/json")
            else:
                self._reply(404)
            return
        try:
            payload = json.loads(body) if body else {}
        except ValueError as e:
            self._reply(400, json.dumps(
                {"error": f"undecodable JSON body: {e}"}).encode(),
                content_type="application/json")
            return
        try:
            code, reply = handler(payload)
        except Exception as e:  # noqa: BLE001 — a handler bug must not
            code, reply = 500, {  # tear down the rendezvous server
                "error": f"{type(e).__name__}: {e}"}
            log.exception("serving route %s failed", path)
        self._reply(code, json.dumps(reply).encode(),
                    content_type="application/json")

    def do_PUT(self) -> None:  # noqa: N802
        self._count()
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._verify(body):
            self._reply(401)
            return
        if self.path == BATCH_PATH:
            self._handle_batch(body)
            return
        try:
            reply = self._apply_one(self.path, body)
        except EpochFencedError as e:
            self._reply(409, json.dumps({"error": str(e)}).encode(),
                        content_type="application/json")
            return
        except _DeltaResync:
            self._reply(409, json.dumps({
                "server_id": self.server.server_id,  # type: ignore
                "resync": True}).encode(),
                content_type="application/json")
            return
        if reply is None:
            self._reply(200)
        else:
            self._reply(200, json.dumps(reply).encode(),
                        content_type="application/json")

    def _apply_one(self, path: str, body: bytes) -> Optional[dict]:
        """Store one PUT.  Health renewals answer with the abort
        verdict (the heartbeat's batched round trip); metrics PUTs may
        be delta payloads merged server-side; both reply the
        ``server_id`` so clients detect failovers."""
        httpd = self.server
        if path.startswith(_METRICS_PREFIX):
            delta = _parse_metrics_delta(body)
            if delta is not None:
                body = _merge_metrics_delta(
                    httpd.store, path, delta,  # type: ignore
                    httpd.server_id)  # type: ignore[attr-defined]
            apply_put(httpd, path, body)
            return {"server_id": httpd.server_id}  # type: ignore
        if path.startswith(_TIMESERIES_PREFIX):
            delta = _parse_ts_delta(body)
            if delta is not None:
                body = _merge_ts_delta(
                    httpd.store, path, delta,  # type: ignore
                    httpd.server_id)  # type: ignore[attr-defined]
            apply_put(httpd, path, body)
            return {"server_id": httpd.server_id}  # type: ignore
        apply_put(httpd, path, body)
        if path.startswith(_HEALTH_PREFIX):
            return {"server_id": httpd.server_id,  # type: ignore
                    "abort": _decode_abort(httpd.store)}  # type: ignore
        return None

    def _handle_batch(self, body: bytes) -> None:
        """``PUT /batch``: apply many KV entries in one signed request
        (the relay tree's upstream leg).  Undecodable entries are
        counted and skipped; a fenced epoch write rejects the batch."""
        try:
            payload = json.loads(body)
        except ValueError as e:
            self._reply(400, json.dumps(
                {"error": f"undecodable batch body: {e}"}).encode(),
                content_type="application/json")
            return
        applied = skipped = 0
        try:
            for entry in payload.get("entries") or ():
                path = entry.get("p") if isinstance(entry, dict) else None
                if not isinstance(path, str) or not path.startswith("/"):
                    skipped += 1
                    continue
                try:
                    value = b64decode(entry.get("v") or "")
                except (ValueError, TypeError):
                    skipped += 1
                    continue
                apply_put(self.server, path, value)
                applied += 1
        except EpochFencedError as e:
            self._reply(409, json.dumps({"error": str(e)}).encode(),
                        content_type="application/json")
            return
        self._reply(200, json.dumps({
            "server_id": self.server.server_id,  # type: ignore
            "abort": _decode_abort(self.server.store),  # type: ignore
            "applied": applied,
            "skipped": skipped,
        }).encode(), content_type="application/json")

    def do_DELETE(self) -> None:  # noqa: N802
        self._count()
        if not self._verify():
            self._reply(401)
            return
        deleted = self.server.store.delete_matching(self.path)  # type: ignore
        with self.server.lock:  # type: ignore
            for k in deleted:
                self.server.lease_times.pop(k, None)  # type: ignore
            # only whole-scope deletes mark rendezvous finalization;
            # per-key deletes (sanitizer fingerprint GC) must not grow
            # this set one entry per dispatch
            if self.path.rstrip("/").count("/") == 1:
                self.server.finalized.add(self.path)  # type: ignore
        self._reply(200)

    def log_message(self, fmt, *args):  # silence default stderr spam
        log.debug("kvstore: " + fmt, *args)


class QuietThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that does not print tracebacks for expected
    connection teardowns: a stopped server aborting its keep-alive
    connections (``rdv_dead``) and clients hanging up mid-request."""

    def handle_error(self, request, client_address):  # noqa: D102
        import sys as _sys

        exc = _sys.exc_info()[1]
        if isinstance(exc, (ConnectionAbortedError, ConnectionResetError,
                            BrokenPipeError)):
            return
        super().handle_error(request, client_address)


class RendezvousServer:
    """Threaded KV server owned by the launcher (reference
    run/http/http_server.py RendezvousServer; started by gloo_run at
    reference run/gloo_run.py:268-272)."""

    def __init__(self, secret: Optional[bytes] = None, port: int = 0,
                 journal_path: Optional[str] = None,
                 shards: Optional[int] = None):
        self._httpd = QuietThreadingHTTPServer(("0.0.0.0", port),
                                               KVStoreHandler)
        store = ShardedKVStore(shards=shards)
        journal = None
        if journal_path:
            import os as _os

            from .journal import Journal, replay

            # recovery BEFORE journaling resumes: a restarted primary
            # picks its state (and, critically, the committed epoch the
            # fence compares against) back up from its own journal
            # instead of starting empty — without re-journaling the
            # replayed records
            if _os.path.exists(journal_path):
                n = replay(journal_path, store)
                if n:
                    log.info("rendezvous: recovered %d journal records "
                             "from %s", n, journal_path)
            journal = Journal(journal_path)
            store.journal = journal
        self._journal = journal
        self._httpd.store = store  # type: ignore[attr-defined]
        # guards the non-sharded side state: lease_times + finalized
        self._httpd.lock = threading.Lock()  # type: ignore[attr-defined]
        self._httpd.secret = secret  # type: ignore[attr-defined]
        self._httpd.finalized = set()  # type: ignore[attr-defined]
        self._httpd.lease_times = {}  # type: ignore[attr-defined]
        self._httpd.serving_frontend = None  # type: ignore[attr-defined]
        # per-incarnation identity: clients detect a restart/failover by
        # the server_id changing in mutating replies and scope reads
        self._httpd.server_id = uuid.uuid4().hex  # type: ignore
        self._httpd.requests_served = 0  # type: ignore[attr-defined]
        self._httpd.count_lock = threading.Lock()  # type: ignore
        # serializes the /membership/epoch check-then-put (apply_put)
        self._httpd.fence_lock = threading.Lock()  # type: ignore
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def store(self) -> ShardedKVStore:
        return self._httpd.store  # type: ignore[attr-defined]

    @property
    def server_id(self) -> str:
        return self._httpd.server_id  # type: ignore[attr-defined]

    @property
    def requests_served(self) -> int:
        """Total HTTP requests handled (the churn benchmark's
        request-rate instrument, scripts/control_plane_bench.py)."""
        return self._httpd.requests_served  # type: ignore[attr-defined]

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="hvd-rendezvous",
        )
        self._thread.start()
        log.debug("rendezvous server on port %d", self.port)
        return self.port

    def stop(self) -> None:
        self._httpd.rdv_dead = True  # type: ignore[attr-defined]
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
        # release the port: pooled keep-alive clients must see a dead
        # primary as connection-refused, not a silent accept-less bind
        self._httpd.server_close()
        if self._journal is not None:
            self._journal.close()

    # direct (in-process) access for the launcher itself
    def get(self, scope: str, key: str) -> Optional[bytes]:
        return self.store.get(f"/{scope}/{key}")

    def put(self, scope: str, key: str, value: bytes) -> None:
        """One in-process write, through the same fence/journal/lease
        choke point as the HTTP surface (raises
        :class:`EpochFencedError` on a regressed epoch commit)."""
        apply_put(self._httpd, f"/{scope}/{key}", value)

    def delete(self, scope: str, key: str) -> None:
        """Drop one key (e.g. the elastic driver revoking a dead rank's
        /health lease)."""
        path = f"/{scope}/{key}"
        self.store.pop(path)
        with self._httpd.lock:  # type: ignore[attr-defined]
            self._httpd.lease_times.pop(path, None)  # type: ignore

    def scope_items(self, scope: str) -> Dict[str, bytes]:
        """Snapshot of every key under ``scope`` (key names without the
        scope prefix) — the elastic driver's poll of announces/acks."""
        prefix = f"/{scope}/"
        return {k[len(prefix):]: v
                for k, v in self.store.prefix_items(prefix).items()}

    def scope_since(self, scope: str,
                    since: Optional[int] = None) -> Dict[str, object]:
        """In-process equivalent of ``GET /scope/<name>?since=V``."""
        return self.store.scope_since(scope, since)

    def health_report(self) -> Dict[str, object]:
        """In-process equivalent of GET /health (the elastic driver polls
        lease verdicts without going through its own HTTP stack)."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            lease_times = dict(self._httpd.lease_times)  # type: ignore
        return build_health_report(self.store.items(), lease_times)

    def membership_report(self) -> Dict[str, object]:
        """In-process equivalent of GET /membership."""
        return build_membership_report(self.store.items())

    def autotune_report(self) -> Dict[str, object]:
        """In-process equivalent of GET /autotune."""
        return build_autotune_report(self.store.items())

    def profile_report(self) -> Dict[str, object]:
        """In-process equivalent of GET /profile."""
        return build_profile_report(self.store.items())

    def timeseries_report(self) -> Dict[str, object]:
        """In-process equivalent of GET /timeseries (the watchdog's
        per-tick read when it runs next to this server)."""
        return build_timeseries_report(self.store.items())

    def alerts_report(self) -> Dict[str, object]:
        """In-process equivalent of GET /alerts."""
        return build_alerts_report(self.store.items())

    def events_report(self, since_ts: Optional[float] = None,
                      kind: Optional[str] = None) -> Dict[str, object]:
        """In-process equivalent of GET /events (the flight-recorder
        log, oldest first — observe/events.py)."""
        return build_events_report(self.store.items(), since_ts=since_ts,
                                   kind=kind)

    def projection_report(self) -> Optional[Dict[str, object]]:
        """In-process equivalent of GET /projection (None when no
        projection summary has been pushed)."""
        raw = self.get(PROJECTION_SCOPE, PROJECTION_SUMMARY_KEY)
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except (ValueError, TypeError):
            return {"error": "<undecodable projection summary>"}

    def attach_serving(self, frontend) -> None:
        """Attach a serving front-end (serving/frontend.py): POST
        /infer, POST /serving/pull|result, and GET /serving route to it
        from then on.  ``None`` detaches."""
        self._httpd.serving_frontend = frontend  # type: ignore

    def serving_report(self) -> Optional[Dict[str, object]]:
        """In-process equivalent of GET /serving (None when no serving
        plane is attached)."""
        frontend = getattr(self._httpd, "serving_frontend", None)
        return None if frontend is None else frontend.report()

    def clear_scope(self, scope: str) -> None:
        """Drop every key under ``scope`` (the supervisor resets the
        ``abort``/``health`` scopes between restart attempts so a stale
        flag cannot abort the fresh incarnation)."""
        prefix = f"/{scope}/"
        self.store.clear_scope(scope)
        with self._httpd.lock:  # type: ignore[attr-defined]
            lease_times = self._httpd.lease_times  # type: ignore
            for k in [k for k in lease_times if k.startswith(prefix)]:
                del lease_times[k]


def find_free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]
