"""CLI-flag → env-var translation and YAML config override.

Mirror of reference horovod/run/common/util/config_parser.py (+ the YAML
hook at run/run.py:446-449,609-613): every tunable exists in three layers
that must stay consistent — HVD_* env var (consumed by the runtime,
horovod_tpu/utils/env.py), tpurun CLI flag (this file translates), optional
YAML config file (overrides CLI args before translation)."""

from __future__ import annotations

from typing import Dict, Optional

from ..utils import env as env_util

# YAML section/key → argparse dest (reference config_parser.py mapping)
_CONFIG_SCHEMA = {
    "params": {
        "fusion_threshold_mb": "fusion_threshold_mb",
        "cycle_time_ms": "cycle_time_ms",
        "cache_capacity": "cache_capacity",
        "hierarchical_allreduce": "hierarchical_allreduce",
        "hierarchical_allgather": "hierarchical_allgather",
        "ring_min_bytes": "ring_min_bytes",
        "compression": "compression",
        "no_error_feedback": "no_error_feedback",
        "two_level_allreduce": "two_level_allreduce",
    },
    "autotune": {
        "enabled": "autotune",
        "log_file": "autotune_log_file",
        "warmup_samples": "autotune_warmup_samples",
        "steps_per_sample": "autotune_steps_per_sample",
        "bayes_opt_max_samples": "autotune_bayes_opt_max_samples",
        "gaussian_process_noise": "autotune_gaussian_process_noise",
        "profile_guided": "profile_guided",
        "window_steps": "autotune_window_steps",
        "guard_band_pct": "autotune_guard_band_pct",
    },
    "timeline": {
        "filename": "timeline_filename",
        "mark_cycles": "timeline_mark_cycles",
    },
    "stall_check": {
        "disable": "no_stall_check",
        "warning_time_seconds": "stall_check_warning_time_seconds",
        "shutdown_time_seconds": "stall_check_shutdown_time_seconds",
    },
    "library_options": {
        "num_streams": "num_streams",
    },
    "serving": {
        "enabled": "serve",
        "max_batch": "serve_max_batch",
        "max_wait_ms": "serve_max_wait_ms",
        "slo_ms": "serve_slo_ms",
        "autoscale": "serve_autoscale",
    },
    "control_plane": {
        "relay": "relay",
        "journal": "journal",
    },
    "logging": {
        "level": "log_level",
        "hide_timestamp": "log_hide_timestamp",
    },
}


def set_args_from_config(args, config: dict, override_args: set) -> None:
    """Apply YAML config onto parsed args, skipping flags the user passed
    explicitly (reference config_parser.set_args_from_config)."""
    for section, keys in _CONFIG_SCHEMA.items():
        section_cfg = config.get(section) or {}
        for yaml_key, dest in keys.items():
            if yaml_key in section_cfg and dest not in override_args:
                setattr(args, dest, section_cfg[yaml_key])


def env_from_args(args) -> Dict[str, str]:
    """Translate parsed tpurun args into the HVD_* env dict for workers
    (reference config_parser.set_env_from_args, called run/run.py:841)."""
    env: Dict[str, str] = {}

    def setb(name, val):
        if val:
            env[name] = "1"

    if getattr(args, "fusion_threshold_mb", None) is not None:
        env[env_util.HVD_FUSION_THRESHOLD] = str(
            int(args.fusion_threshold_mb * 1024 * 1024)
        )
    if getattr(args, "cycle_time_ms", None) is not None:
        env[env_util.HVD_CYCLE_TIME] = str(args.cycle_time_ms)
    if getattr(args, "cache_capacity", None) is not None:
        env[env_util.HVD_CACHE_CAPACITY] = str(args.cache_capacity)
    if getattr(args, "ring_min_bytes", None) is not None:
        env[env_util.HVD_RING_MIN_BYTES] = str(args.ring_min_bytes)
    setb(env_util.HVD_HIERARCHICAL_ALLREDUCE,
         getattr(args, "hierarchical_allreduce", False))
    setb(env_util.HVD_HIERARCHICAL_ALLGATHER,
         getattr(args, "hierarchical_allgather", False))
    if getattr(args, "compression", None):
        env[env_util.HVD_COMPRESSION] = str(args.compression)
    if getattr(args, "no_error_feedback", False):
        env[env_util.HVD_COMPRESSION_ERROR_FEEDBACK] = "0"
    setb(env_util.HVD_TWO_LEVEL_ALLREDUCE,
         getattr(args, "two_level_allreduce", False))

    setb(env_util.HVD_AUTOTUNE, getattr(args, "autotune", False))
    if getattr(args, "autotune", False):
        if getattr(args, "autotune_log_file", None):
            env[env_util.HVD_AUTOTUNE_LOG] = str(args.autotune_log_file)
        for attr, var in [
            ("autotune_warmup_samples", env_util.HVD_AUTOTUNE_WARMUP_SAMPLES),
            ("autotune_steps_per_sample",
             env_util.HVD_AUTOTUNE_STEPS_PER_SAMPLE),
            ("autotune_bayes_opt_max_samples",
             env_util.HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES),
            ("autotune_gaussian_process_noise",
             env_util.HVD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE),
        ]:
            if getattr(args, attr, None) is not None:
                env[var] = str(getattr(args, attr))

    setb(env_util.HVD_AUTOTUNE_PROFILE_GUIDED,
         getattr(args, "profile_guided", False))
    if getattr(args, "autotune_window_steps", None) is not None:
        env[env_util.HVD_AUTOTUNE_WINDOW_STEPS] = str(
            args.autotune_window_steps)
    if getattr(args, "autotune_guard_band_pct", None) is not None:
        env[env_util.HVD_AUTOTUNE_GUARD_BAND_PCT] = str(
            args.autotune_guard_band_pct)

    if getattr(args, "timeline_filename", None):
        env[env_util.HVD_TIMELINE] = str(args.timeline_filename)
        setb(env_util.HVD_TIMELINE_MARK_CYCLES,
             getattr(args, "timeline_mark_cycles", False))
    if getattr(args, "trace_start_step", None) is not None:
        env[env_util.HVD_TRACE_START_STEP] = str(args.trace_start_step)
    if getattr(args, "trace_end_step", None) is not None:
        env[env_util.HVD_TRACE_END_STEP] = str(args.trace_end_step)

    if getattr(args, "network_interface", None):
        env[env_util.HVD_NETWORK_INTERFACE] = str(args.network_interface)

    setb(env_util.HVD_STALL_CHECK_DISABLE,
         getattr(args, "no_stall_check", False))
    if getattr(args, "stall_check_warning_time_seconds", None) is not None:
        env[env_util.HVD_STALL_CHECK_TIME_SECONDS] = str(
            args.stall_check_warning_time_seconds
        )
    if getattr(args, "stall_check_shutdown_time_seconds", None) is not None:
        env[env_util.HVD_STALL_SHUTDOWN_TIME_SECONDS] = str(
            args.stall_check_shutdown_time_seconds
        )

    setb(env_util.HVD_SERVE, getattr(args, "serve", False))
    if getattr(args, "serve_max_batch", None) is not None:
        env[env_util.HVD_SERVE_MAX_BATCH] = str(args.serve_max_batch)
    if getattr(args, "serve_max_wait_ms", None) is not None:
        env[env_util.HVD_SERVE_MAX_WAIT_MS] = str(args.serve_max_wait_ms)
    if getattr(args, "serve_slo_ms", None) is not None:
        env[env_util.HVD_SERVE_SLO_MS] = str(args.serve_slo_ms)
    setb(env_util.HVD_SERVE_AUTOSCALE,
         getattr(args, "serve_autoscale", False))

    if getattr(args, "log_level", None):
        env[env_util.HVD_LOG_LEVEL] = str(args.log_level)
    setb(env_util.HVD_LOG_HIDE_TIME, getattr(args, "log_hide_timestamp", False))
    return env
