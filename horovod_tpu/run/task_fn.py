"""Worker-side stub for function mode (reference horovod/run/task_fn.py /
run_task.py: fetch the pickled fn from the KV store, execute, publish the
result)."""

from __future__ import annotations

import os
import pickle
import sys
import traceback

from .http_client import get_kv, put_kv


def main() -> int:
    addr = os.environ["HVD_RUN_KV_ADDR"]
    port = int(os.environ["HVD_RUN_KV_PORT"])
    secret = bytes.fromhex(os.environ["HVD_RUN_SECRET"])
    pid = os.environ["HVD_RUN_PID"]

    blob = get_kv(addr, port, "job", "fn", secret=secret, wait=True)
    assert blob is not None
    fn, args, kwargs = pickle.loads(blob)
    try:
        value = fn(*args, **kwargs)
        payload = {"value": value, "error": None}
        rc = 0
    except Exception:  # noqa: BLE001
        payload = {"value": None, "error": traceback.format_exc()}
        rc = 1
    # final metrics snapshot: short function-mode jobs end before any
    # push interval elapses, so the worker flushes its registry here and
    # the parent's GET /metrics sees every rank
    from ..metrics.push import push_snapshot
    from ..metrics.registry import registry

    if registry.enabled:
        push_snapshot(addr, port, int(pid), secret)
    put_kv(addr, port, "result", pid, pickle.dumps(payload), secret=secret)
    return rc


if __name__ == "__main__":
    sys.exit(main())
