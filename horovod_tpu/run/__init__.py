"""horovod_tpu.run — the launcher package.

Re-exports the function-mode API at the package level so
``from horovod_tpu.run import run`` works exactly like the reference's
``from horovod.run import run`` (reference horovod/run/__init__.py:16).
"""

from .run import run, run_commandline  # noqa: F401
