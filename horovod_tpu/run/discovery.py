"""TPU pod host discovery for the launcher.

The reference launcher probes ssh reachability and NICs to find usable
hosts/interfaces (reference run/run.py:62-115 cached ssh checks,
:198-268 ring-wise NIC intersection).  On TPU pods neither applies: the
platform already knows the workers.  SURVEY §7.1's stated replacement is
metadata-based resolution — sources, in order:

1. ``HVD_TPU_HOSTS`` — explicit override, same ``h1:8,h2:8`` syntax as
   ``-H``;
2. ``TPU_WORKER_HOSTNAMES`` — comma-separated worker hostnames, the env
   the TPU runtime provisions on pod VMs (what jax.distributed reads);
3. the GCE metadata server's ``worker-network-endpoints`` instance
   attribute (comma-separated entries whose LAST ``:``-field is the
   worker IP — the format jax's cloud_tpu_cluster parser consumes).

Slots per host default to the locally visible chip count, read without
initializing any TPU runtime (the launcher must not grab libtpu's
exclusive chip lock before its workers do).
"""

from __future__ import annotations

import os
import urllib.error
import urllib.request
from typing import List, Optional

from .hosts import HostInfo, parse_hosts

_METADATA_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/"
    "attributes/worker-network-endpoints"
)


def _metadata_endpoints(timeout: float = 2.0) -> Optional[str]:
    req = urllib.request.Request(
        _METADATA_URL, headers={"Metadata-Flavor": "Google"}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode()
    except (urllib.error.URLError, OSError, TimeoutError):
        return None


def _local_chip_count() -> int:
    """Local chips WITHOUT initializing a TPU runtime: importing jax here
    would take libtpu's exclusive lock inside the launcher and break the
    workers it spawns.  /dev/accel* is the chip inventory on TPU VMs."""
    env = os.environ.get("HVD_TPU_SLOTS")
    if env:
        return max(int(env), 1)
    import glob

    chips = len(glob.glob("/dev/accel*"))
    return chips if chips > 0 else 4  # 4 = common v5e host shape


def discover_tpu_hosts(default_slots: Optional[int] = None) -> Optional[List[HostInfo]]:
    """Resolve the pod's worker hosts, or None when nothing is
    discoverable (caller falls back to localhost)."""
    explicit = os.environ.get("HVD_TPU_HOSTS")
    if explicit:
        return parse_hosts(explicit)

    slots = default_slots or _local_chip_count()

    names = os.environ.get("TPU_WORKER_HOSTNAMES")
    if names:
        return [HostInfo(h.strip(), slots)
                for h in names.split(",") if h.strip()]

    endpoints = _metadata_endpoints()
    if endpoints:
        hosts = []
        for entry in endpoints.split(","):
            entry = entry.strip()
            if not entry:
                continue
            # the worker IP is the last :-field (matching jax
            # cloud_tpu_cluster's split(':')[-1] of each entry); bare
            # "ip" entries pass through unchanged
            hosts.append(HostInfo(entry.split(":")[-1], slots))
        return hosts or None
    return None
