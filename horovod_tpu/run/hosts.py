"""Host parsing and slot allocation.

Mirror of the reference's host handling: ``-H host1:4,host2:4`` / hostfile
parsing (reference run/run.py:696-740) and gloo_run's slot allocation that
assigns each process a ``SlotInfo(rank, local_rank, cross_rank, sizes...)``
(reference run/gloo_run.py:53-111)."""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional


@dataclass
class HostInfo:
    hostname: str
    slots: int


@dataclass
class SlotInfo:
    hostname: str
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int


def parse_hosts(hosts_string: str) -> List[HostInfo]:
    """``"host1:2,host2:4"`` → HostInfo list; bare hostnames get 1 slot
    (reference run/run.py parse of -H)."""
    hosts = []
    for part in hosts_string.split(","):
        part = part.strip()
        if not part:
            continue
        m = re.match(r"^(?P<host>[\w.\-\[\]]+):(?P<slots>\d+)$", part)
        if m:
            hosts.append(HostInfo(m.group("host"), int(m.group("slots"))))
        else:
            hosts.append(HostInfo(part, 1))
    return hosts


def parse_hostfile(path: str) -> List[HostInfo]:
    """Hostfile lines ``hostname slots=N`` (reference run/run.py hostfile
    format, --hostfile)."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            m = re.match(r"^(?P<host>[\w.\-]+)(\s+slots=(?P<slots>\d+))?$",
                         line)
            if not m:
                raise ValueError(f"bad hostfile line: {line!r}")
            slots = int(m.group("slots") or 1)
            hosts.append(HostInfo(m.group("host"), slots))
    return hosts


def allocate_slots(hosts: List[HostInfo], np: int) -> List[SlotInfo]:
    """Fill hosts in order (map-by slot) until ``np`` ranks are placed —
    the reference's _allocate (run/gloo_run.py:53-111): rank = global order,
    local_rank = index on host, cross_rank = index of this local_rank's
    "column" across hosts."""
    total = sum(h.slots for h in hosts)
    if np > total:
        raise ValueError(
            f"requested np={np} exceeds available slots {total}"
        )
    placements: List[List[str]] = []  # per host: hostnames of placed ranks
    slots: List[SlotInfo] = []
    remaining = np
    per_host: List[int] = []
    for h in hosts:
        take = min(h.slots, remaining)
        per_host.append(take)
        remaining -= take
        if remaining == 0:
            break
    hosts_used = hosts[: len(per_host)]

    rank = 0
    for hi, h in enumerate(hosts_used):
        for lr in range(per_host[hi]):
            cross_size = sum(1 for n in per_host if n > lr)
            cross_rank = sum(1 for n in per_host[:hi] if n > lr)
            slots.append(SlotInfo(
                hostname=h.hostname, rank=rank, size=np,
                local_rank=lr, local_size=per_host[hi],
                cross_rank=cross_rank, cross_size=cross_size,
            ))
            rank += 1
    return slots
