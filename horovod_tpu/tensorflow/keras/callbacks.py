"""Keras callbacks over the TF binding (reference
horovod/_keras/callbacks.py: BroadcastGlobalVariablesCallbackImpl,
MetricAverageCallbackImpl, LearningRateWarmupCallbackImpl)."""

from __future__ import annotations

import math

import numpy as np
import tensorflow as tf

from .. import allreduce, broadcast_variables, rank
from ... import core
from ...core import Average


def _world() -> int:
    """The TF binding's data parallelism is per-process (its transport
    reduces over processes), so processes — not devices — are the world
    size for metric guards and LR scaling."""
    return max(core.process_size(), 1)


class BroadcastGlobalVariablesCallback(tf.keras.callbacks.Callback):
    """Broadcast all model/optimizer variables from ``root_rank`` on the
    first batch (reference _keras/callbacks.py:21-45) — the
    checkpoint/resume idiom: rank 0 restores, everyone else receives."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done:
            return
        broadcast_variables(self.model.variables, self.root_rank)
        opt = getattr(self.model, "optimizer", None)
        if opt is not None and getattr(opt, "variables", None) is not None:
            vars = opt.variables() if callable(opt.variables) else opt.variables
            broadcast_variables(vars, self.root_rank)
        self.broadcast_done = True


class MetricAverageCallback(tf.keras.callbacks.Callback):
    """Average epoch metrics over all processes before they reach other
    callbacks/logs (reference _keras/callbacks.py:48-77)."""

    def on_epoch_end(self, epoch, logs=None):
        if logs is None or _world() == 1:
            return
        for k, v in list(logs.items()):
            logs[k] = float(np.asarray(allreduce(
                tf.constant(float(v)), op=Average,
                name=f"metric.{epoch}.{k}",
            )))


class _LRAdjuster:
    """Shared LR plumbing for the warmup/schedule callbacks: resolve the
    optimizer's LR variable across Keras versions, assign it, and (when
    enabled) rescale SGD momentum accumulators by new_lr/old_lr so the
    effective velocity tracks the changing LR (reference
    _keras/callbacks.py momentum restoration)."""

    momentum_correction = True
    _prev_lr = None

    def _lr_var(self):
        opt = self.model.optimizer
        lr = getattr(opt, "learning_rate", None)
        return lr if lr is not None else getattr(opt, "lr")

    @staticmethod
    def _get(var):
        # Keras 3 LR is a keras Variable (.numpy()); Keras 2 went through
        # backend.get_value
        return float(np.asarray(
            var.numpy() if hasattr(var, "numpy")
            else tf.keras.backend.get_value(var)
        ))

    @staticmethod
    def _set(var, value):
        if hasattr(var, "assign"):
            var.assign(value)
        else:
            tf.keras.backend.set_value(var, value)

    def _apply_lr(self, new_lr: float) -> None:
        if self.momentum_correction and self._prev_lr not in (None, 0.0):
            moms = getattr(self.model.optimizer, "momentums", None)
            if moms:
                ratio = new_lr / self._prev_lr
                for m in moms:
                    m.assign(m * ratio)
        self._set(self._lr_var(), new_lr)
        self._prev_lr = new_lr


class LearningRateScheduleCallback(_LRAdjuster, tf.keras.callbacks.Callback):
    """Multiplier schedule over epoch ranges (reference
    _keras/callbacks.py LearningRateScheduleCallback): within
    [start_epoch, end_epoch) the LR is initial_lr * multiplier(epoch);
    ``staircase`` floors the (fractional) epoch, matching the reference's
    per-batch interpolation toggle."""

    def __init__(self, initial_lr: float, multiplier,
                 start_epoch: int = 0, end_epoch=None, staircase: bool = True,
                 momentum_correction: bool = True, steps_per_epoch=None):
        super().__init__()
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.multiplier = (
            multiplier if callable(multiplier)
            else (lambda epoch: multiplier)
        )
        self._epoch = 0

    def _apply(self, epoch_f: float) -> None:
        epoch = math.floor(epoch_f) if self.staircase else epoch_f
        if epoch < self.start_epoch or (
            self.end_epoch is not None and epoch >= self.end_epoch
        ):
            return
        self._apply_lr(self.initial_lr * self.multiplier(epoch))

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        if self.staircase:
            self._apply(float(epoch))

    def on_batch_begin(self, batch, logs=None):
        if self.staircase:
            return
        steps = self.steps_per_epoch or (self.params or {}).get("steps") or 1
        self._apply(self._epoch + batch / steps)


class LearningRateWarmupCallback(_LRAdjuster, tf.keras.callbacks.Callback):
    """Linear LR warmup from lr/size to lr over ``warmup_epochs``
    (reference _keras/callbacks.py:79-135: large-batch training warms up
    the size-scaled learning rate)."""

    def __init__(self, warmup_epochs: int = 5, momentum_correction=True,
                 steps_per_epoch=None, verbose: int = 0):
        super().__init__()
        self.warmup_epochs = warmup_epochs
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose
        self._initial_lr = None
        self._epoch = 0

    def on_train_begin(self, logs=None):
        self._initial_lr = self._get(self._lr_var())

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch

    def on_batch_begin(self, batch, logs=None):
        if self._epoch >= self.warmup_epochs:
            return
        steps = self.steps_per_epoch or (self.params or {}).get("steps") or 1
        progress = (self._epoch * steps + batch) / (
            self.warmup_epochs * steps
        )
        w = _world()
        factor = 1.0 / w + (1.0 - 1.0 / w) * progress
        new_lr = self._initial_lr * factor
        self._apply_lr(new_lr)
        if self.verbose and rank() == 0 and batch == 0:
            print(f"LearningRateWarmup: epoch {self._epoch} "
                  f"lr={self._initial_lr * factor:.6f}")

    def on_epoch_end(self, epoch, logs=None):
        if epoch == self.warmup_epochs - 1:
            self._apply_lr(self._initial_lr)
