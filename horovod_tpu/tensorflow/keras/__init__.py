"""horovod_tpu.tensorflow.keras: Keras-flavored entry points (reference
horovod/tensorflow/keras/__init__.py — DistributedOptimizer +
callbacks)."""

from .. import (  # noqa: F401
    init, shutdown, rank, local_rank, size, local_size, cross_rank,
    cross_size, is_initialized, allreduce, allgather, broadcast,
    broadcast_object, broadcast_variables, Compression,
    DistributedOptimizer,
)
from . import callbacks  # noqa: F401
