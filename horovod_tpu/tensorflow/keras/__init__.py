"""horovod_tpu.tensorflow.keras: Keras-flavored entry points (reference
horovod/tensorflow/keras/__init__.py — DistributedOptimizer +
callbacks)."""

from .. import (  # noqa: F401
    init, shutdown, rank, local_rank, size, local_size, cross_rank,
    cross_size, is_initialized, allreduce, allgather, broadcast,
    broadcast_object, broadcast_variables, Compression,
    DistributedOptimizer,
)
from . import callbacks  # noqa: F401


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """Load a saved Keras model with its optimizer re-wrapped in
    :func:`DistributedOptimizer`, restored slot state included
    (reference horovod/tensorflow/keras/__init__.py load_model /
    horovod/keras/__init__.py:117).

    The reference intercepts optimizer deserialization with a
    ``custom_objects`` wrapping factory; Keras 3 resolves its built-in
    optimizers from the internal registry before consulting
    ``custom_objects``, so the equivalent here is a post-load re-wrap:
    the deserialized optimizer's restored variables (iteration count,
    momenta, ...) are copied into the Distributed subclass built from
    its config — same net result, retraining picks up where the save
    left off, now with allreduced gradients.

    ``custom_optimizers`` is accepted for signature parity (Keras 3
    deserializes custom optimizer classes via ``custom_objects`` /
    ``keras.saving.register_keras_serializable``)."""
    del custom_optimizers  # Keras 3: registration handles custom classes
    import tensorflow as tf

    model = tf.keras.models.load_model(filepath,
                                       custom_objects=custom_objects)
    opt = getattr(model, "optimizer", None)
    if opt is None or getattr(type(opt), "_hvd_distributed", False):
        return model
    wrapped = DistributedOptimizer(opt, compression=compression)
    if getattr(opt, "built", False):
        wrapped.build(model.trainable_variables)
        # strict: a silent length mismatch would resume training from
        # partially-zeroed slot state with no error
        for dst, src in zip(wrapped.variables, opt.variables,
                            strict=True):
            dst.assign(src)
    # swap in place: compile() would discard the restored loss/metrics
    # wiring, and Keras 3's train_step reads self.optimizer directly
    model.optimizer = wrapped
    return model
