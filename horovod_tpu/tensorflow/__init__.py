"""horovod_tpu.tensorflow: the TensorFlow-flavored API surface.

Mirror of horovod/tensorflow (reference horovod/tensorflow/__init__.py +
mpi_ops.py): ``allreduce`` (dense + IndexedSlices→allgather),
``allgather``, ``broadcast``, ``broadcast_variables``,
``DistributedOptimizer``, ``DistributedGradientTape``, ``Compression``.

Architecture: the reference routes TF tensors through custom AsyncOpKernels
(tensorflow/mpi_ops.cc) into the background-thread/NCCL stack; here TF
eager tensors bridge to the XLA/native data plane via numpy interchange and
the eager SPMD programs (horovod_tpu/eager.py) — same transport as the
torch binding.  TF-on-TPU compiled compute is the JAX path in this
framework (core.py/spmd.py); this module serves TF-ecosystem code (Keras
models, tf.data pipelines) running its math on the host while gradients
ride the framework's collectives.

Import is lazy-gated: ``import horovod_tpu.tensorflow`` raises ImportError
only if tensorflow itself is unavailable.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import tensorflow as tf  # gate: module import fails cleanly without TF

from .. import core, eager
from ..core import Average, Sum, Adasum, Min, Max  # noqa: F401
from ..runtime import eager_controller

init = core.init
shutdown = core.shutdown
rank = core.rank
local_rank = core.local_rank
size = core.size
local_size = core.local_size
cross_rank = core.cross_rank
cross_size = core.cross_size
is_initialized = core.is_initialized
mpi_enabled = core.mpi_enabled
nccl_built = core.nccl_built


class Compression:
    """Gradient compression for the wire (reference
    tensorflow/compression.py: NoneCompressor / FP16Compressor).  fp16
    stays fp16 here — the host-side eager plane has no MXU preference;
    the compiled JAX path's Compression maps fp16→bf16 instead."""

    class none:
        @staticmethod
        def compress(t):
            return t, None

        @staticmethod
        def decompress(t, ctx):
            return t

    class fp16:
        @staticmethod
        def compress(t):
            if t.dtype in (tf.float32, tf.float64):
                return tf.cast(t, tf.float16), t.dtype
            return t, None

        @staticmethod
        def decompress(t, ctx):
            return tf.cast(t, ctx) if ctx is not None else t


def _np(tensor) -> np.ndarray:
    return tensor.numpy() if hasattr(tensor, "numpy") else np.asarray(tensor)


def _allreduce_np(arr: np.ndarray, op, nm: str) -> np.ndarray:
    out = eager.process_allreduce(np.asarray(arr), op=op, name=nm)
    # the wire path may at-least-1d scalars; an allreduce preserves shape
    return np.ascontiguousarray(np.asarray(out)).reshape(np.shape(arr))




def _run(np_fn, tensor, out_shape):
    """Execute the numpy-side collective: directly in eager mode, through
    ``tf.py_function`` under a ``tf.function`` trace (the reference's
    AsyncOpKernels are graph ops natively; py_function is the eager
    plane's graph adapter — Keras compiles train_step)."""
    if tf.executing_eagerly():
        return tf.convert_to_tensor(np_fn(_np(tensor)))
    out = tf.py_function(
        func=lambda t: tf.convert_to_tensor(np_fn(t.numpy())),
        inp=[tensor], Tout=tensor.dtype,
    )
    out.set_shape(out_shape)
    return out


def allreduce(tensor, average=None, device_dense="", device_sparse="",
              compression=Compression.none, op=None, name: Optional[str] = None):
    """Dense tensors: cross-process reduction over the data plane.
    ``tf.IndexedSlices``: allgather of (values, indices) instead
    (reference tensorflow/__init__.py:75-90).  Works eagerly and inside
    ``tf.function`` (Keras train steps)."""
    op = _normalize_op(average, op)
    if isinstance(tensor, tf.IndexedSlices):
        values = allgather(tensor.values,
                           name=None if name is None else f"{name}.values")
        indices = allgather(tensor.indices,
                            name=None if name is None else f"{name}.indices")
        if op == Average:
            # the allgather ran over processes (the eager transport's
            # participants), so that is the averaging denominator
            values = values / core.process_size()
        elif op != Sum:
            raise ValueError(f"unsupported op for IndexedSlices: {op}")
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)

    # All five reference ops have real host-plane semantics now:
    # Min/Max elementwise and Adasum's VHDD tree run in the native data
    # plane (csrc/controller.cc MinMaxPayload/AdasumReduce, csrc/ring.cc)
    # — eager.process_allreduce routes and validates.
    comp, ctx = compression.compress(tensor)
    nm = name or eager_controller.next_name("allreduce.tf")
    out = _run(lambda a: _allreduce_np(a, op, nm), comp, comp.shape)
    return compression.decompress(out, ctx)


def allgather(tensor, name: Optional[str] = None):
    """Concatenate every process's tensor along dim 0 (reference
    HorovodAllgatherOp; varying first dimensions allowed)."""
    nm = name or eager_controller.next_name("allgather.tf")
    out_shape = tf.TensorShape([None]).concatenate(tensor.shape[1:])
    return _run(lambda a: eager.process_allgather(a, name=nm), tensor,
                out_shape)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None):
    nm = name or eager_controller.next_name("broadcast.tf")
    return _run(lambda a: eager.process_broadcast(a, root_rank, name=nm),
                tensor, tensor.shape)


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None):
    return eager.broadcast_object(obj, root_rank=root_rank, name=name)


def broadcast_variables(variables, root_rank: int = 0) -> None:
    """Assign root's values into every process's variables (reference
    tensorflow/__init__.py broadcast_variables / the TF1
    BroadcastGlobalVariablesHook body)."""
    for var in variables:
        var.assign(broadcast(var, root_rank))


_normalize_op = eager.normalize_op


# ---------------------------------------------------------------------------
# gradient aggregation
# ---------------------------------------------------------------------------
def _allreduce_grads(grads, *, op, compression, sparse_as_dense):
    out = []
    for g in grads:
        if g is None:
            out.append(None)
            continue
        if isinstance(g, tf.IndexedSlices) and sparse_as_dense:
            g = tf.convert_to_tensor(g)
        out.append(allreduce(g, op=op, compression=compression))
    return out


class DistributedGradientTape:
    """Wrap tf.GradientTape so .gradient() returns globally-reduced
    gradients (reference tensorflow/__init__.py:483-539).  With
    ``HVD_TRACE_DIR`` set, the first ``.gradient()`` call dumps the
    per-rank trace artifacts with no manual Recorder calls — the fork's
    in-optimizer wiring (reference tensorflow/__init__.py:282,295)."""

    def __init__(self, gradtape, device_dense="", device_sparse="",
                 compression=Compression.none, sparse_as_dense=False,
                 op=Average):
        from .recorder import GradientRecorder

        self._tape = gradtape
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense
        self._op = op
        self._recorder = GradientRecorder()

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        self._recorder.record(grads, sources)
        return _allreduce_grads(
            grads, op=self._op, compression=self._compression,
            sparse_as_dense=self._sparse_as_dense,
        )


def DistributedOptimizer(optimizer, name=None, use_locking=False,
                         device_dense="", device_sparse="",
                         compression=Compression.none,
                         sparse_as_dense=False, op=Average,
                         backward_passes_per_step: int = 1):
    """A dynamically-created subclass of the given Keras optimizer whose
    ``apply_gradients`` sees globally-reduced gradients — the reference's
    own construction (horovod/keras/__init__.py create_distributed_optimizer
    builds ``type(cls.__name__, (cls,), dict(...))``), which keeps Keras's
    isinstance checks satisfied.  Returns a fresh optimizer built from the
    wrapped one's config (state resets, as in the reference)."""
    if backward_passes_per_step != 1:
        raise NotImplementedError(
            "backward_passes_per_step > 1: accumulate in the training "
            "loop (the TF2 idiom) or use the JAX hvd.DistributedOptimizer"
        )
    if getattr(optimizer.__class__, "_hvd_distributed", False):
        raise ValueError(
            "optimizer is already distributed "
            "(DistributedOptimizer applied twice)"
        )
    base = optimizer.__class__
    from .recorder import GradientRecorder

    recorder = GradientRecorder()  # fork wiring: first pass auto-dumps

    def apply_gradients(self, grads_and_vars, *args, **kwargs):
        gv = list(grads_and_vars)
        recorder.record([g for g, _ in gv], [v for _, v in gv])
        grads = _allreduce_grads(
            [g for g, _ in gv], op=op, compression=compression,
            sparse_as_dense=sparse_as_dense,
        )
        return base.apply_gradients(
            self, list(zip(grads, [v for _, v in gv])), *args, **kwargs
        )

    def apply_gradients_adasum(self, grads_and_vars, *args, **kwargs):
        # Delta-Adasum (reference tensorflow/__init__.py:321-415
        # _DistributedAdasumOptimizer): snapshot → local step → Adasum
        # the parameter deltas → rebase.  Reducing the *update* keeps
        # stateful-optimizer slots consistent with what was applied.
        gv = list(grads_and_vars)
        variables = [v for _, v in gv]
        recorder.record([g for g, _ in gv], variables)
        starts = [tf.identity(v) for v in variables]
        result = base.apply_gradients(self, gv, *args, **kwargs)
        for i, (v, s) in enumerate(zip(variables, starts)):
            reduced = allreduce(
                v - s, op=Adasum, compression=compression,
                name=f"adasum.delta.{i}",
            )
            v.assign(s + tf.cast(reduced, v.dtype))
        return result

    cls = type(base.__name__, (base,), {
        "apply_gradients": apply_gradients_adasum if op == Adasum
        else apply_gradients,
        "_hvd_distributed": True,
    })
    return cls.from_config(optimizer.get_config())
