"""In-optimizer Recorder wiring for the TF binding.

The fork's whole point is ZERO-EFFORT per-rank tracing: wrapping an
optimizer is enough to produce the trace artifacts — the reference's
``DistributedOptimizer.compute_gradients`` itself registers every gradient
tensor with the Recorder (reference horovod/tensorflow/__init__.py:282,295;
recorder.py:176-193 register_tensors, :339-521 TimelineHook), no manual
Recorder calls in user code.

This module is the TPU-native analog: ``GradientRecorder.record(grads,
vars)`` is invoked from inside ``DistributedGradientTape.gradient`` and
``DistributedOptimizer.apply_gradients`` on their first call.  When
``HVD_TRACE_DIR`` is set it dumps, per rank, into ``<dir>/<rank>/``:

* ``dag.gml`` — inside a ``tf.function`` trace, the live FuncGraph's op
  graph (the TF2 analog of the reference's partition GraphDefs: the first
  ``apply_gradients`` runs during tracing, when forward + gradient ops are
  already recorded in the graph); in pure eager mode, the gradient→
  allreduce→variable dataflow of the aggregation step itself.
* ``tensor_shapes.json`` — per-gradient shapes keyed by manifest name.
* ``gradient_name_list.json`` — ``gradients/<var name>`` manifest
  (reference recorder.py gradient name registration).
* ``metadata.json`` — rank/size/framework.

The framework-neutral jaxpr-based Recorder stays in
``horovod_tpu/timeline/recorder.py``; this file only adds the TF hook.
"""

from __future__ import annotations

from typing import Optional

import tensorflow as tf

from ..timeline.recorder import (  # noqa: F401
    Recorder, TimelineHook, structure_dag, write_gml,
    write_gradient_manifest,
)
from ..utils.logging import get_logger

log = get_logger(__name__)


def _var_name(v, i: int) -> str:
    # Keras 3 variables carry the layer-qualified name on .path
    # ('sequential/head/kernel') and only the leaf on .name ('kernel');
    # tf.Variable carries 'scope/name:0' on .name.
    name = getattr(v, "path", None) or getattr(v, "name", None) \
        or f"var_{i}"
    return name.split(":")[0]


def _funcgraph_dag(graph) -> tuple:
    """(nodes, edges) from a live FuncGraph — op type + name + output
    shape, edges following tensor producers (same node vocabulary as the
    jaxpr DAG in timeline/recorder.py so dag.gml consumers see one
    format)."""
    nodes, edges = [], []
    op_id = {}
    for op in graph.get_operations():
        nid = len(nodes)
        node = {"id": nid, "label": op.name, "kind": op.type}
        if op.outputs:
            shape = op.outputs[0].shape
            if shape.rank is not None:
                node["shape"] = [d if d is not None else -1
                                 for d in shape.as_list()]
            node["dtype"] = op.outputs[0].dtype.name
        nodes.append(node)
        op_id[op.name] = nid
    for op in graph.get_operations():
        for inp in op.inputs:
            src = op_id.get(inp.op.name)
            if src is not None:
                edges.append((src, op_id[op.name]))
    return nodes, edges


class GradientRecorder:
    """One per wrapped optimizer/tape; dumps once, on the first gradient
    pass, and is a no-op forever after (and entirely when HVD_TRACE_DIR
    is unset — zero overhead on the untraced path)."""

    def __init__(self, trace_dir: Optional[str] = None):
        self._trace_dir = trace_dir
        self._done = False

    def record(self, grads, variables=None) -> None:
        if self._done:
            return
        self._done = True  # even on failure: never retry per-step
        try:
            rec = Recorder(self._trace_dir)
            if not rec.enabled:
                return
            gv = list(zip(grads, variables)) if variables is not None \
                else [(g, None) for g in grads]
            names, shapes = [], {}
            for i, (g, v) in enumerate(gv):
                if g is None:
                    continue
                name = _var_name(v, i) if v is not None else f"grad_{i}"
                names.append("gradients/" + name)
                t = g.values if isinstance(g, tf.IndexedSlices) else g
                shape = getattr(t, "shape", None)
                if shape is not None and shape.rank is not None:
                    shapes["gradients/" + name] = [
                        d if d is not None else -1 for d in shape.as_list()
                    ]
            write_gradient_manifest(rec, names, shapes)
            graph = tf.compat.v1.get_default_graph() \
                if tf.inside_function() else None
            if graph is not None and graph.get_operations():
                nodes, edges = _funcgraph_dag(graph)
            else:
                nodes, edges = structure_dag(
                    [n[len("gradients/"):] for n in names])
            write_gml(nodes, edges, rec._path("dag.gml"))
            rec.dump_metadata(framework="tensorflow",
                              num_gradients=len(names),
                              in_function=bool(graph is not None))
            log.info("recorder: dumped TF trace artifacts to %s", rec.dir)
        except Exception:  # noqa: BLE001 — tracing must never kill a step
            log.exception("recorder: TF artifact dump failed")
