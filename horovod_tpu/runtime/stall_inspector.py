"""Stall inspector: detect operations stuck waiting too long.

Re-design of horovod/common/stall_inspector.cc/.h (reference): warn when a
tensor has waited > HOROVOD_STALL_CHECK_TIME_SECONDS (default 60) for all
ranks to become ready (stall_inspector.h:39 CheckForStalledTensors), and
optionally shut the job down after HOROVOD_STALL_SHUTDOWN_TIME_SECONDS
(:42, :72-80 knobs).

In the compiled SPMD world a "stall" means a *step* (or an eager collective
dispatch) that never completes — a hung DCN link, a dead host, a deadlocked
input pipeline.  The inspector is a watchdog registry: callers mark
operations begun/ended; a daemon thread warns about entries alive past the
warning threshold and invokes a shutdown callback (default: log fatal +
``os._exit``) past the shutdown threshold.  The launcher-level analog
(a worker exiting kills the job, reference gloo_run.py:253-259) then tears
down the remaining hosts.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)


@dataclass
class _Entry:
    name: str
    start: float
    warned: bool = False


class StallInspector:
    def __init__(
        self,
        *,
        warning_seconds: Optional[float] = None,
        shutdown_seconds: Optional[float] = None,
        enabled: Optional[bool] = None,
        check_interval: float = 1.0,
        on_shutdown: Optional[Callable[[str], None]] = None,
    ):
        self.enabled = (
            enabled if enabled is not None
            else not env_util.get_bool(env_util.HVD_STALL_CHECK_DISABLE)
        )
        self.warning_seconds = (
            warning_seconds if warning_seconds is not None
            else env_util.get_float(env_util.HVD_STALL_CHECK_TIME_SECONDS,
                                    env_util.DEFAULT_STALL_WARNING_SECONDS)
        )
        self.shutdown_seconds = (
            shutdown_seconds if shutdown_seconds is not None
            else env_util.get_float(env_util.HVD_STALL_SHUTDOWN_TIME_SECONDS,
                                    0.0)
        )
        self.check_interval = check_interval
        self.on_shutdown = on_shutdown or self._default_shutdown
        self._entries: Dict[str, _Entry] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.warnings: list = []  # (name, waited_seconds) — for tests/metrics

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="hvd-stall-inspector"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def begin(self, name: str) -> None:
        """Mark an operation in flight (analog of a tensor entering the
        negotiation table)."""
        if not self.enabled:
            return
        with self._lock:
            self._entries[name] = _Entry(name, time.monotonic())

    def end(self, name: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._entries.pop(name, None)

    def watch(self, name: str):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self.begin(name)
            try:
                yield
            finally:
                self.end(name)

        return ctx()

    def register_metrics(self) -> None:
        """Publish this inspector's state to the metrics plane: a queue-
        depth gauge (in-flight watchdog entries), a stalled-ops gauge,
        and the cumulative warning counter.  The gauges are collector-
        driven (polled at scrape/snapshot time), so the begin/end hot
        path stays untouched.  Keyed registration means re-calling (or a
        fresh singleton across hvd.init cycles) replaces, not leaks."""
        from ..metrics import INFLIGHT_OPS, STALLED_OPS, registry

        def collect() -> None:
            now = time.monotonic()
            with self._lock:
                inflight = len(self._entries)
                stalled = sum(
                    1 for e in self._entries.values()
                    if now - e.start > self.warning_seconds
                )
            INFLIGHT_OPS.set(inflight)
            STALLED_OPS.set(stalled)

        registry.register_collector("stall_inspector", collect)

    # ------------------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.check_interval):
            self.check_once()

    def check_once(self) -> None:
        """One inspection pass (reference CheckForStalledTensors: builds the
        warning message listing stalled tensors and waiting ranks)."""
        now = time.monotonic()
        stalled, dead = [], []
        with self._lock:
            for e in self._entries.values():
                waited = now - e.start
                if self.shutdown_seconds > 0 and waited > self.shutdown_seconds:
                    dead.append((e.name, waited))
                elif waited > self.warning_seconds and not e.warned:
                    e.warned = True
                    stalled.append((e.name, waited))
        for name, waited in stalled:
            self.warnings.append((name, waited))
            from ..metrics import STALL_WARNINGS, registry

            if registry.enabled:
                STALL_WARNINGS.inc()
            log.warning(
                "One or more operations were submitted but have not "
                "completed for %.0f seconds: [%s]. Possible causes: a hung "
                "host, a dead DCN/ICI link, or an input pipeline deadlock.",
                waited, name,
            )
        for name, waited in dead:
            self.on_shutdown(name)

    @staticmethod
    def _default_shutdown(name: str) -> None:
        """Stall shutdown = coordinated abort, then local exit.  The old
        behavior (exit alone) stranded every OTHER rank in a silent hang
        until a collective timeout; setting the job-wide flag first means
        peers raise HorovodAbortError naming this rank's stalled op
        within a heartbeat interval (elastic/abort.py)."""
        log.critical(
            "operation [%s] exceeded the stall shutdown threshold; "
            "terminating (HVD_STALL_SHUTDOWN_TIME_SECONDS)", name,
        )
        from ..elastic.abort import trigger

        # best-effort, with a SHORT per-attempt timeout: an unreachable
        # rendezvous (the launcher VM may be the thing that died) must
        # delay this exit by seconds, not the full retry budget
        trigger(
            f"stall shutdown: operation [{name}] exceeded "
            "HVD_STALL_SHUTDOWN_TIME_SECONDS",
            source="stall_inspector", timeout=2.0,
        )
        os._exit(1)


#: process-wide inspector used by the eager plane
inspector = StallInspector()
inspector.register_metrics()
