"""Peer ring data plane: scalable host collectives for the bindings.

The torch/TF/MXNet bindings move host-resident gradients; the reference
hands those to Gloo's ring allreduce (reference
horovod/common/ops/gloo_operations.cc:120-158) or NCCL.  This module is
the TPU-era equivalent over plain worker↔worker TCP (csrc/ring.cc):
bandwidth-optimal ring allreduce with flat per-rank wire volume, vs the
O(n·payload) coordinator star that remains the transport for small
control payloads.

Two pieces:

* :class:`Ring` — thin ctypes wrapper over the native ring (create /
  connect / allreduce / broadcast).  Establishment: every rank opens a
  listener, the listen addresses are allgathered over the coordinator
  star (tiny payload), then each rank dials its right neighbor.
* :class:`RingExecutor` — the ordering layer.  Ring transfers block both
  neighbors, so every rank must run them in ONE global order even though
  the torch binding submits from per-handle threads whose firing order
  differs across ranks.  The negotiation controller already solves this:
  each op is submitted as a named request, and the coordinator's response
  stream (ControllerClient.next_negotiated) is consumed by a single
  dispatcher thread that executes ring ops in response order — exactly
  the reference's design, where the background thread executes the
  coordinator's ResponseList in order (reference controller.h:58-99,
  operations.cc BackgroundThreadLoop).

Ring-routed ops carry a ``ring.`` name prefix so the dispatcher can tell
them apart from XLA-plane negotiations in the same stream.  A rank that
has Joined keeps its dispatcher alive; for a ring op it never submitted
it synthesizes a zero contribution from the response metadata (valid for
sum — the reference's Join supports sum/average only, join.py docs).
"""

from __future__ import annotations

import ctypes
import socket
import threading
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import metrics as _metrics
from ..utils import env as env_util
from ..utils.logging import get_logger
from . import native
from .controller import DATA_OPS, _dtype_code

log = get_logger(__name__)

RING_PREFIX = "ring."
# The reduce op (and broadcast root) is encoded in the negotiated name
# ("ring.min:<name>", "ring.bcast3:<name>") so a joined rank — which
# never submitted the op — can synthesize the correct identity element
# and root from the response alone.
_OP_TAGS = {"allreduce": "sum", "min": "min", "max": "max"}
_TAG_OPS = {v: k for k, v in _OP_TAGS.items()}

_NP_BY_CODE = {0: "float32", 1: "bfloat16", 2: "float16", 3: "float64",
               4: "int32", 5: "int64"}


def _np_dtype(code: int):
    name = _NP_BY_CODE.get(code)
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name or "uint8")


class Ring:
    """The native peer ring (one per process)."""

    def __init__(self, rank: int, nranks: int, *,
                 chunk_bytes: Optional[int] = None):
        self._lib = native.load()
        chunk = chunk_bytes or env_util.get_int("HVD_RING_CHUNK_BYTES",
                                                4 << 20)
        self._h = self._lib.hvd_ring_create(rank, nranks, chunk)
        if not self._h:
            raise RuntimeError("failed to create ring listener")
        self.rank = rank
        self.nranks = nranks

    @property
    def port(self) -> int:
        return self._lib.hvd_ring_port(self._h)

    def connect(self, right_host: str, right_port: int,
                timeout: float = 60.0) -> None:
        host = socket.gethostbyname(right_host)
        rc = self._lib.hvd_ring_connect(
            self._h, host.encode(), right_port, timeout * 1000.0,
        )
        if rc != 0:
            raise ConnectionError(
                f"ring connect to {right_host}:{right_port} failed"
            )

    def allreduce(self, arr: np.ndarray, op: str = "allreduce") -> np.ndarray:
        """In-place ring allreduce; returns the (mutated) array.

        IN-PLACE CONTRACT: a contiguous input is reduced in its own
        buffer (``np.ascontiguousarray`` aliases it); callers that need
        their input preserved must pass a copy.  ``RingExecutor`` copies
        at submit time, so only direct ``Ring`` users carry this burden.
        """
        arr = np.ascontiguousarray(arr)
        rc = self._lib.hvd_ring_allreduce(
            self._h, arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes,
            _dtype_code(str(arr.dtype)), DATA_OPS[op],
        )
        if rc != 0:
            raise RuntimeError(f"ring allreduce failed (op={op})")
        return arr

    def broadcast(self, buf: bytearray, root: int) -> bytearray:
        """In-place pipelined ring broadcast of a byte buffer."""
        if len(buf) == 0:
            return buf
        c_buf = (ctypes.c_char * len(buf)).from_buffer(buf)
        rc = self._lib.hvd_ring_broadcast(self._h, c_buf, len(buf), root)
        if rc != 0:
            raise RuntimeError("ring broadcast failed")
        return buf

    def allgather(self, arr: np.ndarray) -> np.ndarray:
        """Equal-block ring allgather: every rank's ``arr`` concatenated
        on dim 0, one rotation per step (csrc/ring.cc Allgather)."""
        arr = np.ascontiguousarray(arr)
        out = np.empty((self.nranks,) + arr.shape, arr.dtype)
        rc = self._lib.hvd_ring_allgather(
            self._h, arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes,
            out.ctypes.data_as(ctypes.c_void_p), out.nbytes,
        )
        if rc != 0:
            raise RuntimeError("ring allgather failed")
        return out.reshape((self.nranks * arr.shape[0],) + arr.shape[1:])

    def close(self) -> None:
        if self._h:
            self._lib.hvd_ring_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


class RingExecutor:
    """Serializes ring collectives into the coordinator's response order.

    ``submit`` registers the local payload under a ``ring.``-prefixed
    name and files a negotiation request; the dispatcher thread pops
    negotiated responses and executes the ring transfer for each ring op
    — one at a time, in the same order on every rank.
    """

    def __init__(self, client, ring: Ring):
        self._client = client
        self._ring = ring
        self._lock = threading.Lock()
        self._pending: Dict[str, Tuple[np.ndarray, str, int, Future]] = {}
        self._stopping = False
        client.enable_order_stream()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="hvd-ring-dispatch",
        )
        self._thread.start()

    # -- public API ---------------------------------------------------------
    def allreduce(self, name: str, arr: np.ndarray, *,
                  op: str = "allreduce", timeout: float = 60.0) -> np.ndarray:
        """Ring allreduce of ``arr`` under coordinator ordering (blocking).
        The input is copied at submit time — the native ring reduces in
        place (Ring.allreduce), and the caller's buffer must survive."""
        fut = self._submit(name, np.array(arr, copy=True), op, root=0)
        return fut.result(timeout=timeout)

    def broadcast(self, name: str, arr: np.ndarray, root: int,
                  timeout: float = 60.0) -> np.ndarray:
        fut = self._submit(name, np.ascontiguousarray(arr), "broadcast",
                           root=root)
        return fut.result(timeout=timeout)

    def allgather(self, name: str, arr: np.ndarray,
                  timeout: float = 60.0) -> np.ndarray:
        """Equal-shape ring allgather under coordinator ordering; the
        negotiation runs as type allgather, so Join restrictions apply
        (the coordinator refuses gathers while ranks are joined)."""
        fut = self._submit(name, np.ascontiguousarray(np.atleast_1d(arr)),
                           "allgather", root=0)
        return fut.result(timeout=timeout)

    def close(self) -> None:
        """Stop the dispatcher and free the native ring.  The ring is
        only freed after the dispatcher thread exits — freeing under an
        in-flight transfer would be a use-after-free; if the thread is
        wedged mid-op we deliberately leak the native object instead."""
        self._stopping = True
        self._thread.join(timeout=10)
        _metrics.RING_ACTIVE.set(0)
        if not self._thread.is_alive():
            self._ring.close()
        else:
            _leaked.append(self._ring)  # keep alive; never freed

    # -- internals ----------------------------------------------------------
    def _submit(self, name: str, arr: np.ndarray, op: str,
                root: int) -> Future:
        if op == "broadcast":
            tag = f"bcast{root}"
        elif op == "allgather":
            tag = "gather"
        else:
            tag = _OP_TAGS[op]
        name = f"{RING_PREFIX}{tag}:{name}"
        fut: Future = Future()
        with self._lock:
            if name in self._pending:
                raise ValueError(f"ring op {name!r} already in flight")
            self._pending[name] = (arr, op, root, fut)
        # negotiation request: broadcast/allgather negotiate as their own
        # types (Join restrictions apply), the reduce ops as allreduce
        # (min/max share the type; cross-rank op agreement is enforced by
        # MetaKey's name match + the local subgroup key, and all ranks
        # pass the same op for one name).
        req_op = op if op in ("broadcast", "allgather") else "allreduce"
        try:
            self._client.submit(
                name, op=req_op, shape=arr.shape, dtype=str(arr.dtype),
                root_rank=root,
            )
        except BaseException as e:  # noqa: BLE001 — connection lost etc.
            # unwind the pending entry so a retry under the same name is
            # not rejected as "already in flight" and the Future resolves
            with self._lock:
                self._pending.pop(name, None)
            fut.set_exception(e)
            raise
        return fut

    def _loop(self) -> None:
        while not self._stopping:
            try:
                type_code, err, tensors = self._client.next_negotiated(
                    timeout=1.0,
                )
            except TimeoutError:
                continue
            except ConnectionError:
                self._fail_all(ConnectionError("controller connection lost"))
                return
            ring_names = [t for t in tensors if t[0].startswith(RING_PREFIX)]
            if not ring_names:
                continue  # XLA-plane negotiation; not ours
            if type_code == 6:  # coordinator ERROR response
                self._fail(ring_names, RuntimeError(err))
            else:
                self._execute_group(ring_names, type_code)
            # Drain the per-name Wait entries the client recorded for
            # these responses: ring ops never call wait(), and the
            # entries would otherwise accumulate one per collective.
            for nm, _, _ in ring_names:
                try:
                    self._client.wait(nm, timeout=1.0)
                except Exception:  # noqa: BLE001 — drained either way
                    pass

    @staticmethod
    def _identity(op: str, dtype_code: int, nbytes: int) -> np.ndarray:
        """The identity element for a ring reduce a joined rank must
        contribute: 0 for sum, +inf/dtype-max for min, -inf/dtype-min
        for max (zeros would corrupt min/max).  Float-ness comes from the
        wire dtype code, not np.dtype.kind — ml_dtypes' bfloat16 reports
        kind 'V', which np.iinfo rejects."""
        dt = _np_dtype(dtype_code)
        n = max(nbytes, 0) // dt.itemsize
        is_float = dtype_code in (0, 1, 2, 3)
        if op == "min":
            fill = np.inf if is_float else np.iinfo(dt).max
        elif op == "max":
            fill = -np.inf if is_float else np.iinfo(dt).min
        else:
            fill = 0
        return np.full(n, fill, dt)

    def _execute_group(self, ring_names, type_code: int) -> None:
        """Execute one negotiated group of ring ops.

        The coordinator already fused small same-type tensors into one
        response (csrc/controller.cc FuseResponses); this is the host
        plane's fusion *buffer*: same-(op, dtype) reduce ops in the group
        concatenate into a single ring transfer — one 2(n−1)-hop
        schedule instead of one per tensor (the reference's fusion
        buffer, common/operations.cc FuseResponses + buffer assembly).
        Bucket order follows group order, so every rank runs identical
        transfers.  Broadcasts execute singly (different roots can't
        share a buffer)."""
        buckets = {}
        singles = []
        for nm, dtype_code, nbytes in ring_names:
            tag = nm[len(RING_PREFIX):].partition(":")[0]
            if tag in _TAG_OPS:
                buckets.setdefault((tag, dtype_code), []).append(
                    (nm, dtype_code, nbytes))
            else:
                singles.append((nm, dtype_code, nbytes))
        for nm, dtype_code, nbytes in singles:
            self._execute(nm, dtype_code, nbytes, type_code)
        for (tag, dtype_code), items in buckets.items():
            if len(items) == 1:
                nm, dc, nb = items[0]
                self._execute(nm, dc, nb, type_code)
            else:
                self._execute_fused(tag, dtype_code, items)

    def _execute_fused(self, tag: str, dtype_code: int, items) -> None:
        op = _TAG_OPS[tag]
        parts, futs = [], []
        for nm, _, nbytes in items:
            with self._lock:
                entry = self._pending.pop(nm, None)
            if entry is None:  # joined rank: identity contribution
                parts.append((self._identity(op, dtype_code, nbytes),
                              None, nbytes))
                futs.append(None)
            else:
                arr, _, _, fut = entry
                parts.append((arr, arr.shape, nbytes))
                futs.append(fut)
        try:
            for (arr, _, nbytes), (nm, _, _) in zip(parts, items):
                if arr.nbytes != nbytes:
                    raise ValueError(
                        f"ring op {nm!r}: local payload is {arr.nbytes} B "
                        f"but the negotiated size is {nbytes} B"
                    )
            flat = np.concatenate([a.ravel() for a, _, _ in parts])
            out = self._ring.allreduce(flat, op=op)
            if _metrics.on():
                _metrics.RING_OPS.labels(op).inc()
                _metrics.RING_BYTES.inc(flat.nbytes)
            off = 0
            for (arr, shape, _), fut in zip(parts, futs):
                n = arr.size
                if fut is not None:
                    fut.set_result(out[off: off + n].reshape(shape))
                off += n
        except BaseException as e:  # noqa: BLE001
            delivered = False
            for fut in futs:
                if fut is not None and not fut.done():
                    fut.set_exception(e)
                    delivered = True
            if not delivered:  # all-joined group: nobody to tell — log
                log.warning("joined-rank fused ring group failed: %s", e)

    def _execute(self, name: str, dtype_code: int, nbytes: int,
                 type_code: int) -> None:
        with self._lock:
            entry = self._pending.pop(name, None)
        fut = None
        try:
            tag = name[len(RING_PREFIX):].partition(":")[0]
            if entry is None:
                # Joined rank: participate with the op's identity element
                # so the ring stays connected (reference Join semantics,
                # controller.cc:253-264: joined ranks are implicit
                # members).  gather/bcast cannot reach here under Join —
                # the coordinator errors them — but keep the ring alive
                # defensively with a zero block.
                if tag.startswith("bcast"):
                    arr = np.zeros(max(nbytes, 0), np.uint8)
                    op, root = "broadcast", int(tag[len("bcast"):])
                elif tag == "gather":
                    arr = np.zeros(max(nbytes, 0), np.uint8)
                    op, root = "allgather", 0
                else:
                    op = _TAG_OPS.get(tag, "allreduce")
                    arr = self._identity(op, dtype_code, nbytes)
                    root = 0
            else:
                arr, op, root, fut = entry
            if arr.nbytes != nbytes:
                # canonical size from the first submitter disagrees with
                # ours — executing would desync the byte stream for every
                # later ring op; fail this op loudly instead
                raise ValueError(
                    f"ring op {name!r}: local payload is {arr.nbytes} B "
                    f"but the negotiated size is {nbytes} B — all ranks "
                    "must pass identically-shaped tensors"
                )
            if op == "broadcast":
                buf = bytearray(arr.tobytes())
                # writes into buf in place
                self._ring.broadcast(buf, root)  # hvd-lint: disable=HVD008
                out = np.frombuffer(buf, arr.dtype).reshape(arr.shape)
            elif op == "allgather":
                out = self._ring.allgather(arr)
            else:
                out = self._ring.allreduce(arr, op=op)
            if _metrics.on():
                _metrics.RING_OPS.labels(op).inc()
                _metrics.RING_BYTES.inc(arr.nbytes)
            if fut is not None:
                fut.set_result(out)
        except BaseException as e:  # noqa: BLE001
            if fut is not None:
                fut.set_exception(e)
            else:
                log.warning("joined-rank ring op %s failed: %s", name, e)

    def _fail(self, tensors, exc) -> None:
        for nm, _, _ in tensors:
            with self._lock:
                entry = self._pending.pop(nm, None)
            if entry is not None:
                entry[3].set_exception(exc)

    def _fail_all(self, exc) -> None:
        with self._lock:
            pending, self._pending = self._pending, {}
        for arr, op, root, fut in pending.values():
            fut.set_exception(exc)


def _iface_ip(names: str) -> Optional[str]:
    """IPv4 address of the first resolvable interface in the comma list
    (reference --network-interface semantics: the operator names the
    NIC(s) the data plane must ride; each worker resolves locally)."""
    import fcntl
    import struct

    for name in names.split(","):
        name = name.strip()
        if not name:
            continue
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            packed = struct.pack("256s", name.encode()[:255])
            addr = fcntl.ioctl(s.fileno(), 0x8915, packed)[20:24]
            return socket.inet_ntoa(addr)  # 0x8915 = SIOCGIFADDR
        except OSError:
            continue
        finally:
            s.close()
    log.warning("no interface in %r has an IPv4 address", names)
    return None


def establish(client, rank: int, nranks: int, *,
              host: Optional[str] = None) -> Optional[RingExecutor]:
    """Bring up the ring: listener → address allgather over the star →
    dial the right neighbor → all-ranks-ok agreement → executor.

    Every rank participates in both allgathers even after a local
    failure, and the ring only activates when EVERY rank connected —
    a half-established ring (some ranks falling back to the star) would
    deadlock the first large collective.  Returns None (on all ranks,
    consistently) when any link failed."""
    # Advertised-address priority: explicit arg > operator's NIC
    # override (--network-interface, resolved per worker) > the
    # launcher-known hostname (HVD_RING_HOST) > self-resolution.
    # A mandated-but-unresolvable NIC list raises: silently advertising
    # another interface (typically the management NIC) would ride the
    # wrong network — fail at launch, as the reference does for an
    # absent GLOO_IFACE.  But the raise happens AFTER both setup
    # allgathers: a rank that bails before them (heterogeneous NIC
    # names resolving on some workers only) would leave resolving peers
    # blocked in establish() until the stall deadline instead of
    # degrading fast.
    nic_error: Optional[str] = None
    my_host = host
    if not my_host:
        ifaces = env_util.get_str(env_util.HVD_NETWORK_INTERFACE)
        if ifaces:
            my_host = _iface_ip(ifaces)
            if my_host is None:
                nic_error = (
                    f"none of the interfaces in "
                    f"--network-interface={ifaces!r} has an IPv4 "
                    "address on this worker"
                )
                if client is None:  # no peers to unblock
                    raise RuntimeError(nic_error)
    ring = None
    addr = b""
    if nic_error is None:
        try:
            ring = Ring(rank, nranks)
            my_host = my_host or env_util.get_str("HVD_RING_HOST") \
                or socket.gethostbyname(socket.gethostname())
            addr = f"{my_host}:{ring.port}".encode()
        except Exception as e:  # noqa: BLE001
            log.warning("ring listener failed: %s", e)

    addrs: List[bytes] = client.allgather_data("ring.__setup__", addr)
    ok = ring is not None and all(addrs)
    if ok:
        try:
            right = addrs[(rank + 1) % nranks].decode()
            right_host, right_port = right.rsplit(":", 1)
            ring.connect(right_host, int(right_port))
        except Exception as e:  # noqa: BLE001
            log.warning("ring connect failed: %s", e)
            ok = False

    oks = client.allgather_data("ring.__ok__", b"1" if ok else b"0")
    if nic_error is not None:
        # both allgathers done — peers have already degraded to the
        # star consistently; now surface the launch error locally
        raise RuntimeError(nic_error)
    if not all(o == b"1" for o in oks):
        if ring is not None:
            ring.close()
        log.warning("ring plane disabled: ranks not all connected; "
                    "host collectives stay on the coordinator star")
        _metrics.RING_ACTIVE.set(0)
        return None
    _metrics.RING_ACTIVE.set(1)
    return RingExecutor(client, ring)


_leaked: List[Ring] = []
