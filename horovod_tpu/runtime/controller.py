"""Python interface to the native negotiation controller.

The eager-plane control protocol (see csrc/controller.cc for the design
rationale and reference citations): worker processes submit named tensors;
the rank-0 coordinator validates cross-rank agreement, fuses, and
broadcasts response lists.  In multi-controller deployments this runs
before each eager XLA collective so all processes issue identical
collectives in identical order — Horovod's original raison d'être
(reference controller.h:58-99 protocol doc).
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils import env as env_util
from . import native

# RequestType / DataType codes must match csrc/common.h.
REQUEST_TYPES = {
    "allreduce": 0, "allgather": 1, "broadcast": 2, "join": 3,
    "adasum": 4, "alltoall": 5,
}
# Host data-plane op codes (the kData op byte, csrc/controller.cc
# HandleData/ComputeDataResult): negotiation types plus elementwise
# min/max, which have no negotiation RequestType of their own.
DATA_OPS = dict(REQUEST_TYPES, min=6, max=7)
_DTYPES = {
    "float32": 0, "bfloat16": 1, "float16": 2, "float64": 3,
    "int32": 4, "int64": 5, "uint8": 6, "bool": 7,
}


def _dtype_code(dtype) -> int:
    return _DTYPES.get(str(np.dtype(dtype) if dtype != "bfloat16" else "bfloat16")
                       if dtype != "bfloat16" else "bfloat16",
                       _DTYPES.get(str(dtype), 0))


def _peer_status_suffix() -> str:
    """Name the missing ranks on a negotiation timeout: the rendezvous
    ``GET /health`` lease verdicts say which ranks are still renewing and
    which went silent, so operators — and the elastic driver — can
    identify the dead rank from the error itself instead of replaying the
    job.  Best-effort: an un-wired or unreachable rendezvous yields an
    empty suffix, never a second failure."""
    try:
        from ..elastic.abort import _rendezvous_from_env

        wired = _rendezvous_from_env()
        if wired is None:
            return ""
        from ..run.http_client import get_health

        addr, port, secret = wired
        report = get_health(addr, port, secret=secret, timeout=2.0)
        ranks = report.get("ranks", {})
        if not ranks:
            return ""
        by_verdict: dict = {}
        for rank in sorted(ranks, key=lambda r: (len(r), r)):
            verdict = ranks[rank].get("verdict", "unknown")
            by_verdict.setdefault(verdict, []).append(rank)
        detail = ", ".join(
            f"{v}=[{','.join(by_verdict[v])}]"
            for v in ("live", "stale", "dead", "unknown") if v in by_verdict
        )
        missing = by_verdict.get("dead", []) + by_verdict.get("stale", [])
        hint = (f"; rank(s) {','.join(missing)} have not arrived"
                if missing else "")
        return f" (rank health: {detail}{hint})"
    except Exception:  # noqa: BLE001 — diagnosis must not mask the timeout
        return ""


class ControllerServer:
    """Coordinator (rank 0 owns it; reference: the coordinator role in
    controller.cc:196-326)."""

    def __init__(self, nranks: int, *, port: int = 0,
                 cycle_ms: Optional[float] = None,
                 fusion_threshold: Optional[int] = None,
                 stall_warn_sec: Optional[float] = None):
        lib = native.load()
        self._lib = lib
        self._h = lib.hvd_server_start(
            port, nranks,
            cycle_ms if cycle_ms is not None else env_util.cycle_time_ms(),
            fusion_threshold if fusion_threshold is not None
            else env_util.fusion_threshold_bytes(),
            stall_warn_sec if stall_warn_sec is not None
            else env_util.get_float(env_util.HVD_STALL_CHECK_TIME_SECONDS,
                                    env_util.DEFAULT_STALL_WARNING_SECONDS),
        )
        if not self._h:
            raise RuntimeError("failed to start controller server")
        # Coordinator counters ride the metrics plane as polled gauges —
        # the scrape-time analog of the reference's rank-0-only stats
        # (controller.cc:164-193), now visible wherever the server lives.
        # _handle_lock orders collect() against stop(): a scrape-thread
        # collector passing an unguarded handle check while stop() frees
        # the native object would call into freed memory.  The collector
        # holds only a WEAK reference (a strong closure would pin the
        # server forever in the global registry and disable the __del__
        # safety net), and its key is per-instance so two servers in one
        # process never clobber each other's registration.
        import threading
        import weakref

        self._handle_lock = threading.Lock()
        self._collector_key = f"controller_server:{id(self)}"
        from ..metrics import (
            CONTROLLER_CACHE_HITS, CONTROLLER_CYCLES, CONTROLLER_STALLS,
            registry,
        )

        ref = weakref.ref(self)

        def collect() -> None:
            srv = ref()
            if srv is None:
                return
            with srv._handle_lock:
                if not srv._h:
                    return
                CONTROLLER_CYCLES.set(srv.cycles)
                CONTROLLER_CACHE_HITS.set(srv.cache_hits)
                CONTROLLER_STALLS.set(srv.stall_warnings)

        registry.register_collector(self._collector_key, collect)

    @property
    def port(self) -> int:
        return self._lib.hvd_server_port(self._h)

    @property
    def cache_hits(self) -> int:
        return self._lib.hvd_server_cache_hits(self._h)

    @property
    def cycles(self) -> int:
        return self._lib.hvd_server_cycles(self._h)

    @property
    def stall_warnings(self) -> int:
        return self._lib.hvd_server_stall_warnings(self._h)

    def stop(self) -> None:
        if self._h:
            from ..metrics import registry

            registry.unregister_collector(self._collector_key)
            with self._handle_lock:
                self._lib.hvd_server_stop(self._h)
                self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:  # noqa: BLE001
            pass


class ControllerClient:
    """Per-process worker client (reference: the worker role,
    SendReadyTensors/RecvFinalTensors in mpi_controller.cc:107-120)."""

    def __init__(self, host: str, port: int, rank: int):
        lib = native.load()
        self._lib = lib
        self._h = lib.hvd_client_connect(host.encode(), port, rank)
        if not self._h:
            raise RuntimeError(f"failed to connect controller {host}:{port}")
        self.rank = rank

    def submit(self, name: str, *, op: str = "allreduce",
               shape: Sequence[int] = (), dtype="float32",
               root_rank: int = 0) -> None:
        arr = (ctypes.c_longlong * len(shape))(*shape)
        rc = self._lib.hvd_client_submit(
            self._h, name.encode(), REQUEST_TYPES[op], _dtype_code(dtype),
            self.rank, root_rank, arr, len(shape),
        )
        if rc != 0:
            raise RuntimeError("controller submit failed (connection lost)")

    def wait(self, name: str, timeout: float = 60.0) -> List[str]:
        """Block until `name` is negotiated; returns the fused group (the
        tensors to execute in one collective).  Raises on error responses
        (the reference surfaces coordinator ERROR responses as Python
        exceptions, ops/collective_operations.cc:230-232)."""
        err = ctypes.create_string_buffer(4096)
        group = ctypes.create_string_buffer(1 << 16)
        rc = self._lib.hvd_client_wait(
            self._h, name.encode(), timeout * 1000.0,
            err, len(err), group, len(group),
        )
        if rc == 0:
            g = group.value.decode()
            return g.split(";") if g else [name]
        if rc == 1:
            raise RuntimeError(err.value.decode())
        if rc == 2:
            raise TimeoutError(
                f"negotiation of {name!r} timed out{_peer_status_suffix()}")
        raise ConnectionError("controller connection lost")

    def submit_data(self, name: str, payload: bytes, *,
                    op: str = "allreduce", dtype="uint8",
                    root_rank: int = 0) -> None:
        """Send this rank's payload for the host data plane (the Gloo-CPU-ops
        analog living in the coordinator, csrc/controller.cc HandleData)."""
        rc = self._lib.hvd_client_submit_data(
            self._h, name.encode(), DATA_OPS[op], _dtype_code(dtype),
            root_rank, payload, len(payload),
        )
        if rc != 0:
            raise RuntimeError("controller submit_data failed (connection lost)")

    def wait_data(self, name: str, timeout: float = 60.0) -> bytes:
        """Block for the coordinator's reduced/gathered payload."""
        n = ctypes.c_longlong(0)
        err = ctypes.create_string_buffer(1024)
        rc = self._lib.hvd_client_wait_data(
            self._h, name.encode(), timeout * 1000.0, None, 0,
            ctypes.byref(n), err, len(err),
        )
        if rc == 4:  # result ready; fetch with a right-sized buffer
            buf = ctypes.create_string_buffer(max(int(n.value), 1))
            rc = self._lib.hvd_client_wait_data(
                self._h, name.encode(), timeout * 1000.0, buf, n.value,
                ctypes.byref(n), err, len(err),
            )
            if rc == 0:
                return buf.raw[: int(n.value)]
        if rc == 0:  # zero-length result
            return b""
        if rc == 1:
            raise RuntimeError(err.value.decode())
        if rc == 2:
            raise TimeoutError(
                f"host collective {name!r} timed out{_peer_status_suffix()}")
        raise ConnectionError("controller connection lost")

    def allreduce_data(self, name: str, arr: "np.ndarray",
                       timeout: float = 60.0,
                       op: str = "allreduce") -> "np.ndarray":
        """Reduce ``arr`` elementwise across all ranks on the coordinator.
        ``op``: allreduce (sum), min, max, or adasum (real VHDD tree,
        csrc/controller.cc AdasumReduce).  Caller divides for Average
        (the reference's divisor trick, torch/mpi_ops.py:94-129)."""
        arr = np.ascontiguousarray(arr)
        dtype = str(arr.dtype)
        if dtype not in ("float32", "float64", "int32", "int64",
                         "bfloat16", "float16"):
            raise TypeError(f"host allreduce unsupported for dtype {dtype}")
        self.submit_data(name, arr.tobytes(), op=op, dtype=dtype)
        out = self.wait_data(name, timeout=timeout)
        return np.frombuffer(out, arr.dtype).reshape(arr.shape).copy()

    def allgather_data(self, name: str, payload: bytes,
                       timeout: float = 60.0) -> List[bytes]:
        """Gather each rank's variable-length payload; returns the list in
        rank order (wire format: u32 count, u32 sizes, blobs)."""
        self.submit_data(name, payload, op="allgather")
        out = self.wait_data(name, timeout=timeout)
        import struct

        (count,) = struct.unpack_from("<I", out, 0)
        sizes = struct.unpack_from(f"<{count}I", out, 4)
        blobs, off = [], 4 + 4 * count
        for s in sizes:
            blobs.append(out[off: off + s])
            off += s
        return blobs

    def broadcast_data(self, name: str, payload: bytes, root_rank: int = 0,
                       timeout: float = 60.0) -> bytes:
        self.submit_data(name, payload, op="broadcast", root_rank=root_rank)
        return self.wait_data(name, timeout=timeout)

    def enable_order_stream(self) -> None:
        """Start recording negotiated responses in coordinator order (the
        execution order the ring executor follows — reference
        controller.h:58-99: the response list IS the execution order)."""
        self._lib.hvd_client_enable_order_stream(self._h)

    def next_negotiated(self, timeout: float = 60.0):
        """Pop the next negotiated response: ``(type_code, error_message,
        [(name, dtype_code, nbytes), ...])`` in coordinator-broadcast
        order — identical on every rank.  Raises TimeoutError /
        ConnectionError."""
        n = ctypes.c_longlong(0)
        buf = ctypes.create_string_buffer(1 << 16)
        rc = self._lib.hvd_client_next_negotiated(
            self._h, timeout * 1000.0, buf, len(buf), ctypes.byref(n),
        )
        if rc == 4:  # huge fused group: retry with the exact size
            buf = ctypes.create_string_buffer(int(n.value))
            rc = self._lib.hvd_client_next_negotiated(
                self._h, timeout * 1000.0, buf, len(buf), ctypes.byref(n),
            )
        if rc == 2:
            raise TimeoutError("no negotiated response within timeout")
        if rc != 0:
            raise ConnectionError("controller connection lost")
        raw = buf.raw[: int(n.value)].decode()
        records = raw.split("\x1e")
        type_s, _, err = records[0].partition("\x1f")
        tensors = []
        for rec in records[1:]:
            name, dtype_s, bytes_s = rec.split("\x1f")
            tensors.append((name, int(dtype_s), int(bytes_s)))
        return int(type_s), err, tensors

    def stats(self, timeout: float = 10.0) -> dict:
        """Query the coordinator's counters over the wire — lets any rank
        observe negotiation health when the server lives in the launcher
        (the reference surfaces these rank-0-side only,
        controller.cc:164-193)."""
        cycles = ctypes.c_longlong(0)
        hits = ctypes.c_longlong(0)
        stalls = ctypes.c_longlong(0)
        rc = self._lib.hvd_client_stats(
            self._h, timeout * 1000.0,
            ctypes.byref(cycles), ctypes.byref(hits), ctypes.byref(stalls),
        )
        if rc == 2:
            raise TimeoutError("controller stats query timed out")
        if rc != 0:
            raise ConnectionError("controller connection lost")
        return {
            "cycles": int(cycles.value),
            "cache_hits": int(hits.value),
            "stall_warnings": int(stalls.value),
        }

    def join(self) -> None:
        self._lib.hvd_client_join(self._h)

    def wait_join(self, timeout: float = 60.0) -> None:
        rc = self._lib.hvd_client_wait_join(self._h, timeout * 1000.0)
        if rc == 2:
            raise TimeoutError(f"join timed out{_peer_status_suffix()}")
        if rc == 3:
            raise ConnectionError("controller connection lost")

    def close(self) -> None:
        if self._h:
            self._lib.hvd_client_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass
