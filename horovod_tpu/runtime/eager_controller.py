"""Process-wide eager-plane controller wiring.

Connects the eager collectives (horovod_tpu/eager.py) to the native
negotiation controller (runtime/controller.py) in multi-controller
deployments: before each eager XLA collective, every process submits the
tensor name/shape/dtype and waits for the coordinator's response — so all
processes issue identical collectives in identical order (the deadlock /
mismatch protection that is Horovod's original purpose; reference
controller.h:58-99).  Single-process jobs skip negotiation entirely — the
analog of the reference's bypass when the response cache fully covers the
cycle (controller.cc:164-193).

The launcher (tpurun) selects this with HVD_CONTROLLER=native and points
workers at the coordinator with HVD_CONTROLLER_ADDR=host:port; process 0
hosts the server.
"""

from __future__ import annotations

import atexit
import os
from typing import List, Optional, Sequence

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)

_server = None
_client = None
_ring_exec = None


def setup_from_env(process_id: int, num_processes: int) -> None:
    """Called from hvd.init().  No-op unless HVD_CONTROLLER=native and the
    job spans multiple controller processes."""
    global _server, _client, _ring_exec
    if _client is not None or num_processes <= 1:
        return
    if env_util.get_str(env_util.HVD_CONTROLLER) != "native":
        return
    addr = env_util.get_str("HVD_CONTROLLER_ADDR")
    if not addr:
        log.warning("HVD_CONTROLLER=native but HVD_CONTROLLER_ADDR unset")
        return
    host, port_s = addr.rsplit(":", 1)
    port = int(port_s)
    import socket

    # the native client dials an IP (inet_pton); resolve hostnames here
    host = socket.gethostbyname(host)
    from .controller import ControllerClient, ControllerServer

    # The launcher (tpurun / function-mode run()) hosts the server itself
    # and marks it external — it binds port 0 there, so no remote-host port
    # race.  Only self-assembled jobs start the server in process 0.
    if process_id == 0 and \
            env_util.get_str("HVD_CONTROLLER_SERVER") != "external":
        _server = ControllerServer(num_processes, port=port)
    _client = ControllerClient(host, port, process_id)
    atexit.register(shutdown)
    # Peer ring for large host payloads (HVD_RING=0 keeps everything on
    # the coordinator star — debugging aid).
    if env_util.get_int("HVD_RING", 1):
        from . import ring as ring_mod

        # establish() degrades collectively: it returns None on EVERY
        # rank when any link failed, so no rank is left ringing alone
        _ring_exec = ring_mod.establish(_client, process_id, num_processes)
    log.info("eager controller active: %s (process %d/%d, ring=%s)",
             addr, process_id, num_processes, _ring_exec is not None)


def active() -> bool:
    return _client is not None


def client():
    """The process's ControllerClient (None when negotiation is inactive).
    Exposes the host data plane: allreduce_data/allgather_data/
    broadcast_data (csrc/controller.cc HandleData — the Gloo-CPU-ops
    analog, reference horovod/common/ops/gloo_operations.cc)."""
    return _client


def ring():
    """The process's RingExecutor (None when the peer ring is down) — the
    scalable path for large host payloads (csrc/ring.cc)."""
    return _ring_exec


_seq = 0


def next_name(prefix: str) -> str:
    """Sequential default tensor names, identical across processes when ops
    are issued in the same order (the reference's handle-derived default
    names, torch/mpi_ops.py allreduce.noname.N)."""
    global _seq
    _seq += 1
    return f"{prefix}.{_seq}"


def negotiate(name: str, *, op: str, shape: Sequence[int], dtype,
              root_rank: int = 0, timeout: float = 60.0) -> Optional[List[str]]:
    """Submit + wait; returns the fused group, or None when negotiation is
    inactive (single controller)."""
    if _client is None:
        return None
    from ..elastic import faults

    faults.on_controller(name)  # HVD_FAULT_SPEC: partition/hang/slow here
    _client.submit(name, op=op, shape=tuple(int(d) for d in shape),
                   dtype=str(dtype), root_rank=root_rank)
    return _client.wait(name, timeout=timeout)


def join(timeout: float = 60.0) -> None:
    if _client is None:
        return
    _client.join()
    _client.wait_join(timeout=timeout)


def server_stats() -> Optional[dict]:
    """Coordinator counters: read locally when this process hosts the
    server, otherwise queried over the wire (launcher-hosted server)."""
    if _server is not None:
        return {
            "cache_hits": _server.cache_hits,
            "cycles": _server.cycles,
            "stall_warnings": _server.stall_warnings,
        }
    if _client is not None:
        try:
            return _client.stats()
        except (TimeoutError, ConnectionError, OSError):
            # no-raise contract: a wedged or shut-down coordinator reads
            # as "no stats available", same as not having one
            return None
    return None


def shutdown() -> None:
    global _server, _client, _ring_exec
    if _ring_exec is not None:
        _ring_exec.close()  # joins the dispatcher, then frees the ring
        _ring_exec = None
    if _client is not None:
        _client.close()
        _client = None
    if _server is not None:
        _server.stop()
        _server = None
