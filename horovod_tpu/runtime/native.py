"""ctypes loader for the native runtime core (build/libhvdcore.so).

Analog of horovod/common/basics.py (reference :22-30 loads the compiled
extension and declares the C ABI) — but instead of a pip-time build, the
library is compiled on demand from csrc/ with g++ (cached under build/).
pybind11 isn't assumed; the C ABI + ctypes keeps the binding dependency-free.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from ..utils.logging import get_logger

log = get_logger(__name__)

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SO_PATH = os.path.join(_ROOT, "build", "libhvdcore.so")
_CSRC = os.path.join(_ROOT, "csrc")

_lib: Optional[ctypes.CDLL] = None
_lock = threading.Lock()


def _build() -> None:
    log.info("building native core: make -C %s", _CSRC)
    subprocess.run(
        ["make", "-C", _CSRC, f"OUT={_SO_PATH}"],
        check=True, capture_output=True,
    )


def _sources_newer() -> bool:
    if not os.path.exists(_SO_PATH):
        return True
    so_mtime = os.path.getmtime(_SO_PATH)
    for f in os.listdir(_CSRC):
        if f.endswith((".cc", ".h")):
            if os.path.getmtime(os.path.join(_CSRC, f)) > so_mtime:
                return True
    return False


def load() -> ctypes.CDLL:
    """Load (building if stale) and type the C API."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if _sources_newer():
            _build()
        lib = ctypes.CDLL(_SO_PATH)

        # timeline
        lib.hvd_timeline_open.restype = ctypes.c_void_p
        lib.hvd_timeline_open.argtypes = [ctypes.c_char_p]
        lib.hvd_timeline_event.restype = None
        lib.hvd_timeline_event.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_char, ctypes.c_double,
            ctypes.c_double, ctypes.c_int,
        ]
        lib.hvd_timeline_close.restype = None
        lib.hvd_timeline_close.argtypes = [ctypes.c_void_p]

        # controller server
        lib.hvd_server_start.restype = ctypes.c_void_p
        lib.hvd_server_start.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_double,
            ctypes.c_longlong, ctypes.c_double,
        ]
        lib.hvd_server_port.restype = ctypes.c_int
        lib.hvd_server_port.argtypes = [ctypes.c_void_p]
        for fn in ("hvd_server_cache_hits", "hvd_server_cycles",
                   "hvd_server_stall_warnings"):
            getattr(lib, fn).restype = ctypes.c_longlong
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.hvd_server_stop.restype = None
        lib.hvd_server_stop.argtypes = [ctypes.c_void_p]

        # controller client
        lib.hvd_client_connect.restype = ctypes.c_void_p
        lib.hvd_client_connect.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ]
        lib.hvd_client_submit.restype = ctypes.c_int
        lib.hvd_client_submit.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
        ]
        lib.hvd_client_join.restype = ctypes.c_int
        lib.hvd_client_join.argtypes = [ctypes.c_void_p]
        lib.hvd_client_wait.restype = ctypes.c_int
        lib.hvd_client_wait.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_double,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.hvd_client_wait_join.restype = ctypes.c_int
        lib.hvd_client_wait_join.argtypes = [ctypes.c_void_p, ctypes.c_double]
        lib.hvd_client_submit_data.restype = ctypes.c_int
        lib.hvd_client_submit_data.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_void_p, ctypes.c_longlong,
        ]
        lib.hvd_client_wait_data.restype = ctypes.c_int
        lib.hvd_client_wait_data.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_double,
            ctypes.c_void_p, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_char_p, ctypes.c_int,
        ]
        lib.hvd_client_stats.restype = ctypes.c_int
        lib.hvd_client_stats.argtypes = [
            ctypes.c_void_p, ctypes.c_double,
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_longlong),
        ]
        lib.hvd_client_close.restype = None
        lib.hvd_client_close.argtypes = [ctypes.c_void_p]
        lib.hvd_client_enable_order_stream.restype = None
        lib.hvd_client_enable_order_stream.argtypes = [ctypes.c_void_p]
        lib.hvd_client_next_negotiated.restype = ctypes.c_int
        lib.hvd_client_next_negotiated.argtypes = [
            ctypes.c_void_p, ctypes.c_double, ctypes.c_char_p,
            ctypes.c_longlong, ctypes.POINTER(ctypes.c_longlong),
        ]

        # peer ring data plane
        lib.hvd_ring_create.restype = ctypes.c_void_p
        lib.hvd_ring_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_longlong,
        ]
        lib.hvd_ring_port.restype = ctypes.c_int
        lib.hvd_ring_port.argtypes = [ctypes.c_void_p]
        lib.hvd_ring_connect.restype = ctypes.c_int
        lib.hvd_ring_connect.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_double,
        ]
        lib.hvd_ring_allreduce.restype = ctypes.c_int
        lib.hvd_ring_allreduce.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong,
            ctypes.c_int, ctypes.c_int,
        ]
        lib.hvd_ring_broadcast.restype = ctypes.c_int
        lib.hvd_ring_broadcast.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong, ctypes.c_int,
        ]
        lib.hvd_ring_allgather.restype = ctypes.c_int
        lib.hvd_ring_allgather.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong,
            ctypes.c_void_p, ctypes.c_longlong,
        ]
        lib.hvd_ring_close.restype = None
        lib.hvd_ring_close.argtypes = [ctypes.c_void_p]

        # autotuner
        lib.hvd_tuner_create.restype = ctypes.c_void_p
        lib.hvd_tuner_create.argtypes = [
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_int, ctypes.c_double,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_ulonglong,
        ]
        lib.hvd_tuner_record.restype = ctypes.c_int
        lib.hvd_tuner_record.argtypes = [
            ctypes.c_void_p, ctypes.c_double, ctypes.c_double,
        ]
        lib.hvd_tuner_x.restype = ctypes.c_double
        lib.hvd_tuner_x.argtypes = [ctypes.c_void_p]
        lib.hvd_tuner_category.restype = ctypes.c_int
        lib.hvd_tuner_category.argtypes = [ctypes.c_void_p]
        lib.hvd_tuner_frozen.restype = ctypes.c_int
        lib.hvd_tuner_frozen.argtypes = [ctypes.c_void_p]
        lib.hvd_tuner_best_score.restype = ctypes.c_double
        lib.hvd_tuner_best_score.argtypes = [ctypes.c_void_p]
        lib.hvd_tuner_last_score.restype = ctypes.c_double
        lib.hvd_tuner_last_score.argtypes = [ctypes.c_void_p]
        lib.hvd_tuner_samples_seen.restype = ctypes.c_int
        lib.hvd_tuner_samples_seen.argtypes = [ctypes.c_void_p]
        lib.hvd_tuner_destroy.restype = None
        lib.hvd_tuner_destroy.argtypes = [ctypes.c_void_p]

        # GP (test cross-check surface)
        lib.hvd_gp_create.restype = ctypes.c_void_p
        lib.hvd_gp_create.argtypes = [
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
        ]
        lib.hvd_gp_fit.restype = None
        lib.hvd_gp_fit.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double), ctypes.c_int,
        ]
        lib.hvd_gp_predict.restype = None
        lib.hvd_gp_predict.argtypes = [
            ctypes.c_void_p, ctypes.c_double,
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
        ]
        lib.hvd_gp_destroy.restype = None
        lib.hvd_gp_destroy.argtypes = [ctypes.c_void_p]

        _lib = lib
        return lib


def available() -> bool:
    try:
        load()
        return True
    except Exception as e:  # noqa: BLE001
        log.warning("native core unavailable: %s", e)
        return False
