"""Front-end request router: the serving plane's face on the
rendezvous HTTP server.

The launcher's :class:`~horovod_tpu.run.http_server.RendezvousServer`
already authenticates every request (HMAC signature) and aggregates the
job's control plane; ``tpurun --serve`` attaches one of these frontends
to it, adding three signed routes (docs/inference.md "Request plane"):

* ``POST /infer`` — one inference request: JSON ``{"inputs": [...]}``
  in, ``{"id", "outputs", "latency_ms", "replica"}`` out (503 at the
  admission cap, 504 past the request timeout, 500 on a replica
  failure).  The handler thread blocks in the broker wait — the server
  is a ``ThreadingHTTPServer``, so concurrent requests ride their own
  threads.
* ``POST /serving/pull`` / ``POST /serving/result`` — the remote
  replica protocol (serving/replica.py :class:`RemoteSource`): workers
  on other hosts pull request batches and post results through the
  same signed channel.
* ``GET /serving`` — the status page: broker window stats (queue
  depth, windowed p50/p99), per-outcome counters, SLO, and the
  autoscaler's world/events when one is attached.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..utils import env as env_util
from ..utils.logging import get_logger
from .broker import QueueFullError, RequestBroker

log = get_logger(__name__)


class ServingFrontend:
    """Route handler attached to a RendezvousServer
    (``server.attach_serving(frontend)``); every handler returns
    ``(http_status, json_payload)`` and never raises into the HTTP
    stack."""

    def __init__(self, broker: RequestBroker, *,
                 autoscaler=None,
                 timeout_s: Optional[float] = None) -> None:
        self.broker = broker
        self.autoscaler = autoscaler
        self.timeout_s = float(
            timeout_s if timeout_s is not None
            else env_util.get_float(env_util.HVD_SERVE_TIMEOUT_SECONDS,
                                    env_util.DEFAULT_SERVE_TIMEOUT_SECONDS))
        self.slo_ms = env_util.get_float(env_util.HVD_SERVE_SLO_MS,
                                         env_util.DEFAULT_SERVE_SLO_MS)

    # -- POST /infer ---------------------------------------------------------
    def handle_infer(self, payload: dict) -> Tuple[int, dict]:
        if not isinstance(payload, dict) or "inputs" not in payload:
            return 400, {"error": "body must be a JSON object with "
                                  "an 'inputs' array"}
        try:
            inputs = np.asarray(payload["inputs"], dtype=np.float32)
        except (TypeError, ValueError) as e:
            return 400, {"error": f"undecodable inputs: {e}"}
        try:
            req = self.broker.submit(inputs)
        except QueueFullError as e:
            return 503, {"error": str(e)}
        try:
            out = self.broker.wait(req, self.timeout_s)
        except TimeoutError as e:
            return 504, {"error": str(e), "id": req.id}
        except RuntimeError as e:
            return 500, {"error": str(e), "id": req.id}
        lat = req.latency_s()
        return 200, {
            "id": req.id,
            "outputs": np.asarray(out).tolist(),
            "latency_ms": round(lat * 1000.0, 3)
            if lat is not None else None,
            "replica": req.completed_by,
        }

    # -- POST /serving/pull and /serving/result (remote replicas) ------------
    def handle_pull(self, payload: dict) -> Tuple[int, dict]:
        replica_id = str(payload.get("replica_id", ""))
        if not replica_id:
            return 400, {"error": "replica_id required"}
        max_n = int(payload.get("max_batch", 1))
        wait_s = float(payload.get("wait_ms", 0.0)) / 1000.0
        # cap the long-poll so a vanished replica's handler thread
        # cannot park forever on the server
        batch = self.broker.pull(replica_id, max_n, min(wait_s, 30.0))
        return 200, {"requests": [
            {"id": r.id, "inputs": np.asarray(r.inputs).tolist()}
            for r in batch]}

    def handle_result(self, payload: dict) -> Tuple[int, dict]:
        replica_id = str(payload.get("replica_id", ""))
        if not replica_id:
            return 400, {"error": "replica_id required"}
        accepted = 0
        for res in payload.get("results", ()):
            req_id = res.get("id")
            if req_id is None:
                continue
            if res.get("error") is not None:
                ok = self.broker.fail(int(req_id), str(res["error"]),
                                      replica_id)
            else:
                ok = self.broker.complete(
                    int(req_id),
                    np.asarray(res.get("output"), dtype=np.float32),
                    replica_id)
            accepted += 1 if ok else 0
        return 200, {"accepted": accepted}

    # -- GET /serving --------------------------------------------------------
    def report(self) -> dict:
        out = {
            "broker": self.broker.window_stats(),
            "slo_ms": self.slo_ms,
            "timeout_s": self.timeout_s,
            "autoscaler": self.autoscaler.snapshot()
            if self.autoscaler is not None else None,
        }
        return out
