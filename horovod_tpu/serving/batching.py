"""Continuous / dynamic batching for inference replicas.

Two pieces (docs/inference.md "Batching"):

* :class:`ContinuousBatcher` — the admit/flush loop.  A batch opens
  when the first request arrives and closes when EITHER
  ``HVD_SERVE_MAX_BATCH`` requests are admitted (flush-on-size) OR
  ``HVD_SERVE_MAX_WAIT_MS`` has passed since the first admit
  (flush-on-deadline), whichever is first.  Batches never straddle the
  deadline waiting for a fuller batch — bounded queueing delay is the
  whole point of the deadline.
* :class:`BatchBucketer` — padded-shape bucketing.  XLA compiles one
  program per input shape, so raw batch sizes would re-jit on every
  distinct fill; the bucketer rounds each batch up to a fixed ladder
  (``HVD_SERVE_BUCKET_SIZES``, default powers of two up to the max
  batch) so the number of compiled programs is bounded by the ladder
  length.  Padding rows are zeros and sliced off after the forward.

Both take an injectable clock so flush behaviour is deterministic
under test (tests/test_serving.py pins flush-on-size vs
flush-on-deadline against a scripted clock).
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import env as env_util


def bucket_sizes_from_env(max_batch: int) -> Tuple[int, ...]:
    """The padded-size ladder: ``HVD_SERVE_BUCKET_SIZES`` (comma list)
    when set, else powers of two up to ``max_batch`` (always including
    ``max_batch`` itself so a full batch needs no padding)."""
    spec = env_util.get_str(env_util.HVD_SERVE_BUCKET_SIZES)
    if spec:
        sizes = sorted({int(s) for s in spec.split(",") if s.strip()})
        if not sizes:
            raise ValueError(
                f"{env_util.HVD_SERVE_BUCKET_SIZES}={spec!r} names no "
                "sizes")
    else:
        sizes, p = [], 1
        while p < max_batch:
            sizes.append(p)
            p *= 2
        sizes.append(max_batch)
        sizes = sorted(set(sizes))
    return tuple(sizes)


class BatchBucketer:
    """Round batch sizes up a fixed ladder so re-jits are bounded."""

    def __init__(self, sizes: Sequence[int]) -> None:
        sizes = sorted({int(s) for s in sizes})
        if not sizes or sizes[0] < 1:
            raise ValueError(f"bucket sizes must be >= 1, got {sizes}")
        self.sizes = tuple(sizes)

    def bucket(self, n: int) -> int:
        """Smallest ladder size >= ``n``.  Anything above the top rung
        has no padded shape to land in — InferenceReplica caps its
        batcher at the top rung, and :meth:`pad` raises rather than
        mis-padding."""
        for s in self.sizes:
            if n <= s:
                return s
        raise ValueError(
            f"batch of {n} exceeds the bucket ladder top "
            f"{self.sizes[-1]} — cap the batcher at the top rung")

    def pad(self, stacked: np.ndarray) -> Tuple[np.ndarray, int]:
        """Pad a ``[n, ...]`` array with zero rows up to the bucket
        size; returns ``(padded, n)`` so the caller slices the real
        rows back off the output."""
        n = stacked.shape[0]
        b = self.bucket(n)
        if b == n:
            return stacked, n
        pad_width = [(0, b - n)] + [(0, 0)] * (stacked.ndim - 1)
        return np.pad(stacked, pad_width), n


class ContinuousBatcher:
    """The admit/flush loop over a broker-shaped ``pull`` callable.

    ``pull(max_n, wait_s) -> list`` is the only contract — the in-
    process :class:`~horovod_tpu.serving.broker.RequestBroker` and the
    HTTP remote source both fit.  ``clock`` is injectable for
    deterministic tests.
    """

    def __init__(self, pull: Callable[[int, float], List],
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.pull = pull
        self.max_batch = int(
            max_batch if max_batch is not None
            else env_util.get_int(env_util.HVD_SERVE_MAX_BATCH,
                                  env_util.DEFAULT_SERVE_MAX_BATCH))
        self.max_wait_s = float(
            max_wait_ms if max_wait_ms is not None
            else env_util.get_float(env_util.HVD_SERVE_MAX_WAIT_MS,
                                    env_util.DEFAULT_SERVE_MAX_WAIT_MS)
        ) / 1000.0
        self.clock = clock
        self.batches = 0

    def next_batch(self, idle_wait_s: float = 0.1) -> List:
        """One admit/flush cycle: block up to ``idle_wait_s`` for the
        first request (empty list when none arrives — the replica loop
        spins), then admit until the size cap or the deadline.  The
        opening pull asks for a FULL batch: a backlog fills the batch
        in one round trip (one HTTP pull for a RemoteSource), and the
        deadline loop only runs for the unfilled remainder."""
        batch = self.pull(self.max_batch, idle_wait_s)
        if not batch:
            return []
        deadline = self.clock() + self.max_wait_s
        while len(batch) < self.max_batch:
            remaining = deadline - self.clock()
            if remaining <= 0:
                break
            more = self.pull(self.max_batch - len(batch), remaining)
            if not more:
                break  # pull honored the deadline; nothing arrived
            batch.extend(more)
        self.batches += 1
        self._record_fill(len(batch))
        return batch

    def _record_fill(self, n: int) -> None:
        try:
            from .. import metrics

            if metrics.on():
                metrics.SERVE_BATCH_FILL.observe(n)
        except Exception:  # noqa: BLE001
            pass
