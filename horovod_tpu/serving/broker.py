"""The shared request queue every inference replica pulls from.

The serving plane's single source of truth for request state
(docs/inference.md): the front-end router submits requests here, data-
parallel replicas pull them in batches, and completion resolves the
submitter's wait.  The broker owns the **zero-drop / zero-dup**
contract the autoscaler's epoch transitions are measured against:

* a request exists in exactly one place — the pending queue or one
  replica's in-flight table — until it is completed exactly once
  (late duplicates are counted and ignored, never re-delivered);
* a **draining** replica stops receiving new work but keeps completing
  what it pulled (the scale-down handshake, elastic/driver.py
  ``remove(drain=True)``);
* a replica that dies uncleanly has its in-flight requests **requeued**
  at the front of the queue in submission order, so a crash loses no
  request either (it costs latency, not answers).

Everything is condition-variable based and in-process; remote replicas
reach the same object through the rendezvous server's ``POST
/serving/pull`` / ``/serving/result`` routes (serving/frontend.py).
Latency/queue-depth signals feed the metrics plane
(``hvd_serve_*``) and the windowed p50/p99 the autoscaler reads.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)


class QueueFullError(RuntimeError):
    """Admission control: the broker's pending queue is at
    ``HVD_SERVE_QUEUE_LIMIT`` — the front-end maps this to a 503 so
    overload degrades to rejections instead of unbounded latency."""


class Request:
    """One inference request, tracked from submit to completion."""

    __slots__ = ("id", "inputs", "submit_time", "pull_time",
                 "complete_time", "output", "error", "pulled_by",
                 "completed_by", "done")

    def __init__(self, req_id: int, inputs) -> None:
        self.id = req_id
        self.inputs = inputs
        self.submit_time = time.monotonic()
        self.pull_time: Optional[float] = None
        self.complete_time: Optional[float] = None
        self.output = None
        self.error: Optional[str] = None
        self.pulled_by: Optional[str] = None
        self.completed_by: Optional[str] = None
        self.done = threading.Event()

    def latency_s(self) -> Optional[float]:
        if self.complete_time is None:
            return None
        return self.complete_time - self.submit_time


def percentile(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (0 < q <= 100) on a copy — the one
    p50/p99 rule shared by the broker window, the load generator, and
    the bench leg, so every report agrees."""
    if not values:
        return None
    vs = sorted(values)
    idx = max(int(len(vs) * q / 100.0 + 0.999999) - 1, 0)
    return vs[min(idx, len(vs) - 1)]


class RequestBroker:
    """Thread-safe continuous-batching request queue.

    ``queue_limit``: admission cap (``HVD_SERVE_QUEUE_LIMIT``).
    ``window_s``: how much completion history the p50/p99 window keeps
    (the autoscaler's latency signal; default 30 s).
    """

    def __init__(self, queue_limit: Optional[int] = None,
                 window_s: float = 30.0) -> None:
        self.queue_limit = int(
            queue_limit if queue_limit is not None
            else env_util.get_int(env_util.HVD_SERVE_QUEUE_LIMIT,
                                  env_util.DEFAULT_SERVE_QUEUE_LIMIT))
        self.window_s = float(window_s)
        self._cond = threading.Condition()
        self._pending: deque = deque()
        self._inflight: Dict[str, Dict[int, Request]] = {}
        self._draining: set = set()
        self._by_id: Dict[int, Request] = {}
        self._next_id = 0
        self._window: deque = deque()  # (complete_time, latency_s)
        # counters (mirrored into hvd_serve_* where a family exists)
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.rejected = 0
        self.duplicates = 0
        self.requeued = 0
        self.abandoned = 0

    # -- submitter side ------------------------------------------------------
    def submit(self, inputs) -> Request:
        """Admit one request (raises :class:`QueueFullError` at the
        cap).  Returns the tracked request; pair with :meth:`wait`."""
        with self._cond:
            if len(self._pending) >= self.queue_limit:
                self.rejected += 1
                self._record_outcome("rejected")
                raise QueueFullError(
                    f"serving queue at its {self.queue_limit}-request "
                    "admission cap")
            req = Request(self._next_id, inputs)
            self._next_id += 1
            self._pending.append(req)
            self._by_id[req.id] = req
            self.submitted += 1
            self._set_depth_gauge()
            self._cond.notify_all()
        return req

    def wait(self, req: Request, timeout: Optional[float] = None):
        """Block until ``req`` completes; returns its output.  Raises
        TimeoutError past ``timeout`` (default
        ``HVD_SERVE_TIMEOUT_SECONDS``) and RuntimeError when the
        replica failed the request."""
        if timeout is None:
            timeout = env_util.get_float(
                env_util.HVD_SERVE_TIMEOUT_SECONDS,
                env_util.DEFAULT_SERVE_TIMEOUT_SECONDS)
        if not req.done.wait(timeout):
            if self._abandon(req):
                self._record_outcome("timeout")
                raise TimeoutError(
                    f"request {req.id} not completed within {timeout:g}s")
            # a replica completed it in the race window: the answer is
            # already counted 'ok' — deliver it, don't 504 it
        if req.error is not None:
            raise RuntimeError(
                f"request {req.id} failed on replica "
                f"{req.completed_by}: {req.error}")
        return req.output

    def submit_and_wait(self, inputs, timeout: Optional[float] = None):
        return self.wait(self.submit(inputs), timeout)

    def _abandon(self, req: Request) -> bool:
        """The submitter gave up (wait timeout): withdraw the request
        so replicas don't burn capacity answering it — under sustained
        overload, serving abandoned requests keeps fresh ones timing
        out long after offered load drops.  If a replica is already
        computing it, its late completion lands as a counted duplicate
        (never a second 'ok' on top of the recorded timeout).  False
        when the request completed in the race window — the caller
        should deliver that answer, not discard it."""
        with self._cond:
            if req.complete_time is not None:
                return False
            req.complete_time = time.monotonic()
            req.error = "abandoned after wait timeout"
            self.abandoned += 1
            found = False
            for table in self._inflight.values():
                if table.pop(req.id, None) is not None:
                    found = True
            if not found:
                try:
                    self._pending.remove(req)
                except ValueError:
                    pass
            self._by_id.pop(req.id, None)
            self._set_depth_gauge()
            self._cond.notify_all()
        req.done.set()
        return True

    # -- replica side --------------------------------------------------------
    def pull(self, replica_id: str, max_n: int = 1,
             wait_s: float = 0.0) -> List[Request]:
        """Hand up to ``max_n`` pending requests to ``replica_id``,
        blocking up to ``wait_s`` for the first one.  A draining
        replica always gets ``[]`` — that is the stop-pulling half of
        the drain handshake."""
        deadline = time.monotonic() + max(wait_s, 0.0)
        with self._cond:
            while True:
                if replica_id in self._draining:
                    return []
                if self._pending:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cond.wait(remaining)
            now = time.monotonic()
            batch: List[Request] = []
            table = self._inflight.setdefault(replica_id, {})
            while self._pending and len(batch) < max_n:
                req = self._pending.popleft()
                req.pull_time = now
                req.pulled_by = replica_id
                table[req.id] = req
                batch.append(req)
            self._set_depth_gauge()
        self._record_queue_wait(batch, now)
        return batch

    def complete(self, req_or_id, output, replica_id: str) -> bool:
        """Resolve one request exactly once; True iff this call was
        the resolving one.  A duplicate completion (e.g. a requeued
        request answered by both the dead replica's last gasp and its
        successor) is counted and dropped — the submitter only ever
        sees the first answer."""
        return self._finish(req_or_id, replica_id, output=output)

    def fail(self, req_or_id, error: str, replica_id: str) -> bool:
        """Resolve one request with an error (the submitter's wait
        raises); True iff this call was the resolving one."""
        return self._finish(req_or_id, replica_id, error=str(error))

    def _finish(self, req_or_id, replica_id: str, output=None,
                error: Optional[str] = None) -> bool:
        """Resolve a request exactly once; True iff THIS call resolved
        it (duplicates return False whether the result was an output or
        an error)."""
        with self._cond:
            req = req_or_id if isinstance(req_or_id, Request) \
                else self._by_id.get(req_or_id)
            if req is None or req.complete_time is not None:
                self.duplicates += 1
                return False
            req.complete_time = time.monotonic()
            req.output = output
            req.error = error
            req.completed_by = replica_id
            # evict the request from wherever it lives now: usually the
            # completer's own in-flight table, but a requeue may have
            # moved it back to the queue (late completion by the
            # original puller) or into a successor's table
            if self._inflight.get(replica_id, {}).pop(req.id,
                                                      None) is None:
                for table in self._inflight.values():
                    table.pop(req.id, None)
                try:
                    self._pending.remove(req)
                except ValueError:
                    pass
            self._by_id.pop(req.id, None)
            if error is None:
                self.completed += 1
                lat = req.latency_s()
                self._window.append((req.complete_time, lat))
                self._trim_window(req.complete_time)
                self._record_latency(lat)
                self._record_outcome("ok")
            else:
                self.failed += 1
                self._record_outcome("error")
            self._set_depth_gauge()
            self._cond.notify_all()
        req.done.set()
        return True

    # -- drain / failure handling --------------------------------------------
    def drain_begin(self, replica_id: str) -> None:
        """Stop handing work to ``replica_id``; its in-flight requests
        stay with it (a drain finishes them, docs/inference.md)."""
        with self._cond:
            self._draining.add(replica_id)
            self._cond.notify_all()

    def drain_end(self, replica_id: str) -> None:
        with self._cond:
            self._draining.discard(replica_id)

    def wait_drained(self, replica_id: str, timeout: float) -> bool:
        """Block until ``replica_id`` has no in-flight requests (True)
        or ``timeout`` passes (False) — the finish-in-flight half of
        the drain handshake."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._inflight.get(replica_id):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    def inflight_count(self, replica_id: Optional[str] = None) -> int:
        with self._cond:
            if replica_id is not None:
                return len(self._inflight.get(replica_id, {}))
            return sum(len(t) for t in self._inflight.values())

    def requeue(self, replica_id: str) -> int:
        """A replica died uncleanly: push its pulled-but-incomplete
        requests back to the FRONT of the queue in submission order so
        a successor answers them — a crash costs latency, never
        answers."""
        with self._cond:
            table = self._inflight.pop(replica_id, {})
            self._draining.discard(replica_id)
            stranded = sorted(table.values(), key=lambda r: r.id)
            for req in reversed(stranded):
                req.pull_time = None
                self._pending.appendleft(req)
            n = len(stranded)
            self.requeued += n
            self._set_depth_gauge()
            if n:
                self._cond.notify_all()
        if n:
            self._record_requeues(n)
            log.warning("replica %s died with %d in-flight request(s); "
                        "requeued", replica_id, n)
        return n

    # -- signals -------------------------------------------------------------
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._pending)

    def window_stats(self, now: Optional[float] = None) -> dict:
        """The autoscaler's view: queue depth, in-flight totals, and
        windowed p50/p99/mean latency (ms) over the last
        ``window_s`` seconds of completions."""
        now = time.monotonic() if now is None else now
        with self._cond:
            self._trim_window(now)
            lats = [lat for _, lat in self._window]
            stats = {
                "queue_depth": len(self._pending),
                "inflight": sum(len(t) for t in self._inflight.values()),
                "draining": sorted(self._draining),
                "window_completions": len(lats),
                "submitted": self.submitted,
                "completed": self.completed,
                "failed": self.failed,
                "rejected": self.rejected,
                "duplicates": self.duplicates,
                "requeued": self.requeued,
                "abandoned": self.abandoned,
            }
        for name, q in (("p50_ms", 50.0), ("p99_ms", 99.0)):
            v = percentile(lats, q)
            stats[name] = round(v * 1000.0, 3) if v is not None else None
        stats["mean_ms"] = round(sum(lats) / len(lats) * 1000.0, 3) \
            if lats else None
        return stats

    def _trim_window(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._window and self._window[0][0] < cutoff:
            self._window.popleft()

    # -- metrics plumbing (never raises into the data path) ------------------
    def _set_depth_gauge(self) -> None:
        try:
            from .. import metrics

            if metrics.on():
                metrics.SERVE_QUEUE_DEPTH.set(len(self._pending))
        except Exception:  # noqa: BLE001
            pass

    def _record_outcome(self, outcome: str) -> None:
        try:
            from .. import metrics

            if metrics.on():
                metrics.SERVE_REQUESTS.labels(outcome).inc()
        except Exception:  # noqa: BLE001
            pass

    def _record_latency(self, latency_s: float) -> None:
        try:
            from .. import metrics

            if metrics.on():
                metrics.SERVE_LATENCY.observe(latency_s)
        except Exception:  # noqa: BLE001
            pass

    def _record_queue_wait(self, batch: List[Request], now: float) -> None:
        try:
            from .. import metrics

            if metrics.on():
                for req in batch:
                    metrics.SERVE_QUEUE_WAIT.observe(now - req.submit_time)
        except Exception:  # noqa: BLE001
            pass

    def _record_requeues(self, n: int) -> None:
        try:
            from .. import metrics

            if metrics.on():
                metrics.SERVE_REQUEUES.inc(n)
        except Exception:  # noqa: BLE001
            pass
