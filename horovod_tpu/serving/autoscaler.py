"""Traffic-driven autoscaling policy + the elastic-driver binding.

The serving plane reuses PR 5's versioned-epoch membership machinery to
scale with *load* instead of failures (docs/inference.md "Autoscaling"):

* :class:`AutoscalePolicy` is the pure decision function the tests pin:
  **grow** when queue depth per replica stays above
  ``HVD_SERVE_QUEUE_HIGH`` — or windowed p99 stays above
  ``HVD_SERVE_SLO_MS`` — for ``HVD_SERVE_HYSTERESIS_TICKS``
  consecutive ticks; **shrink** when depth per replica stays at or
  below ``HVD_SERVE_QUEUE_LOW`` with p99 inside the SLO for the same
  run of ticks.  A ``HVD_SERVE_COOLDOWN_SECONDS`` refractory period
  after every action plus the two independent tick counters is the
  hysteresis that keeps the world from flapping.
* :class:`ServingAutoscaler` binds the policy to a live
  :class:`~horovod_tpu.elastic.driver.ElasticDriver` and
  :class:`~horovod_tpu.serving.broker.RequestBroker`: the driver calls
  :meth:`tick` from its supervision poll (stable epochs only), and a
  decision becomes a membership epoch — grow admits a held spare
  (``driver.admit_spare``), shrink runs the lossless drain handshake
  (``driver.remove(..., drain=True)``) so no in-flight request is
  dropped across the transition.
* The digital twin's serving hook
  (:func:`~horovod_tpu.timeline.replay.projection.serving_slo_headroom`,
  docs/projection.md) prices a capacity change BEFORE it is taken: a
  shrink whose projected p99 at one fewer replica would breach the SLO
  is held (the predictive guard, ``HVD_PROJECT_SLO_GUARD=0`` disables),
  and the per-direction projected headroom is surfaced on
  ``GET /serving``.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)


class AutoscalePolicy:
    """Hysteresis-damped threshold policy; pure and clock-injectable."""

    def __init__(self, *, queue_high: Optional[float] = None,
                 queue_low: Optional[float] = None,
                 slo_ms: Optional[float] = None,
                 hysteresis_ticks: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.queue_high = float(
            queue_high if queue_high is not None
            else env_util.get_float(env_util.HVD_SERVE_QUEUE_HIGH,
                                    env_util.DEFAULT_SERVE_QUEUE_HIGH))
        self.queue_low = float(
            queue_low if queue_low is not None
            else env_util.get_float(env_util.HVD_SERVE_QUEUE_LOW,
                                    env_util.DEFAULT_SERVE_QUEUE_LOW))
        self.slo_ms = float(
            slo_ms if slo_ms is not None
            else env_util.get_float(env_util.HVD_SERVE_SLO_MS,
                                    env_util.DEFAULT_SERVE_SLO_MS))
        self.hysteresis_ticks = int(
            hysteresis_ticks if hysteresis_ticks is not None
            else env_util.get_int(env_util.HVD_SERVE_HYSTERESIS_TICKS,
                                  env_util.DEFAULT_SERVE_HYSTERESIS_TICKS))
        self.cooldown_s = float(
            cooldown_s if cooldown_s is not None
            else env_util.get_float(env_util.HVD_SERVE_COOLDOWN_SECONDS,
                                    env_util.DEFAULT_SERVE_COOLDOWN_SECONDS))
        self.min_replicas = int(
            min_replicas if min_replicas is not None
            else env_util.get_int(env_util.HVD_SERVE_MIN_REPLICAS,
                                  env_util.DEFAULT_SERVE_MIN_REPLICAS))
        self.max_replicas = int(
            max_replicas if max_replicas is not None
            else env_util.get_int(env_util.HVD_SERVE_MAX_REPLICAS, 0))
        self.clock = clock
        self._over_ticks = 0
        self._idle_ticks = 0
        self._last_action_t: Optional[float] = None

    def reset(self) -> None:
        self._over_ticks = 0
        self._idle_ticks = 0
        self._last_action_t = None

    def cancel_last_action(self) -> None:
        """A decision this policy issued could not actually be executed
        (e.g. every held spare turned out blocklisted): lift the
        cooldown it started, so real capacity changes aren't delayed by
        a no-op."""
        self._last_action_t = None

    def in_cooldown(self) -> bool:
        return (self._last_action_t is not None
                and self.clock() - self._last_action_t < self.cooldown_s)

    def decide(self, *, queue_depth: int, p99_ms: Optional[float],
               replicas: int, spares: int = 0) -> str:
        """One tick: returns ``"grow"``, ``"shrink"``, or ``"hold"``.

        Tick counters advance even inside the cooldown (so a breach
        that SPANS the cooldown acts immediately after it), but no
        action fires until the cooldown elapses."""
        replicas = max(int(replicas), 1)
        per_replica = queue_depth / replicas
        slo_breach = p99_ms is not None and p99_ms > self.slo_ms
        overloaded = per_replica > self.queue_high or slo_breach
        idle = (per_replica <= self.queue_low
                and (p99_ms is None or p99_ms <= self.slo_ms))
        # the two counters are exclusive: a tick feeds one and zeroes
        # the other, so one noisy sample restarts the opposing run
        if overloaded:
            self._over_ticks += 1
            self._idle_ticks = 0
        elif idle:
            self._idle_ticks += 1
            self._over_ticks = 0
        else:
            self._over_ticks = 0
            self._idle_ticks = 0
        if self.in_cooldown():
            return "hold"
        if self._over_ticks >= self.hysteresis_ticks:
            can_grow = spares > 0 and (
                self.max_replicas <= 0 or replicas < self.max_replicas)
            if can_grow:
                self._last_action_t = self.clock()
                self._over_ticks = 0
                return "grow"
            return "hold"
        if self._idle_ticks >= self.hysteresis_ticks \
                and replicas > self.min_replicas:
            self._last_action_t = self.clock()
            self._idle_ticks = 0
            return "shrink"
        return "hold"


class ServingAutoscaler:
    """Driver-attached autoscaler: ticks read the broker, decisions
    commit membership epochs.

    ``pick_victim(driver) -> worker_id`` chooses the scale-down target;
    the default drains the most recently admitted non-initial worker
    (LIFO — scale back down to the core fleet first), falling back to
    the highest-ranked worker, and never rank 0."""

    def __init__(self, driver, broker, policy: Optional[AutoscalePolicy]
                 = None, *, pick_victim: Optional[Callable] = None,
                 headroom_fn: Optional[Callable] = None) -> None:
        self.driver = driver
        self.broker = broker
        self.policy = policy or AutoscalePolicy()
        self.pick_victim = pick_victim or self._default_victim
        # SLO-headroom hook (the digital twin's serving projection,
        # utils/slo.py — dependency-free math, no replay-stack import
        # on the serving path): projected slo − p99 after a replica
        # delta; injectable for tests
        if headroom_fn is None:
            from ..utils.slo import serving_slo_headroom

            headroom_fn = serving_slo_headroom
        self.headroom_fn = headroom_fn
        self.slo_guard = env_util.get_bool(
            env_util.HVD_PROJECT_SLO_GUARD, True)
        self._last_headroom: dict = {}
        self.events = []  # (direction, worker, epoch) history

    @staticmethod
    def _default_victim(driver) -> Optional[str]:
        candidates = [w for w in driver.world[1:]
                      if w not in driver.finished]
        if not candidates:
            return None
        external = [w for w in candidates if w not in driver.initial]
        return (external or candidates)[-1]

    def tick(self) -> str:
        """One autoscale evaluation (called by ``ElasticDriver.poll``
        on stable epochs).  Returns the decision taken."""
        stats = self.broker.window_stats()
        self._export_gauges(stats)
        replicas = len(self.driver.world)
        self._last_headroom = self._headroom(stats, replicas)
        decision = self.policy.decide(
            queue_depth=stats["queue_depth"], p99_ms=stats["p99_ms"],
            replicas=replicas, spares=len(self.driver.spares))
        if decision == "shrink" and self.slo_guard:
            # predictive guard: don't take a shrink the twin already
            # prices as an SLO breach — the hysteresis counters would
            # only discover it after real requests paid for it
            headroom = self._last_headroom.get("shrink_ms")
            if headroom is not None and headroom < 0:
                log.warning(
                    "autoscale shrink held: projected p99 at %d replicas "
                    "breaches the %.1f ms SLO by %.1f ms "
                    "(HVD_PROJECT_SLO_GUARD=0 disables)",
                    replicas - 1, self.policy.slo_ms, -headroom)
                self.policy.cancel_last_action()
                return "hold"
        if decision == "grow":
            worker = self.driver.admit_spare(
                reason=f"autoscale grow: queue_depth="
                       f"{stats['queue_depth']} p99_ms={stats['p99_ms']}")
            if worker is None:
                # every held spare was unusable (blocklisted/already in
                # world): nothing changed, so no cooldown either
                self.policy.cancel_last_action()
                return "hold"
            self._record_event("grow", worker, stats)
        elif decision == "shrink":
            worker = self.pick_victim(self.driver)
            if worker is None:
                self.policy.cancel_last_action()
                return "hold"
            ok = self.driver.remove(
                worker,
                f"autoscale shrink: queue_depth={stats['queue_depth']} "
                f"p99_ms={stats['p99_ms']}", drain=True)
            if not ok:
                # min_np would be violated — not an error, just a floor
                self.driver.failed_reason = None
                self.policy.cancel_last_action()
                return "hold"
            self._record_event("shrink", worker, stats)
        return decision

    def _headroom(self, stats: dict, replicas: int) -> dict:
        """Projected SLO headroom (ms) per replica delta — None entries
        when the window carries no latency data or the hook fails (the
        twin must never take down the autoscaler)."""
        out = {}
        for key, delta in (("grow_ms", 1), ("shrink_ms", -1)):
            try:
                out[key] = self.headroom_fn(stats, replicas,
                                            self.policy.slo_ms, delta)
            except Exception:  # noqa: BLE001
                out[key] = None
        return out

    def _record_event(self, direction: str, worker: str,
                      stats: Optional[dict] = None) -> None:
        self.events.append((direction, worker, self.driver.epoch))
        log.warning("autoscale %s: worker %s (epoch %d)", direction,
                    worker, self.driver.epoch)
        try:
            from .. import metrics

            if metrics.on():
                metrics.SERVE_AUTOSCALE_EVENTS.labels(direction).inc()
        except Exception:  # noqa: BLE001
            pass
        try:
            from ..observe import events as events_mod

            events_mod.record_event(
                f"autoscale.{direction}", severity="info",
                payload={
                    "worker": worker,
                    "epoch": self.driver.epoch,
                    "replicas": len(self.driver.world),
                    "queue_depth": (stats or {}).get("queue_depth"),
                    "p99_ms": (stats or {}).get("p99_ms"),
                    "slo_headroom_ms": dict(self._last_headroom),
                })
        except Exception:  # noqa: BLE001 — recording is best-effort
            pass

    def _export_gauges(self, stats: dict) -> None:
        try:
            from .. import metrics

            if metrics.on():
                if stats.get("p99_ms") is not None:
                    metrics.SERVE_P99_MS.set(stats["p99_ms"])
                    from ..metrics import timeseries

                    if timeseries.on():
                        timeseries.record(timeseries.SERVE_P99_MS_SERIES,
                                          stats["p99_ms"])
                metrics.SERVE_REPLICAS.set(len(self.driver.world))
        except Exception:  # noqa: BLE001
            pass

    def snapshot(self) -> dict:
        """State for ``GET /serving``."""
        p = self.policy
        return {
            "replicas": len(self.driver.world),
            "world": list(self.driver.world),
            "spares": list(self.driver.spares),
            "epoch": self.driver.epoch,
            "events": [{"direction": d, "worker": w, "epoch": e}
                       for d, w, e in self.events[-20:]],
            "policy": {
                "queue_high": p.queue_high, "queue_low": p.queue_low,
                "slo_ms": p.slo_ms,
                "hysteresis_ticks": p.hysteresis_ticks,
                "cooldown_s": p.cooldown_s,
                "min_replicas": p.min_replicas,
                "max_replicas": p.max_replicas,
            },
            "in_cooldown": p.in_cooldown(),
            # projected slo − p99 per replica delta (docs/projection.md):
            # what the last tick's window said a grow/shrink would buy
            "slo_headroom_ms": dict(self._last_headroom),
            "slo_guard": self.slo_guard,
        }
