"""Inference replica worker: checkpoint → jitted batched forward → pull
loop.

One replica = one worker in the serving world (docs/inference.md).  It
loads trained parameters (``utils/checkpoint`` layout, optionally
compressed at rest with PR 7's int8/fp8 quantizers for serving
density), jits the batched forward once per padded bucket size
(serving/batching.py bounds the bucket ladder, so compiles are
bounded), and pulls work from the shared request broker — in process,
or over the rendezvous server's ``POST /serving/pull`` route when the
replica runs on another host (:class:`RemoteSource`).

Draining (the lossless scale-down handshake): :meth:`drain` stops the
pull loop from receiving new work, finishes everything in flight, and
returns — the elastic driver commits the shrink epoch only after the
ack (elastic/driver.py ``remove(drain=True)``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional, Sequence, Tuple

import numpy as np

from ..utils import env as env_util
from ..utils.logging import get_logger
from .batching import BatchBucketer, ContinuousBatcher, bucket_sizes_from_env

log = get_logger(__name__)


# -- weight compression at rest ----------------------------------------------
def compress_params(params: Any, wire: str = "int8") -> Tuple[Any, dict]:
    """Quantize every float leaf of ``params`` with the wire-format
    quantizers from ops/compression.py (per-tensor scale, group size 1
    — no summation headroom needed: weights are stored, not reduced).
    Returns ``(compressed_tree, info)`` where each compressed leaf is a
    ``(q, dequant_factor)`` pair; ``info`` carries the byte ratio the
    serving-density story is about."""
    import jax

    from ..ops.compression import numpy_quantize

    orig_bytes = 0
    comp_bytes = 0

    def _one(leaf):
        nonlocal orig_bytes, comp_bytes
        arr = np.asarray(leaf)
        orig_bytes += arr.nbytes
        if not np.issubdtype(arr.dtype, np.floating):
            comp_bytes += arr.nbytes
            return leaf
        q, factor = numpy_quantize(arr, group_size=1, wire=wire)
        comp_bytes += q.nbytes
        return (q, float(factor))

    tree = jax.tree_util.tree_map(_one, params)
    info = {"wire": wire, "orig_bytes": orig_bytes,
            "compressed_bytes": comp_bytes,
            "ratio": round(orig_bytes / comp_bytes, 3) if comp_bytes
            else None}
    return tree, info


def decompress_params(tree: Any, dtype=np.float32) -> Any:
    """Materialize a :func:`compress_params` tree back to float arrays
    (done once at replica start — weights are compressed at rest, not
    per batch)."""
    import jax

    from ..ops.compression import numpy_dequantize

    def _one(leaf):
        if isinstance(leaf, tuple) and len(leaf) == 2 \
                and isinstance(leaf[1], float):
            return numpy_dequantize(np.asarray(leaf[0]),
                                    leaf[1]).astype(dtype)
        return leaf

    return jax.tree_util.tree_map(_one, tree, is_leaf=lambda x:
                                  isinstance(x, tuple))


def load_params(checkpoint_path: str, like: Any,
                step: Optional[int] = None) -> Any:
    """Restore a trained parameter pytree for serving — the
    ``utils/checkpoint`` layout (``step_N`` dirs + COMMITTED sentinels)
    without the training-time broadcast: a serving replica is a
    standalone process, not a rank in a training world."""
    from ..utils.checkpoint import restore_checkpoint

    return restore_checkpoint(checkpoint_path, like, step=step,
                              broadcast=False)


class InferenceReplica:
    """One pull→batch→forward→complete worker.

    ``apply_fn(params, batch) -> outputs`` is the model's batched
    forward (a flax ``model.apply``-shaped callable).  ``source`` is
    anything broker-shaped (``pull``/``complete``/``fail`` keyed by
    this replica's id) — the in-process broker or a
    :class:`RemoteSource`.  ``jit=False`` runs the forward as plain
    python (tests use it to script service times)."""

    def __init__(self, source, apply_fn: Callable, params: Any, *,
                 replica_id: str, max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 bucket_sizes: Optional[Sequence[int]] = None,
                 weight_compression: Optional[str] = None,
                 jit: bool = True) -> None:
        self.source = source
        self.apply_fn = apply_fn
        self.replica_id = str(replica_id)
        self.jit = jit
        self.compression_info: Optional[dict] = None
        wc = weight_compression if weight_compression is not None \
            else env_util.get_str(env_util.HVD_SERVE_WEIGHT_COMPRESSION)
        if wc and wc != "none":
            # compressed at rest for density; materialized once here
            compressed, self.compression_info = compress_params(params, wc)
            params = decompress_params(compressed)
        self.params = params
        max_batch = int(
            max_batch if max_batch is not None
            else env_util.get_int(env_util.HVD_SERVE_MAX_BATCH,
                                  env_util.DEFAULT_SERVE_MAX_BATCH))
        self.bucketer = BatchBucketer(
            bucket_sizes if bucket_sizes is not None
            else bucket_sizes_from_env(max_batch))
        top = self.bucketer.sizes[-1]
        if max_batch > top:
            # a batch larger than the top rung has no padded shape to
            # land in — admitting one would fail wholesale
            log.warning("HVD_SERVE_MAX_BATCH %d exceeds the bucket "
                        "ladder top %d; capping the batcher", max_batch,
                        top)
            max_batch = top
        self.batcher = ContinuousBatcher(
            lambda n, wait_s: source.pull(self.replica_id, n, wait_s),
            max_batch=max_batch, max_wait_ms=max_wait_ms)
        self._jitted: Optional[Callable] = None
        self._buckets_seen: set = set()
        self._thread: Optional[threading.Thread] = None
        self._stop_flag = threading.Event()
        self.requests = 0
        self.batches = 0

    # -- forward -------------------------------------------------------------
    def _forward(self, bucket: int) -> Callable:
        """One jitted callable for every bucket (jax.jit specializes
        per input shape under the hood); ``bucket`` is recorded so
        :attr:`recompiles` reports how many distinct padded shapes —
        i.e. XLA programs — this replica has hit."""
        self._buckets_seen.add(int(bucket))
        fn = self._jitted
        if fn is None:
            if self.jit:
                import jax

                fn = jax.jit(self.apply_fn)
            else:
                fn = self.apply_fn
            self._jitted = fn
        return fn

    @property
    def recompiles(self) -> int:
        """Distinct padded batch shapes executed (one XLA program
        each) — bounded by the bucket ladder."""
        return len(self._buckets_seen)

    def warmup(self, sample) -> None:
        """Compile every bucket size up front with ``sample`` (one
        request's input) so the first real request on each padded shape
        doesn't pay an XLA compile."""
        import numpy as np

        sample = np.asarray(sample)
        for b in self.bucketer.sizes:
            np.asarray(self._forward(b)(self.params,
                                        np.stack([sample] * b)))

    def process(self, batch) -> None:
        """Run one pulled batch: stack, pad to the bucket, forward,
        complete each request with its row.  Per-request failures fail
        that request, not the replica."""
        try:
            stacked = np.stack([np.asarray(r.inputs) for r in batch])
            padded, n = self.bucketer.pad(stacked)
            out = self._forward(padded.shape[0])(self.params, padded)
            out = np.asarray(out)
        except Exception as e:  # noqa: BLE001 — a poison batch must
            for req in batch:   # not kill the replica loop
                try:
                    self.source.fail(req, f"{type(e).__name__}: {e}",
                                     self.replica_id)
                except Exception:  # noqa: BLE001
                    log.warning("could not deliver failure for "
                                "request %s", req.id)
            return
        for i, req in enumerate(batch):
            # per-request delivery: one failed result post (past its
            # retry budget) must not strand the REST of a computed
            # batch in the broker's in-flight table
            try:
                self.source.complete(req, out[i], self.replica_id)
            except Exception as e:  # noqa: BLE001
                try:
                    self.source.fail(
                        req, f"result delivery failed: {e}",
                        self.replica_id)
                except Exception:  # noqa: BLE001
                    log.warning("stranded request %s: result "
                                "delivery failed twice (%s)", req.id, e)
        self.requests += len(batch)
        self.batches += 1

    # -- the loop ------------------------------------------------------------
    def start(self) -> "InferenceReplica":
        self._stop_flag.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"hvd-serve-replica-{self.replica_id}")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop_flag.is_set():
            try:
                batch = self.batcher.next_batch(idle_wait_s=0.05)
                if batch:
                    self.process(batch)
            except Exception:  # noqa: BLE001 — a transient source
                # error (e.g. one refused RemoteSource HTTP pull) must
                # not kill the replica thread while its worker is still
                # in the committed world
                log.exception("replica %s pull loop error; retrying",
                              self.replica_id)
                self._stop_flag.wait(0.2)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Lossless stop: no new pulls, finish in flight, join the
        loop.  Returns True when everything completed in time."""
        if timeout is None:
            timeout = env_util.get_float(
                env_util.HVD_SERVE_DRAIN_TIMEOUT_SECONDS,
                env_util.get_float(env_util.HVD_ELASTIC_TIMEOUT_SECONDS,
                                   env_util.DEFAULT_ELASTIC_TIMEOUT_SECONDS))
        drain_begin = getattr(self.source, "drain_begin", None)
        if drain_begin is not None:
            drain_begin(self.replica_id)
        drained = True
        wait_drained = getattr(self.source, "wait_drained", None)
        if wait_drained is not None:
            drained = wait_drained(self.replica_id, timeout)
        # the loop thread joining means the current batch ran to
        # completion — for sources with no wait_drained (RemoteSource:
        # the in-flight table lives launcher-side) this is the only
        # local evidence the drain actually finished; a slow batch
        # outliving the timeout must NOT read as drained
        joined = self.stop(join_timeout=timeout)
        return drained and joined

    def stop(self, join_timeout: float = 5.0) -> bool:
        """Stop the loop; True iff it joined inside ``join_timeout``
        (False means a batch is still executing)."""
        self._stop_flag.set()
        joined = True
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)
            joined = not self._thread.is_alive()
            if not joined:
                log.warning("replica %s loop did not stop within %.1fs",
                            self.replica_id, join_timeout)
            self._thread = None
        return joined

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()


class RemoteSource:
    """Broker-shaped adapter for replicas on other hosts: ``pull`` and
    ``complete``/``fail`` ride the rendezvous server's signed
    ``POST /serving/pull`` / ``POST /serving/result`` routes
    (run/http_client.py), so a remote replica worker runs the exact
    same :class:`InferenceReplica` loop as an in-process one."""

    class _Req:
        __slots__ = ("id", "inputs")

        def __init__(self, req_id: int, inputs) -> None:
            self.id = req_id
            self.inputs = inputs

    def __init__(self, addr: str, port: int,
                 secret: Optional[bytes] = None) -> None:
        self.addr = addr
        self.port = port
        self.secret = secret

    @classmethod
    def from_env(cls) -> "RemoteSource":
        """Wire from the launcher-exported rendezvous env
        (HVD_METRICS_KV_ADDR/PORT/SECRET) — what ``hvd_serve --worker``
        under ``tpurun --serve`` uses."""
        addr = env_util.get_str(env_util.HVD_METRICS_KV_ADDR)
        port = env_util.get_int(env_util.HVD_METRICS_KV_PORT, 0)
        if not addr or not port:
            raise RuntimeError(
                "RemoteSource needs the rendezvous wiring "
                "(HVD_METRICS_KV_ADDR/PORT); run under tpurun --serve "
                "or pass addr/port explicitly")
        secret_hex = env_util.get_str(env_util.HVD_METRICS_SECRET)
        return cls(addr, port,
                   bytes.fromhex(secret_hex) if secret_hex else None)

    def pull(self, replica_id: str, max_n: int, wait_s: float):
        from ..run.http_client import serve_pull

        out = serve_pull(self.addr, self.port, replica_id, max_n,
                         wait_ms=wait_s * 1000.0, secret=self.secret,
                         timeout=wait_s + 10.0)
        return [self._Req(r["id"], np.asarray(r["inputs"],
                                              dtype=np.float32))
                for r in out.get("requests", ())]

    def complete(self, req, output, replica_id: str) -> bool:
        from ..run.http_client import serve_result

        out = serve_result(self.addr, self.port, replica_id,
                           [{"id": req.id,
                             "output": np.asarray(output).tolist()}],
                           secret=self.secret)
        return bool(out.get("accepted"))

    def fail(self, req, error: str, replica_id: str) -> bool:
        from ..run.http_client import serve_result

        out = serve_result(self.addr, self.port, replica_id,
                           [{"id": req.id, "error": str(error)}],
                           secret=self.secret)
        return bool(out.get("accepted"))

    # drain for a remote replica is driven by the membership drain key
    # (elastic/membership.py drain_requested/ack_drain); the broker-side
    # drain_begin is issued by the driver's handshake, so the remote
    # source needs no local drain state.


def serve_worker_loop(apply_fn: Callable, params: Any, *,
                      replica_id: Optional[str] = None,
                      source=None, poll_s: float = 0.5,
                      stop_event: Optional[threading.Event] = None) -> None:
    """The ``hvd_serve --worker`` body: run an :class:`InferenceReplica`
    against the launcher's broker and honor the elastic drain
    handshake — on a ``drain.<worker>`` key, finish in flight, ack,
    and exit; on eviction from the committed world, exit."""
    from ..elastic import membership

    wid = replica_id if replica_id is not None else membership.worker_id()
    source = source if source is not None else RemoteSource.from_env()
    replica = InferenceReplica(source, apply_fn, params,
                               replica_id=str(wid)).start()
    try:
        while stop_event is None or not stop_event.is_set():
            time.sleep(poll_s)
            if membership.drain_requested() is not None:
                if replica.drain():
                    membership.ack_drain()
                else:
                    # work still in flight: an ack would record this as
                    # a lossless drain and skip the launcher-side
                    # requeue — let the driver's timeout take the
                    # lossy path instead
                    log.warning("drain timed out with work in flight; "
                                "exiting without ack")
                return
            rec = membership.current_record()
            try:
                rec = membership.get_epoch_record() or rec
            except Exception:  # noqa: BLE001 — keep serving through a
                pass            # rendezvous blip
            if rec is not None and str(wid) not in rec.get("world", ()):
                log.info("worker %s no longer in the committed world; "
                         "stopping replica", wid)
                return
    finally:
        replica.stop()
