"""In-process serving plane: broker + replicas + elastic driver +
autoscaler wired together.

This is the serving analog of the elastic runtime's in-process test
worlds: every moving part is real — a live
:class:`~horovod_tpu.run.http_server.RendezvousServer`, real membership
epochs committed by a real
:class:`~horovod_tpu.elastic.driver.ElasticDriver`, real replica
threads pulling from a real broker — but it all runs in one process,
which is what makes the grow/shrink/zero-drop story benchmarkable in
tier-1 (tests/test_serving.py), checkable from the CLI
(``hvd_serve --check``), and cheap to bench (``bench.py
--child-serve``).

The plane plays the WORKER side of the membership protocol for the
replicas it hosts: it acks committed epochs (the driver's stability
barrier), starts a replica when its worker is admitted into the world,
and answers the drain handshake (stop pulling → finish in flight →
``drain_ack``) when the driver scales one down.  Worker-side actions
run on their own thread so the driver's blocking drain wait can never
deadlock against them.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..utils.logging import get_logger
from .autoscaler import AutoscalePolicy, ServingAutoscaler
from .broker import RequestBroker
from .frontend import ServingFrontend
from .replica import InferenceReplica

log = get_logger(__name__)


class LocalServingPlane:
    """One-process serving world.

    Non-elastic (``elastic=False``): ``replicas`` workers serve a
    fixed fleet — no driver, no threads beyond the replica loops.

    Elastic (``elastic=True``): an :class:`ElasticDriver` owns the
    world (initial workers ``"0"..str(replicas-1)``), ``spare_workers``
    are announced and HELD for the autoscaler, and a policy-driven
    :class:`ServingAutoscaler` commits grow/shrink epochs from the
    broker's load signals.  ``pump_interval`` paces the driver poll.
    """

    def __init__(self, apply_fn: Callable, params: Any, *,
                 replicas: int = 1,
                 spare_workers: Sequence[str] = (),
                 elastic: bool = False,
                 rdv_server=None,
                 policy: Optional[AutoscalePolicy] = None,
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 bucket_sizes: Optional[Sequence[int]] = None,
                 jit: bool = True,
                 min_np: int = 1,
                 drain_timeout_s: float = 10.0,
                 pump_interval: float = 0.05) -> None:
        self.apply_fn = apply_fn
        self.params = params
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.bucket_sizes = bucket_sizes
        self.jit = jit
        self.drain_timeout_s = drain_timeout_s
        self.pump_interval = pump_interval
        self.broker = RequestBroker()
        self.replicas: Dict[str, InferenceReplica] = {}
        self.epochs_seen: Dict[int, List[str]] = {}
        self._acked: set = set()            # (epoch, worker)
        self._drained: Dict[str, int] = {}  # worker -> epoch at drain
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._owns_server = False
        self.server = rdv_server
        self.driver = None
        self.autoscaler = None
        initial = [str(i) for i in range(replicas)]
        self.hosted = set(initial) | {str(w) for w in spare_workers}
        if elastic:
            if self.server is None:
                from ..run.http_server import RendezvousServer

                self.server = RendezvousServer(secret=None)
                self.server.start()
                self._owns_server = True
            from ..elastic.driver import ElasticDriver

            self.driver = ElasticDriver(self.server, initial,
                                        min_np=min_np, controller="xla",
                                        drain_timeout=drain_timeout_s)
            self.driver.on_remove = (
                lambda w, drained:
                None if drained else self.broker.requeue(w))
            self.autoscaler = ServingAutoscaler(self.driver, self.broker,
                                                policy)
            self.driver.attach_autoscaler(self.autoscaler)
            for w in spare_workers:
                self.announce_spare(str(w))
        self.frontend = ServingFrontend(self.broker,
                                        autoscaler=self.autoscaler)
        if self.server is not None:
            self.server.attach_serving(self.frontend)
        for w in initial:
            self._start_replica(w)

    # -- replica lifecycle ---------------------------------------------------
    def _start_replica(self, worker: str) -> InferenceReplica:
        rep = InferenceReplica(
            self.broker, self.apply_fn, self.params, replica_id=worker,
            max_batch=self.max_batch, max_wait_ms=self.max_wait_ms,
            bucket_sizes=self.bucket_sizes, jit=self.jit)
        self.broker.drain_end(worker)  # re-admitted after an old drain
        self.replicas[worker] = rep.start()
        return rep

    # -- membership worker side ----------------------------------------------
    def announce_spare(self, worker: str) -> None:
        from ..run.http_server import ANNOUNCE_PREFIX, MEMBERSHIP_SCOPE

        self.hosted.add(worker)
        self.server.put(MEMBERSHIP_SCOPE, f"{ANNOUNCE_PREFIX}{worker}",
                        json.dumps({"worker": worker,
                                    "time": time.time()}).encode())

    def start(self) -> "LocalServingPlane":
        """Start the elastic supervision threads (no-op when not
        elastic): the driver pump and the worker-side watcher."""
        if self.driver is None:
            return self
        self._stop.clear()
        for name, fn in (("hvd-serve-pump", self._pump),
                         ("hvd-serve-watch", self._watch)):
            t = threading.Thread(target=fn, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        return self

    def _pump(self) -> None:
        while not self._stop.is_set():
            try:
                self.driver.poll()
            except Exception:  # noqa: BLE001 — supervision must survive
                log.exception("serving plane driver poll failed")
            self._stop.wait(self.pump_interval)

    def _watch(self) -> None:
        from ..run.http_server import (
            DRAIN_ACK_PREFIX,
            DRAIN_PREFIX,
            MEMBERSHIP_SCOPE,
            READY_PREFIX,
        )

        while not self._stop.is_set():
            try:
                items = self.server.scope_items(MEMBERSHIP_SCOPE)
                raw = items.get("epoch")
                rec = json.loads(raw) if raw is not None else None
                if rec is not None:
                    epoch = int(rec.get("epoch", 0))
                    world = [str(w) for w in rec.get("world", ())]
                    self.epochs_seen.setdefault(epoch, world)
                    for w in world:
                        if w not in self.hosted:
                            continue
                        if (epoch, w) not in self._acked:
                            self.server.put(
                                MEMBERSHIP_SCOPE,
                                f"{READY_PREFIX}{epoch}.{w}",
                                json.dumps({"worker": w}).encode())
                            self._acked.add((epoch, w))
                        if w in self._drained \
                                and epoch > self._drained[w]:
                            # a LATER epoch re-admitted this worker
                            # (the drain's shrink commit bumped the
                            # epoch past the marker) — the marker must
                            # not suppress its replica forever.  Same-
                            # epoch sightings are the pre-commit drain
                            # window, where restarting would resurrect
                            # a zombie replica.
                            del self._drained[w]
                        rep = self.replicas.get(w)
                        if (rep is None or not rep.running) \
                                and w not in self._drained:
                            if rep is not None:
                                # the thread died uncleanly: hand its
                                # in-flight work to the fresh replica
                                self.broker.requeue(w)
                            self._start_replica(w)
                epoch_now = int(rec.get("epoch", 0)) \
                    if rec is not None else 0
                for key in list(items):
                    # "drain_ack." keys don't match the "drain." prefix
                    if not key.startswith(DRAIN_PREFIX):
                        continue
                    w = key[len(DRAIN_PREFIX):]
                    rep = self.replicas.get(w)
                    if rep is None or w in self._drained:
                        continue
                    self._drained[w] = epoch_now
                    if rep.drain(self.drain_timeout_s):
                        self.server.put(
                            MEMBERSHIP_SCOPE, f"{DRAIN_ACK_PREFIX}{w}",
                            json.dumps({"worker": w,
                                        "time": time.time()}).encode())
                    else:
                        # acking a drain that left work in flight would
                        # make the driver record a lossless removal and
                        # skip the requeue; stay silent — the driver's
                        # timeout takes the lossy path, whose on_remove
                        # hook requeues — and hand the leftovers back
                        # ourselves right away
                        log.warning("drain of replica %s timed out "
                                    "with work in flight; not acking",
                                    w)
                        self.broker.requeue(w)
            except Exception:  # noqa: BLE001
                log.exception("serving plane watcher failed")
            self._stop.wait(self.pump_interval / 2.0)

    # -- request plane -------------------------------------------------------
    def submit_and_wait(self, inputs, timeout: Optional[float] = None):
        return self.broker.submit_and_wait(inputs, timeout)

    def status(self) -> dict:
        return self.frontend.report()

    def live_replicas(self) -> List[str]:
        return sorted(w for w, r in self.replicas.items() if r.running)

    def shutdown(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        for rep in self.replicas.values():
            rep.stop()
        if self.driver is not None:
            self.driver.shutdown()
        if self._owns_server and self.server is not None:
            self.server.stop()


# -- shared fixtures (CLI --check, bench leg, tests) -------------------------
#: THE bench workload — one definition so ``bench.py --child-serve``
#: and ``hvd_serve --bench`` can never silently measure different
#: traces (seeded, so both are reproducible)
BENCH_FIXTURE_KWARGS = dict(
    jit=True, replicas=2, warmup=True, seed=11, base_rps=40.0,
    burst_rps=200.0, pre_s=0.5, burst_s=0.5, post_s=0.3, slo_ms=100.0)


def run_bench_fixture() -> dict:
    """The canonical serving bench: :func:`run_serving_fixture` under
    :data:`BENCH_FIXTURE_KWARGS`."""
    return run_serving_fixture(**BENCH_FIXTURE_KWARGS)
def make_mlp_serving_fn(features=(64, 32, 10), in_dim: int = 32,
                        seed: int = 0):
    """A small flax MLP for serving fixtures: returns
    ``(apply_fn, params, sample_input)``."""
    import jax
    import numpy as np

    from ..models.mlp import MLP

    model = MLP(features=tuple(features))
    sample = np.zeros((1, in_dim), dtype=np.float32)
    variables = model.init(jax.random.PRNGKey(seed), sample)
    return model.apply, variables, sample[0]


def run_serving_fixture(*, jit: bool = False, replicas: int = 2,
                        seed: int = 7, base_rps: float = 50.0,
                        burst_rps: float = 250.0, pre_s: float = 0.4,
                        burst_s: float = 0.4, post_s: float = 0.2,
                        slo_ms: float = 250.0,
                        service_ms: float = 0.0,
                        warmup: bool = False) -> dict:
    """The deterministic serving fixture behind ``hvd_serve --check``
    and ``bench.py --child-serve``: a seeded bursty open-loop trace
    against a small MLP replica fleet, summarized as
    ``serve_p50_ms``/``serve_p99_ms``/``goodput_under_burst`` plus the
    broker's zero-drop accounting."""
    import numpy as np

    from .loadgen import OpenLoopLoadGenerator, bursty_arrivals

    apply_fn, params, sample = make_mlp_serving_fn(seed=seed)
    if service_ms > 0:
        inner = apply_fn

        def apply_fn(p, x, _inner=inner):  # scripted service time
            time.sleep(service_ms / 1000.0 * x.shape[0])
            return _inner(p, x)

    plane = LocalServingPlane(apply_fn, params, replicas=replicas,
                              jit=jit, max_batch=4, max_wait_ms=4.0)
    try:
        if warmup and jit:
            for rep in plane.replicas.values():
                rep.warmup(sample)
        arrivals, burst_windows = bursty_arrivals(
            base_rps, burst_rps, pre_s=pre_s, burst_s=burst_s,
            post_s=post_s, seed=seed)
        rng = np.random.RandomState(seed)
        inputs = rng.randn(max(len(arrivals), 1),
                           *sample.shape).astype(np.float32)
        gen = OpenLoopLoadGenerator(
            plane.submit_and_wait, arrivals, lambda i: inputs[i],
            slo_ms=slo_ms, timeout_s=30.0)
        summary = gen.run(burst_windows)
        stats = plane.broker.window_stats()
        return {
            "serve_p50_ms": summary["p50_ms"],
            "serve_p99_ms": summary["p99_ms"],
            "goodput_under_burst": summary.get("goodput_under_burst"),
            "goodput": summary["goodput"],
            "offered": summary["offered"],
            "completed": summary["completed"],
            "slo_ms": slo_ms,
            "replicas": replicas,
            "batches": sum(r.batcher.batches
                           for r in plane.replicas.values()),
            "broker": {k: stats[k] for k in
                       ("submitted", "completed", "failed", "rejected",
                        "duplicates", "requeued")},
        }
    finally:
        plane.shutdown()
