"""Synthetic open-loop load generation: seeded Poisson arrivals and
bursty traces, plus the latency/goodput summary every serving report
shares.

**Open loop** means arrivals are scheduled by the trace alone — a slow
server does not slow the offered load down (closed-loop generators
hide overload by self-throttling; an open loop exposes it as queue
growth and p99 blowup, which is exactly the signal the autoscaler
acts on).  Traces are deterministic under a seed, so tier-1 can pin
behaviour (tests/test_serving.py) and the bench leg
(``bench.py --child-serve``) is reproducible.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .broker import percentile


def poisson_arrivals(rate_rps: float, duration_s: float, seed: int,
                     start_s: float = 0.0) -> List[float]:
    """Arrival offsets (seconds) of a homogeneous Poisson process:
    exponential inter-arrival gaps at ``rate_rps``, seeded."""
    if rate_rps <= 0 or duration_s <= 0:
        return []
    rng = np.random.RandomState(seed)
    out: List[float] = []
    t = start_s
    end = start_s + duration_s
    while True:
        t += float(rng.exponential(1.0 / rate_rps))
        if t >= end:
            return out
        out.append(t)


def bursty_arrivals(base_rps: float, burst_rps: float, *,
                    pre_s: float, burst_s: float, post_s: float,
                    seed: int) -> Tuple[List[float],
                                        List[Tuple[float, float]]]:
    """A three-phase trace — steady ``base_rps``, a burst at
    ``burst_rps``, then a quiet tail at ``base_rps`` — as one sorted
    arrival list plus the burst window(s).  Each phase is an
    independent seeded Poisson segment, so the whole trace is
    deterministic under ``seed``."""
    arrivals = poisson_arrivals(base_rps, pre_s, seed, 0.0)
    burst_window = (pre_s, pre_s + burst_s)
    arrivals += poisson_arrivals(burst_rps, burst_s, seed + 1, pre_s)
    arrivals += poisson_arrivals(base_rps, post_s, seed + 2,
                                 pre_s + burst_s)
    return sorted(arrivals), [burst_window]


def summarize(records: Sequence[dict], slo_ms: float,
              burst_windows: Optional[Sequence[Tuple[float, float]]]
              = None) -> dict:
    """The serving summary: p50/p99/mean latency over completed
    requests, plus goodput = completed-within-SLO / offered — overall
    and (``goodput_under_burst``) restricted to requests that arrived
    inside a burst window, the number that shows whether the
    autoscaler actually absorbed the burst.

    ``records``: ``{"t": arrival_s, "latency_ms": float|None,
    "ok": bool}`` per offered request (``latency_ms`` None when the
    request timed out or was rejected)."""

    def _stats(recs):
        offered = len(recs)
        lats = [r["latency_ms"] for r in recs
                if r.get("ok") and r.get("latency_ms") is not None]
        good = sum(1 for r in recs
                   if r.get("ok") and r.get("latency_ms") is not None
                   and r["latency_ms"] <= slo_ms)
        return {
            "offered": offered,
            "completed": len(lats),
            "p50_ms": round(percentile(lats, 50.0), 3)
            if lats else None,
            "p99_ms": round(percentile(lats, 99.0), 3)
            if lats else None,
            "mean_ms": round(sum(lats) / len(lats), 3) if lats else None,
            "goodput": round(good / offered, 4) if offered else None,
        }

    out = _stats(list(records))
    out["slo_ms"] = slo_ms
    if burst_windows:
        in_burst = [r for r in records
                    if any(lo <= r["t"] < hi for lo, hi in burst_windows)]
        burst = _stats(in_burst)
        out["goodput_under_burst"] = burst["goodput"]
        out["burst_offered"] = burst["offered"]
        out["burst_p99_ms"] = burst["p99_ms"]
    return out


class OpenLoopLoadGenerator:
    """Fire a trace open-loop against a ``submit(inputs, timeout)``
    callable (broker ``submit_and_wait``, an HTTP ``post_infer``
    closure, ...), one thread per request so a stalled request never
    delays the next arrival.

    ``make_input(i)`` builds request ``i``'s payload (seed it for
    determinism).  ``time_scale`` compresses the trace clock (0.5 runs
    a 4 s trace in 2 s) without changing the trace itself."""

    def __init__(self, submit: Callable, arrivals: Sequence[float],
                 make_input: Callable[[int], object], *,
                 slo_ms: float, timeout_s: float = 30.0,
                 time_scale: float = 1.0) -> None:
        self.submit = submit
        self.arrivals = list(arrivals)
        self.make_input = make_input
        self.slo_ms = float(slo_ms)
        self.timeout_s = float(timeout_s)
        self.time_scale = float(time_scale)
        self.records: List[dict] = []
        self._lock = threading.Lock()

    def _fire(self, i: int, arrival: float) -> None:
        inputs = self.make_input(i)
        rec = {"t": arrival, "latency_ms": None, "ok": False,
               "rejected": False}
        t0 = time.monotonic()
        try:
            self.submit(inputs, self.timeout_s)
            rec["latency_ms"] = (time.monotonic() - t0) * 1000.0
            rec["ok"] = True
        except TimeoutError:
            pass
        except Exception as e:  # noqa: BLE001 — rejections and server
            rec["rejected"] = True  # errors are a recorded outcome,
            rec["error"] = f"{type(e).__name__}: {e}"  # not a crash
        with self._lock:
            self.records.append(rec)

    def run(self, burst_windows: Optional[Sequence[Tuple[float, float]]]
            = None) -> dict:
        """Play the whole trace, join every request, and return the
        :func:`summarize` report (records stay on ``self.records``)."""
        threads: List[threading.Thread] = []
        t0 = time.monotonic()
        for i, arrival in enumerate(self.arrivals):
            delay = arrival * self.time_scale - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=self._fire, args=(i, arrival),
                                  daemon=True,
                                  name=f"hvd-loadgen-{i}")
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=self.timeout_s + 5.0)
        with self._lock:
            records = list(self.records)
        return summarize(records, self.slo_ms, burst_windows)
