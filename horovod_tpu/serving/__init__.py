"""Traffic-driven autoscaling serving plane (docs/inference.md).

Turns a trained checkpoint into a horizontally-scalable inference
service on the machinery the training runtime already has: replicas
load weights through ``utils/checkpoint`` (optionally int8/fp8-
compressed at rest, ops/compression.py), a continuous-batching engine
bounds queueing delay and re-jits (``HVD_SERVE_MAX_BATCH`` /
``HVD_SERVE_MAX_WAIT_MS`` / padded-shape buckets), the rendezvous HTTP
server fronts the request plane (signed ``POST /infer``,
``GET /serving``), the metrics plane carries the SLO signals
(``hvd_serve_*``), and PR 5's versioned-epoch elastic membership
scales the fleet with *load* — queue depth and p99-vs-SLO headroom
commit grow/shrink epochs without relaunch and without dropping
in-flight requests (the drain handshake, elastic/driver.py).

Entry points: ``tpurun --serve``, ``scripts/hvd_serve.py``, and the
in-process :class:`~horovod_tpu.serving.plane.LocalServingPlane`.
"""

from .autoscaler import AutoscalePolicy, ServingAutoscaler  # noqa: F401
from .batching import (  # noqa: F401
    BatchBucketer,
    ContinuousBatcher,
    bucket_sizes_from_env,
)
from .broker import (  # noqa: F401
    QueueFullError,
    Request,
    RequestBroker,
    percentile,
)
from .frontend import ServingFrontend  # noqa: F401
from .loadgen import (  # noqa: F401
    OpenLoopLoadGenerator,
    bursty_arrivals,
    poisson_arrivals,
    summarize,
)
from .plane import (  # noqa: F401
    LocalServingPlane,
    make_mlp_serving_fn,
    run_bench_fixture,
    run_serving_fixture,
)
from .replica import (  # noqa: F401
    InferenceReplica,
    RemoteSource,
    compress_params,
    decompress_params,
    load_params,
    serve_worker_loop,
)
