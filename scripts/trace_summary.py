"""Trace analysis over per-rank communication timelines.

The fork's raison d'être is per-rank trace capture for dPRO-style replay
(reference timeline.cc per-rank ``<dir>/<local_rank>/comm.json``,
recorder.py DAG/shape dumps).  This is the first-pass analyzer those
traces feed: per-tensor negotiation vs execution time, per-op totals,
cross-rank skew — the numbers a comm-bottleneck hunt starts from.

Run:  python scripts/trace_summary.py <timeline_dir>
"""

from __future__ import annotations

import argparse
import collections
import json
import os


def load_rank_events(path: str):
    """comm.json may be live (no closing bracket) — parse leniently."""
    with open(path) as f:
        txt = f.read().strip()
    if txt.endswith(","):
        txt = txt[:-1]
    if not txt.endswith("]"):
        txt += "]"
    return json.loads(txt)


def summarize(timeline_dir: str) -> dict:
    ranks = {}
    for entry in sorted(os.listdir(timeline_dir)):
        f = os.path.join(timeline_dir, entry, "comm.json")
        if os.path.isfile(f):
            ranks[entry] = load_rank_events(f)
    if not ranks:
        raise FileNotFoundError(
            f"no <rank>/comm.json under {timeline_dir}"
        )

    per_rank = {}
    for rank, events in ranks.items():
        ops = collections.defaultdict(
            lambda: {"count": 0, "total_us": 0.0, "negotiate_us": 0.0}
        )
        open_spans = {}
        for ev in events:
            name, ph = ev.get("name", ""), ev.get("ph")
            key = (name, ev.get("tid"))
            if ph == "B":
                open_spans[key] = ev["ts"]
            elif ph == "E" and key in open_spans:
                dur = ev["ts"] - open_spans.pop(key)
                if name.startswith("NEGOTIATE_"):
                    op = name[len("NEGOTIATE_"):]
                    ops[op]["negotiate_us"] += dur
                    ops[op]["count"] += 1
            elif ph == "X":
                # per-rank readiness markers are digit-named micro events
                # inside NEGOTIATE (timeline.negotiate_rank_ready) — not ops
                if name.isdigit() or name == "CYCLE_START":
                    continue
                d = ops[name]
                d["total_us"] += ev.get("dur", 0.0)
                if not name.startswith("NEGOTIATE_"):
                    d["exec_count"] = d.get("exec_count", 0) + 1
        per_rank[rank] = {op: dict(v) for op, v in ops.items()}

    # cross-rank skew: same op's total time, max/min across ranks
    all_ops = sorted({op for r in per_rank.values() for op in r})
    skew = {}
    for op in all_ops:
        totals = [r.get(op, {}).get("total_us", 0.0)
                  for r in per_rank.values()]
        if any(totals):
            skew[op] = {
                "min_us": min(totals), "max_us": max(totals),
                "skew": (max(totals) / min(totals)
                         if min(totals) > 0 else None),
            }
    return {"ranks": per_rank, "cross_rank_skew": skew}


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("timeline_dir")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    args = parser.parse_args(argv)
    s = summarize(args.timeline_dir)
    if args.json:
        print(json.dumps(s, indent=2))
        return s
    for rank, ops in s["ranks"].items():
        print(f"rank {rank}:")
        for op, v in sorted(ops.items()):
            neg = v.get("negotiate_us", 0.0)
            tot = v.get("total_us", 0.0)
            n = v.get("exec_count", 0) or v.get("count", 0)
            overhead = f"  negotiate {neg:9.1f} us" if neg else ""
            print(f"  {op:<22} n={n:<4} exec {tot:10.1f} us{overhead}")
    if s["cross_rank_skew"]:
        print("cross-rank skew (exec total, max/min):")
        for op, v in s["cross_rank_skew"].items():
            sk = f"{v['skew']:.2f}x" if v["skew"] else "n/a"
            print(f"  {op:<22} {sk}")
    return s


if __name__ == "__main__":
    main()
