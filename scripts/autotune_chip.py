"""Autotuner convergence session on the real chip (+ mesh phase).

Drives the GP autotuner (optim/autotune.py over csrc/autotune.cc) on a
live ResNet-50 training loop until it freezes, then grid-searches the
fusion threshold with every grid point interleaved round-robin
(min-of-rounds — the shared chip drifts ~2x between windows) and checks
the converged knob lands within noise of the grid best.  The per-sample
scores stream to the CSV log exactly as the reference's
--autotune-log-file does (reference parameter_manager.cc LogParameters).

Phase B (run with --platform cpu under
XLA_FLAGS=--xla_force_host_platform_device_count=8) repeats on the
8-device mesh with ResNet-18, where the hierarchical flag changes the
compiled program (the 1-chip phase can only tune the threshold knob —
its collectives collapse on a single device).

Writes scripts/out/autotune_chip.json (or autotune_mesh.json for
--platform cpu) + the CSV log at scripts/out/autotune_{chip,mesh}_log.csv.

Usage:  python scripts/autotune_chip.py                 # real chip
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
          python scripts/autotune_chip.py --platform cpu  # mesh phase
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "out")


def _timed_call(step, state, x, y):
    import jax
    import numpy as np

    t0 = time.perf_counter()
    state, loss = step(state, x, y)
    np.asarray(jax.device_get(loss))
    return state, time.perf_counter() - t0


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--platform", default=None,
                        help="None = real chip; cpu = 8-device mesh phase")
    parser.add_argument("--batch-size", type=int, default=None)
    parser.add_argument("--max-samples", type=int, default=12)
    parser.add_argument("--steps-per-sample", type=int, default=5)
    parser.add_argument("--rounds", type=int, default=3)
    args = parser.parse_args(argv)

    os.environ["HVD_AUTOTUNE_STEPS_PER_SAMPLE"] = str(args.steps_per_sample)
    os.environ["HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"] = str(args.max_samples)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models import MODELS
    from horovod_tpu.training import (
        init_train_state, make_train_step, shard_batch,
    )

    hvd.init(platform=args.platform)
    on_chip = jax.devices()[0].platform != "cpu"
    tag = "chip" if on_chip else "mesh"
    model_name = "ResNet50" if on_chip else "ResNet18"
    batch = args.batch_size or (128 if on_chip else 8)
    image = 224 if on_chip else 64

    os.makedirs(OUT_DIR, exist_ok=True)
    log_csv = os.path.join(OUT_DIR, f"autotune_{tag}_log.csv")
    if os.path.exists(log_csv):
        os.remove(log_csv)

    model = MODELS[model_name](num_classes=1000, dtype=jnp.bfloat16)
    opt = optax.sgd(0.01, momentum=0.9)

    def loss_fn(logits, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    def build(threshold=None, hierarchical=False, autotune=None):
        return make_train_step(
            apply_fn=model.apply, loss_fn=loss_fn, optimizer=opt,
            has_batch_stats=True, threshold_bytes=threshold,
            hierarchical=hierarchical, autotune=autotune,
            autotune_log_file=log_csv if autotune else None,
        )

    rng = np.random.default_rng(0)
    x = shard_batch(rng.uniform(
        size=(batch * hvd.size(), image, image, 3)).astype(np.float32))
    y = shard_batch(rng.integers(
        0, 1000, size=(batch * hvd.size(),)).astype(np.int32))
    state = init_train_state(
        model, opt, jnp.zeros((2, image, image, 3)), has_batch_stats=True)

    # --- Phase 1: let the tuner run to convergence -----------------------
    step = build(autotune=True)
    pm = step.parameter_manager
    calls = 0
    budget = (3 + args.max_samples + 2) * args.steps_per_sample * \
        len([False, True])
    t_start = time.perf_counter()
    while not pm.frozen and calls < budget:
        state, _ = _timed_call(step, state, x, y)
        calls += 1
    tune_seconds = time.perf_counter() - t_start
    converged = {
        "frozen": pm.frozen,
        "calls": calls,
        "tune_seconds": round(tune_seconds, 1),
        "threshold_bytes": int(pm.current.fusion_threshold_bytes),
        "hierarchical": bool(pm.current.hierarchical_allreduce),
    }
    print(f"autotune[{tag}]: frozen={pm.frozen} after {calls} calls "
          f"({tune_seconds:.0f}s): threshold="
          f"{converged['threshold_bytes']} "
          f"hierarchical={converged['hierarchical']}", flush=True)

    # --- Phase 2: interleaved grid around the converged knobs ------------
    grid = [
        ("grid_1MB", 1 << 20, False),
        ("grid_8MB", 8 << 20, False),
        ("grid_64MB", 64 << 20, False),
        ("grid_256MB", 256 << 20, False),
        ("converged", converged["threshold_bytes"],
         converged["hierarchical"]),
    ]
    if not on_chip:
        grid.append(("grid_hier_8MB", 8 << 20, True))
    steps = {}
    for name, thr, hier in grid:
        steps[name] = build(threshold=thr, hierarchical=hier)
        state, _ = _timed_call(steps[name], state, x, y)  # compile+warm
    best = {name: float("inf") for name, *_ in grid}
    for r in range(args.rounds):
        for name, *_ in grid:
            state, dt = _timed_call(steps[name], state, x, y)
            best[name] = min(best[name], dt)
            print(f"round {r} {name}: {dt * 1e3:.2f} ms", flush=True)

    grid_best = min(best, key=best.get)
    result = {
        "platform": tag,
        "model": model_name,
        "batch": batch,
        "world_size": hvd.size(),
        "converged": converged,
        "grid_ms": {k: round(v * 1e3, 2) for k, v in best.items()},
        "grid_best": grid_best,
        "converged_within_pct_of_best": round(
            (best["converged"] / best[grid_best] - 1) * 100, 1),
        "log_csv": log_csv,
    }
    path = os.path.join(OUT_DIR, f"autotune_{tag}.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result, indent=1))
    print("wrote", path)
    return result


if __name__ == "__main__":
    main()
