#!/usr/bin/env python
"""hvd_verify: whole-program collective-schedule model checker.

Where hvd_lint flags single-statement smells, this proves (bounded)
schedule compatibility across ranks interprocedurally: it builds a call
graph over the given training code, enumerates the execution paths each
rank can take through rank-tainted branches (loops unrolled up to
HVD_VERIFY_LOOP_BOUND, at most HVD_VERIFY_MAX_PATHS paths per entry),
projects every path's collective sequence per communication group
(world / intra-host local / cross-host / process sets / per-epoch
elastic worlds / ``axis:<name>`` mesh axes, with ``ppermute`` lowered
to first-class point-to-point SendRecv events), and checks the
sequences pairwise:

    HVD009  schedule divergence within one group
    HVD010  blocking collective reachable on a strict subset of ranks
    HVD011  cross-group ordering inversion (intra vs cross stages)
    HVD012  collective on an abort/cleanup path that peers skip
    HVD013  unmatched/cyclic point-to-point schedule (pipeline deadlock)
    HVD014  cross-AXIS ordering inversion (HVD011 over mesh axes)
    HVD015  axis-shape contract violation (MoE capacity vs axis size)

A finding prints a counterexample trace — the diverging rank set, the
collective, and the exact branch chain (file:line per decision) — in
text and, with ``--json``, as a machine-checkable payload.

Run::

    python scripts/hvd_verify.py examples/ horovod_tpu/   # verify the repo
    python scripts/hvd_verify.py --json my_train.py       # CI consumption
    python scripts/hvd_verify.py --entry train_step my_train.py
    python scripts/hvd_verify.py --list-rules

Suppress like the linter: ``# hvd-lint: disable=HVD010`` on the site (or
anywhere in the enclosing statement), ``# hvd-lint: disable-file=…`` for
the file.  Exit codes: 0 clean, 1 findings, 2 usage error.  The runtime
counterpart is the group/epoch-aware HVD_SANITIZER=1 collective
sanitizer (docs/analysis.md).
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from horovod_tpu.analysis.cli import main_verify  # noqa: E402

if __name__ == "__main__":
    sys.exit(main_verify())
