"""On-chip A/B for the Pallas conv+BN kernels (round-4 VERDICT #1a).

Measures ops/conv_bn.py against XLA's fused equivalents on the real
chip, interleaved in one process (the shared chip fluctuates ~2x between
runs; interleaving + min-of-N is the reliable comparison — same
methodology as scripts/pallas_residual_experiment.py).  Two shapes from
the HBM-bound 56x56 ResNet-50 stage (PERF.md profile):

* the bottleneck 3x3 at C=64 ([B, 56, 56, 64] -> 64), and
* a C=256 wide variant ([B, 56, 56, 256] -> 256) for lane-width contrast
  (C=64 leaves half the 128-lane MXU idle; C=256 fills it).

Variants: fused conv+BN-apply+ReLU (inference/apply half) and
conv+stats epilogue (training half).  Writes
scripts/out/conv_bn_experiment.json; verdict goes to docs/PERF.md.

Usage: python scripts/pallas_conv_bn_experiment.py [--batch 128]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu.ops.conv_bn import (
    conv3x3_bn_relu, conv3x3_stats, xla_conv3x3_bn_relu, xla_conv3x3_stats,
)


def _sync(out):
    leaf = jax.tree_util.tree_leaves(out)[-1]
    np.asarray(jax.device_get(leaf.sum() if leaf.ndim else leaf))


def best_ms(fn, *args, n=5, inner=3):
    out = fn(*args)
    _sync(out)  # compile + warm
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        for _ in range(inner):
            out = fn(*args)
        _sync(out)
        best = min(best, (time.perf_counter() - t0) / inner)
    return best * 1e3


# ops per timed call, chained in-graph (carry feeds the next iteration):
# the tunnel's ~10 ms per-dispatch latency would otherwise dominate a
# sub-ms kernel and the A/B would measure dispatch, not the kernels
_K = 16


def _loop_apply(fn):
    @jax.jit
    def looped(x, w, scale, bias):
        return jax.lax.fori_loop(
            0, _K, lambda i, y: fn(y, w, scale, bias), x)

    return looped


def _loop_stats(fn):
    @jax.jit
    def looped(x, w):
        def body(i, carry):
            y, s, sq = carry
            y2, s2, sq2 = fn(y, w)
            return y2, s + s2, sq + sq2

        c = y0, s0, sq0 = (x, jnp.zeros((x.shape[3],), jnp.float32),
                           jnp.zeros((x.shape[3],), jnp.float32))
        return jax.lax.fori_loop(0, _K, body, c)

    return looped


def run_shape(batch: int, c: int) -> list:
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 56, 56, c)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(3, 3, c, c)) * 0.05, jnp.bfloat16)
    scale = jnp.asarray(rng.uniform(0.5, 1.5, size=(c,)), jnp.float32)
    bias = jnp.asarray(rng.normal(size=(c,)), jnp.float32)

    flops = 2 * batch * 56 * 56 * 9 * c * c  # conv MACs x2, per op

    # correctness on-chip before timing anything
    got = np.asarray(jax.jit(conv3x3_bn_relu)(x, w, scale, bias),
                     np.float32)
    want = np.asarray(jax.jit(xla_conv3x3_bn_relu)(x, w, scale, bias),
                      np.float32)
    np.testing.assert_allclose(got, want, rtol=0.1, atol=0.15)

    rows = []
    # interleave A/B inside each variant
    for name, a_fn, a_args, b_fn, b_args in [
        ("conv+bn_apply+relu",
         _loop_apply(xla_conv3x3_bn_relu), (x, w, scale, bias),
         _loop_apply(conv3x3_bn_relu), (x, w, scale, bias)),
        ("conv+stats_epilogue",
         _loop_stats(xla_conv3x3_stats), (x, w),
         _loop_stats(conv3x3_stats), (x, w)),
    ]:
        # symmetric A/B/A/B interleave: both sides get two windows, min
        # of each — the shared chip drifts ~2x between windows and an
        # asymmetric schedule (A B A) biases whichever side got two
        a1 = best_ms(a_fn, *a_args)
        b1 = best_ms(b_fn, *b_args)
        a2 = best_ms(a_fn, *a_args)
        b2 = best_ms(b_fn, *b_args)
        xla_best = min(a1, a2) / _K
        pl_best = min(b1, b2) / _K
        rows.append({
            "shape": f"[{batch},56,56,{c}]x{c}",
            "variant": name,
            "xla_ms": xla_best,
            "pallas_ms": pl_best,
            "xla_tflops": flops / xla_best / 1e9,
            "pallas_tflops": flops / pl_best / 1e9,
            "pallas_vs_xla": xla_best / pl_best,
        })
        print(f"{rows[-1]['shape']} {name}: XLA {xla_best:.2f} ms "
              f"({rows[-1]['xla_tflops']:.1f} TF), Pallas {pl_best:.2f} ms "
              f"({rows[-1]['pallas_tflops']:.1f} TF)  -> "
              f"{rows[-1]['pallas_vs_xla']:.2f}x", flush=True)
    return rows


def run_end_to_end(batch: int = 128, k_steps: int = 10) -> list:
    """Interleaved ResNet-50 train-step A/B: conv_bn='xla' vs 'pallas'
    (the fused 3x3+BN+ReLU in every stride-1 bottleneck).  Same harness
    as bench.py (K in-graph steps via lax.scan)."""
    import jax.numpy as jnp
    import optax

    from horovod_tpu.models.resnet import ResNet50
    from horovod_tpu.training import (
        init_train_state, make_train_step, shard_batch,
    )

    rng = np.random.default_rng(42)
    x = shard_batch(
        rng.uniform(size=(batch, 224, 224, 3)).astype(np.float32))
    y = shard_batch(rng.integers(0, 1000, size=(batch,)).astype(np.int32))

    def build(conv_bn):
        model = ResNet50(num_classes=1000, dtype=jnp.bfloat16,
                         conv_bn=conv_bn)
        opt = optax.sgd(0.01, momentum=0.9)

        def loss_fn(logits, labels):
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()

        step = make_train_step(
            apply_fn=model.apply, loss_fn=loss_fn, optimizer=opt,
            has_batch_stats=True, in_graph_steps=k_steps,
        )
        state = init_train_state(model, opt, jnp.zeros((2, 224, 224, 3)),
                                 has_batch_stats=True)
        return step, state

    def time_steps(step, state, n=4):
        # the step donates its state: thread it and hand it BACK so the
        # next timing window does not execute on donated buffers
        state, loss = step(state, x, y)  # compile + warm
        _sync(loss)
        best = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            state, loss = step(state, x, y)
            _sync(loss)
            best = min(best, (time.perf_counter() - t0) / k_steps)
        return best * 1e3, state

    xla_step, xla_state = build("xla")
    pl_step, pl_state = build("pallas")
    # interleave: A B A B (shared-chip drift hits both sides)
    a1, xla_state = time_steps(xla_step, xla_state)
    b1, pl_state = time_steps(pl_step, pl_state)
    a2, xla_state = time_steps(xla_step, xla_state)
    b2, pl_state = time_steps(pl_step, pl_state)
    xla_ms, pl_ms = min(a1, a2), min(b1, b2)
    row = {
        "variant": "resnet50_train_step_e2e",
        "batch": batch,
        "xla_ms": xla_ms,
        "pallas_ms": pl_ms,
        "xla_img_s": batch / xla_ms * 1e3,
        "pallas_img_s": batch / pl_ms * 1e3,
        "pallas_vs_xla": xla_ms / pl_ms,
    }
    print(f"e2e resnet50 b{batch}: XLA {xla_ms:.1f} ms/step "
          f"({row['xla_img_s']:.0f} img/s), Pallas conv_bn {pl_ms:.1f} ms "
          f"({row['pallas_img_s']:.0f} img/s)  -> "
          f"{row['pallas_vs_xla']:.2f}x", flush=True)
    return [row]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--phase", choices=["standalone", "e2e"],
                    default="standalone",
                    help="run phases in separate processes: the "
                         "standalone shape buffers + two resident "
                         "ResNet-50 train states overflow HBM together")
    args = ap.parse_args()
    hvd.init()

    if args.phase == "standalone":
        rows = run_shape(args.batch, 64) + run_shape(args.batch, 256)
    else:
        rows = run_end_to_end(args.batch)

    dest = os.path.join(os.path.dirname(os.path.abspath(__file__)), "out")
    os.makedirs(dest, exist_ok=True)
    path = os.path.join(dest, "conv_bn_experiment.json")
    merged = {"batch": args.batch, "rows": [],
              "method": "interleaved A/B/A/B min windows on the real "
                        "chip, device_get sync"}
    if os.path.exists(path):
        with open(path) as f:
            merged = json.load(f)
        # stamp the CURRENT run's batch/method: stale top-level fields
        # would misattribute rows measured at a different --batch
        merged["batch"] = args.batch
        merged["method"] = ("interleaved A/B/A/B min windows on the real "
                            "chip, device_get sync")
    kept = [r for r in merged.get("rows", [])
            if not any(r.get("variant") == n.get("variant")
                       and r.get("shape") == n.get("shape")
                       for n in rows)]
    merged["rows"] = kept + rows
    with open(path, "w") as f:
        json.dump(merged, f, indent=2)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
